"""Structured-event bus: JSONL sink, env/FFConfig-gated.

Every event is one JSON object per line with at least ``ts`` (unix
seconds, float) and ``kind`` (a registered name from EVENT_KINDS);
kind-specific required payload fields are declared alongside so tests
and ``tools/ffobs.py validate`` can check emitted logs mechanically.

Disabled (the default) the bus costs ONE attribute check per emit —
instrumentation stays in the hot search loops without a measurable
tax.  Enable with ``FLEXFLOW_TPU_OBS=/path/to/log.jsonl`` (read at
import; ``BUS.configure`` re-arms at any time) or
``FFConfig.obs_log_file`` (applied by ``FFModel.compile``).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional

from flexflow_tpu.obs.flight import FLIGHT

SCHEMA_VERSION = 1

# kind -> payload fields that must be present (beyond ts/kind).
# Extra fields are always allowed; the schema floors, not ceilings.
EVENT_KINDS = {
    # bus lifecycle
    "obs.meta": {"schema", "pid"},
    # search layer (search/driver.py)
    "search.begin": {"nodes", "devices"},
    "search.baseline": {"cost_s"},
    "search.substitution": {"xfer", "action"},
    "search.candidate": {"cost_s", "best_s", "improved"},
    "search.split": {"op", "pre_nodes", "post_nodes"},
    # k-way chain decomposition (production-scale graphs, PR 7) —
    # emitted since the chain search landed but never registered, so
    # ffobs validate rejected logs containing them
    "search.chain": {"nodes", "segments"},
    "search.chain_done": {"bound_s", "cost_s"},
    # series-parallel decomposition (PR 12, search/decompose.py): one
    # event per oversized (sub)graph naming the chosen decomposition —
    # mode "chain" (width-1 bottleneck cuts, the PR 7 degenerate case),
    # "sp" (bounded-width frontier cuts), or "fallback" with the
    # ``reason`` the graph degraded to binary recursion, so a
    # bottleneck-free thousand-node graph can never slow down silently
    "search.decompose": {"nodes", "mode"},
    "search.decompose_done": {"mode", "bound_s", "cost_s"},
    "search.floor": {"kept_dp", "dp_cost_s", "searched_cost_s"},
    "search.result": {"cost_s", "rewritten"},
    "search.perf": {"search_seconds", "calibration_seconds", "full_sims",
                    "delta_sims"},
    "search.log": {"msg"},
    # joint strategy x comm-plan co-search (search/comm_plan.py): one
    # event per comm-plan decision — served=True rode the signature
    # memo ("memo") or the persistent layer ("disk"), False paid the
    # full choose_sync_schedule sweep ("search")
    "search.comm_plan": {"served", "source", "groups"},
    # the per-group optimizer-state sharding choice the co-search
    # adopted for its final result (ZeRO-1 dimension)
    "search.zero_groups": {"groups", "credit_s"},
    # serve-objective result (search/serving.py, FFConfig.objective):
    # the SHD16x-gated p99/KV-residency numbers of the returned strategy
    "search.serve": {"p99_s", "kv_bytes_per_device"},
    # KV-lane decision (search/driver.py _choose_kv_precision): the
    # chosen pool dtype, whether it was searched or pinned, the
    # declared shared-prefix pages, and the per-dtype priced p99 map
    "search.kv": {"dtype", "searched", "shared_prefix_pages"},
    # prefill/decode disaggregation search (search/disaggregation.py):
    # one event per proposal decision — colocated vs disaggregated
    # serve-currency step, the KV-handoff price, and whether the
    # two-block placement was adopted (honest zero = adopted=False)
    "search.disagg": {"adopted", "colocated_ms", "disagg_ms",
                      "handoff_ms"},
    # one event per fleet proposal decision (search/fleet.py): the
    # N-replica partition, routing policy, per-class p99 roll-up and
    # whether the fleet beat the single replica (honest zero =
    # adopted=False)
    "search.fleet": {"adopted", "replicas", "single_ms", "fleet_ms"},
    # fleet router (runtime/fleet.py): one event per routed request —
    # which replica the searched per-class fractions dispatched it to
    "fleet.route": {"rid", "replica", "slo"},
    # elastic fleet re-size (runtime/controller.py research_fleet):
    # measured per-class p99 drift triggered a fleet re-search that
    # may change N
    "fleet.scale": {"step", "from_replicas", "to_replicas"},
    # continuous-batching decode executor (runtime/decode.py): one
    # event per composed decode frame (admissions/evictions/page
    # residency + measured latency, predicted_s when a serving pricer
    # supplied one) and one end-of-run roll-up — the decode phase of
    # the predicted-vs-measured story (ffobs report renders both)
    "decode.frame": {"frame", "active", "admitted", "evicted",
                     "pages_in_use"},
    "decode.summary": {"frames", "completed", "measured_p50_s",
                       "measured_p99_s"},
    # per-request serving lifecycle (runtime/decode.py): one event per
    # completed request carrying its spans — queue wait, TTFT (enqueue
    # -> first generated token), TPOT (steady per-token), e2e — the
    # request-level currency of the serving telemetry.  Armed requests
    # only: the executor checks the bus ONCE per frame when off.
    "decode.request": {"rid", "phase"},
    # chunked prefill lane (runtime/prefill.py): one event per admitted
    # prompt that went through the batched KV writer — tokens written,
    # chunk passes paid (vs one decode frame per token without it)
    "decode.prefill": {"rid", "tokens", "chunks"},
    # radix prefix sharing (runtime/decode.py PageAllocator): one
    # prefix_hit per admission that claimed trie-cached pages by
    # refcount instead of allocating (pages claimed, prompt tokens
    # skipped); one cow per copy-on-write page copy at a mid-page
    # divergence (the reserve-on-divergence path)
    "decode.prefix_hit": {"rid", "pages", "tokens"},
    "decode.cow": {"rid", "src_page", "dst_page", "tokens"},
    # device-trace ingestion + lane matching (obs/trace_ingest.py):
    # one trace.ingest per parsed capture, one trace.lane_match per
    # predicted sync-bucket lane (matched by annotation tag, never by
    # fuzzy kernel name)
    "trace.ingest": {"path", "events", "lanes"},
    "trace.lane_match": {"lane", "matched"},
    # Prometheus exposition endpoint start (obs/exposition.py,
    # FLEXFLOW_TPU_METRICS_PORT)
    "metrics.exposition": {"port"},
    # DP inner loop (search/dp.py)
    "dp.split": {"op", "pre_nodes", "post_nodes", "cost_s"},
    "dp.summary": {"memo_hits", "memo_misses"},
    # calibration / cost-model provenance
    "calibration.ignored": {"backend", "machine"},
    "calibration.staleness": {"ratio", "threshold"},
    # the automatic re-probe policy acting on a drift-stale table:
    # deferred=False re-probed on the live backend, True fell back to
    # the roofline (live backend cannot probe for the machine model)
    "calibration.reprobe": {"backend", "deferred"},
    # compile-time strategy explanation (model.py)
    "strategy.table": {"rows"},
    # static analysis (flexflow_tpu/analysis): one event per finding —
    # "pass" is the producing pass (invariants/sharding/equivalence/
    # strategy), "code" the stable finding code (PCG0xx/SHD1xx/…)
    "analysis.finding": {"pass", "code"},
    # runtime (model.fit / runtime/profiler.py)
    "profile.summary": {"steps"},
    "drift.report": {"predicted_s", "measured_s", "ratio", "stale"},
    "metrics.snapshot": {"counters"},
    # always-on training controller (runtime/controller.py): the
    # drift→re-search→hot-swap / elastic-recovery decision stream, plus
    # the deterministic fault-injection harness (runtime/faults.py)
    "fault.injected": {"fault", "step"},
    "controller.research": {"step", "trigger", "search_seconds"},
    "controller.swap": {"step", "swap_seconds", "fallback"},
    "controller.recovery": {"step", "cause"},
    "controller.retry": {"step", "attempt"},
    "controller.fallback": {"step", "reason"},
    # the measured-p99 drift watch (serving currency): the controller
    # saw a measured decode p99 vs the searched prediction; drifted
    # past threshold => the next step re-searches with this trigger
    "controller.p99_drift": {"step", "ratio", "drifted"},
    # SLO burn-rate watch (obs/slo.py via controller.observe_burn_rate):
    # one event per class per observation — multi-window error-budget
    # burn; fired=True arms a re-search BEFORE raw p99 crosses the
    # drift threshold
    "controller.burn_rate": {"step", "slo", "fast", "slow", "fired"},
    "controller.summary": {"steps", "swaps", "recoveries"},
    # request-scoped tracing (obs/tracing.py): one trace.span per
    # CLOSED span when the bus is armed; trace.open lines appear only
    # in flight-recorder dumps (the in-flight requests at dump time)
    "trace.span": {"trace_id", "span", "span_id", "dur_s"},
    "trace.open": {"trace_id", "span", "span_id"},
    # flight recorder (obs/flight.py): flight.meta heads every dump
    # file; flight.dump is emitted on the bus when a post-mortem was
    # written (fault injection, controller fallback, atexit/SIGTERM)
    "flight.meta": {"reason", "events", "dropped"},
    "flight.dump": {"path", "events", "open_spans", "reason"},
    # event-volume guard roll-up: per-kind counts the sampler
    # suppressed (emitted at close so totals stay exactly recoverable)
    "obs.sampled": {"counts"},
}

_VALID_ACTIONS = frozenset(
    {"pushed", "pruned", "duplicate", "invalid", "pinned"}
)


def validate_event(obj) -> List[str]:
    """Schema errors for one decoded JSONL event ([] = valid)."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return ["event is not a JSON object"]
    ts = obj.get("ts")
    if not isinstance(ts, (int, float)):
        errors.append("missing/non-numeric 'ts'")
    kind = obj.get("kind")
    if not isinstance(kind, str) or not kind:
        errors.append("missing 'kind'")
        return errors
    required = EVENT_KINDS.get(kind)
    if required is None:
        errors.append(f"unknown kind {kind!r}")
        return errors
    for field in required:
        if field not in obj:
            errors.append(f"{kind}: missing field {field!r}")
    if kind == "search.substitution" and obj.get("action") not in _VALID_ACTIONS:
        errors.append(
            f"search.substitution: action {obj.get('action')!r} not in "
            f"{sorted(_VALID_ACTIONS)}"
        )
    return errors


class EventBus:
    """Append-only JSONL event sink.  Thread-safe; ``enabled`` is a
    plain attribute so the disabled fast path is one load + branch."""

    def __init__(self):
        self.enabled = False
        self.path: Optional[str] = None
        self._sink = None
        self._lock = threading.Lock()
        self._atexit_armed = False
        # event-volume guard: kind -> rate (float < 1.0, probability)
        # or cap (int >= 1, first-N).  None = no sampling configured,
        # so the armed hot path pays a single ``is not None`` check.
        self._sample: Optional[Dict[str, float]] = None
        self._sample_seed = 0
        self._emitted: Dict[str, int] = {}
        self.sampled_out: Dict[str, int] = {}

    # ------------------------------------------------------------------
    def configure(self, path: str) -> None:
        """Open (or switch to) a JSONL sink at ``path`` and enable the
        bus.  Idempotent for a repeated identical path.  Writes are
        block-buffered (a per-event flush syscall would tax the chatty
        per-candidate search events); an atexit hook drains the buffer
        on normal interpreter exit, and flush()/close() do so on
        demand."""
        with self._lock:
            if not self._atexit_armed:
                atexit.register(self.flush)
                self._atexit_armed = True
            if self._sink is not None and self.path == path:
                self.enabled = True
                return
            if self._sink is not None:
                self._sink.close()
            self._sink = open(path, "a")
            self.path = path
            self.enabled = True
        self.emit("obs.meta", schema=SCHEMA_VERSION, pid=os.getpid())

    def configure_sampling(self, spec, seed: int = 0) -> None:
        """Arm the per-kind event-volume guard.  ``spec`` is either a
        dict or a ``"kind=rate,kind=cap"`` string: a value < 1.0 keeps
        that fraction of events (deterministic, seeded — the keep
        decision hashes (kind, ordinal, seed), so it is independent of
        interleaving across kinds); an integer >= 1 caps the kind at
        its first N events.  Unlisted kinds are never sampled.
        Suppressed events are counted exactly in ``sampled_out`` and
        rolled up as one ``obs.sampled`` event at close, so totals
        stay recoverable from the log."""
        if isinstance(spec, str):
            parsed: Dict[str, float] = {}
            for part in spec.split(","):
                part = part.strip()
                if not part:
                    continue
                name, _, val = part.partition("=")
                v = float(val)
                parsed[name.strip()] = v if v < 1.0 else int(v)
            spec = parsed
        self._sample = dict(spec) if spec else None
        self._sample_seed = int(seed)
        self._emitted = {}
        self.sampled_out = {}

    def _sample_keep(self, kind: str) -> bool:
        rate = self._sample.get(kind)  # type: ignore[union-attr]
        if rate is None:
            return True
        n = self._emitted.get(kind, 0) + 1
        self._emitted[kind] = n
        if isinstance(rate, int):
            keep = n <= rate
        else:
            h = zlib.crc32(f"{kind}:{n}:{self._sample_seed}".encode())
            keep = h < rate * 2**32
        if not keep:
            self.sampled_out[kind] = self.sampled_out.get(kind, 0) + 1
        return keep

    def close(self) -> None:
        if self.enabled and self.sampled_out:
            self.emit("obs.sampled", counts=dict(self.sampled_out))
        with self._lock:
            self.enabled = False
            if self._sink is not None:
                self._sink.close()
                self._sink = None
            self.path = None

    def flush(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.flush()

    # ------------------------------------------------------------------
    def emit(self, kind: str, **payload) -> None:
        # flight recorder sees EVERY event, armed bus or not — the
        # post-mortem ring must survive the off-by-default discipline
        # (one plain-attribute check + a deque append, no encoding)
        if FLIGHT.enabled:
            FLIGHT.record(kind, payload)
        if not self.enabled:
            return
        if self._sample is not None and not self._sample_keep(kind):
            return
        evt = {"ts": time.time(), "kind": kind}
        evt.update(payload)
        try:
            line = json.dumps(evt, default=_jsonable)
        except (TypeError, ValueError):  # never let telemetry crash work
            line = json.dumps({"ts": evt["ts"], "kind": kind,
                               "error": "unserializable payload"})
        with self._lock:
            if self._sink is not None:
                self._sink.write(line + "\n")


def _jsonable(obj):
    """Best-effort coercion for payload values (numpy scalars, views).
    ``tolist`` first: ``item()`` raises on arrays with size != 1."""
    for attr in ("tolist", "item"):
        fn = getattr(obj, attr, None)
        if fn is not None:
            try:
                return fn()
            except (TypeError, ValueError):
                continue
    return repr(obj)


BUS = EventBus()

_env = os.environ.get("FLEXFLOW_TPU_OBS", "")
if _env and _env != "0":
    try:
        BUS.configure(_env if _env not in ("1", "true") else "ffobs.jsonl")
    except OSError:  # unwritable path must not break imports
        pass
del _env

_env = os.environ.get("FLEXFLOW_TPU_OBS_SAMPLE", "")
if _env:
    try:
        BUS.configure_sampling(
            _env,
            seed=int(os.environ.get("FLEXFLOW_TPU_OBS_SAMPLE_SEED", "0")))
    except ValueError:  # malformed spec must not break imports
        pass
del _env
