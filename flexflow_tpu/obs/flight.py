"""Always-on flight recorder: a bounded ring of the most recent
events, dumped to a post-mortem JSONL when something goes wrong.

The event bus is off by default — deliberately, the serving hot loop
pays one boolean per frame — which means a fault or controller
fallback in an UNARMED process leaves no artifact at all.  The flight
recorder fixes exactly that hole: ``EventBus.emit`` hands every event
to ``FLIGHT.record`` BEFORE the ``enabled`` check, so the last-N
events are always in memory (a ``collections.deque`` append of an
already-built payload — no JSON encoding, no I/O), and a dump site
(fault injector, controller fallback, atexit/SIGTERM when armed with a
dump dir, or an explicit ``FLIGHT.dump``) writes them out together
with the tracer's still-open spans — the in-flight requests at the
moment of death.

Overhead discipline mirrors the bus: ``FLIGHT.enabled`` is a plain
attribute checked once per emit; ``FLEXFLOW_TPU_FLIGHT=0`` turns the
recorder off entirely, ``FLEXFLOW_TPU_FLIGHT_RING`` resizes the ring
(default 512), ``FLEXFLOW_TPU_FLIGHT_DIR`` arms automatic dumps (and
the atexit/SIGTERM hook) into that directory.

Dump format: JSONL, first line a ``flight.meta`` record (reason,
counts), then the ring's events verbatim (oldest first), then one
``trace.open`` line per still-open span.  ``ffobs trace`` renders it.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import time
from typing import Deque, List, Optional, Tuple

_DEF_CAPACITY = 512


class FlightRecorder:
    """Bounded in-memory event ring + post-mortem dump."""

    def __init__(self, capacity: int = _DEF_CAPACITY):
        self.enabled = True
        self.capacity = capacity
        self.ring: Deque[Tuple[float, str, dict]] = collections.deque(
            maxlen=capacity)
        self.recorded = 0  # total ever recorded (ring drops the rest)
        self.dumps = 0
        self.dump_dir: Optional[str] = None
        self.last_dump_path: Optional[str] = None
        self._hooks_armed = False

    # -- hot path --------------------------------------------------------
    def record(self, kind: str, payload: dict) -> None:
        """Called by ``EventBus.emit`` for EVERY event, armed bus or
        not.  Must stay allocation-light: one tuple + deque append."""
        self.recorded += 1
        self.ring.append((time.time(), kind, payload))

    # -- configuration ---------------------------------------------------
    def configure(self, dump_dir: Optional[str] = None,
                  capacity: Optional[int] = None,
                  enabled: Optional[bool] = None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)
        if capacity is not None and capacity != self.capacity:
            self.capacity = int(capacity)
            self.ring = collections.deque(self.ring,
                                          maxlen=self.capacity)
        if dump_dir is not None:
            self.dump_dir = dump_dir
            self._arm_hooks()

    def reset(self) -> None:
        """Clear the ring and counters (tests)."""
        self.ring.clear()
        self.recorded = 0
        self.dumps = 0
        self.last_dump_path = None

    def _arm_hooks(self) -> None:
        if self._hooks_armed:
            return
        self._hooks_armed = True
        atexit.register(self._dump_at_exit)
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                self.dump(reason="sigterm")
                if callable(prev):
                    prev(signum, frame)
                elif prev == signal.SIG_DFL:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            pass  # non-main thread / restricted env: atexit still fires

    def _dump_at_exit(self) -> None:
        if self.dump_dir and self.ring:
            try:
                self.dump(reason="atexit")
            except OSError:
                pass

    # -- dump ------------------------------------------------------------
    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> Optional[str]:
        """Write the ring + open spans to ``path`` (or a fresh file in
        ``dump_dir``).  Returns the path, or None when neither is set
        — post-mortems are opt-in by destination, never by overhead."""
        if not self.enabled:
            return None
        if path is None:
            if self.dump_dir is None:
                return None
            self.dumps += 1
            path = os.path.join(
                self.dump_dir,
                f"flight-{os.getpid()}-{self.dumps:03d}.jsonl")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        from flexflow_tpu.obs.events import BUS, _jsonable
        from flexflow_tpu.obs.tracing import TRACER

        events = list(self.ring)
        open_spans = TRACER.open_spans()
        with open(path, "w") as f:
            meta = {"ts": time.time(), "kind": "flight.meta",
                    "reason": reason, "events": len(events),
                    "dropped": max(self.recorded - len(events), 0)}
            f.write(json.dumps(meta, default=_jsonable) + "\n")
            for t, kind, payload in events:
                evt = {"ts": t, "kind": kind}
                evt.update(payload)
                f.write(json.dumps(evt, default=_jsonable) + "\n")
            for span in open_spans:
                evt = {"ts": time.time(), "kind": "trace.open",
                       "trace_id": span.trace_id, "span": span.name,
                       "span_id": span.span_id,
                       "parent_id": span.parent_id,
                       "start_s": span.start_s}
                if span.attrs:
                    evt["attrs"] = dict(span.attrs)
                f.write(json.dumps(evt, default=_jsonable) + "\n")
        self.last_dump_path = path
        if BUS.enabled:
            BUS.emit("flight.dump", path=path, events=len(events),
                     open_spans=len(open_spans), reason=reason)
        return path

    def tail(self, n: int = 50) -> List[Tuple[float, str, dict]]:
        """The most recent ``n`` ring entries (newest last)."""
        if n <= 0:
            return []
        return list(self.ring)[-n:]


FLIGHT = FlightRecorder(
    capacity=int(os.environ.get("FLEXFLOW_TPU_FLIGHT_RING",
                                _DEF_CAPACITY) or _DEF_CAPACITY))
if os.environ.get("FLEXFLOW_TPU_FLIGHT", "") == "0":
    FLIGHT.enabled = False
_dir = os.environ.get("FLEXFLOW_TPU_FLIGHT_DIR", "")
if _dir:
    FLIGHT.configure(dump_dir=_dir)
del _dir
