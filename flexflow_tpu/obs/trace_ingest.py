"""Device-trace ingestion + predicted-lane matching.

``runtime.profiler.device_trace`` (jax.profiler) writes a TensorBoard
profile logdir; this module parses its Chrome-trace JSON
(``plugins/profile/<run>/<host>.trace.json[.gz]``) into normalized
event rows and matches the ``obs/annotate.py`` tags found there
against the simulator's predicted lanes — by TAG EQUALITY on the
stable lane ids both sides share (``bucket:<name>:sync``), never by
fuzzy kernel names.  The result is a ``LaneDriftReport``: per sync
bucket, predicted vs measured issue time, duration, and their
step-relative fractions — the measured side the per-bucket DriftReport
rows honestly left ``None`` since the sync-schedule PR.

Stdlib-only (json/gzip — no jax import), so the committed test fixture
and offline captures ingest anywhere the logdir lands.

Honesty: a CPU-mesh capture carries HOST-observed lane markers (the
``io_callback`` stamps bracket the lane's thunks in the host
timeline); the absolute seconds therefore compare host wall time to
machine-model predictions.  The scale-free comparison — each lane's
issue point and duration as FRACTIONS of its own step — is the drift
signal (``*_frac_ratio``); absolute ratios are reported alongside,
labeled by ``source``.  ICI/DCN wire behavior stays simulated until
the same capture runs on a TPU.
"""

from __future__ import annotations

import glob
import gzip
import json
import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.obs.annotate import PHASE_PREFIX, STEP_PHASE, parse_tag


@dataclass
class TraceEvent:
    """One normalized complete-slice event from the capture."""

    name: str
    ts_us: float
    dur_us: float
    pid: int = 0
    tid: int = 0


def find_trace_file(path: str) -> Optional[str]:
    """Resolve a capture to its Chrome-trace JSON: ``path`` may be the
    logdir handed to ``device_trace`` (the newest
    ``plugins/profile/<run>/*.trace.json[.gz]`` wins), a run
    directory, or the trace file itself."""
    if os.path.isfile(path):
        return path
    hits = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        hits += glob.glob(os.path.join(path, pat))
        hits += glob.glob(os.path.join(path, "plugins", "profile", "*", pat))
    if not hits:
        return None
    return max(hits, key=os.path.getmtime)


def read_trace_events(path: str) -> List[TraceEvent]:
    """Normalized ``X``-phase rows of one Chrome-trace JSON file
    (gzipped or plain).  Raises ValueError on a file that is not a
    trace document."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path}: no traceEvents array")
    out: List[TraceEvent] = []
    for e in events:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        name = e.get("name")
        ts = e.get("ts")
        if not isinstance(name, str) or not isinstance(ts, (int, float)):
            continue
        dur = e.get("dur")
        out.append(TraceEvent(
            name=name, ts_us=float(ts),
            dur_us=float(dur) if isinstance(dur, (int, float)) else 0.0,
            pid=int(e.get("pid") or 0), tid=int(e.get("tid") or 0)))
    out.sort(key=lambda ev: ev.ts_us)
    return out


@dataclass
class IngestResult:
    """The annotated content of one capture: step windows, paired lane
    marker spans, and named phase spans."""

    path: str
    events: int
    # [(start_us, end_us)] of ff.phase/step annotation windows
    step_spans: List[Tuple[float, float]] = field(default_factory=list)
    # lane_id -> [(issue_ts_us, done_ts_us)] paired in time order
    lanes: Dict[str, List[Tuple[float, float]]] = field(
        default_factory=dict)
    # phase tag -> [duration seconds] of non-step ff.phase/* spans
    phases: Dict[str, List[float]] = field(default_factory=dict)


def ingest(path: str, emit: bool = True) -> Optional[IngestResult]:
    """Parse a capture (logdir or trace file) and pull out every
    annotated tag.  None when no trace file exists.  Emits a
    ``trace.ingest`` event when the bus is armed."""
    trace_file = find_trace_file(path)
    if trace_file is None:
        return None
    events = read_trace_events(trace_file)
    result = IngestResult(path=trace_file, events=len(events))
    open_issue: Dict[str, float] = {}
    for e in events:
        if e.name.startswith(PHASE_PREFIX):
            if e.name == STEP_PHASE:
                result.step_spans.append((e.ts_us, e.ts_us + e.dur_us))
            else:
                result.phases.setdefault(e.name, []).append(
                    e.dur_us / 1e6)
            continue
        parsed = parse_tag(e.name)
        if parsed is None:
            continue
        lane, marker = parsed
        if marker == "issue":
            # a re-issued lane before its done marker (dropped done —
            # e.g. capture stopped mid-step) abandons the open stamp
            open_issue[lane] = e.ts_us
        elif marker == "done" and lane in open_issue:
            result.lanes.setdefault(lane, []).append(
                (open_issue.pop(lane), e.ts_us))
    if emit:
        from flexflow_tpu.obs.events import BUS

        if BUS.enabled:
            BUS.emit("trace.ingest", path=result.path,
                     events=result.events, lanes=len(result.lanes),
                     steps=len(result.step_spans))
    return result


@dataclass
class LaneDriftReport:
    """Predicted-vs-measured drift per sync lane, from a real capture.

    ``lanes`` rows:
      lane, samples, matched,
      predicted_issue_s / predicted_sync_s / predicted_exposed_s
        (the simulator's bucket lane, seconds from step start),
      measured_issue_s / measured_sync_s
        (mean host-trace offsets/durations over the captured steps),
      predicted_issue_frac / measured_issue_frac and
      predicted_sync_frac / measured_sync_frac
        (each side normalized by ITS OWN step length — the scale-free
        comparison a host-clock capture supports),
      issue_frac_ratio / sync_frac_ratio (measured/predicted fraction;
        None when a side is missing or ~0).
    """

    steps: int
    predicted_total_s: float
    measured_step_s: float
    threshold: float
    lanes: List[dict] = field(default_factory=list)
    unmatched_predicted: List[str] = field(default_factory=list)
    unmatched_trace: List[str] = field(default_factory=list)
    source: str = "host_trace"

    @property
    def matched(self) -> int:
        return sum(1 for r in self.lanes if r.get("matched"))

    @property
    def matched_all(self) -> bool:
        return bool(self.lanes) and not self.unmatched_predicted

    @property
    def stale_lanes(self) -> List[str]:
        """Lanes whose measured step-relative sync share drifted past
        the threshold — the per-lane analogue of DriftReport.stale."""
        out = []
        lo = 1.0 / (1.0 + self.threshold)
        hi = 1.0 + self.threshold
        for r in self.lanes:
            ratio = r.get("sync_frac_ratio")
            if isinstance(ratio, (int, float)) and (
                    ratio > hi or ratio < lo):
                out.append(r["lane"])
        return out

    def to_dict(self) -> dict:
        return {
            "steps": self.steps,
            "predicted_total_s": self.predicted_total_s,
            "measured_step_s": self.measured_step_s,
            "threshold": self.threshold,
            "source": self.source,
            "matched": self.matched,
            "matched_all": self.matched_all,
            "stale_lanes": self.stale_lanes,
            "lanes": self.lanes,
            "unmatched_predicted": self.unmatched_predicted,
            "unmatched_trace": self.unmatched_trace,
        }

    def __str__(self) -> str:
        return (
            f"LaneDriftReport: {self.matched}/{len(self.lanes)} lanes "
            f"tag-matched over {self.steps} step(s)"
            + (f", {len(self.stale_lanes)} drifted" if self.stale_lanes
               else "")
            + (f", unmatched predicted: {self.unmatched_predicted}"
               if self.unmatched_predicted else ""))


def _ratio(meas, pred) -> Optional[float]:
    if (isinstance(meas, (int, float)) and isinstance(pred, (int, float))
            and pred > 1e-12 and math.isfinite(pred)
            and math.isfinite(meas)):
        return meas / pred
    return None


def match_lanes(
    result: IngestResult,
    predicted_breakdown: dict,
    threshold: float = 0.5,
    emit: bool = True,
) -> Optional[LaneDriftReport]:
    """Match the capture's lane markers against the predicted
    ``sync_buckets`` lanes of a ``Simulator.simulate(breakdown=...)``
    dict.  Matching is exact on the shared lane id; each matched lane
    aggregates every (step-window, marker-pair) occurrence.  None when
    the prediction carries no bucket lanes.  Emits one
    ``trace.lane_match`` event per predicted lane when the bus is
    armed."""
    rows = predicted_breakdown.get("sync_buckets") or []
    total = predicted_breakdown.get("total_s")
    if not rows or not isinstance(total, (int, float)) \
            or not math.isfinite(total) or total <= 0:
        return None
    # assign each lane occurrence to the step window containing its
    # issue marker; occurrences outside any window (compile step, the
    # capture's warm-up tail) are dropped rather than skewing the means
    spans = result.step_spans
    if not spans:
        return None
    step_walls = [max(0.0, e - s) / 1e6 for s, e in spans]
    mean_step = sum(step_walls) / len(step_walls)

    def _window(ts_us: float):
        for i, (s, e) in enumerate(spans):
            if s <= ts_us <= e:
                return i
        return None

    report = LaneDriftReport(
        steps=len(spans), predicted_total_s=float(total),
        measured_step_s=mean_step, threshold=threshold)
    seen_pred = set()
    for row in rows:
        lane = row.get("lane") or f"bucket:{row.get('name')}:sync"
        seen_pred.add(lane)
        pred_issue = row.get("start_s")
        pred_sync = row.get("sync_s")
        occ = []
        for issue_us, done_us in result.lanes.get(lane, ()):
            w = _window(issue_us)
            if w is None:
                continue
            occ.append(((issue_us - spans[w][0]) / 1e6,
                        (done_us - issue_us) / 1e6,
                        step_walls[w]))
        matched = bool(occ)
        m_issue = m_sync = m_wall = None
        if matched:
            m_issue = sum(o[0] for o in occ) / len(occ)
            m_sync = sum(o[1] for o in occ) / len(occ)
            m_wall = sum(o[2] for o in occ) / len(occ)
        p_issue_frac = _ratio(pred_issue, total)
        p_sync_frac = _ratio(pred_sync, total)
        m_issue_frac = _ratio(m_issue, m_wall)
        m_sync_frac = _ratio(m_sync, m_wall)
        lane_row = {
            "lane": lane,
            "matched": matched,
            "samples": len(occ),
            "predicted_issue_s": pred_issue,
            "predicted_sync_s": pred_sync,
            "predicted_exposed_s": row.get("exposed_s"),
            "measured_issue_s": m_issue,
            "measured_sync_s": m_sync,
            "predicted_issue_frac": p_issue_frac,
            "measured_issue_frac": m_issue_frac,
            "predicted_sync_frac": p_sync_frac,
            "measured_sync_frac": m_sync_frac,
            "issue_frac_ratio": _ratio(m_issue_frac, p_issue_frac),
            "sync_frac_ratio": _ratio(m_sync_frac, p_sync_frac),
        }
        report.lanes.append(lane_row)
        if not matched:
            report.unmatched_predicted.append(lane)
    report.unmatched_trace = sorted(
        lane for lane in result.lanes if lane not in seen_pred)
    if emit:
        from flexflow_tpu.obs.events import BUS

        if BUS.enabled:
            for r in report.lanes:
                BUS.emit("trace.lane_match", lane=r["lane"],
                         matched=r["matched"], samples=r["samples"],
                         predicted_sync_s=r["predicted_sync_s"],
                         measured_sync_s=r["measured_sync_s"],
                         sync_frac_ratio=r["sync_frac_ratio"])
    return report


def build_lane_drift_report(
    path: str,
    predicted_breakdown: Optional[dict],
    threshold: float = 0.5,
    emit: bool = True,
) -> Optional[LaneDriftReport]:
    """ingest + match in one call: capture logdir/file -> report.
    None when there is no capture, no annotated step window, or no
    predicted bucket lane to match against."""
    if not predicted_breakdown:
        return None
    result = ingest(path, emit=emit)
    if result is None:
        return None
    return match_lanes(result, predicted_breakdown,
                       threshold=threshold, emit=emit)


def apply_lane_measurements(drift_report, lane_report) -> int:
    """Fill the measured side of a ``DriftReport``'s per-bucket rows
    from a matched ``LaneDriftReport`` — the fields the sync-schedule
    PR honestly recorded as ``None`` until a real capture existed.
    Returns the number of rows populated."""
    if drift_report is None or lane_report is None:
        return 0
    by_lane = {r["lane"]: r for r in lane_report.lanes if r["matched"]}
    filled = 0
    for row in getattr(drift_report, "sync_buckets", None) or []:
        lane = row.get("lane") or f"bucket:{row.get('name')}:sync"
        got = by_lane.get(lane)
        if got is None:
            continue
        row["measured_s"] = got["measured_sync_s"]
        row["measured_issue_s"] = got["measured_issue_s"]
        row["measured_source"] = lane_report.source
        filled += 1
    return filled
