"""Request-scoped tracing: Dapper-style span trees for the serving
fleet and the controller's decision episodes.

PR 13 closed the measured loop with SCALAR per-request records (queue/
TTFT/TPOT/e2e numbers in ``executor.request_records``); this module
gives those quantities causal structure.  A **trace id is minted at
enqueue** (``request_root`` — the fleet router's ``route()`` or the
executor's ``submit()``, whichever sees the request first) and child
spans open at every lifecycle edge:

* ``route``   — the router decision (replica tag), zero-duration;
* ``queue``   — enqueue → admission (re-opened on preemption re-queue,
  so a preempted request's timeline partitions into residency windows);
* ``prefill`` — admission → prompt cached (with one ``prefill.chunk``
  child per batched chunk pass, runtime/prefill.py);
* ``decode``  — decode-loop residency (prompt cached → EOS/evict/
  preempt);
* the root ``request`` span closes at eviction/EOS/expiry with the
  outcome.

Controller episodes (re-search, hot swap, refleet, fallback) become
spans too, so a p99-drift → re-search → hot-apply chain reads as ONE
tree in the same export.

The phase children partition the request's lifetime, so their summed
durations reproduce the measured e2e (``validate_trace`` checks
nesting, orphans, and that sum — the well-formedness contract the
bench asserts per request).

Overhead discipline matches the event bus: ``TRACER.enabled`` is a
plain attribute, read ONCE per frame / submit batch by the
instrumented hot paths; disarmed (the default) every edge is a single
boolean check.  Closed spans are kept in a bounded buffer, emitted as
``trace.span`` events when the bus is armed, observed into the
``trace.span_s|span=<name>`` registry histograms, and exported as a
real Chrome-trace/Perfetto JSON (``export_chrome_trace``) viewable
next to the predicted timeline (obs/trace.py) and the device-trace
capture.  ``FLEXFLOW_TPU_TRACE=<path.json>`` arms the tracer at import
and exports the Chrome trace at interpreter exit (``=1`` arms
in-memory only).

Stdlib-only, no jax import (tools must read artifacts without jax).
"""

from __future__ import annotations

import atexit
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

# the phase children that PARTITION a request's lifetime (route and
# prefill.chunk nest inside them; their durations must not be double
# counted by the sum-to-e2e validation)
REQUEST_PHASES = ("queue", "prefill", "decode")
REQUEST_ROOT = "request"
EPISODE_ROOT = "controller.episode"


class Span:
    """One span: closed when ``end_s`` is set, open otherwise."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start_s",
                 "end_s", "attrs")

    def __init__(self, trace_id: str, span_id: int,
                 parent_id: Optional[int], name: str, start_s: float,
                 attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_s = start_s
        self.end_s: Optional[float] = None
        self.attrs = dict(attrs) if attrs else {}

    @property
    def dur_s(self) -> Optional[float]:
        return None if self.end_s is None else self.end_s - self.start_s

    def to_jsonable(self) -> dict:
        out = {"trace_id": self.trace_id, "span_id": self.span_id,
               "parent_id": self.parent_id, "span": self.name,
               "start_s": self.start_s}
        if self.end_s is not None:
            out["end_s"] = self.end_s
            out["dur_s"] = self.end_s - self.start_s
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class Tracer:
    """Process-wide span collector.  ``enabled`` is a plain attribute
    (the one-boolean contract); every mutator below is a no-op-shaped
    cheap call the instrumentation sites guard with ONE read of it."""

    def __init__(self, max_spans: int = 65536):
        self.enabled = False
        self.max_spans = max_spans
        self.spans: List[Span] = []  # closed spans, oldest first
        self.dropped = 0             # closed spans the bound evicted
        self._open: Dict[str, List[Span]] = {}  # trace_id -> open spans
        self._rids: Dict[str, str] = {}         # live rid -> trace_id
        self._mint = 0               # trace counter (ids stay unique
        self._sid = 0                # across runs in one process)
        self._export_path: Optional[str] = None
        self._atexit_armed = False

    # -- arming ---------------------------------------------------------
    def configure(self, export_path: Optional[str] = None,
                  max_spans: Optional[int] = None) -> None:
        """Arm the tracer; ``export_path`` additionally schedules a
        Chrome-trace export at interpreter exit."""
        self.enabled = True
        if max_spans:
            self.max_spans = int(max_spans)
        if export_path:
            self._export_path = export_path
            if not self._atexit_armed:
                atexit.register(self._export_at_exit)
                self._atexit_armed = True

    def close(self) -> None:
        self.enabled = False
        self._export_path = None

    def reset(self) -> None:
        """Drop every span and live-trace mapping (tests)."""
        self.spans = []
        self.dropped = 0
        self._open = {}
        self._rids = {}

    def _export_at_exit(self) -> None:
        if self._export_path and (self.spans or self._open):
            try:
                self.export_chrome_trace(self._export_path)
            except OSError:  # telemetry must never break exit
                pass

    # -- minting + span edges -------------------------------------------
    def request_root(self, rid: str, **attrs) -> str:
        """The request's trace id, minting a fresh trace + open root
        ``request`` span on first sight of ``rid`` (idempotent: the
        fleet router mints at route time, the replica's ``submit`` then
        finds the mapping and only adds children)."""
        tid = self._rids.get(rid)
        if tid is not None:
            return tid
        self._mint += 1
        tid = f"{rid}#{self._mint}"
        self._rids[rid] = tid
        self.begin(tid, REQUEST_ROOT, parent=None, rid=rid, **attrs)
        return tid

    def episode_root(self, **attrs) -> str:
        """Mint a controller-episode trace (root span
        ``controller.episode``) and return its trace id."""
        self._mint += 1
        tid = f"ctl#{self._mint}"
        self.begin(tid, EPISODE_ROOT, parent=None, **attrs)
        return tid

    def trace_of(self, rid: str) -> Optional[str]:
        """The LIVE trace id for ``rid`` (None once its root closed)."""
        return self._rids.get(rid)

    def begin(self, trace_id: str, name: str,
              parent: Optional[str] = None, **attrs) -> Span:
        """Open a child span.  ``parent`` names an OPEN span of the
        same trace (the newest one wins when re-opened names repeat);
        None attaches to the trace's root when one is open."""
        opens = self._open.setdefault(trace_id, [])
        parent_id = None
        want = parent if parent is not None else None
        for sp in reversed(opens):
            if want is None or sp.name == want:
                parent_id = sp.span_id
                break
        self._sid += 1
        span = Span(trace_id, self._sid, parent_id, name,
                    time.perf_counter(), attrs)
        opens.append(span)
        return span

    def end(self, trace_id: str, name: str, **attrs) -> Optional[Span]:
        """Close the newest open span named ``name`` (None when no such
        span is open — callers use that to detect which phase a
        preempted sequence was in)."""
        opens = self._open.get(trace_id)
        if not opens:
            return None
        for i in range(len(opens) - 1, -1, -1):
            if opens[i].name == name:
                span = opens.pop(i)
                self._close(span, attrs)
                return span
        return None

    def end_any(self, trace_id: str, names: Iterable[str],
                **attrs) -> Optional[Span]:
        """Close whichever of ``names`` is open (newest first) — the
        preemption edge, where the victim may be mid-prefill or
        mid-decode."""
        for name in names:
            span = self.end(trace_id, name, **attrs)
            if span is not None:
                return span
        return None

    def annotate(self, trace_id: str, name: str,
                 parent: Optional[str] = None, **attrs) -> Span:
        """A zero-duration span (an instant decision, e.g. the router
        pick) — opened and closed at the same clock read."""
        span = self.begin(trace_id, name, parent=parent, **attrs)
        opens = self._open.get(trace_id)
        if opens and opens[-1] is span:
            opens.pop()
        self._close(span, {})
        span.end_s = span.start_s
        return span

    def finish_trace(self, trace_id: str, **attrs) -> None:
        """Close every still-open span of the trace, the root last
        (root takes ``attrs`` — the request/episode outcome)."""
        opens = self._open.pop(trace_id, None)
        if not opens:
            return
        root = opens[0]
        for span in reversed(opens[1:]):
            self._close(span, {})
        self._close(root, attrs)

    def finish_request(self, rid: str, **attrs) -> None:
        """Close the request's trace and retire the rid mapping (a
        later re-use of the rid mints a FRESH trace)."""
        tid = self._rids.pop(rid, None)
        if tid is not None:
            self.finish_trace(tid, **attrs)

    def _close(self, span: Span, attrs: dict) -> None:
        if span.end_s is None:
            span.end_s = time.perf_counter()
        if attrs:
            span.attrs.update(attrs)
        self.spans.append(span)
        if len(self.spans) > self.max_spans:
            drop = len(self.spans) - self.max_spans
            del self.spans[:drop]
            self.dropped += drop
        # roll up into the registry (exposition serves it live) + the
        # event stream (ffobs trace/report read it offline)
        from flexflow_tpu.obs.events import BUS
        from flexflow_tpu.obs.metrics import METRICS

        dur = span.end_s - span.start_s
        METRICS.histogram(f"trace.span_s|span={span.name}").observe(dur)
        if BUS.enabled:
            BUS.emit("trace.span", trace_id=span.trace_id,
                     span=span.name, span_id=span.span_id,
                     parent_id=span.parent_id, start_s=span.start_s,
                     dur_s=dur, **span.attrs)

    # -- introspection ---------------------------------------------------
    def open_spans(self, trace_id: Optional[str] = None) -> List[Span]:
        if trace_id is not None:
            return list(self._open.get(trace_id, ()))
        return [s for opens in self._open.values() for s in opens]

    def trace_ids(self) -> List[str]:
        seen: List[str] = []
        for s in self.spans:
            if s.trace_id not in seen:
                seen.append(s.trace_id)
        for tid in self._open:
            if tid not in seen:
                seen.append(tid)
        return seen

    def trace_spans(self, trace_id: str) -> List[Span]:
        out = [s for s in self.spans if s.trace_id == trace_id]
        out += self._open.get(trace_id, [])
        return out

    # -- validation ------------------------------------------------------
    def validate_trace(self, trace_id: str,
                       e2e_s: Optional[float] = None,
                       tol: float = 0.25,
                       eps_s: float = 2e-3) -> List[str]:
        """Well-formedness problems of one span tree ([] = valid):
        every non-root parent must exist (no orphans), children must
        nest inside their parent's window, no span may remain open,
        and — when the measured ``e2e_s`` is supplied — the phase
        children's summed durations must reproduce it within ``tol``
        (relative) + ``eps_s`` (absolute clock slack)."""
        problems: List[str] = []
        spans = self.trace_spans(trace_id)
        if not spans:
            return [f"{trace_id}: no spans"]
        by_id = {s.span_id: s for s in spans}
        roots = [s for s in spans if s.parent_id is None]
        if len(roots) != 1:
            problems.append(f"{trace_id}: {len(roots)} root spans")
        for s in spans:
            if s.end_s is None:
                problems.append(f"{trace_id}: span {s.name!r} still open")
            if s.parent_id is None:
                continue
            parent = by_id.get(s.parent_id)
            if parent is None:
                problems.append(
                    f"{trace_id}: ORPHAN span {s.name!r} "
                    f"(parent {s.parent_id} missing)")
                continue
            if s.start_s < parent.start_s - eps_s or (
                    s.end_s is not None and parent.end_s is not None
                    and s.end_s > parent.end_s + eps_s):
                problems.append(
                    f"{trace_id}: span {s.name!r} escapes parent "
                    f"{parent.name!r} window")
        if e2e_s is not None and roots:
            root_id = roots[0].span_id
            phase_sum = sum(
                (s.dur_s or 0.0) for s in spans
                if s.parent_id == root_id and s.name in REQUEST_PHASES)
            if abs(phase_sum - e2e_s) > tol * max(e2e_s, 1e-9) + eps_s:
                problems.append(
                    f"{trace_id}: phase spans sum to {phase_sum:.4f}s "
                    f"vs measured e2e {e2e_s:.4f}s (tol {tol})")
        return problems

    # -- export ----------------------------------------------------------
    def export_chrome_trace(self, path: str) -> int:
        """Write closed + still-open spans as a Chrome Trace Event JSON
        (the format Perfetto loads — same ``ph:"X"``/``ph:"M"`` µs
        shape as the predicted-timeline export, obs/trace.py).  One
        process row; one thread row per trace, named by its trace id.
        Returns the number of span slices written."""
        spans = list(self.spans) + self.open_spans()
        if not spans:
            events: List[dict] = []
            with open(path, "w") as f:
                json.dump({"traceEvents": events,
                           "displayTimeUnit": "ms"}, f)
            return 0
        t0 = min(s.start_s for s in spans)
        now = time.perf_counter()
        # stable thread rows: traces in first-span order
        tids: Dict[str, int] = {}
        for s in sorted(spans, key=lambda s: s.start_s):
            tids.setdefault(s.trace_id, len(tids) + 1)
        events = [{
            "ph": "M", "pid": 1, "tid": 0, "name": "process_name",
            "args": {"name": "flexflow_tpu request traces"},
        }]
        for trace_id, tid in tids.items():
            events.append({
                "ph": "M", "pid": 1, "tid": tid, "name": "thread_name",
                "args": {"name": trace_id},
            })
        n = 0
        for s in sorted(spans, key=lambda s: (tids[s.trace_id],
                                              s.start_s, s.span_id)):
            end = s.end_s if s.end_s is not None else now
            args = {"trace_id": s.trace_id, "span_id": s.span_id,
                    "parent_id": s.parent_id, "open": s.end_s is None}
            args.update(s.attrs)
            events.append({
                "ph": "X", "pid": 1, "tid": tids[s.trace_id],
                "name": s.name,
                "ts": round((s.start_s - t0) * 1e6, 3),
                "dur": max(round((end - s.start_s) * 1e6, 3), 0.001),
                "args": args,
            })
            n += 1
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                      f)
        return n


TRACER = Tracer()

_env = os.environ.get("FLEXFLOW_TPU_TRACE", "")
if _env and _env != "0":
    TRACER.configure(
        export_path=_env if _env not in ("1", "true") else None)
del _env


def span_forest(records: Iterable[dict]) -> Dict[str, List[dict]]:
    """Group decoded ``trace.span``/``trace.open`` event dicts by
    trace id (stdlib helper shared with tools/ffobs.py — JSONL in,
    per-trace span lists out, submission order preserved)."""
    out: Dict[str, List[dict]] = {}
    for e in records:
        if e.get("kind") in ("trace.span", "trace.open"):
            tid = e.get("trace_id")
            if isinstance(tid, str):
                out.setdefault(tid, []).append(e)
    return out


def forest_stats(forest: Dict[str, List[dict]]) -> Tuple[int, int, int]:
    """(total spans, max tree depth, orphan count) over a span forest
    — the ``ffobs report`` "Request traces" roll-up; orphans are a
    validation failure."""
    total = 0
    orphans = 0
    max_depth = 0
    for spans in forest.values():
        total += len(spans)
        by_id = {e.get("span_id"): e for e in spans
                 if e.get("span_id") is not None}

        def depth(e, seen=()) -> int:
            pid = e.get("parent_id")
            if pid is None or e.get("span_id") in seen:
                return 1
            parent = by_id.get(pid)
            if parent is None:
                return 1
            return 1 + depth(parent, seen + (e.get("span_id"),))

        for e in spans:
            pid = e.get("parent_id")
            if pid is not None and pid not in by_id:
                orphans += 1
            max_depth = max(max_depth, depth(e))
    return total, max_depth, orphans
