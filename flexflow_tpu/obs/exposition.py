"""Prometheus text exposition of the metrics registry.

The registry (``obs/metrics.py``) is in-process; operators scrape.
``render_prometheus`` turns a registry snapshot into Prometheus text
format 0.0.4 (counters, gauges, and histogram SUMMARIES — count/sum
plus quantile series, the shape a reservoir-sampled histogram can
honestly export).  ``start_metrics_server`` serves it from a stdlib
``http.server`` daemon thread at ``/metrics``;
``FLEXFLOW_TPU_METRICS_PORT=<port>`` arms it process-wide at import
(``maybe_start_from_env``, called by ``flexflow_tpu.obs``).  Offline,
``tools/ffobs.py metrics`` renders the same text from a
``metrics.snapshot`` event in a JSONL log — no live process needed.

Stdlib-only, no jax import.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional

PREFIX = "flexflow_tpu"
_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

_QUANTILES = ("p50", "p95", "p99")


def _metric_name(name: str) -> str:
    """Dotted registry names -> Prometheus-legal metric names
    (``fit.step_s`` -> ``flexflow_tpu_fit_step_s``)."""
    return f"{PREFIX}_{_NAME_RE.sub('_', name)}"


def _split_labels(name: str):
    """Registry names carry optional inline labels after ``|``
    (``decode.ttft_s|replica=0,slo=interactive`` — the fleet's
    per-replica/per-class series, runtime/decode.py).  Returns
    (base_name, [(key, value), ...]); a malformed suffix stays part of
    the name rather than dropping the series."""
    if "|" not in name:
        return name, []
    base, _, raw = name.partition("|")
    labels = []
    for part in raw.split(","):
        if "=" not in part:
            return name, []
        k, _, v = part.partition("=")
        k = k.strip()
        v = v.strip()
        if not k or not v:
            return name, []
        labels.append((_NAME_RE.sub("_", k), v.replace('"', "'")))
    return base, labels


def _label_block(labels, extra: str = "") -> str:
    """``{k="v",...}`` rendering; ``extra`` is a pre-formatted pair
    (the summary quantile) merged into the same block."""
    pairs = [f'{k}="{v}"' for k, v in labels]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if isinstance(v, float) else str(v)


def render_prometheus(snapshot: Dict[str, dict]) -> str:
    """Prometheus text for a ``MetricsRegistry.snapshot()``-shaped
    dict (also the payload of a ``metrics.snapshot`` JSONL event):
    counters -> ``counter``, gauges -> ``gauge``, histograms ->
    ``summary`` (count/sum exact, quantiles from the seeded
    reservoir)."""
    lines = []
    typed = set()  # one TYPE line per base metric, labeled series share it

    def _type(m: str, kind: str) -> None:
        if (m, kind) not in typed:
            typed.add((m, kind))
            lines.append(f"# TYPE {m} {kind}")

    for name, value in sorted((snapshot.get("counters") or {}).items()):
        base, labels = _split_labels(name)
        m = _metric_name(base)
        _type(m, "counter")
        lines.append(f"{m}{_label_block(labels)} {_fmt(value)}")
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        base, labels = _split_labels(name)
        m = _metric_name(base)
        _type(m, "gauge")
        lines.append(f"{m}{_label_block(labels)} {_fmt(value)}")
    for name, summ in sorted((snapshot.get("histograms") or {}).items()):
        if not isinstance(summ, dict):
            continue
        base, labels = _split_labels(name)
        m = _metric_name(base)
        _type(m, "summary")
        for q in _QUANTILES:
            if q in summ:
                block = _label_block(labels,
                                     extra=f'quantile="0.{q[1:]}"')
                lines.append(f"{m}{block} {_fmt(summ[q])}")
        lab = _label_block(labels)
        lines.append(f"{m}_count{lab} {_fmt(summ.get('count', 0))}")
        if "sum" in summ:
            lines.append(f"{m}_sum{lab} {_fmt(summ['sum'])}")
        for extra in ("min", "max", "mean"):
            if extra in summ:
                lines.append(f"{m}_{extra}{lab} {_fmt(summ[extra])}")
    return "\n".join(lines) + ("\n" if lines else "")


class MetricsServer:
    """Daemon-threaded ``/metrics`` endpoint over the live registry.
    ``port=0`` binds an ephemeral port (tests); ``.port`` reports the
    bound one."""

    def __init__(self, port: int, registry=None, host: str = "127.0.0.1"):
        import http.server

        if registry is None:
            from flexflow_tpu.obs.metrics import METRICS as registry  # noqa: N813

        reg = registry

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = render_prometheus(reg.snapshot()).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *_a):  # scrapes must not spam stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ff-metrics",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


_SERVER: Optional[MetricsServer] = None


def start_metrics_server(port: int, registry=None) -> MetricsServer:
    """Start (or return the already-running) exposition endpoint and
    emit a ``metrics.exposition`` event when the bus is armed."""
    global _SERVER
    if _SERVER is not None:
        return _SERVER
    _SERVER = MetricsServer(port, registry=registry)
    from flexflow_tpu.obs.events import BUS

    if BUS.enabled:
        BUS.emit("metrics.exposition", port=_SERVER.port,
                 host=_SERVER.host)
    return _SERVER


def stop_metrics_server() -> None:
    global _SERVER
    if _SERVER is not None:
        _SERVER.close()
        _SERVER = None


def maybe_start_from_env() -> Optional[MetricsServer]:
    """``FLEXFLOW_TPU_METRICS_PORT=<port>`` arms the endpoint at
    import; unset/0/invalid/unbindable stays silent — telemetry must
    never break imports."""
    raw = os.environ.get("FLEXFLOW_TPU_METRICS_PORT", "")
    try:
        port = int(raw)
    except ValueError:
        return None
    if port <= 0:
        return None
    try:
        return start_metrics_server(port)
    except OSError:
        return None
