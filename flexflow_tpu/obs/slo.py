"""SLO burn-rate signals: multi-window error-budget consumption per
serving class, as an earlier and less noisy controller trigger than
raw p99 drift.

The p99-drift trigger (PR 13) compares a measured tail quantile
against ``drift_threshold`` — it fires only once the tail itself has
moved past 1.5x, and a single straggler can swing a small-window p99.
SRE burn-rate alerting inverts the question: an SLOClass with target
quantile q carries an error budget of ``1 - q`` (the tolerated
violation fraction); the **burn rate** over a window is the observed
violation fraction divided by that budget.  A burn rate of 1.0 spends
budget exactly on schedule; sustained 2x spends it twice as fast.
Firing only when BOTH a fast and a slow completion window burn past a
factor keeps the signal early (the fast window reacts within a few
completions) AND quiet (the slow window vetoes one-off stragglers).

Crucially this fires on episodes p99-drift NEVER sees: a persistent
moderate violation — every request at 1.3x target — keeps p99 below
the 1.5x drift threshold while torching the entire error budget
(violation fraction 1.0 → burn rate 1/budget, e.g. 100x at q=0.99).

Windows are counted in COMPLETIONS, not wall time, matching how
``request_records`` arrive from the executor drain.

Stdlib-only; gauges land in the shared registry as
``slo.burn_rate|slo=<class>,window=fast|slow`` for /metrics.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

DEFAULT_FAST = 8     # completions in the fast window
DEFAULT_SLOW = 32    # completions in the slow window
DEFAULT_FIRE = 2.0   # both windows must burn past this factor
DEFAULT_BUDGET = 0.01  # error budget when the class carries no quantile


def _window_burn(lat: Sequence[float], target_s: float, window: int,
                 budget: float) -> Optional[float]:
    """Burn rate over the trailing ``window`` completions (None until
    the window is full — an empty window must not fire)."""
    if len(lat) < window or window <= 0:
        return None
    tail = lat[-window:]
    violations = sum(1 for v in tail if v > target_s)
    return (violations / window) / max(budget, 1e-9)


def burn_rates(records: Iterable[dict], targets: Dict[str, float], *,
               metric: str = "ttft_s",
               budgets: Optional[Dict[str, float]] = None,
               fast: int = DEFAULT_FAST, slow: int = DEFAULT_SLOW,
               fire: float = DEFAULT_FIRE) -> Dict[str, dict]:
    """Per-class multi-window burn rates over finished-request records
    (the ``executor.request_records`` shape: dicts carrying ``slo``
    and the latency ``metric``).  Returns, per class with a target::

        {"fast": r|None, "slow": r|None, "fired": bool,
         "target_s": t, "budget": b, "completions": n}

    and sets ``slo.burn_rate|slo=<c>,window=fast|slow`` gauges so the
    exposition endpoint serves the signal live.
    """
    budgets = budgets or {}
    by_class: Dict[str, List[float]] = {}
    for rec in records:
        slo = rec.get("slo")
        v = rec.get(metric)
        if slo in targets and isinstance(v, (int, float)):
            by_class.setdefault(slo, []).append(float(v))

    from flexflow_tpu.obs.metrics import METRICS

    out: Dict[str, dict] = {}
    for slo, target_s in targets.items():
        lat = by_class.get(slo, [])
        budget = budgets.get(slo, DEFAULT_BUDGET)
        r_fast = _window_burn(lat, target_s, fast, budget)
        r_slow = _window_burn(lat, target_s, min(slow, max(len(lat),
                                                          fast)),
                              budget) if len(lat) >= fast else None
        fired = (r_fast is not None and r_slow is not None
                 and r_fast > fire and r_slow > fire)
        out[slo] = {"fast": r_fast, "slow": r_slow, "fired": fired,
                    "target_s": target_s, "budget": budget,
                    "completions": len(lat)}
        if r_fast is not None:
            METRICS.gauge(
                f"slo.burn_rate|slo={slo},window=fast").set(r_fast)
        if r_slow is not None:
            METRICS.gauge(
                f"slo.burn_rate|slo={slo},window=slow").set(r_slow)
    return out


def first_fire_indices(latencies: Sequence[float], target_s: float, *,
                       budget: float = DEFAULT_BUDGET,
                       fast: int = DEFAULT_FAST,
                       slow: int = DEFAULT_SLOW,
                       fire: float = DEFAULT_FIRE,
                       drift_threshold: float = 0.5,
                       p99_window: int = 32,
                       quantile: float = 0.99,
                       ) -> Tuple[Optional[int], Optional[int]]:
    """Replay a latency stream and return the completion index (1-based
    count of completions seen) at which (a) the burn-rate trigger and
    (b) the raw p99-drift trigger would first fire — the bench's
    burn-fires-earlier claim.  p99-drift fires when the trailing
    ``p99_window`` quantile exceeds ``target_s * (1 + drift_threshold)``
    (the ``observe_p99`` ratio contract).
    """
    burn_at: Optional[int] = None
    drift_at: Optional[int] = None
    seen: List[float] = []
    for i, v in enumerate(latencies, start=1):
        seen.append(float(v))
        if burn_at is None:
            r_fast = _window_burn(seen, target_s, fast, budget)
            r_slow = _window_burn(
                seen, target_s, min(slow, max(len(seen), fast)),
                budget) if len(seen) >= fast else None
            if (r_fast is not None and r_slow is not None
                    and r_fast > fire and r_slow > fire):
                burn_at = i
        if drift_at is None and len(seen) >= min(p99_window, fast):
            tail = sorted(seen[-p99_window:])
            k = max(int(round(quantile * (len(tail) - 1))), 0)
            p99 = tail[k]
            if target_s > 0 and (p99 / target_s - 1.0) > drift_threshold:
                drift_at = i
        if burn_at is not None and drift_at is not None:
            break
    return burn_at, drift_at
