"""DecodeAttentionOp — single-token decode attention over a paged KV
cache (the serving-side sibling of MultiHeadAttentionOp).

One decode step projects the fresh token's q/k/v, scatters the new
k/v into this layer's page-pool cache (model STATE, threaded through
``ctx.state_in``/``state_out`` like the MoE CacheOp and the EF
residuals), and attends the query against the sequence's RAGGED cache
via ``kernels/ragged_paged_attention``.  Inputs:

* hidden     [B, 1, E]            — the decode frame's token embeddings
* page_table [B, pages_per_seq]   — int32 page ids into the pool
* seq_lens   [B]                  — int32 tokens ALREADY cached per
                                    sequence (the fresh token lands at
                                    position seq_lens[b]; attention
                                    runs over seq_lens[b] + 1 tokens)

B is the decode frame's fixed sequence-slot count (``max_seqs``) —
the continuous-batching executor (runtime/decode.py) composes ragged
requests into frames of exactly this shape so the compiled program
never re-specializes.

Parallelization: batch (slot 0) shards SEQUENCES — each device then
holds only its sequences' cache pages; the replica slot shards HEADS
(classic decode TP: every device holds every sequence's pages but only
H/r heads of them, partial-summing the output projection like MHA).
Both genuinely divide per-device KV residency and KV read traffic —
``kv_cache_bytes``/``sharded_bytes_accessed`` expose exactly that to
the cost model, which is what makes the serving objective's
TP-vs-batch Pareto real instead of asserted.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax.numpy as jnp

from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.core.optype import OperatorType
from flexflow_tpu.core.ptensor import DataType, ParallelTensorShape
from flexflow_tpu.initializers import DEFAULT_WEIGHT_INIT, Initializer
from flexflow_tpu.ops.base import (
    REPLICA_SLOT,
    LoweringContext,
    Operator,
    OpSharding,
    ShardAnnot,
    WeightSpec,
    register_op,
)


def _quantize_kv(x):
    """Per-token symmetric int8 quantization of fresh K or V rows:
    x [..., H, D] fp32 -> (int8 payload, fp32 scale over the trailing
    (H, D) axes).  One scale per token (the pool's per-(page, slot)
    "page_slot" layout) — amax/127 symmetric, the EQuARX-style scheme
    whose drift bound the accuracy-contract test asserts."""
    amax = jnp.max(jnp.abs(x), axis=(-2, -1))
    s = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(x / s[..., None, None]),
                 -127, 127).astype(jnp.int8)
    return q, s.astype(jnp.float32)


@register_op
class DecodeAttentionOp(Operator):
    """hidden [B, 1, E], page_table [B, pages_per_seq] i32,
    seq_lens [B] i32 -> [B, 1, E].

    attrs: embed_dim, num_heads, page_size, pages_per_seq, num_pages
    (pool size; default max_seqs * pages_per_seq), use_kernel (take the
    Pallas ragged-paged path when shapes allow), kv_dtype (POOL dtype
    of the cache — "fp32"/"bf16"/"int8", the searched KV-precision
    lane; present in ``attrs`` ONLY when not "fp32", so the default
    pool adds no attr and signatures/digests/cost-cache keys stay
    byte-identical to the pre-precision tree).
    """

    op_type = OperatorType.DECODE_ATTENTION
    # the op reads + writes its KV cache through the model-state dict:
    # impure, never remat-wrapped
    writes_state = True
    # use_kernel selects the execution path, not the math — one probe
    # record serves both
    _CALIBRATION_INERT_ATTRS = frozenset({"use_kernel"})

    def __init__(
        self,
        name,
        input_shapes,
        embed_dim: int,
        num_heads: int,
        page_size: int = 16,
        pages_per_seq: int = 8,
        num_pages: int = 0,
        use_kernel: bool = True,
        kv_dtype: str = "fp32",
        kernel_initializer: Initializer | None = None,
    ):
        assert embed_dim % num_heads == 0
        assert page_size >= 1 and pages_per_seq >= 1
        assert kv_dtype in ("fp32", "bf16", "int8"), kv_dtype
        b = input_shapes[0].sizes[0]
        num_pages = num_pages or b * pages_per_seq
        assert num_pages >= b, (
            f"page pool ({num_pages}) smaller than the decode frame's "
            f"sequence slots ({b})")
        self._kernel_init = kernel_initializer or DEFAULT_WEIGHT_INIT
        # extension-only attr discipline (like ServingSpec.signature's
        # occupancy part): the default fp32 pool contributes NO attr
        extra = {} if kv_dtype == "fp32" else {"kv_dtype": kv_dtype}
        super().__init__(
            name,
            input_shapes,
            embed_dim=embed_dim,
            num_heads=num_heads,
            page_size=page_size,
            pages_per_seq=pages_per_seq,
            num_pages=num_pages,
            use_kernel=use_kernel,
            **extra,
        )

    # ---- shapes ----------------------------------------------------------
    def infer(self) -> Sequence[ParallelTensorShape]:
        h = self.input_shapes[0]
        assert h.ndim == 3 and h.sizes[1] == 1, (
            f"decode attention wants [B, 1, E] hidden, got {h.sizes}")
        pt = self.input_shapes[1]
        assert pt.ndim == 2 and pt.sizes[0] == h.sizes[0], pt.sizes
        assert pt.sizes[1] == self.attrs["pages_per_seq"], pt.sizes
        sl = self.input_shapes[2]
        assert sl.ndim == 1 and sl.sizes[0] == h.sizes[0], sl.sizes
        return (
            ParallelTensorShape.make(
                (h.sizes[0], 1, self.attrs["embed_dim"]), h.dtype),
        )

    @property
    def head_dim(self) -> int:
        return self.attrs["embed_dim"] // self.attrs["num_heads"]

    @property
    def max_seqs(self) -> int:
        return self.input_shapes[0].sizes[0]

    @property
    def max_seq_len(self) -> int:
        return self.attrs["page_size"] * self.attrs["pages_per_seq"]

    @property
    def kv_dtype(self) -> str:
        return self.attrs.get("kv_dtype", "fp32")

    def weight_specs(self) -> Sequence[WeightSpec]:
        a = self.attrs
        e, h = a["embed_dim"], a["num_heads"]
        dk = self.head_dim
        qe = self.input_shapes[0].sizes[-1]
        return [
            WeightSpec("wq", (qe, h, dk), DataType.FLOAT32, self._kernel_init),
            WeightSpec("wk", (qe, h, dk), DataType.FLOAT32, self._kernel_init),
            WeightSpec("wv", (qe, h, dk), DataType.FLOAT32, self._kernel_init),
            WeightSpec("wo", (h, dk, e), DataType.FLOAT32, self._kernel_init),
        ]

    # ---- state (the paged KV cache) -------------------------------------
    def state_specs(self):
        """The layer's page-pool cache, in the POOL dtype: fp32 by
        default (decode numerics match the training-side attention's
        accumulate dtype); bf16/int8 under the searched KV-precision
        lane, an int8 pool carrying per-(page, slot) fp32 scales —
        the "page_slot" layout, one symmetric scale per cached token
        shared across heads, so scattering a fresh token never
        rescales already-written slots."""
        a = self.attrs
        shape = (a["num_pages"], a["page_size"], a["num_heads"],
                 self.head_dim)
        kvd = self.kv_dtype
        if kvd == "bf16":
            return [
                ("k_cache", shape, jnp.bfloat16, 0.0),
                ("v_cache", shape, jnp.bfloat16, 0.0),
            ]
        if kvd == "int8":
            sshape = (a["num_pages"], a["page_size"])
            return [
                ("k_cache", shape, jnp.int8, 0),
                ("v_cache", shape, jnp.int8, 0),
                ("k_scale", sshape, jnp.float32, 0.0),
                ("v_scale", sshape, jnp.float32, 0.0),
            ]
        return [
            ("k_cache", shape, jnp.float32, 0.0),
            ("v_cache", shape, jnp.float32, 0.0),
        ]

    def state_shardings(self, mv: MachineView):
        """ShardAnnot per state var under ``mv`` — the lowering places
        the page pool with it (compiler/lowering.py init_params), so
        the residency ``kv_cache_bytes`` credits is residency the
        compiled program realizes: page dim over the batch axes (each
        device holds its own sequences' pages), head dim over the
        replica axes (decode TP)."""
        b = max(mv.dim_degrees[0], 1) if mv.dim_degrees else 1
        r = max(mv.replica_degree, 1)
        annot = ShardAnnot((b, 1, r, 1), idx=(0, -1, REPLICA_SLOT, -1))
        out = {"k_cache": annot, "v_cache": annot}
        if self.kv_dtype == "int8":
            # the scales shard with the page dim but REPLICATE over the
            # head split — every replica's heads share the per-token
            # scale row
            s_annot = ShardAnnot((b, 1), replica=r, idx=(0, -1))
            out["k_scale"] = s_annot
            out["v_scale"] = s_annot
        return out

    # ---- lowering --------------------------------------------------------
    def forward(self, ctx: LoweringContext, inputs, weights):
        from flexflow_tpu.kernels.ragged_paged_attention import (
            _xla_ragged_paged,
            _xla_ragged_paged_quant,
            ragged_paged_attention,
            ragged_paged_attention_quant,
        )

        a = self.attrs
        hidden, page_table, seq_lens = inputs
        page_table = page_table.astype(jnp.int32)
        seq_lens = seq_lens.astype(jnp.int32)
        cd = ctx.compute_dtype
        x = hidden[:, 0, :].astype(cd)  # [B, E]
        wq, wk, wv, wo = (weights[n].astype(cd)
                          for n in ("wq", "wk", "wv", "wo"))
        q = jnp.einsum("be,ehd->bhd", x, wq)
        k_new = jnp.einsum("be,ehd->bhd", x, wk).astype(jnp.float32)
        v_new = jnp.einsum("be,ehd->bhd", x, wv).astype(jnp.float32)

        ps = a["page_size"]
        k_cache = ctx.state_in[f"{self.name}/k_cache"]
        v_cache = ctx.state_in[f"{self.name}/v_cache"]
        # scatter the fresh token at position seq_lens[b]: pool page
        # page_table[b, seq_lens[b] // ps], slot seq_lens[b] % ps.
        # EVERY frame row scatters (rows cannot be excluded from a
        # static-shape scatter) — the executor's frame-composition
        # contract is that a row it wants IGNORED points at a page no
        # live sequence owns (runtime/decode.py: an idle slot's own
        # static range, or the reserved scratch page of an
        # oversubscribed pool), so the stray write lands in garbage no
        # one reads.
        slot = seq_lens % ps
        # a full sequence (seq_lens == max_seq_len) must be evicted by
        # the executor before it is stepped again; clamp keeps the
        # gather in-bounds rather than trusting jax's silent clamping
        page_idx = jnp.minimum(seq_lens // ps, self.attrs["pages_per_seq"] - 1)
        page = jnp.take_along_axis(
            page_table, page_idx[:, None], axis=1)[:, 0]
        kvd = self.kv_dtype
        if kvd == "int8":
            # quantize-on-scatter: the fresh token's fp32 rows collapse
            # to int8 + one per-token scale; the pool never holds fp32
            k_q, k_s = _quantize_kv(k_new)
            v_q, v_s = _quantize_kv(v_new)
            k_scale = ctx.state_in[f"{self.name}/k_scale"]
            v_scale = ctx.state_in[f"{self.name}/v_scale"]
            k_cache = k_cache.at[page, slot].set(k_q)
            v_cache = v_cache.at[page, slot].set(v_q)
            k_scale = k_scale.at[page, slot].set(k_s)
            v_scale = v_scale.at[page, slot].set(v_s)
            ctx.state_out[f"{self.name}/k_scale"] = k_scale
            ctx.state_out[f"{self.name}/v_scale"] = v_scale
        else:
            # bf16 stores the cast; fp32 stores the rows UNCHANGED —
            # the historical (bit-identical, test-enforced) path
            k_cache = k_cache.at[page, slot].set(
                k_new.astype(k_cache.dtype))
            v_cache = v_cache.at[page, slot].set(
                v_new.astype(v_cache.dtype))
        ctx.state_out[f"{self.name}/k_cache"] = k_cache
        ctx.state_out[f"{self.name}/v_cache"] = v_cache

        scale = 1.0 / math.sqrt(self.head_dim)
        lens = seq_lens + 1  # the fresh token attends to itself too
        qf = q.astype(jnp.float32)
        if kvd == "int8":
            if a["use_kernel"]:
                out = ragged_paged_attention_quant(
                    qf, k_cache, v_cache, k_scale, v_scale,
                    page_table, lens, scale)
            else:
                out = _xla_ragged_paged_quant(
                    qf, k_cache, v_cache, k_scale, v_scale,
                    page_table, lens, scale)
        elif a["use_kernel"]:
            out = ragged_paged_attention(
                qf, k_cache, v_cache, page_table, lens, scale)
        else:
            out = _xla_ragged_paged(
                qf, k_cache, v_cache, page_table, lens, scale)
        y = jnp.einsum("bhd,hde->be", out.astype(cd), wo,
                       preferred_element_type=jnp.float32)
        return [y[:, None, :].astype(hidden.dtype)]

    # ---- chunked prefill lowering ---------------------------------------
    def forward_chunk(self, ctx: LoweringContext, inputs, weights):
        """The CHUNKED-PREFILL twin of ``forward``: C prompt tokens per
        sequence in ONE pass instead of one decode frame each.  Inputs:

        * hidden    [B, C, E] — the chunk's token embeddings
        * page_table [B, pages_per_seq]
        * positions [B, C] int32 — each token's absolute cache position
          (the caller clamps pad positions into the sequence's own
          allotment; a pad write is overwritten by the decode loop
          before any frame reads it, so no masking is needed)

        Scatters all C tokens' K/V into the page pool and attends each
        query against cache prefix + intra-chunk causal — the same
        dtype discipline as ``forward`` (projections in the compute
        dtype, cache and softmax in fp32), so the populated cache is
        numerically the one the token-by-token path writes
        (runtime/prefill.py proves token identity end-to-end)."""
        import jax

        from flexflow_tpu.kernels.ragged_paged_attention import (
            NEG_INF,
            gather_kv_pages,
            gather_kv_pages_quant,
        )

        a = self.attrs
        hidden, page_table, positions = inputs
        page_table = page_table.astype(jnp.int32)
        positions = positions.astype(jnp.int32)
        cd = ctx.compute_dtype
        x = hidden.astype(cd)  # [B, C, E]
        wq, wk, wv, wo = (weights[n].astype(cd)
                          for n in ("wq", "wk", "wv", "wo"))
        q = jnp.einsum("bce,ehd->bchd", x, wq)
        k_new = jnp.einsum("bce,ehd->bchd", x, wk).astype(jnp.float32)
        v_new = jnp.einsum("bce,ehd->bchd", x, wv).astype(jnp.float32)

        ps = a["page_size"]
        k_cache = ctx.state_in[f"{self.name}/k_cache"]
        v_cache = ctx.state_in[f"{self.name}/v_cache"]
        slot = positions % ps  # [B, C]
        page_idx = jnp.minimum(positions // ps, a["pages_per_seq"] - 1)
        page = jnp.take_along_axis(page_table, page_idx, axis=1)  # [B, C]
        kvd = self.kv_dtype
        if kvd == "int8":
            # batched quantize-on-scatter, same per-token scheme as the
            # decode step — the chunked path populates the SAME pool
            k_q, k_s = _quantize_kv(k_new)  # [B, C, H, D] / [B, C]
            v_q, v_s = _quantize_kv(v_new)
            k_scale = ctx.state_in[f"{self.name}/k_scale"]
            v_scale = ctx.state_in[f"{self.name}/v_scale"]
            k_cache = k_cache.at[page, slot].set(k_q)
            v_cache = v_cache.at[page, slot].set(v_q)
            k_scale = k_scale.at[page, slot].set(k_s)
            v_scale = v_scale.at[page, slot].set(v_s)
            ctx.state_out[f"{self.name}/k_scale"] = k_scale
            ctx.state_out[f"{self.name}/v_scale"] = v_scale
        else:
            k_cache = k_cache.at[page, slot].set(
                k_new.astype(k_cache.dtype))
            v_cache = v_cache.at[page, slot].set(
                v_new.astype(v_cache.dtype))
        ctx.state_out[f"{self.name}/k_cache"] = k_cache
        ctx.state_out[f"{self.name}/v_cache"] = v_cache

        # each chunk query attends to every cached position <= its own:
        # the prefix written by earlier chunks plus the intra-chunk
        # causal triangle (this chunk's K/V are already in the pool)
        scale = 1.0 / math.sqrt(self.head_dim)
        if kvd == "int8":
            k_dense = gather_kv_pages_quant(k_cache, k_scale,
                                            page_table)  # [B, S, H, D]
            v_dense = gather_kv_pages_quant(v_cache, v_scale, page_table)
        else:
            k_dense = gather_kv_pages(k_cache, page_table)  # [B, S, H, D]
            v_dense = gather_kv_pages(v_cache, page_table)
        qf = q.astype(jnp.float32)
        s = jnp.einsum("bchd,bshd->bchs", qf, k_dense) * scale
        pos_k = jnp.arange(k_dense.shape[1], dtype=jnp.int32)
        mask = pos_k[None, None, :] <= positions[:, :, None]  # [B, C, S]
        s = jnp.where(mask[:, :, None, :], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bchs,bshd->bchd", p, v_dense)
        y = jnp.einsum("bchd,hde->bce", out.astype(cd), wo,
                       preferred_element_type=jnp.float32)
        return [y.astype(hidden.dtype)]

    # ---- degree propagation ---------------------------------------------
    def propagate(self, mv: MachineView) -> OpSharding:
        b, s, e_deg = mv.dim_degrees
        assert s == 1, "decode token dim is length 1 — unsplittable"
        assert e_deg == 1, "embed dim of attention output stays whole"
        assert self.max_seqs % max(b, 1) == 0, (
            "sequence slots must divide evenly over the batch degree")
        r = mv.replica_degree  # head split -> partial sums over wo
        h_annot = ShardAnnot((b, 1, 1), replica=r)
        pt_annot = ShardAnnot((b, 1), replica=r)
        sl_annot = ShardAnnot((b,), replica=r)
        out = ShardAnnot(mv.dim_degrees, replica=r, partial=r > 1)
        R = REPLICA_SLOT
        head_w = ShardAnnot((1, r, 1), replica=b, idx=(-1, R, -1))
        ws = (
            head_w, head_w, head_w,
            ShardAnnot((r, 1, 1), replica=b, idx=(R, -1, -1)),  # wo
        )
        return OpSharding(inputs=(h_annot, pt_annot, sl_annot),
                          weights=ws, outputs=(out,))

    def splittable_output_dims(self) -> Tuple[int, ...]:
        return (0,)  # sequence slots; the token dim is length 1

    def max_replica_degree(self) -> int:
        return self.attrs["num_heads"]

    # ---- cost hooks ------------------------------------------------------
    def flops(self) -> float:
        a = self.attrs
        bsz = self.max_seqs
        e, h, dk = a["embed_dim"], a["num_heads"], self.head_dim
        proj = 2.0 * bsz * e * h * dk * 4  # q, k, v, o projections
        attn = 2.0 * bsz * h * self.max_seq_len * dk * 2
        return proj + attn

    # KV quantize-overhead pricing (the EQuARX discipline the cost
    # model's wire-precision terms follow, machine_model.QUANT_PASSES):
    # writing a quantized token costs streaming passes over the
    # per-step fp32 token buffer (read the projections, round, write
    # payload + scales).  The READ side's dequant runs in-register on
    # bytes already streamed — its price IS the smaller stream, so no
    # extra read pass is charged.
    KV_QUANT_PASSES = 3.0

    def _kv_payload_bytes_per_token(self) -> float:
        """K + V PAYLOAD bytes per cached token in the pool dtype
        (scales excluded — they shard differently)."""
        itemsize = {"fp32": 4.0, "bf16": 2.0, "int8": 1.0}[self.kv_dtype]
        return 2.0 * self.attrs["num_heads"] * self.head_dim * itemsize

    def _kv_scale_bytes_per_token(self) -> float:
        """The int8 pool's per-(page, slot) fp32 k/v scales: 8 bytes
        per cached token, replicated over a head split."""
        return 8.0 if self.kv_dtype == "int8" else 0.0

    def kv_bytes_per_token(self) -> float:
        """K + V bytes one cached token occupies across all heads, in
        the POOL dtype (int8 includes its two fp32 scales)."""
        return (self._kv_payload_bytes_per_token()
                + self._kv_scale_bytes_per_token())

    def kv_cache_bytes(self, mv: MachineView, serving=None) -> float:
        """Per-device resident bytes of this layer's page pool under
        ``mv`` — the KV-residency term of the simulator's HBM check.
        Batch degree shards sequences (each device holds its sequences'
        pages — realized by the executor's slot-aligned allocation),
        the replica degree shards heads; both divide the payload, while
        an int8 pool's scales divide only by batch (each replica needs
        every token's scale).  When the serving arrival model declares
        an expected shared prefix (``ServingSpec.shared_prefix_pages``
        — realized by the executor's radix prefix sharing), residency
        is the SHARED total: the common-prefix pages exist once, not
        once per sequence."""
        tokens = self.attrs["num_pages"] * self.attrs["page_size"]
        b = max(mv.dim_degrees[0], 1) if mv.dim_degrees else 1
        r = max(mv.replica_degree, 1)
        per_dev = (tokens * self._kv_payload_bytes_per_token() / (b * r)
                   + tokens * self._kv_scale_bytes_per_token() / b)
        if serving is not None:
            factor = getattr(serving, "shared_residency_factor", None)
            if factor is not None:
                per_dev *= factor()
        return per_dev

    def bytes_accessed(self) -> float:
        # activations + weights + the full-occupancy cache read (the
        # decode-dominant term: attention streams every live KV byte)
        base = super().bytes_accessed()
        return base + (self.max_seqs * self.max_seq_len
                       * self.kv_bytes_per_token())

    def sharded_bytes_accessed(self, mv: MachineView,
                               serving=None) -> float:
        """Per-shard bytes under ``mv`` — the decode op's replacement
        for the cost model's uniform ``bytes_accessed() / parts`` rule:
        a head split divides the KV stream like a batch split does (each
        device reads only its own heads' columns), and under a serving
        arrival model the cache read scales with the RAGGED p99 shard
        load instead of full occupancy (search/serving.py
        ``load_factor`` — the currency the serve objective ranks in)."""
        b = max(mv.dim_degrees[0], 1) if mv.dim_degrees else 1
        r = max(mv.replica_degree, 1)
        # activations shard with the sequence slots; the projection
        # weights shard with the HEADS (a batch split replicates them —
        # every device streams the full wq..wo, the head split's real
        # second win beside the balanced cache read)
        act = sum(s.num_bytes for s in self.input_shapes)
        act += sum(s.num_bytes for s in self.output_shapes)
        wbytes = 0.0
        for ws in self._weight_specs:
            n = 1
            for d in ws.shape:
                n *= d
            wbytes += n * ws.dtype.itemsize
        live = self.max_seqs * self.max_seq_len
        # attention streams each sequence's OWN pages (a prefix shared
        # in residency is still read once per attending sequence), so
        # the stream term never takes the shared-residency discount —
        # the pool DTYPE is what shrinks it
        kv = live * self._kv_payload_bytes_per_token() / (b * r)
        # each replica streams every one of its sequences' scales
        kv += live * self._kv_scale_bytes_per_token() / b
        if serving is not None:
            kv *= serving.load_factor(b)
        quant = 0.0
        if self.kv_dtype != "fp32":
            # quantize overhead on the write path (KV_QUANT_PASSES,
            # class comment): per step each slot collapses one fp32
            # K + V token to the pool dtype
            tok_fp32 = (self.max_seqs * 2.0 * self.attrs["num_heads"]
                        * self.head_dim * 4.0)
            quant = self.KV_QUANT_PASSES * tok_fp32 / (b * r)
        return act / b + wbytes / r + kv + quant
