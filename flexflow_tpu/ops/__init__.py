"""Operator library: every dense op of the reference's src/ops/* with
TPU-native lowering (see ops.base for the contract)."""

from flexflow_tpu.ops.base import (
    LoweringContext,
    Operator,
    OpSharding,
    OP_REGISTRY,
    ShardAnnot,
    WeightSpec,
    register_op,
)
from flexflow_tpu.ops.inout import ConstantOp, InputOp, NoOp
from flexflow_tpu.ops.elementwise import ElementBinaryOp, ElementUnaryOp
from flexflow_tpu.ops.linear import LinearOp
from flexflow_tpu.ops.shape_ops import (
    CastOp,
    ConcatOp,
    FlatOp,
    ReshapeOp,
    ReverseOp,
    SplitOp,
    TransposeOp,
)
from flexflow_tpu.ops.norm import BatchNormOp, DropoutOp, LayerNormOp, SoftmaxOp
from flexflow_tpu.ops.conv import Conv2DOp, Pool2DOp
from flexflow_tpu.ops.embedding import EmbeddingOp
from flexflow_tpu.ops.attention import BatchMatmulOp, MultiHeadAttentionOp
from flexflow_tpu.ops.decode_attention import DecodeAttentionOp
from flexflow_tpu.ops.reductions import GatherOp, MeanOp, TopKOp
from flexflow_tpu.ops.moe import AggregateOp, AggregateSpecOp, CacheOp, GroupByOp

__all__ = [
    "LoweringContext",
    "Operator",
    "OpSharding",
    "OP_REGISTRY",
    "ShardAnnot",
    "WeightSpec",
    "register_op",
    "ConstantOp",
    "InputOp",
    "NoOp",
    "ElementBinaryOp",
    "ElementUnaryOp",
    "LinearOp",
    "CastOp",
    "ConcatOp",
    "FlatOp",
    "ReshapeOp",
    "ReverseOp",
    "SplitOp",
    "TransposeOp",
    "BatchNormOp",
    "DropoutOp",
    "LayerNormOp",
    "SoftmaxOp",
    "Conv2DOp",
    "Pool2DOp",
    "EmbeddingOp",
    "BatchMatmulOp",
    "DecodeAttentionOp",
    "MultiHeadAttentionOp",
    "GatherOp",
    "MeanOp",
    "TopKOp",
    "AggregateOp",
    "AggregateSpecOp",
    "CacheOp",
    "GroupByOp",
]
