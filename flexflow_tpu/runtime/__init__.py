from flexflow_tpu.runtime.dataloader import SingleDataLoader
from flexflow_tpu.runtime.decode import (
    ContinuousBatchingExecutor,
    DecodeRequest,
    PageAllocator,
    compiled_decode_step,
)

__all__ = [
    "SingleDataLoader",
    "ContinuousBatchingExecutor",
    "DecodeRequest",
    "PageAllocator",
    "compiled_decode_step",
]
