from flexflow_tpu.runtime.controller import TrainingController, shrink_config
from flexflow_tpu.runtime.dataloader import SingleDataLoader
from flexflow_tpu.runtime.faults import (
    Fault,
    FaultPlan,
    TransientCollectiveError,
)
from flexflow_tpu.runtime.decode import (
    ContinuousBatchingExecutor,
    DecodeRequest,
    PageAllocator,
    compiled_decode_step,
)
from flexflow_tpu.runtime.fleet import FleetExecutor

__all__ = [
    "Fault",
    "FaultPlan",
    "SingleDataLoader",
    "TrainingController",
    "TransientCollectiveError",
    "shrink_config",
    "ContinuousBatchingExecutor",
    "DecodeRequest",
    "FleetExecutor",
    "PageAllocator",
    "compiled_decode_step",
]
