"""Always-on training controller — the loop that KEEPS the best
parallelization instead of finding it once.

Every mechanism it composes already exists in this tree: per-phase
DriftReports with auto re-probe (obs/drift.py, the driver's re-probe
policy), a warm re-search served from the persistent caches
(search/driver.py), legality gates on every served strategy
(flexflow_tpu/analysis), and a checkpoint format that re-applies
shardings on restore (runtime/checkpoint.py).  The controller closes
the loop:

* **drift → live re-search → hot swap**: it watches the calibration
  signature (content digest of the persisted CalibrationTable) and the
  measured-vs-predicted step drift per fit phase; when re-probing —
  or an injected drift — rotates the signature, it re-searches for the
  current cost surface and hot-swaps the strategy BETWEEN steps via
  ``FFModel.swap_strategy`` (in-memory checkpoint, value-identity fp32
  re-shard, swap-legality gate SHD170-172).
* **elastic meshes**: on device loss (preemption; simulated by the
  fault harness via a shrunken ``force_cpu_devices`` mesh slice) it
  rebuilds the FFConfig for the surviving device set, re-searches, and
  re-homes the full training state — per-group ZeRO shards and KV page
  pools included — onto the shrunken mesh, resuming from the last
  completed step.
* **transient faults**: collective failures retry with bounded
  backoff; a fault that outlives the retry budget (or a searched comm
  plan that fails its legality lint post-swap) degrades gracefully to
  the monolithic fp32 sync path instead of killing the run.
* **torn checkpoints**: a corrupted ``step_N`` triggers a restore
  drill that falls back to the newest COMPLETE snapshot and replays
  deterministically (the rng counter rides the checkpoint).

Faults come from a seeded ``runtime.faults.FaultPlan`` (or the
``FLEXFLOW_TPU_FAULTS`` env var), so every recovery path is
reproducible bit-for-bit under a fixed fault seed.
"""

from __future__ import annotations

import math
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.obs.events import BUS
from flexflow_tpu.obs.flight import FLIGHT
from flexflow_tpu.obs.tracing import TRACER
from flexflow_tpu.runtime.faults import (
    FaultPlan,
    TransientCollectiveError,
)


def shrink_config(config, num_devices: int):
    """An FFConfig for the surviving device set: same knobs, the
    machine model re-sized without changing WHAT machine it describes.
    The platform field especially must survive — calibration coherence
    (driver.coherent_calibration) keys on it, and a recovered run that
    silently flipped from a host_cpu model to the tpu_v5e default
    would lose its calibration and mis-price every strategy."""
    import dataclasses

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.core.machine import MachineSpec

    kw = {f.name: getattr(config, f.name)
          for f in dataclasses.fields(FFConfig)}
    kw["num_devices"] = num_devices
    kw["search_num_devices"] = 0
    spec = config.machine_spec
    if spec is None or spec == MachineSpec.tpu_v5e(config.num_devices):
        kw["machine_spec"] = None  # the default family: re-derive
    elif spec == MachineSpec.host_cpu(config.num_devices):
        # the CPU-host model's constants SCALE with the device count
        # (virtual devices serialize through the host) — rebuild, don't
        # resize
        kw["machine_spec"] = MachineSpec.host_cpu(num_devices)
    else:
        # machine-file or hand-built spec: keep its link/FLOP constants
        # and platform, shrink the count; the physical torus no longer
        # describes the surviving set, so let it re-derive
        kw["machine_spec"] = dataclasses.replace(
            spec, num_devices=num_devices, ici_torus=())
    return FFConfig(**kw)


class TrainingController:
    """Drive a compiled FFModel's training steps under the always-on
    policy above.

    >>> ctl = TrainingController(model, faults=plan,
    ...                          checkpoint_dir="/ckpt")
    >>> out = ctl.run(x, y, steps=20)
    >>> out["history"][-1]["loss"], ctl.stats["swaps"]
    """

    def __init__(self, model, faults: Optional[FaultPlan] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 0, max_retries: int = 2,
                 backoff_s: float = 0.0, drift_check_every: int = 1,
                 drift_window: int = 4, verbose: bool = False):
        import jax

        assert model.compiled is not None, "compile() the model first"
        if jax.process_count() > 1:
            raise NotImplementedError(
                "TrainingController is single-process (multihost elastic "
                "recovery needs a coordinated restart protocol)")
        self.model = model
        self.faults = faults if faults is not None else FaultPlan.from_env()
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.drift_check_every = max(1, drift_check_every)
        self.drift_window = max(2, drift_window)
        self.verbose = verbose
        self.stats: Dict[str, object] = {
            "steps": 0, "swaps": 0, "recoveries": 0, "retries": 0,
            "fallbacks": 0, "restores": 0,
            "swap_seconds": [], "research_seconds": [],
            "research_warm": [], "research_detail": [],
        }
        self.history: List[dict] = []
        self._step_times: List[float] = []
        self._armed_collective = None
        # measured-drift triggers from the OBSERVED side of the loop:
        # a serving p99 feed past threshold (observe_p99) or a
        # device-trace lane report with drifted lanes
        # (observe_lane_drift / model.lane_drift_report) — consumed at
        # the next step boundary as first-class re-search triggers
        # next to the calibration-signature watch
        self._p99_trigger: Optional[float] = None
        self._fleet_trigger: Optional[float] = None
        # SLO burn-rate trigger (observe_burn_rate): fires on error-
        # budget consumption BEFORE the tail itself crosses the drift
        # threshold — the earlier, less noisy leg of the serving watch
        self._burn_trigger: Optional[str] = None
        self._lane_trigger: Optional[str] = None
        self._lane_seen = None
        self._ckpt_mgr = None
        if checkpoint_dir is not None:
            from flexflow_tpu.runtime.checkpoint import CheckpointManager

            self._ckpt_mgr = CheckpointManager(checkpoint_dir)

    # -- calibration-signature watch ------------------------------------
    def _live_cal_state(self) -> Tuple[Optional[str], bool]:
        """(content digest, stale flag) of the persisted calibration
        table — the signature whose rotation triggers the live
        re-search.  (None, False) when no table is configured.  The
        check runs every ``drift_check_every`` steps, so an unchanged
        file (the overwhelmingly common case) is answered from an
        os.stat fast-path instead of re-parsing + re-hashing the whole
        table in the step hot loop."""
        path = self.model.config.calibration_file
        if not path or not os.path.exists(path):
            return None, False
        st = os.stat(path)
        stat_sig = (st.st_mtime_ns, st.st_size)
        cached = getattr(self, "_cal_stat_cache", None)
        if cached is not None and cached[0] == stat_sig:
            return cached[1]
        try:
            from flexflow_tpu.search.calibration import CalibrationTable
            from flexflow_tpu.search.cost_cache import calibration_digest

            table = CalibrationTable.load(path)
        except (OSError, ValueError, KeyError, TypeError):
            # malformed rows (hand edit, partial write by another tool)
            # must not kill the training hot loop — same robustness
            # contract as fflint's stdlib mirror of this parse
            return None, False
        state = (calibration_digest(table), bool(table.stale))
        self._cal_stat_cache = (stat_sig, state)
        return state

    # -- measured-drift feeds (serving p99 + device-trace lanes) ---------
    def observe_p99(self, measured_s: float,
                    predicted_s: Optional[float] = None,
                    step: Optional[int] = None) -> Optional[float]:
        """Feed a measured serving p99 (e.g.
        ``ContinuousBatchingExecutor.measured_p99(window)``) against
        the searched prediction.  Emits ``controller.p99_drift``;
        drifted past the model's drift threshold, the next step
        boundary re-searches with trigger ``"p99_drift"``.  Returns
        the measured/predicted ratio (None when either side is
        missing)."""
        pred = predicted_s
        if pred is None:
            pred = (getattr(self.model, "predicted_breakdown", None)
                    or {}).get("total_s")
        if (not pred or not math.isfinite(pred) or not measured_s
                or not math.isfinite(measured_s)):
            return None
        ratio = measured_s / pred
        thr = self.model.config.drift_threshold
        drifted = ratio > 1.0 + thr or ratio < 1.0 / (1.0 + thr)
        BUS.emit("controller.p99_drift",
                 step=step if step is not None else self.stats["steps"],
                 ratio=ratio, drifted=drifted, predicted_s=pred,
                 measured_s=measured_s, threshold=thr)
        if drifted:
            self._p99_trigger = ratio
        return ratio

    def observe_burn_rate(self, source, targets: Optional[Dict[str, float]] = None,
                          metric: str = "ttft_s",
                          budgets: Optional[Dict[str, float]] = None,
                          fast: int = 8, slow: int = 32,
                          fire: float = 2.0,
                          step: Optional[int] = None) -> Optional[Dict[str, dict]]:
        """Feed an executor/fleet's finished-request records through the
        multi-window SLO burn-rate computer (obs/slo.py): per class,
        the violation fraction of the trailing fast and slow completion
        windows over the class's error budget.  ``targets`` defaults to
        the live fleet proposal's per-class p99 predictions
        (``model.fleet.per_class_p99_s``); ``budgets`` default to
        ``1 - quantile`` per SLOClass when the source carries a class
        table.  One ``controller.burn_rate`` event per class; any class
        burning past ``fire`` on BOTH windows arms a ``"burn_rate"``
        re-search at the next step boundary — an EARLIER trigger than
        ``observe_p99``: a persistent moderate violation (say every
        request at 1.3x target) torches the budget while the raw p99
        stays under the 1.5x drift threshold forever.  Returns the
        per-class burn map (None when nothing was comparable)."""
        from flexflow_tpu.obs.slo import burn_rates

        if targets is None:
            prop = getattr(self.model, "fleet", None)
            if prop is None:
                return None
            targets = dict(prop.per_class_p99_s)
        targets = {k: v for k, v in targets.items()
                   if v and math.isfinite(v)}
        if not targets:
            return None
        if budgets is None:
            classes = getattr(source, "slo_classes", None) or {}
            budgets = {name: max(1.0 - cls.quantile, 1e-4)
                       for name, cls in classes.items()
                       if name in targets}
        records = getattr(source, "request_records", source)
        rates = burn_rates(records, targets, metric=metric,
                           budgets=budgets, fast=fast, slow=slow,
                           fire=fire)
        step = step if step is not None else self.stats["steps"]
        fired = None
        for name, row in sorted(rates.items()):
            BUS.emit("controller.burn_rate", step=step, slo=name,
                     fast=row["fast"], slow=row["slow"],
                     fired=row["fired"], target_s=row["target_s"],
                     budget=row["budget"],
                     completions=row["completions"])
            if row["fired"]:
                fired = name if fired is None else f"{fired},{name}"
        if fired is not None:
            self._burn_trigger = fired
        return rates or None

    def observe_fleet(self, fleet, proposal=None, metric: str = "ttft_s",
                      window: int = 0,
                      step: Optional[int] = None) -> Optional[Dict[str, float]]:
        """Feed a ``FleetExecutor``'s measured per-class p99 windows
        against a fleet proposal's predictions (``per_class_p99_s``,
        search/fleet.py).  One ``controller.p99_drift`` event per
        class (tagged ``slo=``); any class past the model's drift
        threshold arms a FLEET re-search with the worst
        measured/predicted ratio as its load scale — consumed by
        ``maybe_refleet`` (or directly ``research_fleet``), which can
        re-size N.  Returns the per-class ratio map (None when nothing
        was comparable)."""
        prop = proposal if proposal is not None \
            else getattr(self.model, "fleet", None)
        if prop is None:
            return None
        thr = self.model.config.drift_threshold
        ratios: Dict[str, float] = {}
        worst = None
        for name, pred in sorted(prop.per_class_p99_s.items()):
            if not pred or not math.isfinite(pred):
                continue
            measured = fleet.measured_request_p99(metric, slo=name,
                                                  window=window)
            if not measured or not math.isfinite(measured):
                continue
            ratio = measured / pred
            ratios[name] = ratio
            drifted = ratio > 1.0 + thr or ratio < 1.0 / (1.0 + thr)
            BUS.emit("controller.p99_drift",
                     step=step if step is not None
                     else self.stats["steps"],
                     ratio=ratio, drifted=drifted, predicted_s=pred,
                     measured_s=measured, threshold=thr, slo=name)
            if drifted:
                worst = ratio if worst is None else max(worst, ratio)
        if worst is not None:
            self._fleet_trigger = worst
        return ratios or None

    def research_fleet(self, step: Optional[int] = None,
                       load_scale: Optional[float] = None,
                       proposal=None):
        """Re-run the fleet search with the measured drift folded into
        the offered load (``propose_fleet(load_scale=)``) — the
        elastic re-size: a saturated fleet's re-search shifts the
        optimum toward more replicas, a lightly-loaded one toward
        fewer.  Hot-applies the new proposal onto ``model.fleet`` (the
        same slot the compile-time search fills; callers rebuild their
        ``FleetExecutor`` from it) and emits ``fleet.scale``.  The
        load scale is clamped to [1, 8] so a pathological measured
        window cannot demand an unpriceable load."""
        from flexflow_tpu.search.driver import coherent_calibration
        from flexflow_tpu.search.fleet import propose_fleet

        prop = proposal if proposal is not None \
            else getattr(self.model, "fleet", None)
        scale = load_scale if load_scale is not None \
            else (self._fleet_trigger or 1.0)
        self._fleet_trigger = None
        scale = min(8.0, max(1.0, float(scale)))
        step = step if step is not None else self.stats["steps"]
        tid = None
        if TRACER.enabled:
            tid = TRACER.episode_root(trigger="fleet_drift", step=step)
            TRACER.begin(tid, "refleet", parent="controller.episode",
                         load_scale=round(scale, 4))
        new = propose_fleet(
            self.model.graph, self.model.strategy, self.model.config,
            calibration=coherent_calibration(self.model.config),
            base_graph=getattr(self.model, "fleet_base_graph", None),
            load_scale=scale)
        old_n = len(prop.replicas) if prop is not None else 1
        new_n = len(new.replicas) if new is not None else old_n
        if tid is not None:
            TRACER.end(tid, "refleet", to_replicas=new_n)
            TRACER.finish_trace(tid, outcome="applied"
                                if new is not None else "kept")
        BUS.emit("fleet.scale", step=step, from_replicas=old_n,
                 to_replicas=new_n, load_scale=round(scale, 6),
                 resized=new_n != old_n)
        self.stats["fleet_scales"] = \
            int(self.stats.get("fleet_scales", 0)) + 1
        if self.verbose:
            print(f"[controller] fleet re-search at load x{scale:.2f}: "
                  f"{old_n} -> {new_n} replicas")
        if new is not None:
            self.model.fleet = new
        return new

    def maybe_refleet(self, step: Optional[int] = None):
        """Consume a pending fleet drift trigger (armed by
        ``observe_fleet``): re-search and hot-apply, or None when no
        drift is pending — the idempotent per-step hook a serving loop
        calls next to ``step()``."""
        if self._fleet_trigger is None:
            return None
        return self.research_fleet(step=step)

    def observe_lane_drift(self, lane_report) -> None:
        """Feed a matched ``LaneDriftReport`` (obs/trace_ingest.py);
        any stale lane arms a ``"lane_drift"`` re-search at the next
        step boundary.  ``_watch_drift`` also consumes a fresh
        ``model.lane_drift_report`` automatically."""
        if lane_report is None or lane_report is self._lane_seen:
            return
        self._lane_seen = lane_report
        stale = lane_report.stale_lanes
        if stale:
            self._lane_trigger = ",".join(stale[:4])

    def _watch_drift(self, step: int) -> None:
        """The controller's own per-phase DriftReport: measured mean of
        the trailing step window vs the compile-time prediction.  On
        calibration staleness it marks the persisted table + cost cache
        exactly like ``model._report_profile`` — the next signature
        check then sees the rotation and re-searches."""
        # a device-trace lane report the model's fit produced since the
        # last check rides the same watch (per-lane drift is a sharper
        # signal than the aggregate step ratio: it names WHICH comm
        # lane the cost model mispriced)
        self.observe_lane_drift(
            getattr(self.model, "lane_drift_report", None))
        pred = getattr(self.model, "predicted_breakdown", None)
        window = self._step_times[1:]  # step 0 pays compile
        if (not pred or not pred.get("calibrated")
                or len(window) < self.drift_window):
            return
        from flexflow_tpu.obs.drift import build_drift_report

        measured = sum(window[-self.drift_window:]) / self.drift_window
        report = build_drift_report(
            pred, measured_step_s=measured,
            threshold=self.model.config.drift_threshold, calibrated=True)
        if report is None:
            return
        BUS.emit("drift.report", phase=f"step_{step}", **report.to_dict())
        if not report.calibration_stale:
            return
        cfg = self.model.config
        if cfg.calibration_file:
            from flexflow_tpu.search.calibration import CalibrationTable

            CalibrationTable.mark_stale_file(
                cfg.calibration_file, report.ratio)
        from flexflow_tpu.search.cost_cache import (
            mark_calibration_stale,
            resolve_cost_cache_path,
        )

        cache_path = resolve_cost_cache_path(cfg)
        if cache_path:
            mark_calibration_stale(cache_path)

    # -- re-search + swap ------------------------------------------------
    def _research(self, config, trigger: str, step: int):
        """Warm re-search for the current graph under ``config``; the
        result must pass the swap gate against the LIVE state, else the
        search falls back to strategy-only on the current graph (a
        rewritten graph that re-homes every weight is adopted, one that
        invents or drops weights is not)."""
        from flexflow_tpu.analysis import errors_only, lint_swap
        from flexflow_tpu.search import driver as _driver

        t0 = time.perf_counter()
        new_graph, strategy = _driver.optimize_strategy(
            self.model.graph, config, return_graph=True)
        episodes = [dict(_driver.LAST_SEARCH_STATS)]
        dp_fallback = False
        if errors_only(lint_swap(self.model.graph, new_graph, strategy,
                                 config.num_devices)):
            new_graph = self.model.graph
            if new_graph.num_nodes > _driver.CHAIN_MIN_NODES:
                # a strategy-only search past the chain threshold falls
                # into the driver's flat whole-graph DP (documented not
                # to terminate at thousand-node scale, and the drift
                # rotation just invalidated the persistent caches) — a
                # LIVE run degrades to plain data parallelism, always
                # legal and swappable, instead of stalling mid-step
                from flexflow_tpu.compiler.lowering import (
                    data_parallel_strategy,
                )

                strategy = data_parallel_strategy(
                    new_graph, config.num_devices)
                dp_fallback = True
            else:
                strategy = _driver.optimize_strategy(
                    self.model.graph, config, return_graph=False)
                episodes.append(dict(_driver.LAST_SEARCH_STATS))
        seconds = time.perf_counter() - t0
        # the episode may span TWO searches (rewritten graph rejected by
        # the swap gate → strategy-only fallback): sum the search/probe
        # seconds across both, and call it warm only when every search
        # was cache-served — a cold first search is not erased by a warm
        # second one
        search_s = sum(float(e.get("search_seconds") or 0.0)
                       for e in episodes)
        cal_s = sum(float(e.get("calibration_seconds") or 0.0)
                    for e in episodes)
        warm = all(bool(e.get("result_cache_hit")) for e in episodes)
        self.stats["research_seconds"].append(seconds)
        self.stats["research_warm"].append(warm)
        self.stats["research_detail"].append({
            "wall_s": seconds, "trigger": trigger, "warm": warm,
            "search_s": search_s, "calibration_s": cal_s,
            "searches": len(episodes), "dp_fallback": dp_fallback,
        })
        BUS.emit("controller.research", step=step, trigger=trigger,
                 search_seconds=search_s, calibration_seconds=cal_s,
                 wall_s=seconds, warm=warm, nodes=new_graph.num_nodes)
        if self.verbose:
            print(f"# controller: re-search ({trigger}) at step {step}: "
                  f"{search_s:.3f}s search + {cal_s:.3f}s re-probe "
                  f"({seconds:.3f}s wall){' warm' if warm else ''}")
        return new_graph, strategy

    def _swap(self, step: int, strategy, graph=None, config=None) -> dict:
        report = self.model.swap_strategy(strategy, graph=graph,
                                          config=config)
        # measured step times describe the PREVIOUS program; the drift
        # watch must not judge the new one by them
        self._step_times = []
        self.stats["swaps"] += 1
        self.stats["swap_seconds"].append(report["swap_seconds"])
        if report["fallback"]:
            self.stats["fallbacks"] += 1
        BUS.emit("controller.swap", step=step,
                 swap_seconds=report["swap_seconds"],
                 fallback=report["fallback"],
                 fresh=len(report["fresh"]),
                 dropped=len(report["dropped"]))
        if self.verbose:
            print(f"# controller: hot swap at step {step} in "
                  f"{report['swap_seconds']:.3f}s"
                  + (" (fp32 monolithic fallback)"
                     if report["fallback"] else ""))
        return report

    def _research_and_swap(self, step: int, trigger: str,
                           config=None) -> None:
        cfg = config if config is not None else self.model.config
        # the controller episode is a trace too: a drift → re-search →
        # hot-apply chain reads as ONE span tree next to the request
        # traces it was triggered by (same Chrome-trace export)
        tid = None
        if TRACER.enabled:
            tid = TRACER.episode_root(trigger=trigger, step=step)
            TRACER.begin(tid, "research", parent="controller.episode")
        new_graph, strategy = self._research(cfg, trigger, step)
        if tid is not None:
            TRACER.end(tid, "research")
            TRACER.begin(tid, "swap", parent="controller.episode")
        self._swap(step, strategy,
                   graph=new_graph if new_graph is not self.model.graph
                   else None,
                   config=config)
        if tid is not None:
            TRACER.end(tid, "swap")
            TRACER.finish_trace(tid, outcome="applied")
        self._cal_state = self._live_cal_state()

    def _monolithic_fallback(self, step: int, reason: str) -> None:
        """Degrade to the monolithic fp32 sync path: the searched comm
        plan (schedule/precision/zero groups) is dropped and the SAME
        strategy re-lowers — gradients stay bit-exact, only the
        overlap/compression win is surrendered."""
        cfg = self.model.config
        cfg.sync_schedule = "off"
        cfg.sync_precision = "fp32"
        cfg.co_search = False
        cfg.sync_ef = "off"
        # the per-group optimizer-sharding map is part of the searched
        # comm plan too — swap_strategy carries a still-linting map
        # forward by design, so the fallback must drop it explicitly
        self.model.zero_groups = ()
        self.stats["fallbacks"] += 1
        BUS.emit("controller.fallback", step=step, reason=reason)
        # a fallback is exactly the moment a post-mortem is worth its
        # bytes: dump the flight ring (last-N events + open spans)
        FLIGHT.dump(reason=f"controller-fallback-step{step}")
        if self.verbose:
            print(f"# controller: falling back to monolithic fp32 sync "
                  f"at step {step} ({reason})")
        # with the plan knobs off, the swap itself rebuilds no searched
        # plan — its own fallback flag stays False and is not re-counted
        self._swap(step, self.model.strategy)
        if self._armed_collective is not None:
            # the fault models a broken collective in the searched comm
            # path, which the fallback just removed
            self.faults.neutralize(self._armed_collective)
            self._armed_collective = None

    # -- fault handling ----------------------------------------------------
    def _handle_faults(self, step: int) -> Optional[int]:
        """Inject + recover every fault due at ``step``.  Returns a
        rewound step to resume from (checkpoint restore drill), else
        None."""
        resume_at = None
        for fault in (self.faults.due(step) if self.faults else ()):
            BUS.emit("fault.injected", fault=fault.kind, step=step,
                     arg=fault.arg)
            if self.verbose:
                print(f"# controller: fault {fault.kind} at step {step}")
            if fault.kind == "calibration_drift":
                path = self.model.config.calibration_file
                if path and os.path.exists(path):
                    self.faults.inject_calibration_drift(fault, path)
                else:
                    fault.fired = True
            elif fault.kind == "device_loss":
                survivors = self.faults.inject_device_loss(
                    fault, self.model.config.num_devices)
                cfg = shrink_config(self.model.config, survivors)
                self._research_and_swap(step, "device_loss", config=cfg)
                self.stats["recoveries"] += 1
                BUS.emit("controller.recovery", step=step,
                         cause="device_loss", devices=survivors)
            elif fault.kind == "p99_drift":
                # seeded serving-currency drift: the measured decode
                # p99 came in at draw x the searched prediction —
                # routed through the same observe_p99 watch a live
                # executor feeds, so the trigger path is identical
                ratio = self.faults.inject_p99_drift(fault)
                pred = (getattr(self.model, "predicted_breakdown", None)
                        or {}).get("total_s")
                if pred and math.isfinite(pred):
                    self.observe_p99(pred * ratio, predicted_s=pred,
                                     step=step)
            elif fault.kind == "collective_failure":
                self._armed_collective = fault
            elif fault.kind == "corrupt_checkpoint":
                if self._ckpt_mgr is not None:
                    self.faults.inject_corrupt_checkpoint(
                        fault, self.checkpoint_dir)
                    try:
                        restored = self._ckpt_mgr.restore(self.model)
                    except (FileNotFoundError, ValueError) as e:
                        # nothing complete to rewind to (the fault fired
                        # before the first save, or truncated the only
                        # snapshot): the LIVE in-memory state is intact,
                        # so the run continues instead of dying on the
                        # drill it exists to survive
                        BUS.emit("controller.fallback", step=step,
                                 reason=f"restore drill skipped: {e}")
                        if self.verbose:
                            print(f"# controller: no complete snapshot "
                                  f"to rewind to at step {step}; "
                                  f"continuing on live state")
                    else:
                        self.stats["recoveries"] += 1
                        self.stats["restores"] += 1
                        BUS.emit("controller.recovery", step=step,
                                 cause="checkpoint",
                                 restored_step=restored)
                        resume_at = restored + 1
                else:
                    fault.fired = True
        return resume_at

    # -- the loop ----------------------------------------------------------
    def run(self, x, y, steps: int,
            batch_size: Optional[int] = None) -> dict:
        """Run ``steps`` optimizer steps over (x, y) in deterministic
        sequential batches (no shuffle: recovery replay and the
        bit-exactness oracles need byte-identical batch streams)."""
        import jax

        model = self.model
        cfg = model.config
        if cfg.comp_mode != "training":
            raise RuntimeError("controller drives training models only")
        bs = batch_size or cfg.batch_size
        xs = [np.asarray(a)
              for a in (x if isinstance(x, (list, tuple)) else [x])]
        y = np.asarray(y)
        num_batches = len(y) // bs
        if num_batches == 0:
            raise ValueError(
                f"no full batch: {len(y)} samples < batch_size {bs}")
        self._cal_state = self._live_cal_state()
        step = 0
        while step < steps:
            resume_at = self._handle_faults(step)
            if resume_at is not None:
                # the restore drill rewound the run; history past the
                # restored step is replayed deterministically (the rng
                # counter rode the checkpoint)
                self.history = [h for h in self.history
                                if h["step"] < resume_at]
                step = resume_at
                continue
            if step % self.drift_check_every == 0:
                self._watch_drift(step)
                state = self._live_cal_state()
                if state != self._cal_state:
                    self._research_and_swap(step, "calibration_drift")
            if self._burn_trigger is not None:
                # the SLO error budget is burning on both windows —
                # the earlier leg of the serving watch: it consumes
                # BEFORE the raw-p99 trigger, and a step where both
                # armed re-searches once, not twice
                self._burn_trigger = None
                self._p99_trigger = None
                self._research_and_swap(step, "burn_rate")
            if self._p99_trigger is not None:
                # the serving currency drifted past threshold: the
                # searched strategy's p99 claim is falsified — re-search
                # on the current cost surface (same first-class standing
                # as the calibration-signature rotation)
                self._p99_trigger = None
                self._research_and_swap(step, "p99_drift")
            if self._lane_trigger is not None:
                self._lane_trigger = None
                self._research_and_swap(step, "lane_drift")
            b = step % num_batches
            idx = slice(b * bs, (b + 1) * bs)
            model._rng_counter += 1
            rng = jax.random.key(model._rng_counter)
            t0 = time.perf_counter()
            attempt = 0
            while True:
                # (re)place the batch each attempt: a mid-step fallback
                # swap re-lowers onto a fresh mesh object, and the batch
                # must land under the CURRENT program's shardings
                inputs = [
                    jax.device_put(a[idx],
                                   model.compiled.input_sharding(i))
                    for i, a in enumerate(xs)
                ]
                labels = jax.device_put(
                    y[idx], model.compiled.batch_sharding())
                try:
                    if self._armed_collective is not None:
                        self.faults.check_collective(
                            self._armed_collective)
                    (model.params, model.opt_state, model.state, loss,
                     _metrics) = model.compiled.train_step(
                        model.params, model.opt_state, model.state, rng,
                        inputs, labels)
                    loss = float(loss)
                    break
                except TransientCollectiveError as e:
                    attempt += 1
                    self.stats["retries"] += 1
                    BUS.emit("controller.retry", step=step,
                             attempt=attempt, backoff_s=self.backoff_s)
                    if attempt > self.max_retries:
                        self._monolithic_fallback(step, str(e))
                        continue
                    if self.backoff_s:
                        time.sleep(self.backoff_s * attempt)
            self._armed_collective = None
            if attempt == 0:
                # a retried step's wall time includes the failed
                # attempts + backoff sleeps — feeding it to the drift
                # watch would mark the calibration stale (and burn a
                # re-probe allowance) over a network hiccup that never
                # touched the cost surface
                self._step_times.append(time.perf_counter() - t0)
            self.stats["steps"] = int(self.stats["steps"]) + 1
            self.history.append({"step": step, "loss": loss})
            if (self._ckpt_mgr is not None and self.checkpoint_every
                    and (step + 1) % self.checkpoint_every == 0):
                self._ckpt_mgr.save(step, model)
            if self.verbose:
                print(f"# controller: step {step} loss={loss:.4f}")
            step += 1
        if not all(math.isfinite(h["loss"]) for h in self.history):
            # surface divergence loudly — a swapped run must not quietly
            # report a NaN trajectory as success
            BUS.emit("controller.fallback", step=steps,
                     reason="non-finite loss in history")
        BUS.emit("controller.summary", steps=self.stats["steps"],
                 swaps=self.stats["swaps"],
                 recoveries=self.stats["recoveries"],
                 retries=self.stats["retries"],
                 fallbacks=self.stats["fallbacks"])
        BUS.flush()
        return {"history": list(self.history), "stats": dict(self.stats)}
