"""Checkpoint / resume.

A capability the reference *lacks* (SURVEY.md §5: weights are only
reachable via ``ParallelTensorBase::set_tensor/get_tensor``,
reference: include/flexflow/parallel_tensor.h:157-161, with no
optimizer-state or model checkpoint format).  Here checkpointing is
first-class: the full training state — params, optimizer slots, mutable
op state (batch-norm stats, caches), rng counter and step — round-trips
through an on-disk store, and restore re-applies each array's sharding
on the compiled mesh (``jax.device_put`` onto the live sharding), so a
checkpoint written under one strategy can be resumed under another.

Backend: orbax-checkpoint when importable (async-capable, the JAX
ecosystem standard), else a self-contained .npz + JSON-manifest format.
Both write the same logical tree; the manifest records keypaths so a
restore validates structure before touching device memory.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

try:  # pragma: no cover - exercised when orbax present
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    ocp = None
    _HAS_ORBAX = False

_STEP_RE = re.compile(r"^step_(\d+)$")


def _flatten(tree) -> Tuple[List[Tuple[str, np.ndarray]], Any]:
    """Flatten a pytree to (dotted-keypath, host ndarray) pairs."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        key = "/".join(_path_token(p) for p in path) or "_root"
        out.append((key, np.asarray(leaf)))
    return out, treedef


def _path_token(p) -> str:
    import jax

    if isinstance(p, jax.tree_util.DictKey):
        return str(p.key)
    if isinstance(p, jax.tree_util.SequenceKey):
        return str(p.idx)
    if isinstance(p, jax.tree_util.GetAttrKey):
        return str(p.name)
    return str(p)


def _reshard_leaf(leaf, val: np.ndarray):
    """One host array placed back onto a live leaf's sharding + dtype
    (the fp32 value-identity re-shard both restore paths rely on)."""
    import jax

    val = val.astype(leaf.dtype)
    sharding = getattr(leaf, "sharding", None)
    # Re-apply only real mesh shardings. A SingleDeviceSharding
    # template leaf (e.g. optimizer slots before the first step)
    # must stay UNCOMMITTED, or the next jitted step sees it
    # pinned to one device while params span the mesh.
    if sharding is not None and not isinstance(
        sharding, jax.sharding.SingleDeviceSharding
    ):
        return jax.device_put(val, sharding)
    return val


def _restore_like(template, arrays: Dict[str, np.ndarray]):
    """Rebuild ``template``'s tree from host arrays, preserving each live
    leaf's sharding + dtype (device_put onto the existing sharding)."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        key = "/".join(_path_token(p) for p in path) or "_root"
        if key not in arrays:
            raise KeyError(f"checkpoint missing tensor {key!r}")
        val = arrays[key]
        if hasattr(leaf, "shape"):
            if tuple(val.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"shape mismatch for {key}: checkpoint {tuple(val.shape)} "
                    f"vs model {tuple(leaf.shape)}"
                )
            leaves.append(_reshard_leaf(leaf, val))
        else:  # python scalar leaf (e.g. step counters)
            leaves.append(type(leaf)(val))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _restore_matching(template, arrays: Dict[str, np.ndarray]):
    """Lenient sibling of ``_restore_like`` for HOT swaps: checkpoint
    values land on every matching keypath, template leaves with no
    (shape-compatible) saved value keep their fresh init, and saved
    keys with no home are reported instead of raising — a comm-plan
    change legitimately drops lowering-created state (EF residuals)
    and the caller must be able to say so.  Returns
    ``(tree, fresh_keys, dropped_keys)``."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves, fresh, used = [], [], set()
    for path, leaf in flat:
        key = "/".join(_path_token(p) for p in path) or "_root"
        val = arrays.get(key)
        if val is None or (hasattr(leaf, "shape")
                           and tuple(val.shape) != tuple(leaf.shape)):
            fresh.append(key)
            leaves.append(leaf)
            continue
        used.add(key)
        if hasattr(leaf, "shape"):
            leaves.append(_reshard_leaf(leaf, val))
        else:
            leaves.append(type(leaf)(val))
    dropped = sorted(set(arrays) - used)
    return jax.tree_util.tree_unflatten(treedef, leaves), fresh, dropped


def snapshot_in_memory(model) -> Dict[str, Any]:
    """Host-side copy of a compiled FFModel's full training state —
    the in-memory checkpoint the hot-swap path re-shards from.  Real
    copies (``np.array(copy=True)``): the next train step donates the
    device buffers, and on CPU ``np.asarray`` of a jax array is a
    zero-copy view of exactly that donated memory."""
    snap: Dict[str, Any] = {"trees": {}, "rng_counter": int(
        getattr(model, "_rng_counter", 0))}
    for name, tree in (("params", model.params),
                       ("opt_state", model.opt_state),
                       ("state", model.state)):
        flat, _ = _flatten(tree)
        snap["trees"][name] = {k: np.array(v, copy=True) for k, v in flat}
    return snap


def restore_in_memory(model, snap: Dict[str, Any]) -> Dict[str, list]:
    """Place a ``snapshot_in_memory`` capture onto the model's CURRENT
    (freshly re-lowered) state templates — each value device_put onto
    the new strategy's sharding, a value-identity operation at fp32.
    Returns ``{"fresh": [...], "dropped": [...]}`` keypaths (new
    lowering-created state vs state the new comm plan no longer
    carries)."""
    report = {"fresh": [], "dropped": []}
    for name, template in (("params", model.params),
                           ("opt_state", model.opt_state),
                           ("state", model.state)):
        tree, fresh, dropped = _restore_matching(
            template, snap["trees"].get(name, {}))
        if name == "state" and isinstance(tree, dict):
            # the model-state dict GROWS during training (per-iteration
            # outputs like a CacheOp's score land after step 1): carry
            # those live entries across the swap too — uncommitted, the
            # next jitted step places them.  EF residuals are the one
            # exception: they are DERIVED from the comm plan, and a
            # residual for a wire the new plan no longer compresses is
            # meaningless — those stay dropped (and reported).
            carried = [k for k in dropped
                       # a key already in the template landed in
                       # `dropped` because its saved SHAPE mismatched —
                       # the fresh init must win there, not the stale
                       # buffer
                       if k not in tree
                       and not k.endswith("/ef_residual")]
            for k in carried:
                tree[k] = snap["trees"][name][k]
            dropped = [k for k in dropped if k not in carried]
        setattr(model, name, tree)
        report["fresh"] += [f"{name}/{k}" for k in fresh]
        report["dropped"] += [f"{name}/{k}" for k in dropped]
    model._rng_counter = int(snap.get("rng_counter", 0))
    return report


class CheckpointManager:
    """Save/restore full training state with retention.

    >>> mgr = CheckpointManager("/tmp/ckpt", max_to_keep=3)
    >>> mgr.save(step, model)
    >>> step = mgr.restore(model)   # model must be compile()d first
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 use_orbax: Optional[bool] = None, async_save: bool = False):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        if use_orbax and not _HAS_ORBAX:
            raise ValueError("use_orbax=True but orbax-checkpoint is not installed")
        self.use_orbax = _HAS_ORBAX if use_orbax is None else use_orbax
        # async_save: save() blocks only for the device->host copy (the
        # training step may DONATE the device buffers right after) and
        # persists to disk in a background thread — training overlaps
        # serialization + IO.  wait() (or the next save/restore) joins.
        self.async_save = async_save
        # single-slot box shared with the finalizer — the finalizer must
        # not capture self, or the weakref never fires
        self._pending_box: list = [None]
        self._executor = None
        if async_save:
            import weakref
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-save"
            )
            # a dropped manager (or interpreter exit) must not lose a
            # write error silently: join the pending future and raise
            # in whoever finalizes
            self._finalizer = weakref.finalize(
                self, CheckpointManager._drain, self._executor,
                self._pending_box,
            )
        os.makedirs(self.directory, exist_ok=True)
        # a previous writer may have died mid-publish: recover/reclaim
        # its leftovers before this manager lists or writes anything
        self._recover_strays()

    @staticmethod
    def _drain(executor, pending_box):
        fut, pending_box[0] = pending_box[0], None
        try:
            if fut is not None:
                fut.result()
        finally:
            executor.shutdown(wait=True)

    def wait(self) -> None:
        """Block until the in-flight async save (if any) is durable on
        disk; re-raises any persistence error in the caller."""
        fut, self._pending_box[0] = self._pending_box[0], None
        if fut is not None:
            fut.result()

    def close(self) -> None:
        """Join the in-flight save and shut the writer thread down;
        surfaces any persistence error.  Also runs at finalization."""
        self.wait()
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._finalizer.detach()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    # ------------------------------------------------------------------
    def save(self, step: int, model) -> str:
        """Snapshot a compiled FFModel's full training state."""
        assert model.compiled is not None, "compile() before save"
        import jax

        if jax.process_count() > 1:
            # multihost: every process participates in ONE coordinated
            # orbax save of the globally-sharded trees (each process
            # writes its addressable shards; orbax barriers internally)
            # — np.asarray of non-addressable shards would raise, and
            # per-process npz writes would race on the step directory
            return self._multihost_save(step, model)
        state_trees = {
            "params": model.params,
            "opt_state": model.opt_state,
            "state": model.state,
        }
        arrays: Dict[str, np.ndarray] = {}
        manifest: Dict[str, Any] = {"step": step, "trees": {}}
        for tree_name, tree in state_trees.items():
            flat, _ = _flatten(tree)
            manifest["trees"][tree_name] = [k for k, _ in flat]
            for k, v in flat:
                arrays[f"{tree_name}/{k}"] = v
        manifest["rng_counter"] = int(getattr(model, "_rng_counter", 0))

        path = self._step_dir(step)
        if not self.async_save:
            self._write_snapshot(path, arrays, manifest)
            return path
        self.wait()  # one in-flight save at a time; surfaces prior errors
        # REAL copies NOW — the caller's next train step donates the
        # device buffers (lowering jits with donate_argnums), and on the
        # CPU backend np.asarray of a jax array is a zero-copy VIEW of
        # exactly that donated memory; copy=True is what makes handing
        # the arrays to the background thread safe
        arrays = {k: np.array(v, copy=True) for k, v in arrays.items()}
        self._pending_box[0] = self._executor.submit(
            self._write_snapshot, path, arrays, manifest
        )
        return path

    # ------------------------------------------------------------------
    def _multihost_tree(self, model) -> Dict[str, Any]:
        return {
            "params": model.params,
            "opt_state": model.opt_state,
            "state": model.state,
            "rng_counter": np.int64(getattr(model, "_rng_counter", 0)),
        }

    def _multihost_save(self, step: int, model) -> str:
        """Coordinated multi-process snapshot via orbax StandardCheckpointer
        (reference has no model checkpointing at all, SURVEY §5; the
        multi-host story here mirrors its GASNet collective launch —
        every process calls save on the SAME directory).  Synchronous:
        the donation-safe async path needs per-host copies, which
        multihost sharding makes orbax's job, not ours."""
        import jax

        import orbax.checkpoint as _ocp

        path = self._step_dir(step)
        if os.path.exists(path) and jax.process_index() == 0:
            shutil.rmtree(path)
        # all processes must observe the deletion before the collective
        # save starts — without the barrier they race into the
        # half-deleted directory
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_clear_{step}")
        ckptr = _ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), self._multihost_tree(model))
        ckptr.wait_until_finished()
        if jax.process_index() == 0:
            self._gc()
        return path

    def _multihost_restore(self, model, step: int) -> int:
        import jax

        import orbax.checkpoint as _ocp

        path = self._step_dir(step)
        tree = self._multihost_tree(model)
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = getattr(model.compiled, "mesh", None)
        repl = (NamedSharding(mesh, PartitionSpec())
                if mesh is not None else None)

        def to_abstract(a):
            if isinstance(a, jax.Array):
                sh = a.sharding
                if (repl is not None and jax.process_count() > 1
                        and len(sh.device_set) == 1):
                    # per-process uncommitted scalars (optimizer step
                    # counters) must come back GLOBAL-replicated, or the
                    # restored array is committed to one device and the
                    # next global-mesh jit rejects the argument mix
                    sh = repl
                return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
            return jax.ShapeDtypeStruct(
                np.shape(a), np.asarray(a).dtype, sharding=repl)

        abstract = jax.tree.map(to_abstract, tree)
        ckptr = _ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.abspath(path), abstract)
        model.params = restored["params"]
        model.opt_state = restored["opt_state"]
        model.state = restored["state"]
        model._rng_counter = int(restored["rng_counter"])
        return step

    def _write_snapshot(self, path: str, arrays, manifest) -> None:
        """Atomic publish: the full snapshot lands in a temp dir first
        and only a complete one is swapped in via ``os.replace`` — a
        kill at ANY point leaves either the previous complete
        ``step_N`` or none, never a half-written one (the temp/old
        names don't match ``_STEP_RE``, so listing ignores them)."""
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        if self.use_orbax:
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.join(tmp, "tree"), arrays)
        else:
            np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        old = path + ".old"
        if os.path.exists(old):
            shutil.rmtree(old)
        if os.path.exists(path):
            # re-saving an existing step: move the old dir aside first
            # (os.replace cannot atomically replace a non-empty dir);
            # the rename pair keeps the non-step names outside the
            # crash window's visible set
            os.rename(path, old)
        os.replace(tmp, path)
        if os.path.exists(old):
            shutil.rmtree(old)
        self._gc()

    # ------------------------------------------------------------------
    def snapshot_complete(self, step: int) -> bool:
        """True when ``step_N`` on disk is a COMPLETE snapshot: the
        manifest parses and the payload it promises is actually there
        (npz central directory readable, key set == manifest keys; for
        orbax trees, the tree/metadata dirs exist).  A torn write on
        shared storage — or an injected ``corrupt_checkpoint`` fault —
        fails this check instead of surfacing mid-restore."""
        return self._complete_dir(self._step_dir(step))

    def _complete_dir(self, path: str) -> bool:
        mf = os.path.join(path, "manifest.json")
        if not os.path.exists(mf):
            # multihost orbax snapshot: positive metadata marker only
            return os.path.exists(
                os.path.join(path, "_CHECKPOINT_METADATA"))
        try:
            with open(mf) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return False
        want = {
            f"{tree}/{k}"
            for tree, keys in manifest.get("trees", {}).items()
            for k in keys
        }
        npz = os.path.join(path, "arrays.npz")
        if os.path.exists(npz):
            import zipfile

            try:
                with np.load(npz) as z:
                    return set(z.files) == want
            except (OSError, ValueError, EOFError, zipfile.BadZipFile):
                return False
        tree_dir = os.path.join(path, "tree")
        return os.path.isdir(tree_dir) and bool(os.listdir(tree_dir))

    def latest_complete_step(self) -> Optional[int]:
        """Newest step whose snapshot passes ``snapshot_complete`` —
        the restore anchor when the newest ``step_N`` was torn."""
        for step in reversed(self.all_steps()):
            if self.snapshot_complete(step):
                return step
        return None

    def restore(self, model, step: Optional[int] = None) -> int:
        """Load a snapshot into a compiled FFModel; returns the step."""
        assert model.compiled is not None, "compile() before restore"
        import jax

        self.wait()  # an in-flight async save must land first
        if step is None:
            if self.latest_step() is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
            step = self.latest_complete_step()
            if step is None:
                raise ValueError(
                    f"no COMPLETE checkpoint in {self.directory}: every "
                    f"step_N fails the manifest/payload completeness "
                    f"check (torn writes?)")
            skipped = [s for s in self.all_steps() if s > step]
            if skipped:
                import warnings

                warnings.warn(
                    f"checkpoint step(s) {skipped} are truncated "
                    f"(manifest/payload mismatch) — restoring the newest "
                    f"complete step {step}", stacklevel=2)
        path = self._step_dir(step)
        if jax.process_count() > 1 or not os.path.exists(
                os.path.join(path, "manifest.json")):
            # multihost snapshots are orbax directories (no manifest);
            # they also restore fine single-process from a multihost run.
            # Dispatch only on POSITIVE evidence of an orbax snapshot —
            # a corrupt single-host snapshot or stray directory would
            # otherwise surface as a confusing orbax internal error.
            if jax.process_count() == 1 and not os.path.exists(
                    os.path.join(path, "_CHECKPOINT_METADATA")):
                raise ValueError(
                    f"unrecognized snapshot at {path}: neither a "
                    "manifest.json (single-host) nor an orbax "
                    "_CHECKPOINT_METADATA (multihost) is present — the "
                    "snapshot may be corrupt or from an interrupted save"
                )
            return self._multihost_restore(model, step)
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        if self.use_orbax and os.path.isdir(os.path.join(path, "tree")):
            ckptr = ocp.PyTreeCheckpointer()
            arrays = ckptr.restore(os.path.join(path, "tree"))
        else:
            with np.load(os.path.join(path, "arrays.npz")) as z:
                arrays = {k: z[k] for k in z.files}
        by_tree: Dict[str, Dict[str, np.ndarray]] = {}
        for key, val in arrays.items():
            tree_name, sub = key.split("/", 1)
            by_tree.setdefault(tree_name, {})[sub] = np.asarray(val)
        # validate structure against the manifest BEFORE touching device
        # memory, and build all new trees before assigning any — a failed
        # restore must leave the model untouched (no mixed old/new state)
        templates = {"params": model.params, "opt_state": model.opt_state,
                     "state": model.state}
        for tree_name, template in templates.items():
            want = set(manifest["trees"].get(tree_name, []))
            have = {k for k, _ in _flatten(template)[0]}
            if want != have:
                missing = sorted(have - want)[:5]
                extra = sorted(want - have)[:5]
                raise ValueError(
                    f"checkpoint structure mismatch in {tree_name!r}: "
                    f"missing={missing} unexpected={extra}"
                )
        restored = {
            name: _restore_like(template, by_tree.get(name, {}))
            for name, template in templates.items()
        }
        model.params = restored["params"]
        model.opt_state = restored["opt_state"]
        model.state = restored["state"]
        model._rng_counter = int(manifest.get("rng_counter", 0))
        return int(manifest["step"])

    # ------------------------------------------------------------------
    def _gc(self) -> None:
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(self._step_dir(victim), ignore_errors=True)
        self._recover_strays()

    def _recover_strays(self) -> None:
        """Leftovers of a publish interrupted mid-swap (never part of
        the visible step set — the regex excludes them).  A kill
        BETWEEN the rename pair leaves a COMPLETE snapshot parked at
        ``step_N.old`` with no visible ``step_N``: that copy is the
        only recoverable data and is renamed back rather than deleted.
        Everything else (.tmp dirs, superseded or incomplete .old
        dirs) is reclaimed."""
        for name in os.listdir(self.directory):
            full = os.path.join(self.directory, name)
            if name.endswith(".old") and _STEP_RE.match(name[:-4]):
                final = os.path.join(self.directory, name[:-4])
                if not os.path.exists(final) and self._complete_dir(full):
                    os.rename(full, final)
                else:
                    shutil.rmtree(full, ignore_errors=True)
            elif name.endswith(".tmp") and _STEP_RE.match(name[:-4]):
                shutil.rmtree(full, ignore_errors=True)
