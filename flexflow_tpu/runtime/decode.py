"""Continuous-batching decode executor.

The runtime half of the serving workload (ROADMAP item 4): compose
RAGGED requests into FIXED decode frames — the [max_seqs]-slot shape
the compiled decode graph (models/decode.py) was specialized for — so
one jitted program serves an arbitrary request stream:

* a ``PageAllocator`` owns the KV page pool; a request is **admitted**
  only when its full page allotment is reservable.  With radix
  **prefix sharing** armed, part of that allotment may CLAIM
  already-cached pages by refcount — a prefix-trie lookup keyed on
  token ids at page granularity finds the longest cached prefix of
  the prompt — and a mid-page divergence duplicates exactly that one
  page at admission (copy-on-write, ``copy_page_fn``).  The residency
  contract is **reserve-on-divergence**: the moment a sequence is
  admitted, every page at or after the first position it will write
  is PRIVATE (refcount 1, asserted by
  ``PageAllocator.assert_divergence_reserved``) — so an admitted
  sequence can always grow to ``max_seq_len`` unpreempted by pool
  pressure, writes never land in a shared page, and eviction returns
  a page to the free list only at refcount zero.  Admission runs in
  **priority order** when SLO classes are armed: higher-priority
  requests admit first, a request whose ``deadline_frames`` passed
  while queued is EXPIRED instead of served late, and a
  strictly-higher-priority arrival may preempt the lowest-priority
  live sequence (pages refcount-released, sequence re-queued with
  its tokens so far — regeneration is deterministic, and re-admission
  may re-claim the prefix a sibling still holds);
* prompts enter through the **chunked prefill lane** when one is armed
  (``prefill_fn`` — runtime/prefill.py builds it from the decode
  model, ``compiled_decode_step(model, prefill_chunk=C)``): the
  prompt's causal forward runs once per C-token chunk and scatters
  K/V straight into the sequence's pages, then the sequence joins the
  decode loop at its LAST prompt token — token-identical to the
  prefill-via-decode fallback (one decode frame per prompt token),
  which remains the no-prefill-fn path; under prefix sharing both
  paths START at the first token past the claimed cached prefix
  (prefill skips pages the trie already holds);
* each ``step`` fills every live slot's next token through ONE decode
  graph call, until ``max_new_tokens`` or EOS;
* every frame emits a ``decode.frame`` obs event (admissions,
  evictions, live slots, pages in use, measured latency, predicted
  latency when the caller supplies the search's number) and the run
  ends with a ``decode.summary`` roll-up — the decode phase of the
  predicted-vs-measured story; ``decode_drift_report`` folds the
  measured frame latencies against the search's predicted p99 into
  the same DriftReport shape model.fit produces for training steps
  (``ffobs report`` renders both).

The executor is deliberately decoupled from FFModel: it drives any
``step_fn(token_ids [B,1] i32, page_table [B,P] i32, seq_lens [B] i32)
-> logits [B, 1, V]``; ``compiled_decode_step`` builds that function
from a compiled decode model (threading the KV-cache state dict
across calls).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from flexflow_tpu.obs import annotate
from flexflow_tpu.obs.events import BUS
from flexflow_tpu.obs.tracing import TRACER


@dataclass
class DecodeRequest:
    """One sequence to serve: the prompt's token ids and how many new
    tokens to generate.  ``eos_id`` stops generation early when the
    model emits it (None = run to max_new_tokens).  ``slo`` names the
    request's SLO class (resolved against the executor's class table);
    ``priority``/``deadline_frames`` override the class defaults —
    higher priority admits first, a deadline (frames from enqueue to
    admission) expires the request instead of serving it late."""

    rid: str
    prompt: Sequence[int]
    max_new_tokens: int = 8
    eos_id: Optional[int] = None
    slo: str = "standard"
    priority: Optional[int] = None
    deadline_frames: Optional[int] = None


@dataclass(frozen=True)
class SLOClass:
    """One request class of the serving deployment: admission priority,
    queue deadline, and the arrival quantile its latency is watched at
    (``measured_request_p99``/``TrainingController.observe_p99`` per
    class).  Persisted into ``__meta__.disaggregation.slo_classes``
    (fflint STR211 checks the shape stdlib-only)."""

    name: str
    priority: int = 0
    deadline_frames: int = 0  # 0 = no deadline
    quantile: float = 0.99

    def to_jsonable(self) -> dict:
        return {"name": self.name, "priority": self.priority,
                "deadline_frames": self.deadline_frames,
                "quantile": self.quantile}


@dataclass
class _Pending:
    """A queued sequence: a fresh submission, or a preempted live
    sequence carrying the tokens it already produced (regeneration is
    deterministic, so re-decoding continues the same stream)."""

    req: DecodeRequest
    seq: int               # submission order (FIFO tie-break)
    priority: int
    deadline_frames: int   # 0 = none
    enqueue_frame: int
    tokens: List[int] = field(default_factory=list)
    generated: int = 0
    preempted: int = 0     # times this sequence lost its slot
    # telemetry stamps carried across preemption (first values win)
    enqueue_t: Optional[float] = None
    admit_t: Optional[float] = None
    prefill_done_t: Optional[float] = None
    first_token_t: Optional[float] = None
    started_frame: Optional[int] = None


@dataclass
class _Live:
    req: DecodeRequest
    pages: List[int]
    tokens: List[int] = field(default_factory=list)  # prompt + generated
    cached: int = 0        # tokens already written into the KV cache
    generated: int = 0
    started_frame: int = 0
    priority: int = 0
    preempted: int = 0
    seq: int = 0
    deadline_frames: int = 0
    enqueue_frame: int = 0
    # request lifecycle span stamps (perf_counter seconds) — populated
    # only while the obs bus is armed (see step()'s one-check contract).
    # prefill_done_t closes the PREFILL span: the cache holds every
    # prompt token but the last, so TTFT decomposes exactly into
    # queue (enqueue→admit) + prefill (admit→prefill_done) +
    # first decode frame (prefill_done→first_token).
    enqueue_t: Optional[float] = None
    admit_t: Optional[float] = None
    prefill_done_t: Optional[float] = None
    first_token_t: Optional[float] = None


class PageAllocator:
    """Free-list page allocator over the decode graph's pool, with
    copy-on-write refcounts and a radix prefix trie.

    Every in-use page carries a refcount (``alloc`` starts it at 1;
    ``share`` lets a second sequence claim it; ``free`` decrements and
    returns the page to the free list only at zero).  The trie maps
    token-id prefixes — at page granularity — to the page caching that
    prefix's K/V, published by ``register_prefix`` as sequences fill
    pages and consulted by ``lookup_prefix`` at admission.  Sharing a
    cached page is sound because a causal decoder's K/V at position i
    is a deterministic function of tokens[:i+1] alone.

    The residency contract is **reserve-on-divergence**: callers must
    arrange (CoW at admission) that every page at or after a
    sequence's first write position is private — checked by
    ``assert_divergence_reserved``.  That preserves the historical
    guarantee in the new regime: an admitted sequence can always grow
    to ``max_seq_len`` unpreempted by pool pressure, because its
    writable tail is reserved up front and shared pages are read-only
    by construction."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, -1, -1))
        self._ref: Dict[int, int] = {}  # in-use page -> refcount
        # prefix trie, flattened: full-prefix tuple -> page caching its
        # last page_size tokens; parent prefix -> {page: token chunk}
        # for mid-page (CoW) matches; page -> (parent, chunk) for
        # removal at refcount zero
        self._prefix: Dict[tuple, int] = {}
        self._children: Dict[tuple, Dict[int, tuple]] = {}
        self._page_key: Dict[int, tuple] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def alloc_ids(self, ids: Sequence[int]) -> Optional[List[int]]:
        """Reserve SPECIFIC page ids (the slot-aligned fast path), or
        None when any is already in use."""
        if any(p not in self._free for p in ids):
            return None
        for p in ids:
            self._free.remove(p)
            self._ref[p] = 1
        return list(ids)

    def share(self, pages: Sequence[int]) -> None:
        """Claim already-cached pages for one more sequence: each must
        be live (a sibling holds it), its refcount goes up by one, and
        ``free`` from either owner now only drops the count."""
        for p in pages:
            assert self._ref.get(p, 0) >= 1, (
                f"page {p} is not live — the trie served a stale hit")
            self._ref[p] += 1

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert 0 <= p < self.num_pages and p not in self._free, p
            r = self._ref.get(p, 0)
            assert r >= 1, f"page {p} freed more times than referenced"
            if r > 1:
                self._ref[p] = r - 1
            else:
                del self._ref[p]
                self._drop_prefix(p)
                self._free.append(p)

    # ---- prefix trie -----------------------------------------------------
    def register_prefix(self, tokens: Sequence[int], page_size: int,
                        pages: Sequence[int], cached: int) -> None:
        """Publish every FULLY-cached page of a sequence (the first
        ``cached`` tokens of ``tokens`` live in ``pages``) into the
        trie.  First registration of a prefix wins; already-published
        pages are skipped, so calling this at every page boundary is
        idempotent and O(full pages)."""
        for j in range(cached // page_size):
            p = pages[j]
            if p in self._page_key:
                continue  # this page already backs a trie entry
            parent = tuple(tokens[:j * page_size])
            chunk = tuple(tokens[j * page_size:(j + 1) * page_size])
            if parent + chunk in self._prefix:
                continue  # a sibling's page already owns this prefix
            self._prefix[parent + chunk] = p
            self._children.setdefault(parent, {})[p] = chunk
            self._page_key[p] = (parent, chunk)

    def lookup_prefix(self, tokens: Sequence[int], page_size: int):
        """Longest cached prefix of ``tokens``: returns
        ``(pages, matched, partial)`` — the fully-matching cached pages
        (claim them via ``share``), the token count they cover, and,
        when a further cached page agrees on ``extra`` more tokens
        mid-page, ``partial = (src_page, extra)`` for the caller to
        copy-on-write.  Pure lookup: claims nothing."""
        tokens = tuple(tokens)
        pages: List[int] = []
        k = 0
        while (k + 1) * page_size <= len(tokens):
            p = self._prefix.get(tokens[:(k + 1) * page_size])
            if p is None:
                break
            pages.append(p)
            k += 1
        matched = k * page_size
        partial = None
        rest = tokens[matched:]
        if rest:
            best_m, best_p = 0, None
            for p, chunk in self._children.get(tokens[:matched],
                                               {}).items():
                m = 0
                for a, b in zip(chunk, rest):
                    if a != b:
                        break
                    m += 1
                if m > best_m:
                    best_m, best_p = m, p
            if best_m:
                partial = (best_p, best_m)
        return pages, matched, partial

    def assert_divergence_reserved(self, pages: Sequence[int],
                                   first_write_page: int) -> None:
        """The reserve-on-divergence invariant, checked at admission:
        every page at or after the first page this sequence will write
        must be PRIVATE (refcount exactly 1) — shared pages are
        read-only, so post-admission writes can never need an
        in-flight CoW and the sequence's growth to ``max_seq_len`` is
        reserved up front."""
        for j in range(first_write_page, len(pages)):
            assert self._ref.get(pages[j], 0) == 1, (
                f"page {pages[j]} (allotment index {j}) is shared at "
                f"refcount {self._ref.get(pages[j], 0)} but lies at or "
                f"after the sequence's first write page "
                f"{first_write_page} — reserve-on-divergence violated")

    def _drop_prefix(self, page: int) -> None:
        """Remove a page's trie entry when its refcount hits zero —
        the bytes are about to be reused, so the prefix is no longer
        cached anywhere."""
        key = self._page_key.pop(page, None)
        if key is None:
            return
        parent, chunk = key
        if self._prefix.get(parent + chunk) == page:
            del self._prefix[parent + chunk]
        kids = self._children.get(parent)
        if kids is not None:
            kids.pop(page, None)
            if not kids:
                del self._children[parent]


class ContinuousBatchingExecutor:
    """Admit ragged requests into fixed decode frames and drive the
    step function until every request completes."""

    def __init__(self, step_fn: Callable, *, max_seqs: int,
                 page_size: int, pages_per_seq: int, num_pages: int = 0,
                 predicted_step_s: Optional[float] = None,
                 prefill_fn: Optional[Callable] = None,
                 prefill_chunk: int = 0,
                 slo_classes: Optional[Sequence[SLOClass]] = None,
                 replica_label: Optional[str] = None,
                 prefix_sharing: bool = False,
                 copy_page_fn: Optional[Callable] = None):
        self.step_fn = step_fn
        # fleet membership (runtime/fleet.py): when set, the request
        # histograms are ALSO observed under `name|replica=...,slo=...`
        # labeled series so /metrics can tell fleet members apart
        self.replica_label = replica_label
        self.max_seqs = max_seqs
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        # chunked prefill lane (runtime/prefill.py): when armed, a
        # prompt's first len-1 tokens are written into the cache in
        # ceil((len-1)/chunk) batched passes at admission instead of
        # one decode frame each; None keeps the historical
        # prefill-via-decode path byte-identical
        self.prefill_fn = prefill_fn
        self.prefill_chunk = int(prefill_chunk or 0)
        if prefill_fn is not None and self.prefill_chunk < 1:
            raise ValueError(
                "prefill_fn needs prefill_chunk >= 1 (the chunk size "
                "the jitted writer was built for)")
        # SLO classes: priority admission / deadline expiry / preemption
        # (empty table = single-class FIFO, the historical behavior)
        self.slo_classes: Dict[str, SLOClass] = {
            c.name: c for c in (slo_classes or ())}
        self._seq = 0  # submission counter (FIFO tie-break)
        # radix prefix sharing: admission claims trie-cached prefix
        # pages by refcount instead of allocating them, mid-page
        # divergence copies that one page via copy_page_fn (CoW at
        # admission — reserve-on-divergence, see PageAllocator), and
        # prefill starts at the first token past the claimed prefix.
        # Off keeps every historical path byte-identical.
        self.prefix_sharing = bool(prefix_sharing)
        self.copy_page_fn = copy_page_fn
        self.allocator = PageAllocator(num_pages or max_seqs * pages_per_seq)
        # slot-aligned allocation: when the pool covers every slot,
        # slot i always takes pages [i*pps, (i+1)*pps) — contiguous
        # slot shards own contiguous page ranges, which is EXACTLY the
        # page-dim split the decode op's state_shardings places under a
        # batch-split view, so the device-local cache streaming the
        # cost model credits to batch splits is realized by the
        # executor, not merely priced.  Undersized (oversubscribed)
        # pools fall back to the free list, where a sequence's pages
        # may land on another group's shard — the locality price of
        # oversubscription.  Prefix sharing ALSO forces the free list:
        # a claimed page lives wherever the sibling's allotment put it,
        # so slot-aligned page identities cannot hold.
        self.slot_aligned = (
            not self.prefix_sharing
            and self.allocator.num_pages >= max_seqs * pages_per_seq)
        # idle frame rows still scatter one garbage k/v (static-shape
        # scatter — the op cannot skip rows), so they must point at a
        # page no LIVE sequence can own.  Slot-aligned pools use the
        # idle slot's OWN range (free by construction while the slot is
        # idle; a later admission rewrites every position before
        # reading it).  Oversubscribed pools RESERVE one scratch page
        # up front — one page of capacity is the price of a pool that
        # can otherwise be fully exhausted while slots sit idle (the
        # free-list fallback of picking "some free page" corrupts live
        # cache exactly then).
        self._scratch_page = None
        if not self.slot_aligned:
            got = self.allocator.alloc(1)
            assert got, "page pool too small to reserve the scratch page"
            self._scratch_page = got[0]
        # the search's predicted (p99) decode-step seconds, when the
        # caller has one — recorded per frame so drift is computable
        self.predicted_step_s = predicted_step_s
        self.slots: List[Optional[_Live]] = [None] * max_seqs
        self.queue: List[_Pending] = []
        self.finished: Dict[str, List[int]] = {}
        self.expired: Dict[str, List[int]] = {}  # deadline-missed rids
        self.frame = 0
        self.frame_seconds: List[float] = []
        self.total_admitted = 0
        self.total_evicted = 0
        self.total_expired = 0
        self.total_preempted = 0
        self.prefill_chunks = 0  # chunked-prefill passes run
        self.prefill_tokens = 0  # prompt tokens written by the lane
        # prefix-sharing roll-up (all zero while sharing is off)
        self.prefix_hits = 0     # admissions that claimed a cached prefix
        self.shared_pages = 0    # pages claimed by refcount, cumulative
        self.cow_copies = 0      # mid-page divergences copied at admission
        self.prefix_tokens = 0   # prompt tokens served from shared cache
        # per-request lifecycle telemetry (enqueue→admit→prefill→first
        # token→EOS/evict spans; TTFT/TPOT/e2e + the TTFT split),
        # recorded only while the obs bus is armed — the hot path
        # checks BUS.enabled ONCE per frame (and once per submit
        # batch) and skips every stamp when it is off
        self.request_records: List[dict] = []

    # ------------------------------------------------------------------
    def submit(self, requests: Sequence[DecodeRequest]) -> None:
        obs = BUS.enabled  # one check per submit batch
        tr = TRACER.enabled  # ditto — the request-trace gate
        for r in requests:
            assert r.prompt, f"request {r.rid!r} has an empty prompt"
            need = len(r.prompt) + r.max_new_tokens
            cap = self.page_size * self.pages_per_seq
            assert need <= cap, (
                f"request {r.rid!r} wants {need} tokens but a sequence "
                f"caps at {cap} (page_size x pages_per_seq)")
            cls = self.slo_classes.get(r.slo)
            entry = _Pending(
                req=r, seq=self._seq,
                priority=(r.priority if r.priority is not None
                          else (cls.priority if cls else 0)),
                deadline_frames=(
                    r.deadline_frames if r.deadline_frames is not None
                    else (cls.deadline_frames if cls else 0)),
                enqueue_frame=self.frame,
                tokens=list(r.prompt),
            )
            self._seq += 1
            if obs:
                entry.enqueue_t = time.perf_counter()
            if tr:
                # trace minted at enqueue (idempotent: the fleet router
                # minted it at route time, then this opens children);
                # the queue span runs enqueue -> admission
                tid = TRACER.request_root(r.rid, slo=r.slo)
                TRACER.begin(tid, "queue", parent="request")
            self.queue.append(entry)

    def _expire(self, obs: bool = False, tr: bool = False) -> int:
        """Drop queued requests whose admission deadline passed —
        deadline-based admission control: a request the deployment can
        no longer serve inside its SLO is refused loudly (recorded in
        ``expired``, one ``decode.request`` phase="expired" event),
        never served late."""
        expired = 0
        kept = []
        for e in self.queue:
            if (e.deadline_frames
                    and self.frame - e.enqueue_frame > e.deadline_frames):
                self.expired[e.req.rid] = e.tokens[len(e.req.prompt):]
                expired += 1
                if obs:
                    rec = {"rid": e.req.rid, "phase": "expired",
                           "slo": e.req.slo,
                           "queued_frames": self.frame - e.enqueue_frame,
                           "deadline_frames": e.deadline_frames}
                    self.request_records.append(rec)
                    BUS.emit("decode.request", **rec)
                if tr:
                    tid = TRACER.trace_of(e.req.rid)
                    if tid is not None:
                        TRACER.end(tid, "queue", expired=True)
                        TRACER.finish_request(e.req.rid,
                                              outcome="expired")
            else:
                kept.append(e)
        self.queue = kept
        self.total_expired += expired
        return expired

    def _preempt_for(self, entry: _Pending, obs: bool,
                     tr: bool = False) -> bool:
        """Free a slot + pages for a strictly-higher-priority pending
        request by evicting the LOWEST-priority live sequence
        (latest-admitted tie-break).  The victim re-queues with its
        tokens so far — regeneration is deterministic, so its stream
        continues unchanged after re-admission."""
        victims = [
            (live.priority, -live.started_frame, -i, i)
            for i, live in enumerate(self.slots)
            if live is not None and live.priority < entry.priority
        ]
        if not victims:
            return False
        _, _, _, i = min(victims)
        live = self.slots[i]
        self.allocator.free(live.pages)
        self.slots[i] = None
        self.total_preempted += 1
        back = _Pending(
            req=live.req, seq=live.seq, priority=live.priority,
            deadline_frames=live.deadline_frames,
            enqueue_frame=live.enqueue_frame,
            tokens=list(live.tokens), generated=live.generated,
            preempted=live.preempted + 1,
            enqueue_t=live.enqueue_t, admit_t=live.admit_t,
            prefill_done_t=live.prefill_done_t,
            first_token_t=live.first_token_t,
            started_frame=live.started_frame,
        )
        self.queue.append(back)
        if obs:
            BUS.emit("decode.request", rid=live.req.rid,
                     phase="preempted", slo=live.req.slo,
                     by=entry.req.rid, tokens=live.generated)
        if tr:
            tid = TRACER.trace_of(live.req.rid)
            if tid is not None:
                # the victim was mid-decode or (via-decode path)
                # mid-prefill; either way its residency window closes
                # and a fresh queue span opens — the re-queue edge
                TRACER.end_any(tid, ("decode", "prefill"),
                               preempted_by=entry.req.rid)
                TRACER.begin(tid, "queue", parent="request",
                             requeue=True)
        return True

    def _run_prefill(self, live: _Live, obs: bool,
                     tr: bool = False) -> None:
        """The chunked prefill lane: write the sequence's first
        ``len(tokens) - 1`` cached-to-be tokens through the batched
        chunk writer (``run_chunked_prefill``, runtime/prefill.py), so
        the decode loop starts at the LAST token and produces the first
        generated token in its first frame.  Under prefix sharing the
        first ``live.cached`` tokens are already in claimed/copied
        pages — the writer starts at the first divergent token."""
        n_pre = len(live.tokens) - 1
        start = live.cached  # shared-prefix skip-ahead (0 off-sharing)
        if n_pre - start <= 0 or self.prefill_fn is None:
            return
        from flexflow_tpu.runtime.prefill import run_chunked_prefill

        with annotate.phase_span(annotate.PREFILL_PHASE):
            chunks = run_chunked_prefill(
                self.prefill_fn, live.tokens, live.pages,
                chunk=self.prefill_chunk,
                cap=self.page_size * self.pages_per_seq,
                start=start,
                trace_id=TRACER.trace_of(live.req.rid) if tr else None)
        live.cached = n_pre
        self.prefill_chunks += chunks
        self.prefill_tokens += n_pre - start
        if obs:
            BUS.emit("decode.prefill", rid=live.req.rid,
                     tokens=n_pre - start,
                     chunks=chunks, chunk=self.prefill_chunk)

    def _admit(self, obs: bool = False, tr: bool = False) -> int:
        """Fill open slots from the queue in (priority, submission)
        order while the allocator can reserve a FULL per-sequence
        allotment; expired requests are refused first, and a
        strictly-higher-priority arrival may preempt the
        lowest-priority live sequence when no allotment is free."""
        self._expire(obs, tr)
        admitted = 0
        while self.queue:
            order = sorted(range(len(self.queue)),
                           key=lambda j: (-self.queue[j].priority,
                                          self.queue[j].seq))
            entry = self.queue[order[0]]
            open_slots = [i for i in range(self.max_seqs)
                          if self.slots[i] is None]
            if not open_slots and not self._preempt_for(entry, obs, tr):
                break
            open_slots = [i for i in range(self.max_seqs)
                          if self.slots[i] is None]
            i = open_slots[0]
            # prefix-sharing claim: the trie lookup runs INSIDE the
            # preempt-retry loop because a preemption below may free a
            # matched page to refcount zero (stale hit otherwise).
            # Only the to-be-cached prefix (all but the last token) is
            # eligible — the last token is fed through decode, and its
            # scatter must land in a page this sequence owns.
            shared: List[int] = []
            matched = 0
            partial = None
            if self.prefix_sharing:
                shared, matched, partial = self.allocator.lookup_prefix(
                    entry.tokens[:-1], self.page_size)
                if partial is not None and self.copy_page_fn is None:
                    partial = None  # cannot CoW without a page copier
            if self.slot_aligned:
                pages = self.allocator.alloc_ids(range(
                    i * self.pages_per_seq, (i + 1) * self.pages_per_seq))
            else:
                pages = self.allocator.alloc(
                    self.pages_per_seq - len(shared))
            if pages is None:
                if not self._preempt_for(entry, obs, tr):
                    break
                continue  # retry with the freed allotment
            if shared:
                self.allocator.share(shared)
                pages = shared + pages
            if partial is not None:
                # mid-page divergence: duplicate the one agreeing page
                # into the first fresh page NOW (CoW at admission), so
                # every post-admission write lands in owned pages
                src, extra = partial
                dst = pages[len(shared)]
                self.copy_page_fn(src, dst)
                matched += extra
                self.cow_copies += 1
                if obs:
                    BUS.emit("decode.cow", rid=entry.req.rid,
                             src_page=src, dst_page=dst, tokens=extra)
            if matched:
                self.prefix_hits += 1
                self.shared_pages += len(shared)
                self.prefix_tokens += matched
                if obs:
                    BUS.emit("decode.prefix_hit", rid=entry.req.rid,
                             pages=len(shared), tokens=matched)
            if self.prefix_sharing:
                self.allocator.assert_divergence_reserved(
                    pages, matched // self.page_size)
            self.queue.pop(order[0])
            live = _Live(req=entry.req, pages=pages,
                         tokens=list(entry.tokens), cached=matched,
                         generated=entry.generated,
                         started_frame=(entry.started_frame
                                        if entry.started_frame is not None
                                        else self.frame),
                         priority=entry.priority,
                         preempted=entry.preempted, seq=entry.seq,
                         deadline_frames=entry.deadline_frames,
                         enqueue_frame=entry.enqueue_frame)
            if obs:
                live.enqueue_t = entry.enqueue_t
                live.admit_t = entry.admit_t or time.perf_counter()
                live.prefill_done_t = entry.prefill_done_t
                live.first_token_t = entry.first_token_t
            tid = TRACER.trace_of(entry.req.rid) if tr else None
            if tid is not None:
                # admission edge: the queue window closes, the prefill
                # window opens (chunk children land under it);
                # cached_prefix records how many prompt tokens the
                # shared cache already held — the span's duration is
                # the cost of the REMAINING tokens only
                TRACER.end(tid, "queue")
                TRACER.begin(tid, "prefill", parent="request",
                             slot=i, pages=len(pages),
                             cached_prefix=matched)
            self._run_prefill(live, obs, tr)
            if self.prefix_sharing and live.cached:
                # publish this sequence's fully-cached pages (claimed
                # ones are already in the trie and skip out)
                self.allocator.register_prefix(
                    live.tokens, self.page_size, live.pages, live.cached)
            if obs and live.prefill_done_t is None:
                # the prefill span closes here for the chunked lane,
                # for single-token prompts, and for prompts fully
                # served from a shared prefix (nothing left to
                # prefill); the via-decode path closes it in step()
                # when the cache holds every prompt token but the last
                if (self.prefill_fn is not None or len(live.tokens) <= 1
                        or live.cached >= len(live.tokens) - 1):
                    live.prefill_done_t = time.perf_counter()
            if tid is not None and (self.prefill_fn is not None
                                    or len(live.tokens) <= 1
                                    or live.cached >= len(live.tokens) - 1):
                # same edge for the span tree: prefill closes, the
                # decode residency window opens (the via-decode path
                # closes prefill in step() instead)
                if TRACER.end(tid, "prefill") is not None:
                    TRACER.begin(tid, "decode", parent="request")
            self.slots[i] = live
            admitted += 1
        self.total_admitted += admitted
        return admitted

    def _evict(self, obs: bool = False, tr: bool = False) -> int:
        """Free finished sequences' pages and reopen their slots."""
        evicted = 0
        for i, live in enumerate(self.slots):
            if live is None:
                continue
            done_gen = live.generated >= live.req.max_new_tokens
            eos = (live.req.eos_id is not None and live.generated > 0
                   and live.tokens[-1] == live.req.eos_id)
            if done_gen or eos:
                self.finished[live.req.rid] = live.tokens[len(live.req.prompt):]
                self.allocator.free(live.pages)
                self.slots[i] = None
                evicted += 1
                if obs:
                    self._record_request(live)
                if tr:
                    tid = TRACER.trace_of(live.req.rid)
                    if tid is not None:
                        TRACER.end(tid, "decode", eos=eos,
                                   tokens=live.generated)
                        TRACER.finish_request(
                            live.req.rid, outcome="finish",
                            tokens=live.generated,
                            preempted=live.preempted)
        self.total_evicted += evicted
        return evicted

    def _record_request(self, live: _Live) -> None:
        """Close a finished request's lifecycle span: queue wait
        (enqueue→admit), TTFT (enqueue→first generated token), TPOT
        (steady per-token after the first), e2e — observed into the
        metrics registry histograms and emitted as one
        ``decode.request`` event.  Called only when the bus was armed
        at eviction time (the caller's one-check-per-frame gate)."""
        from flexflow_tpu.obs.metrics import METRICS

        now = time.perf_counter()
        enq, adm, first = live.enqueue_t, live.admit_t, live.first_token_t
        pre = live.prefill_done_t
        queue_s = (adm - enq) if (enq is not None and adm is not None) \
            else None
        ttft_s = (first - enq) if (enq is not None and first is not None) \
            else None
        # the TTFT split: queue + prefill + first decode frame sum to
        # TTFT exactly (prefill_done closes when the cache holds every
        # prompt token but the last — chunked lane or via-decode alike)
        prefill_s = (pre - adm) if (adm is not None and pre is not None) \
            else None
        first_frame_s = (first - pre) \
            if (pre is not None and first is not None) else None
        e2e_s = (now - enq) if enq is not None else None
        tpot_s = None
        if first is not None and live.generated > 1:
            tpot_s = (now - first) / (live.generated - 1)
        rec = {
            "rid": live.req.rid,
            "phase": "finish",
            "slo": live.req.slo,
            "queue_s": queue_s,
            "prefill_s": prefill_s,
            "first_frame_s": first_frame_s,
            "ttft_s": ttft_s,
            "tpot_s": tpot_s,
            "e2e_s": e2e_s,
            "tokens": live.generated,
            "frames": self.frame - live.started_frame + 1,
            "preempted": live.preempted,
        }
        self.request_records.append(rec)
        # labeled series: the global aggregates stay (back-compat), and
        # the request-latency histograms are ALSO observed per
        # (replica, SLO class) so /metrics can tell fleet members and
        # priority lanes apart (obs/exposition.py parses the |k=v
        # suffix into Prometheus labels).  Same obs gate as the flat
        # series — no new BUS.enabled reads.
        slo = live.req.slo or "standard"
        lab = (f"slo={slo}" if self.replica_label is None
               else f"replica={self.replica_label},slo={slo}")
        labeled = ("decode.queue_s", "decode.ttft_s", "decode.tpot_s",
                   "decode.e2e_s")
        for key, v in (("decode.queue_s", queue_s),
                       ("decode.prefill_s", prefill_s),
                       ("decode.first_frame_s", first_frame_s),
                       ("decode.ttft_s", ttft_s),
                       ("decode.tpot_s", tpot_s),
                       ("decode.e2e_s", e2e_s)):
            if v is not None:
                METRICS.histogram(key).observe(v)
                if key in labeled:
                    METRICS.histogram(f"{key}|{lab}").observe(v)
        BUS.emit("decode.request", **rec)

    # ------------------------------------------------------------------
    def _compose_frame(self):
        """The fixed-shape frame arrays for the CURRENT step: every
        live slot contributes its next uncached token (a prompt token
        still being prefilled, or the last generated token); idle slots
        carry token 0 at length 0 — page_table rows of idle slots point
        at page 0 of live-anywhere pages, masked off by seq_lens=0."""
        b = self.max_seqs
        ids = np.zeros((b, 1), np.int32)
        table = np.zeros((b, self.pages_per_seq), np.int32)
        lens = np.zeros((b,), np.int32)
        active = []
        for i, live in enumerate(self.slots):
            if live is None:
                # idle row: its scatter must land where no live
                # sequence reads (see __init__ — own slot range when
                # slot-aligned, the reserved scratch page otherwise)
                if self.slot_aligned:
                    table[i, :] = np.arange(
                        i * self.pages_per_seq,
                        (i + 1) * self.pages_per_seq)
                else:
                    table[i, :] = self._scratch_page
                continue
            active.append(i)
            ids[i, 0] = live.tokens[live.cached]
            table[i, :len(live.pages)] = live.pages
            lens[i] = live.cached
        return ids, table, lens, active

    def step(self) -> dict:
        """One decode frame: admit, compose, run, harvest, evict.
        Returns the frame record (also emitted as ``decode.frame``).
        The request-span instrumentation costs exactly this one
        ``BUS.enabled`` read per frame when telemetry is off
        (test-enforced)."""
        obs = BUS.enabled  # ONE check per frame gates every span stamp
        tr = TRACER.enabled  # ditto for the request span tree
        admitted = self._admit(obs, tr)
        ids, table, lens, active = self._compose_frame()
        t0 = time.perf_counter()
        with annotate.phase_span(annotate.DECODE_PHASE):
            logits = np.asarray(self.step_fn(ids, table, lens))
        dt = time.perf_counter() - t0
        self.frame_seconds.append(dt)
        next_tokens = logits[:, 0].argmax(axis=-1).astype(np.int32) \
            if logits.ndim == 3 else logits[:, 0].astype(np.int32)
        now = time.perf_counter() if (obs or tr) else 0.0
        for i in active:
            live = self.slots[i]
            live.cached += 1
            if (self.prefix_sharing
                    and live.cached % self.page_size == 0):
                # a page just filled — publish it so later admissions
                # can claim it (generated tokens included: the stream
                # is deterministic, so equal prefixes mean equal K/V)
                self.allocator.register_prefix(
                    live.tokens, self.page_size, live.pages, live.cached)
            if live.cached < len(live.tokens):
                # still prefilling via decode: the next prompt token is
                # queued.  The prefill span closes when only the LAST
                # prompt token remains (the frame that feeds it is the
                # first decode frame — it produces the first token).
                if live.cached >= len(live.tokens) - 1:
                    if obs and live.prefill_done_t is None:
                        live.prefill_done_t = now
                    if tr:
                        tid = TRACER.trace_of(live.req.rid)
                        if tid is not None and TRACER.end(
                                tid, "prefill") is not None:
                            TRACER.begin(tid, "decode",
                                         parent="request")
                continue
            # the model's prediction extends the sequence
            live.tokens.append(int(next_tokens[i]))
            live.generated += 1
            if obs and live.first_token_t is None:
                live.first_token_t = now  # TTFT closes here
        evicted = self._evict(obs, tr)
        rec = {
            "frame": self.frame,
            "active": len(active),
            "admitted": admitted,
            "evicted": evicted,
            "pages_in_use": self.allocator.pages_in_use,
            "queued": len(self.queue),
            "measured_s": dt,
            "predicted_s": self.predicted_step_s,
        }
        if obs:
            from flexflow_tpu.obs.metrics import METRICS

            METRICS.histogram("decode.frame_s").observe(dt)
            BUS.emit("decode.frame", **rec)
        self.frame += 1
        return rec

    def run(self, requests: Sequence[DecodeRequest] = (),
            max_frames: int = 10_000) -> Dict[str, List[int]]:
        """Drive frames until every submitted request finished (or the
        frame cap trips — a stuck executor must fail loud, not spin).
        Returns rid -> generated token ids."""
        if requests:
            self.submit(requests)
        while (self.queue or any(s is not None for s in self.slots)):
            if self.frame >= max_frames:
                raise RuntimeError(
                    f"decode executor exceeded {max_frames} frames with "
                    f"{len(self.queue)} queued and "
                    f"{sum(s is not None for s in self.slots)} live")
            self.step()
        if BUS.enabled:
            BUS.emit("decode.summary", **self.summary())
        return dict(self.finished)

    # ------------------------------------------------------------------
    @staticmethod
    def _quantile(values, f: float):
        if not values:
            return None
        s = sorted(values)
        return s[min(len(s) - 1, int(f * (len(s) - 1)))]

    def measured_p99(self, window: int = 0) -> Optional[float]:
        """p99 of the measured frame latencies — over the trailing
        ``window`` frames when given (the CONTINUOUS drift signal a
        long-running server feeds the controller), else the whole
        run."""
        times = self.frame_seconds[-window:] if window \
            else self.frame_seconds
        return self._quantile(times, 0.99)

    def measured_request_p99(self, metric: str = "ttft_s",
                             slo: Optional[str] = None,
                             window: int = 0) -> Optional[float]:
        """p99 of a per-request latency metric (``ttft_s``/``tpot_s``/
        ``e2e_s``/``queue_s``), optionally restricted to one SLO class
        and to the trailing ``window`` completions — the per-class
        serve-currency signal a long-running server feeds
        ``TrainingController.observe_p99`` (each class watched at its
        own quantile is the SLO story; p99 here matches the spec's
        default)."""
        recs = [r for r in self.request_records
                if r.get("phase") == "finish"
                and (slo is None or r.get("slo") == slo)
                and r.get(metric) is not None]
        if window:
            recs = recs[-window:]
        cls = self.slo_classes.get(slo) if slo else None
        return self._quantile([r[metric] for r in recs],
                              cls.quantile if cls else 0.99)

    def summary(self) -> dict:
        q = lambda f: self._quantile(self.frame_seconds, f)  # noqa: E731
        out = {
            "frames": self.frame,
            "completed": len(self.finished),
            "admitted": self.total_admitted,
            "evicted": self.total_evicted,
            "expired": self.total_expired,
            "preempted": self.total_preempted,
            "prefill_chunks": self.prefill_chunks,
            "prefill_tokens": self.prefill_tokens,
            "measured_p50_s": q(0.5),
            "measured_p99_s": q(0.99),
            "predicted_step_s": self.predicted_step_s,
        }
        if self.prefix_sharing:
            # prefix-sharing roll-up (keys appear only when the mode is
            # armed, keeping historical summaries byte-identical):
            # cumulative hits/claims/copies plus the private-page
            # complement so ffobs can render shared vs private
            out["prefix_hits"] = self.prefix_hits
            out["shared_pages"] = self.shared_pages
            out["private_pages"] = (
                self.total_admitted * self.pages_per_seq
                - self.shared_pages)
            out["cow_copies"] = self.cow_copies
            out["prefix_tokens"] = self.prefix_tokens
        recs = [r for r in self.request_records
                if r.get("phase") == "finish"]
        if recs:
            # request-level currency (recorded while the bus was
            # armed): TTFT / TPOT / e2e percentiles across completions,
            # with TTFT split into its queue + prefill + first-frame
            # components so the prompt path's cost is attributable per
            # phase
            for key in ("ttft_s", "tpot_s", "e2e_s", "queue_s",
                        "prefill_s", "first_frame_s"):
                vals = [r[key] for r in recs if r.get(key) is not None]
                out[f"{key[:-2]}_p50_s"] = self._quantile(vals, 0.5)
                out[f"{key[:-2]}_p99_s"] = self._quantile(vals, 0.99)
            out["requests_recorded"] = len(recs)
            by_class: Dict[str, list] = {}
            for r in recs:
                by_class.setdefault(r.get("slo", "standard"),
                                    []).append(r)
            if self.slo_classes or len(by_class) > 1:
                out["slo_classes"] = {
                    name: {
                        "completed": len(rs),
                        "ttft_p99_s": self._quantile(
                            [r["ttft_s"] for r in rs
                             if r.get("ttft_s") is not None], 0.99),
                        "e2e_p99_s": self._quantile(
                            [r["e2e_s"] for r in rs
                             if r.get("e2e_s") is not None], 0.99),
                    }
                    for name, rs in sorted(by_class.items())
                }
        return out

    def decode_drift_report(self, threshold: float = 0.5,
                            window: int = 0):
        """Predicted-vs-measured DECODE drift: the search's p99 step
        prediction against the measured frame-latency p99 — the decode
        phase of the DriftReport family (obs/drift.py).  ``window``
        restricts the measured side to the trailing frames, turning a
        one-shot report into the continuous serve-currency signal
        (feed ``report.ratio`` — or the executor itself — to
        ``TrainingController.observe_p99`` to make it a re-search
        trigger).  None when either side is missing.  Emitted as a
        ``drift.report`` event when the bus is armed, like model.fit's
        training-side report."""
        from flexflow_tpu.obs.drift import build_drift_report

        measured = self.measured_p99(window)
        if not self.predicted_step_s or not measured:
            return None
        report = build_drift_report(
            {"total_s": self.predicted_step_s},
            measured, threshold=threshold)
        if report is not None:
            report.phases["decode"] = {
                "predicted_s": self.predicted_step_s,
                "measured_s": measured,
                "ratio": report.ratio,
            }
            if BUS.enabled:
                BUS.emit("drift.report", predicted_s=report.predicted_s,
                         measured_s=report.measured_s, ratio=report.ratio,
                         stale=report.stale, phase="decode")
        return report


def compiled_decode_step(model, prefill_chunk: int = 0) -> Callable:
    """A ``step_fn`` over a COMPILED decode model: one jitted forward
    per frame, the KV-cache state dict threaded across calls (the
    caches are model state — compiler/lowering.py init_params placed
    them under the strategy's view).

    ``prefill_chunk > 0`` additionally builds the chunked prefill
    writer over the SAME graph, params and threaded state
    (runtime/prefill.py — one parameter set by construction, the cache
    scatter lands in the placed state arrays), attached as
    ``step.prefill(ids [1,C], positions [1,C], page_table [1,P])`` for
    the executor's ``prefill_fn``."""
    import jax

    compiled = model.compiled
    fn = jax.jit(
        lambda p, s, ins: compiled.apply(p, s, ins, None, False))
    box = {"state": model.state}

    def step(ids, page_table, seq_lens):
        logits, new_state = fn(
            model.params, box["state"], [ids, page_table, seq_lens])
        box["state"] = new_state
        return logits

    step.state = box  # tests inspect the threaded cache

    def copy_page(src: int, dst: int) -> None:
        """CoW page copy for the prefix-sharing executor
        (``copy_page_fn``): duplicate page ``src`` of every layer's
        paged KV state — k/v pools and, under an int8 pool, their
        per-slot scales — into page ``dst``, which the divergent
        sequence then owns.  Rare (once per mid-page divergence at
        admission), so plain dispatch is fine."""
        st = box["state"]
        out = dict(st)
        for key, val in st.items():
            leaf = key.rsplit("/", 1)[-1]
            if leaf in ("k_cache", "v_cache", "k_scale", "v_scale"):
                out[key] = val.at[dst].set(val[src])
        box["state"] = out

    step.copy_page = copy_page
    if prefill_chunk:
        from flexflow_tpu.runtime.prefill import build_chunk_forward

        pf = jax.jit(build_chunk_forward(model.graph,
                                         compiled.compute_dtype))

        def prefill(ids, positions, page_table):
            box["state"] = pf(model.params, box["state"], ids,
                              positions, page_table)

        step.prefill = prefill
    return step
