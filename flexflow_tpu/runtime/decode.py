"""Continuous-batching decode executor.

The runtime half of the serving workload (ROADMAP item 4): compose
RAGGED requests into FIXED decode frames — the [max_seqs]-slot shape
the compiled decode graph (models/decode.py) was specialized for — so
one jitted program serves an arbitrary request stream:

* a ``PageAllocator`` owns the KV page pool; a request is **admitted**
  only when its full page allotment is free (reservation-style
  residency — an admitted sequence can always grow to ``max_seq_len``
  without preemption), and **evicted** (pages freed, slot reopened)
  when it finishes;
* each ``step`` fills every live slot's next token through ONE decode
  graph call — prompt tokens first (prefill-via-decode: correct by
  construction on any mesh; a chunked prefill writer is the on-TPU
  fast path, see models/decode.py build_gpt_prefill), then generated
  tokens until ``max_new_tokens`` or EOS;
* every frame emits a ``decode.frame`` obs event (admissions,
  evictions, live slots, pages in use, measured latency, predicted
  latency when the caller supplies the search's number) and the run
  ends with a ``decode.summary`` roll-up — the decode phase of the
  predicted-vs-measured story; ``decode_drift_report`` folds the
  measured frame latencies against the search's predicted p99 into
  the same DriftReport shape model.fit produces for training steps
  (``ffobs report`` renders both).

The executor is deliberately decoupled from FFModel: it drives any
``step_fn(token_ids [B,1] i32, page_table [B,P] i32, seq_lens [B] i32)
-> logits [B, 1, V]``; ``compiled_decode_step`` builds that function
from a compiled decode model (threading the KV-cache state dict
across calls).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from flexflow_tpu.obs import annotate
from flexflow_tpu.obs.events import BUS


@dataclass
class DecodeRequest:
    """One sequence to serve: the prompt's token ids and how many new
    tokens to generate.  ``eos_id`` stops generation early when the
    model emits it (None = run to max_new_tokens)."""

    rid: str
    prompt: Sequence[int]
    max_new_tokens: int = 8
    eos_id: Optional[int] = None


@dataclass
class _Live:
    req: DecodeRequest
    pages: List[int]
    tokens: List[int] = field(default_factory=list)  # prompt + generated
    cached: int = 0        # tokens already written into the KV cache
    generated: int = 0
    started_frame: int = 0
    # request lifecycle span stamps (perf_counter seconds) — populated
    # only while the obs bus is armed (see step()'s one-check contract)
    enqueue_t: Optional[float] = None
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None


class PageAllocator:
    """Free-list page allocator over the decode graph's pool."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def alloc_ids(self, ids: Sequence[int]) -> Optional[List[int]]:
        """Reserve SPECIFIC page ids (the slot-aligned fast path), or
        None when any is already in use."""
        if any(p not in self._free for p in ids):
            return None
        for p in ids:
            self._free.remove(p)
        return list(ids)

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert 0 <= p < self.num_pages and p not in self._free, p
            self._free.append(p)


class ContinuousBatchingExecutor:
    """Admit ragged requests into fixed decode frames and drive the
    step function until every request completes."""

    def __init__(self, step_fn: Callable, *, max_seqs: int,
                 page_size: int, pages_per_seq: int, num_pages: int = 0,
                 predicted_step_s: Optional[float] = None):
        self.step_fn = step_fn
        self.max_seqs = max_seqs
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.allocator = PageAllocator(num_pages or max_seqs * pages_per_seq)
        # slot-aligned allocation: when the pool covers every slot,
        # slot i always takes pages [i*pps, (i+1)*pps) — contiguous
        # slot shards own contiguous page ranges, which is EXACTLY the
        # page-dim split the decode op's state_shardings places under a
        # batch-split view, so the device-local cache streaming the
        # cost model credits to batch splits is realized by the
        # executor, not merely priced.  Undersized (oversubscribed)
        # pools fall back to the free list, where a sequence's pages
        # may land on another group's shard — the locality price of
        # oversubscription.
        self.slot_aligned = (
            self.allocator.num_pages >= max_seqs * pages_per_seq)
        # idle frame rows still scatter one garbage k/v (static-shape
        # scatter — the op cannot skip rows), so they must point at a
        # page no LIVE sequence can own.  Slot-aligned pools use the
        # idle slot's OWN range (free by construction while the slot is
        # idle; a later admission rewrites every position before
        # reading it).  Oversubscribed pools RESERVE one scratch page
        # up front — one page of capacity is the price of a pool that
        # can otherwise be fully exhausted while slots sit idle (the
        # free-list fallback of picking "some free page" corrupts live
        # cache exactly then).
        self._scratch_page = None
        if not self.slot_aligned:
            got = self.allocator.alloc(1)
            assert got, "page pool too small to reserve the scratch page"
            self._scratch_page = got[0]
        # the search's predicted (p99) decode-step seconds, when the
        # caller has one — recorded per frame so drift is computable
        self.predicted_step_s = predicted_step_s
        self.slots: List[Optional[_Live]] = [None] * max_seqs
        self.queue: List[DecodeRequest] = []
        self.finished: Dict[str, List[int]] = {}
        self.frame = 0
        self.frame_seconds: List[float] = []
        self.total_admitted = 0
        self.total_evicted = 0
        # per-request lifecycle telemetry (enqueue→admit→first
        # token→EOS/evict spans; TTFT/TPOT/e2e), recorded only while
        # the obs bus is armed — the hot path checks BUS.enabled ONCE
        # per frame (and once per submit batch) and skips every stamp
        # when it is off
        self._enqueue_t: Dict[str, float] = {}
        self.request_records: List[dict] = []

    # ------------------------------------------------------------------
    def submit(self, requests: Sequence[DecodeRequest]) -> None:
        obs = BUS.enabled  # one check per submit batch
        for r in requests:
            assert r.prompt, f"request {r.rid!r} has an empty prompt"
            need = len(r.prompt) + r.max_new_tokens
            cap = self.page_size * self.pages_per_seq
            assert need <= cap, (
                f"request {r.rid!r} wants {need} tokens but a sequence "
                f"caps at {cap} (page_size x pages_per_seq)")
            if obs:
                self._enqueue_t[r.rid] = time.perf_counter()
            self.queue.append(r)

    def _admit(self, obs: bool = False) -> int:
        """Fill open slots from the queue while the allocator can
        reserve a FULL per-sequence allotment (admission by page
        residency: an admitted sequence never needs preemption)."""
        admitted = 0
        for i in range(self.max_seqs):
            if self.slots[i] is not None or not self.queue:
                continue
            if self.slot_aligned:
                pages = self.allocator.alloc_ids(range(
                    i * self.pages_per_seq, (i + 1) * self.pages_per_seq))
            else:
                pages = self.allocator.alloc(self.pages_per_seq)
            if pages is None:
                break
            req = self.queue.pop(0)
            live = _Live(req=req, pages=pages,
                         tokens=list(req.prompt),
                         started_frame=self.frame)
            if obs:
                live.enqueue_t = self._enqueue_t.pop(req.rid, None)
                live.admit_t = time.perf_counter()
            self.slots[i] = live
            admitted += 1
        self.total_admitted += admitted
        return admitted

    def _evict(self, obs: bool = False) -> int:
        """Free finished sequences' pages and reopen their slots."""
        evicted = 0
        for i, live in enumerate(self.slots):
            if live is None:
                continue
            done_gen = live.generated >= live.req.max_new_tokens
            eos = (live.req.eos_id is not None and live.generated > 0
                   and live.tokens[-1] == live.req.eos_id)
            if done_gen or eos:
                self.finished[live.req.rid] = live.tokens[len(live.req.prompt):]
                self.allocator.free(live.pages)
                self.slots[i] = None
                evicted += 1
                if obs:
                    self._record_request(live)
        self.total_evicted += evicted
        return evicted

    def _record_request(self, live: _Live) -> None:
        """Close a finished request's lifecycle span: queue wait
        (enqueue→admit), TTFT (enqueue→first generated token), TPOT
        (steady per-token after the first), e2e — observed into the
        metrics registry histograms and emitted as one
        ``decode.request`` event.  Called only when the bus was armed
        at eviction time (the caller's one-check-per-frame gate)."""
        from flexflow_tpu.obs.metrics import METRICS

        now = time.perf_counter()
        enq, adm, first = live.enqueue_t, live.admit_t, live.first_token_t
        queue_s = (adm - enq) if (enq is not None and adm is not None) \
            else None
        ttft_s = (first - enq) if (enq is not None and first is not None) \
            else None
        e2e_s = (now - enq) if enq is not None else None
        tpot_s = None
        if first is not None and live.generated > 1:
            tpot_s = (now - first) / (live.generated - 1)
        rec = {
            "rid": live.req.rid,
            "phase": "finish",
            "queue_s": queue_s,
            "ttft_s": ttft_s,
            "tpot_s": tpot_s,
            "e2e_s": e2e_s,
            "tokens": live.generated,
            "frames": self.frame - live.started_frame + 1,
        }
        self.request_records.append(rec)
        for key, v in (("decode.queue_s", queue_s),
                       ("decode.ttft_s", ttft_s),
                       ("decode.tpot_s", tpot_s),
                       ("decode.e2e_s", e2e_s)):
            if v is not None:
                METRICS.histogram(key).observe(v)
        BUS.emit("decode.request", **rec)

    # ------------------------------------------------------------------
    def _compose_frame(self):
        """The fixed-shape frame arrays for the CURRENT step: every
        live slot contributes its next uncached token (a prompt token
        still being prefilled, or the last generated token); idle slots
        carry token 0 at length 0 — page_table rows of idle slots point
        at page 0 of live-anywhere pages, masked off by seq_lens=0."""
        b = self.max_seqs
        ids = np.zeros((b, 1), np.int32)
        table = np.zeros((b, self.pages_per_seq), np.int32)
        lens = np.zeros((b,), np.int32)
        active = []
        for i, live in enumerate(self.slots):
            if live is None:
                # idle row: its scatter must land where no live
                # sequence reads (see __init__ — own slot range when
                # slot-aligned, the reserved scratch page otherwise)
                if self.slot_aligned:
                    table[i, :] = np.arange(
                        i * self.pages_per_seq,
                        (i + 1) * self.pages_per_seq)
                else:
                    table[i, :] = self._scratch_page
                continue
            active.append(i)
            ids[i, 0] = live.tokens[live.cached]
            table[i, :len(live.pages)] = live.pages
            lens[i] = live.cached
        return ids, table, lens, active

    def step(self) -> dict:
        """One decode frame: admit, compose, run, harvest, evict.
        Returns the frame record (also emitted as ``decode.frame``).
        The request-span instrumentation costs exactly this one
        ``BUS.enabled`` read per frame when telemetry is off
        (test-enforced)."""
        obs = BUS.enabled  # ONE check per frame gates every span stamp
        admitted = self._admit(obs)
        ids, table, lens, active = self._compose_frame()
        t0 = time.perf_counter()
        with annotate.phase_span(annotate.DECODE_PHASE):
            logits = np.asarray(self.step_fn(ids, table, lens))
        dt = time.perf_counter() - t0
        self.frame_seconds.append(dt)
        next_tokens = logits[:, 0].argmax(axis=-1).astype(np.int32) \
            if logits.ndim == 3 else logits[:, 0].astype(np.int32)
        now = time.perf_counter() if obs else 0.0
        for i in active:
            live = self.slots[i]
            live.cached += 1
            if live.cached < len(live.tokens):
                continue  # still prefilling: the next prompt token is queued
            # the model's prediction extends the sequence
            live.tokens.append(int(next_tokens[i]))
            live.generated += 1
            if obs and live.first_token_t is None:
                live.first_token_t = now  # TTFT closes here
        evicted = self._evict(obs)
        rec = {
            "frame": self.frame,
            "active": len(active),
            "admitted": admitted,
            "evicted": evicted,
            "pages_in_use": self.allocator.pages_in_use,
            "queued": len(self.queue),
            "measured_s": dt,
            "predicted_s": self.predicted_step_s,
        }
        if obs:
            from flexflow_tpu.obs.metrics import METRICS

            METRICS.histogram("decode.frame_s").observe(dt)
            BUS.emit("decode.frame", **rec)
        self.frame += 1
        return rec

    def run(self, requests: Sequence[DecodeRequest] = (),
            max_frames: int = 10_000) -> Dict[str, List[int]]:
        """Drive frames until every submitted request finished (or the
        frame cap trips — a stuck executor must fail loud, not spin).
        Returns rid -> generated token ids."""
        if requests:
            self.submit(requests)
        while (self.queue or any(s is not None for s in self.slots)):
            if self.frame >= max_frames:
                raise RuntimeError(
                    f"decode executor exceeded {max_frames} frames with "
                    f"{len(self.queue)} queued and "
                    f"{sum(s is not None for s in self.slots)} live")
            self.step()
        if BUS.enabled:
            BUS.emit("decode.summary", **self.summary())
        return dict(self.finished)

    # ------------------------------------------------------------------
    @staticmethod
    def _quantile(values, f: float):
        if not values:
            return None
        s = sorted(values)
        return s[min(len(s) - 1, int(f * (len(s) - 1)))]

    def measured_p99(self, window: int = 0) -> Optional[float]:
        """p99 of the measured frame latencies — over the trailing
        ``window`` frames when given (the CONTINUOUS drift signal a
        long-running server feeds the controller), else the whole
        run."""
        times = self.frame_seconds[-window:] if window \
            else self.frame_seconds
        return self._quantile(times, 0.99)

    def summary(self) -> dict:
        q = lambda f: self._quantile(self.frame_seconds, f)  # noqa: E731
        out = {
            "frames": self.frame,
            "completed": len(self.finished),
            "admitted": self.total_admitted,
            "evicted": self.total_evicted,
            "measured_p50_s": q(0.5),
            "measured_p99_s": q(0.99),
            "predicted_step_s": self.predicted_step_s,
        }
        recs = self.request_records
        if recs:
            # request-level currency (recorded while the bus was
            # armed): TTFT / TPOT / e2e percentiles across completions
            for key in ("ttft_s", "tpot_s", "e2e_s", "queue_s"):
                vals = [r[key] for r in recs if r.get(key) is not None]
                out[f"{key[:-2]}_p50_s"] = self._quantile(vals, 0.5)
                out[f"{key[:-2]}_p99_s"] = self._quantile(vals, 0.99)
            out["requests_recorded"] = len(recs)
        return out

    def decode_drift_report(self, threshold: float = 0.5,
                            window: int = 0):
        """Predicted-vs-measured DECODE drift: the search's p99 step
        prediction against the measured frame-latency p99 — the decode
        phase of the DriftReport family (obs/drift.py).  ``window``
        restricts the measured side to the trailing frames, turning a
        one-shot report into the continuous serve-currency signal
        (feed ``report.ratio`` — or the executor itself — to
        ``TrainingController.observe_p99`` to make it a re-search
        trigger).  None when either side is missing.  Emitted as a
        ``drift.report`` event when the bus is armed, like model.fit's
        training-side report."""
        from flexflow_tpu.obs.drift import build_drift_report

        measured = self.measured_p99(window)
        if not self.predicted_step_s or not measured:
            return None
        report = build_drift_report(
            {"total_s": self.predicted_step_s},
            measured, threshold=threshold)
        if report is not None:
            report.phases["decode"] = {
                "predicted_s": self.predicted_step_s,
                "measured_s": measured,
                "ratio": report.ratio,
            }
            if BUS.enabled:
                BUS.emit("drift.report", predicted_s=report.predicted_s,
                         measured_s=report.measured_s, ratio=report.ratio,
                         stale=report.stale, phase="decode")
        return report


def compiled_decode_step(model) -> Callable:
    """A ``step_fn`` over a COMPILED decode model: one jitted forward
    per frame, the KV-cache state dict threaded across calls (the
    caches are model state — compiler/lowering.py init_params placed
    them under the strategy's view)."""
    import jax

    compiled = model.compiled
    fn = jax.jit(
        lambda p, s, ins: compiled.apply(p, s, ins, None, False))
    box = {"state": model.state}

    def step(ids, page_table, seq_lens):
        logits, new_state = fn(
            model.params, box["state"], [ids, page_table, seq_lens])
        box["state"] = new_state
        return logits

    step.state = box  # tests inspect the threaded cache
    return step
