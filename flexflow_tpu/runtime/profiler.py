"""Profiling & tracing.

Reference parity (SURVEY.md §5 tracing/profiling):
* Legion iteration tracing → here the train step is already ONE compiled
  XLA program (jit), so "tracing" is structural; what remains is
  observability:
* per-op ``profiling`` flag gating kernel timing printfs (config.h:125)
  → ``StepProfiler`` wall-clock step timing + summary, and
  ``device_trace`` — a context manager around jax.profiler for a real
  XLA/TPU timeline (viewable in TensorBoard/Perfetto);
* on-device op cost measurement (model.cu:38-74 warmup+repeat cuda
  events) → ``measure_operator_cost``: jit the op's forward alone and
  time it on the real chip — used to calibrate the analytic cost model.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

import numpy as np


class StepProfiler:
    """Wall-clock per-step timing with compile-step exclusion."""

    def __init__(self):
        self.step_times: List[float] = []
        self._t_last: Optional[float] = None

    def start_step(self) -> None:
        self._t_last = time.perf_counter()

    def end_step(self) -> None:
        if self._t_last is not None:
            self.step_times.append(time.perf_counter() - self._t_last)
            self._t_last = None

    def summary(self, skip_first: int = 1) -> Dict[str, float]:
        """Stats excluding the first (compile) steps."""
        ts = np.asarray(self.step_times[skip_first:] or self.step_times)
        if len(ts) == 0:
            return {"steps": 0}
        return {
            "steps": len(ts),
            "mean_s": float(ts.mean()),
            "p50_s": float(np.percentile(ts, 50)),
            "p95_s": float(np.percentile(ts, 95)),
            "max_s": float(ts.max()),
        }

    def __str__(self) -> str:
        s = self.summary()
        if not s.get("steps"):
            return "StepProfiler(no steps)"
        return (f"steps={s['steps']} mean={s['mean_s']*1e3:.2f}ms "
                f"p50={s['p50_s']*1e3:.2f}ms p95={s['p95_s']*1e3:.2f}ms")


@contextlib.contextmanager
def device_trace(logdir: str):
    """XLA device timeline trace (TensorBoard `Profile` tab / Perfetto).
    The TPU analog of the reference's `-lg:prof` external tooling."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def measure_operator_cost(op, batch_inputs=None,
                          warmup: int = 2, repeats: int = 5,
                          weight_shapes=None) -> float:
    """Median wall seconds of one jitted forward of ``op`` on the real
    device (reference: Op::measure_operator_cost + model.cu:38-74).

    Builds zero inputs from the op's input shapes unless given; weights
    are initialized via the op's specs (``weight_shapes`` overrides
    per-weight shapes — calibration probes ops at their per-SHARD
    shapes, see search/calibration.py). Results feed the CalibrationTable
    consulted by CostModel.op_cost before its roofline fallback.
    """
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.ops.base import LoweringContext

    if batch_inputs is None:
        batch_inputs = [
            jnp.zeros(s.sizes, s.dtype.to_numpy()) for s in op.input_shapes
        ]
    key = jax.random.key(0)
    weights = {}
    for i, ws in enumerate(getattr(op, "_weight_specs", ())):
        shape = (weight_shapes or {}).get(ws.name, ws.shape)
        weights[ws.name] = ws.initializer.init(
            jax.random.fold_in(key, i), shape, ws.dtype.to_numpy()
        )
    state_in = {}
    for spec in (op.state_specs() if getattr(op, "state_specs", None) else ()):
        name, shape, dtype, fill = spec
        state_in[f"{op.name}/{name}"] = jnp.full(shape, fill, dtype)

    def fwd(inputs, weights):
        ctx = LoweringContext(
            compute_dtype=jnp.float32, train=False, rng=jax.random.key(1),
            seq_length=-1, state_in=dict(state_in), mesh=None,
        )
        outs = op.forward(ctx, inputs, weights)
        return [jnp.sum(o) for o in outs]  # force materialization

    jfwd = jax.jit(fwd)
    for _ in range(warmup):
        jax.block_until_ready(jfwd(batch_inputs, weights))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(jfwd(batch_inputs, weights))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))
