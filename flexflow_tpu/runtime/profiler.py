"""Profiling & tracing.

Reference parity (SURVEY.md §5 tracing/profiling):
* Legion iteration tracing → here the train step is already ONE compiled
  XLA program (jit), so "tracing" is structural; what remains is
  observability:
* per-op ``profiling`` flag gating kernel timing printfs (config.h:125)
  → ``StepProfiler`` wall-clock step timing + summary, and
  ``device_trace`` — a context manager around jax.profiler for a real
  XLA/TPU timeline (viewable in TensorBoard/Perfetto);
* on-device op cost measurement (model.cu:38-74 warmup+repeat cuda
  events) → ``measure_operator_cost``: jit the op's forward alone and
  time it on the real chip — used to calibrate the analytic cost model.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

import numpy as np


class StepProfiler:
    """Wall-clock per-step timing with compile-step exclusion, plus
    named host-side phases within a step (``dispatch``/``wait`` in
    model.fit) — the measured side of the obs DriftReport."""

    def __init__(self):
        self.step_times: List[float] = []
        self.phase_times: Dict[str, List[float]] = {}
        self._t_last: Optional[float] = None
        self._phase_t0: Dict[str, float] = {}

    def start_step(self) -> None:
        self._t_last = time.perf_counter()

    def end_step(self) -> None:
        if self._t_last is not None:
            self.step_times.append(time.perf_counter() - self._t_last)
            self._t_last = None

    def start_phase(self, name: str) -> None:
        self._phase_t0[name] = time.perf_counter()

    def end_phase(self, name: str) -> None:
        t0 = self._phase_t0.pop(name, None)
        if t0 is not None:
            self.phase_times.setdefault(name, []).append(
                time.perf_counter() - t0)

    def summary(self, skip_first: int = 1) -> Dict[str, float]:
        """Stats excluding the first (compile) steps.  When every
        recorded step WOULD be skipped the stats still cover all steps
        but say so via ``includes_compile`` — silently folding the
        compile step back in used to misreport single-step runs as
        steady-state."""
        kept = self.step_times[skip_first:]
        includes_compile = (
            not kept and bool(self.step_times) and skip_first > 0
        )
        ts = np.asarray(kept or self.step_times)
        if len(ts) == 0:
            return {"steps": 0}
        return {
            "steps": len(ts),
            "mean_s": float(ts.mean()),
            "p50_s": float(np.percentile(ts, 50)),
            "p95_s": float(np.percentile(ts, 95)),
            "max_s": float(ts.max()),
            "includes_compile": includes_compile,
        }

    def phase_summary(self, skip_first: int = 1) -> Dict[str, Dict[str, float]]:
        """Per-phase stats with the same compile-step exclusion (and
        the same ``includes_compile`` honesty flag) as ``summary``."""
        out: Dict[str, Dict[str, float]] = {}
        for name, times in self.phase_times.items():
            kept = times[skip_first:]
            includes_compile = not kept and bool(times) and skip_first > 0
            ts = np.asarray(kept or times)
            if len(ts) == 0:
                continue
            out[name] = {
                "count": len(ts),
                "mean_s": float(ts.mean()),
                "total_s": float(ts.sum()),
                "includes_compile": includes_compile,
            }
        return out

    def __str__(self) -> str:
        s = self.summary()
        if not s.get("steps"):
            return "StepProfiler(no steps)"
        return (f"steps={s['steps']} mean={s['mean_s']*1e3:.2f}ms "
                f"p50={s['p50_s']*1e3:.2f}ms p95={s['p95_s']*1e3:.2f}ms")


@contextlib.contextmanager
def device_trace(logdir: str):
    """XLA device timeline trace (TensorBoard `Profile` tab / Perfetto).
    The TPU analog of the reference's `-lg:prof` external tooling.
    While the capture is live, the obs phase/lane annotations are armed
    (obs/annotate.py) so the trace carries the ``ff.phase/*`` /
    ``ff.lane/*`` tags ``obs/trace_ingest.py`` matches back to the
    simulator's predicted lanes."""
    import jax

    from flexflow_tpu.obs import annotate

    jax.profiler.start_trace(logdir)
    annotate.arm()
    try:
        yield
    finally:
        annotate.disarm()
        jax.profiler.stop_trace()


def measure_operator_cost(op, batch_inputs=None,
                          warmup: int = 2, repeats: int = 5,
                          weight_shapes=None):
    """Median wall seconds of one jitted forward of ``op`` on the real
    device, or None when the op cannot be measured meaningfully: no
    floating input/weight to thread a timing dependence through, or the
    op is cheaper than timer noise (a clamped floor would mark it free
    in the calibration table).  Reference: Op::measure_operator_cost +
    model.cu:38-74.

    Builds zero inputs from the op's input shapes unless given; weights
    are initialized via the op's specs (``weight_shapes`` overrides
    per-weight shapes — calibration probes ops at their per-SHARD
    shapes, see search/calibration.py). Results feed the CalibrationTable
    consulted by CostModel.op_cost before its roofline fallback.
    """
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.ops.base import LoweringContext

    if batch_inputs is None:
        batch_inputs = [
            jnp.zeros(s.sizes, s.dtype.to_numpy()) for s in op.input_shapes
        ]
    key = jax.random.key(0)
    weights = {}
    for i, ws in enumerate(getattr(op, "_weight_specs", ())):
        shape = (weight_shapes or {}).get(ws.name, ws.shape)
        weights[ws.name] = ws.initializer.init(
            jax.random.fold_in(key, i), shape, ws.dtype.to_numpy()
        )
    state_in = {}
    for spec in (op.state_specs() if getattr(op, "state_specs", None) else ()):
        name, shape, dtype, fill = spec
        state_in[f"{op.name}/{name}"] = jnp.full(shape, fill, dtype)

    # Through a remote-device tunnel (axon) a single dispatch costs tens
    # of ms and block_until_ready can hang outright, so per-op timing
    # must (a) fence with a host scalar readback and (b) amortize: run
    # the op N times inside ONE jitted lax.scan with a serial data
    # dependence through the carry, then difference two scan lengths —
    # both the round-trip latency and the dispatch cost cancel.
    # Serial dependence: perturb the first floating input (or weight)
    # by a scalar derived from the previous iteration's outputs.
    tgt_kind, tgt_key = None, None
    for i, x in enumerate(batch_inputs):
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            tgt_kind, tgt_key = "input", i
            break
    if tgt_kind is None:
        for name, w in weights.items():
            if jnp.issubdtype(jnp.asarray(w).dtype, jnp.floating):
                tgt_kind, tgt_key = "weight", name
                break
    if tgt_kind is None:
        # no floating leaf to thread the carry through: the scan body
        # would be loop-invariant, XLA would hoist the op out, and the
        # "measurement" would be the 1e-9 floor — poisoning the
        # calibration table with a free op.  Decline instead; callers
        # keep the analytic roofline for such (integer-only) ops.
        return None

    def make(n):
        def fn(inputs, weights):
            def body(c, _):
                ins = list(inputs)
                ws = dict(weights)
                if tgt_kind == "input":
                    ins[tgt_key] = ins[tgt_key] + c.astype(ins[tgt_key].dtype)
                elif tgt_kind == "weight":
                    ws[tgt_key] = ws[tgt_key] + c.astype(ws[tgt_key].dtype)
                ctx = LoweringContext(
                    compute_dtype=jnp.float32, train=False,
                    rng=jax.random.key(1), seq_length=-1,
                    state_in=dict(state_in), mesh=None,
                )
                outs = op.forward(ctx, ins, ws)
                s = sum(jnp.sum(o).astype(jnp.float32) for o in outs)
                # tiny magnitude keeps the perturbation from changing
                # the op's numeric regime while preserving dependence
                return s * jnp.float32(1e-30), None

            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
            return c

        return jax.jit(fn)

    def run_pair(n1, n2):
        j1, j2 = make(n1), make(n2)
        for _ in range(max(1, warmup)):
            float(j1(batch_inputs, weights))
            float(j2(batch_inputs, weights))
        diffs = []
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            float(j1(batch_inputs, weights))
            t1 = time.perf_counter()
            float(j2(batch_inputs, weights))
            diffs.append((time.perf_counter() - t1) - (t1 - t0))
        return float(np.median(diffs)), n2 - n1

    # Adaptive scan length: cheap ops (softmax, layernorm, pool, topk)
    # run below timer noise at the base length, which used to leave
    # them UNMEASURED (the round-3 calibration table had no record for
    # any of them).  Scale the iteration-count difference until the
    # measured delta is resolvable, then trust the per-iteration time.
    span = 5 * max(1, repeats)
    per_iter = None
    for scale in (1, 16, 256):
        delta, iters = run_pair(2, 2 + span * scale)
        if delta > 2e-5:  # well above perf_counter noise
            return delta / iters
        if delta > 0:
            per_iter = delta / iters
    # never resolvable above noise: keep the best positive estimate, or
    # decline (a clamped floor would mark the op free and the search
    # would over-place work on it)
    return per_iter
