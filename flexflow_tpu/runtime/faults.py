"""Deterministic fault injection for the always-on training loop.

The controller's recovery story (runtime/controller.py) is only
testable if the faults themselves are reproducible: every injector
here is a pure function of the ``FaultPlan``'s seed + schedule, so two
runs with the same plan inject byte-identical failures at the same
steps — the property the end-to-end recovery tests assert.

Fault kinds (the four failure classes the ISSUE names):

* ``calibration_drift`` — the world changed under the cost model:
  scales every measured record of the persisted CalibrationTable by a
  seeded drift factor and marks the file stale (the same signal a
  measured DriftReport produces), so the controller's signature watch
  sees a rotation and triggers the warm re-search + hot swap.
* ``device_loss``       — preemption / elastic shrink: ``survivors``
  devices remain.  The controller re-searches for the surviving set
  and re-shards the live state onto the shrunken mesh.
* ``collective_failure``— a transient wire fault in the searched comm
  plan: raises ``TransientCollectiveError`` for ``failures``
  consecutive attempts at the armed step.  Bounded retry/backoff is
  the controller's job; when the fault outlives the retry budget the
  controller falls back to the monolithic fp32 sync path (which this
  injector, modeling a searched-plan-specific fault, does not touch).
* ``corrupt_checkpoint``— a torn write on shared storage: truncates
  the newest on-disk ``step_N`` snapshot so the next restore must
  detect the manifest mismatch and fall back to the newest complete
  step (runtime/checkpoint.py's completeness check).

Env-var spelling (documented in README "Fault tolerance"):

    FLEXFLOW_TPU_FAULTS="calibration_drift@3,device_loss@6:4"
    FLEXFLOW_TPU_FAULT_SEED=7

``kind@step[:arg]`` comma list — arg is ``survivors`` for device_loss
and ``failures`` for collective_failure.
"""

from __future__ import annotations

import json
import os
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from flexflow_tpu.obs.flight import FLIGHT


def _post_mortem(fault: "Fault") -> None:
    """Every injection dumps the flight ring (last-N events + the
    in-flight requests' open spans) — the injected failure is exactly
    the rehearsal for the unplanned one, so it must exercise the
    post-mortem path too.  A no-op unless a dump dir is armed
    (``FLEXFLOW_TPU_FLIGHT_DIR`` / ``FLIGHT.configure``)."""
    FLIGHT.dump(reason=f"fault-{fault.kind}-step{fault.step}")


FAULT_KINDS = (
    "calibration_drift",
    "device_loss",
    "collective_failure",
    "corrupt_checkpoint",
    # the serving currency drifted: a seeded measured-p99 vs
    # searched-p99 ratio fed to the controller's observe_p99 watch —
    # past threshold it becomes a "p99_drift" re-search trigger
    "p99_drift",
)


class TransientCollectiveError(RuntimeError):
    """A collective in the searched comm plan failed; retryable."""


@dataclass
class Fault:
    kind: str
    step: int
    # kind-specific argument: survivors (device_loss), failures
    # (collective_failure); unused otherwise
    arg: Optional[int] = None
    fired: bool = False

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} "
                f"(must be one of {FAULT_KINDS})")
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")
        if (self.kind in ("collective_failure", "device_loss")
                and self.arg is not None and self.arg < 1):
            # a zero failure budget / zero survivors would be accepted
            # and then silently never fire (or blow up mid-run) — a
            # recovery test built on such a plan would test nothing
            raise ValueError(
                f"{self.kind} arg must be >= 1, got {self.arg}")


@dataclass
class FaultPlan:
    """A seeded, ordered fault schedule.  ``due(step)`` hands out the
    faults armed for that step (once each); the kind-specific helpers
    below actually inject them."""

    faults: List[Fault] = field(default_factory=list)
    seed: int = 0

    def __post_init__(self):
        # the drift factors are PRE-DRAWN from the seed at construction
        # (one per fault, in schedule order): injection order can then
        # never perturb determinism, and Date-free replays are exact
        rng = random.Random(self.seed)
        self._draws = {
            id(f): 1.5 + rng.random() * 2.0 for f in self.faults
        }
        # collective_failure remaining-attempt counters
        self._remaining: Dict[int, int] = {
            id(f): (f.arg if f.arg is not None else 1)
            for f in self.faults if f.kind == "collective_failure"
        }

    @staticmethod
    def parse(spec: str, seed: int = 0) -> "FaultPlan":
        """``kind@step[:arg]`` comma list -> FaultPlan."""
        faults = []
        for part in (p.strip() for p in spec.split(",")):
            if not part:
                continue
            if "@" not in part:
                raise ValueError(
                    f"fault {part!r} must be kind@step[:arg]")
            kind, rest = part.split("@", 1)
            arg: Optional[int] = None
            if ":" in rest:
                step_s, arg_s = rest.split(":", 1)
                arg = int(arg_s)
            else:
                step_s = rest
            faults.append(Fault(kind=kind.strip(), step=int(step_s),
                                arg=arg))
        return FaultPlan(faults=faults, seed=seed)

    @staticmethod
    def from_env() -> Optional["FaultPlan"]:
        """FLEXFLOW_TPU_FAULTS / FLEXFLOW_TPU_FAULT_SEED, or None."""
        spec = os.environ.get("FLEXFLOW_TPU_FAULTS", "")
        if not spec:
            return None
        return FaultPlan.parse(
            spec, seed=int(os.environ.get("FLEXFLOW_TPU_FAULT_SEED", "0")))

    # ------------------------------------------------------------------
    def due(self, step: int) -> List[Fault]:
        """Unfired faults scheduled at ``step`` (collective failures
        stay live until their attempt budget drains)."""
        out = []
        for f in self.faults:
            if f.step != step:
                continue
            if f.kind == "collective_failure":
                if self._remaining.get(id(f), 0) > 0:
                    out.append(f)
            elif not f.fired:
                out.append(f)
        return out

    # ---- injectors ----------------------------------------------------
    def inject_calibration_drift(self, fault: Fault,
                                 calibration_file: str) -> float:
        """Scale every measured record by the fault's seeded factor and
        mark the table stale in place.  Returns the factor applied (the
        drift ratio a DriftReport would have reported)."""
        factor = self._draws[id(fault)]
        with open(calibration_file) as f:
            data = json.load(f)
        for row in data.get("records", []):
            row["seconds"] = float(row["seconds"]) * factor
        for row in data.get("clusters", []):
            row["seconds"] = float(row["seconds"]) * factor
        data["stale"] = True
        data["stale_ratio"] = factor
        with open(calibration_file, "w") as f:
            json.dump(data, f, indent=1)
        fault.fired = True
        _post_mortem(fault)
        return factor

    def inject_p99_drift(self, fault: Fault) -> float:
        """The measured serving p99 drifted off the searched
        prediction: returns the seeded measured/predicted ratio
        (1.5x–3.5x — always past the default 0.5 drift threshold, so a
        scheduled p99_drift fault deterministically trips the
        controller's observe_p99 watch)."""
        fault.fired = True
        _post_mortem(fault)
        return self._draws[id(fault)]

    def inject_device_loss(self, fault: Fault, num_devices: int) -> int:
        """Surviving device count after the loss (>= 1)."""
        fault.fired = True
        survivors = fault.arg if fault.arg is not None else max(
            1, num_devices // 2)
        if not 1 <= survivors <= num_devices:
            raise ValueError(
                f"device_loss survivors={survivors} not in "
                f"[1, {num_devices}]")
        _post_mortem(fault)
        return survivors

    def check_collective(self, fault: Fault) -> None:
        """One attempt at the armed step: raises while the fault's
        failure budget lasts, then lets the step through.  The caller
        passes only faults whose searched comm plan is still live —
        after the monolithic fp32 fallback this is not consulted."""
        rem = self._remaining.get(id(fault), 0)
        if rem > 0:
            self._remaining[id(fault)] = rem - 1
            _post_mortem(fault)
            raise TransientCollectiveError(
                f"injected collective failure at step {fault.step} "
                f"({rem - 1} failure(s) remaining)")
        fault.fired = True

    def neutralize(self, fault: Fault) -> None:
        """Retire a collective fault: the monolithic fp32 fallback
        removed the comm path the fault models, so its remaining
        failure budget is void."""
        self._remaining[id(fault)] = 0
        fault.fired = True

    def inject_corrupt_checkpoint(self, fault: Fault,
                                  directory: str) -> Optional[str]:
        """Truncate the newest ``step_N`` snapshot (drop the payload
        behind the manifest) — the torn-write case restore must detect.
        Returns the corrupted path, or None when nothing exists."""
        fault.fired = True
        _post_mortem(fault)
        from flexflow_tpu.runtime.checkpoint import CheckpointManager

        mgr = CheckpointManager(directory)
        step = mgr.latest_step()
        if step is None:
            return None
        path = mgr._step_dir(step)
        for name in ("arrays.npz", "tree"):
            victim = os.path.join(path, name)
            if os.path.isfile(victim):
                with open(victim, "r+b") as f:
                    f.truncate(max(0, os.path.getsize(victim) // 2))
            elif os.path.isdir(victim):
                import shutil

                shutil.rmtree(victim)
        return path
