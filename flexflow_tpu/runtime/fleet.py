"""N-replica serving fleet behind a searched SLO-aware router.

``search/fleet.py`` decides the fleet shape — how many replica blocks,
which strategy each, and which per-SLO-class routing fractions — in
the per-class p99 currency.  This module EXECUTES that decision: N
``ContinuousBatchingExecutor`` replicas behind a router whose dispatch
follows the searched fractions deterministically.

Routing is deficit-style proportional assignment: per (class, replica)
the router tracks how many requests it has sent, and each arrival goes
to the replica minimizing ``(count + 1) / fraction`` over the replicas
its class routes to — the discrete sequence whose running shares
converge to the searched fractions from the very first requests (a
weighted round-robin, not a sampler).  Exact ties break through a
seeded ``random.Random`` so a trace replayed under the same seed maps
every request to the same replica, bit-reproducibly (the routing
determinism test).

Admission stays the replicas' own: each ``ContinuousBatchingExecutor``
keeps its priority lanes, deadline expiry and preemption
(runtime/decode.py) — the router decides WHERE a request queues, the
replica decides WHEN it runs.

Wall-clock semantics: replicas are independent once routed, so
``run()`` drains each replica to completion separately — every
replica's measured spans are self-consistent on its own clock, and
cross-replica concurrency (real fleets run replicas on disjoint
devices) is represented by NOT serializing one replica's frames into
another's latencies.  ``step()`` advances every live replica one frame
for interleaved/elastic operation under the controller.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.obs.events import BUS
from flexflow_tpu.obs.tracing import TRACER
from flexflow_tpu.runtime.decode import (
    ContinuousBatchingExecutor,
    DecodeRequest,
    SLOClass,
)


class FleetExecutor:
    """Route requests over N decode replicas per searched per-class
    fractions; roll per-replica request records up into fleet-level
    per-class percentiles."""

    def __init__(self, replicas: Sequence[ContinuousBatchingExecutor],
                 routing: Dict[str, Sequence[float]], *,
                 slo_classes: Optional[Sequence[SLOClass]] = None,
                 seed: int = 0):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: List[ContinuousBatchingExecutor] = list(replicas)
        for i, ex in enumerate(self.replicas):
            if ex.replica_label is None:
                ex.replica_label = str(i)
        k = len(self.replicas)
        self.routing: Dict[str, Tuple[float, ...]] = {}
        for name, fr in routing.items():
            fr = tuple(float(v) for v in fr)
            if len(fr) != k:
                raise ValueError(
                    f"routing row {name!r} has {len(fr)} fractions for "
                    f"{k} replicas")
            tot = sum(fr)
            if tot <= 0:
                raise ValueError(
                    f"routing row {name!r} routes nowhere: {fr}")
            self.routing[name] = tuple(v / tot for v in fr)
        self.slo_classes: Dict[str, SLOClass] = {
            c.name: c for c in (slo_classes or ())}
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        # deficit counters: class -> per-replica dispatched counts
        self._sent: Dict[str, List[int]] = {}
        self.assignments: Dict[str, int] = {}  # rid -> replica index

    # ------------------------------------------------------------------
    def _fractions(self, slo: str) -> Tuple[float, ...]:
        fr = self.routing.get(slo)
        if fr is None:
            fr = self.routing.get("standard")
        if fr is None:
            k = len(self.replicas)
            fr = tuple(1.0 / k for _ in range(k))
        return fr

    def route(self, req: DecodeRequest) -> int:
        """The replica this request dispatches to (deficit-minimizing
        over its class's searched fractions, seeded tie-break)."""
        slo = req.slo or "standard"
        fr = self._fractions(slo)
        sent = self._sent.setdefault(slo, [0] * len(self.replicas))
        best = None
        ties: List[int] = []
        for r, f in enumerate(fr):
            if f <= 0.0:
                continue
            score = (sent[r] + 1) / f
            if best is None or score < best:
                best, ties = score, [r]
            elif score == best:
                ties.append(r)
        pick = ties[0] if len(ties) == 1 \
            else ties[self._rng.randrange(len(ties))]
        sent[pick] += 1
        return pick

    def submit(self, requests: Sequence[DecodeRequest]) -> None:
        obs = BUS.enabled  # one check per submit batch
        tr = TRACER.enabled  # ditto for the request span tree
        for req in requests:
            i = self.route(req)
            self.assignments[req.rid] = i
            if tr:
                self._trace_route(req, i)
            self.replicas[i].submit([req])
            if obs:
                BUS.emit("fleet.route", rid=req.rid, replica=i,
                         slo=req.slo or "standard")

    def _trace_route(self, req: DecodeRequest, replica: int) -> None:
        """Mint the request's trace at the FRONT (route time — the
        first component that sees the request) and stamp the router's
        decision as a zero-duration ``route`` child with the replica
        tag; the replica's submit then finds the mapping and only adds
        the queue/prefill/decode children."""
        tid = TRACER.request_root(req.rid, slo=req.slo or "standard")
        TRACER.annotate(tid, "route", parent="request", replica=replica,
                        label=self.replicas[replica].replica_label)

    # ------------------------------------------------------------------
    def step(self) -> int:
        """One frame on every replica that has live or queued work —
        the interleaved mode the controller's elastic loop drives.
        Returns how many replicas stepped."""
        stepped = 0
        for ex in self.replicas:
            if ex.queue or any(s is not None for s in ex.slots):
                ex.step()
                stepped += 1
        return stepped

    def run(self, requests: Sequence[DecodeRequest] = (),
            max_frames: int = 10_000) -> Dict[str, List[int]]:
        """Route then drain every replica to completion.  Replicas
        drain INDEPENDENTLY (disjoint devices run concurrently in a
        real fleet): the whole trace is routed first (deficit routing
        sees the global arrival order), then each replica's batch is
        submitted immediately before ITS drain — enqueue stamps land on
        the replica's own clock, so one replica's frames never inflate
        another's queue/TTFT spans.  Returns rid -> generated token ids
        across the fleet."""
        out: Dict[str, List[int]] = {}
        if requests:
            obs = BUS.enabled  # one check per run
            tr = TRACER.enabled
            per_replica: List[List[DecodeRequest]] = \
                [[] for _ in self.replicas]
            for req in requests:
                i = self.route(req)
                self.assignments[req.rid] = i
                per_replica[i].append(req)
                if tr:
                    self._trace_route(req, i)
                if obs:
                    BUS.emit("fleet.route", rid=req.rid, replica=i,
                             slo=req.slo or "standard")
            for ex, batch in zip(self.replicas, per_replica):
                if batch:
                    ex.submit(batch)
                out.update(ex.run(max_frames=max_frames))
        else:
            for ex in self.replicas:
                out.update(ex.run(max_frames=max_frames))
        return out

    # ------------------------------------------------------------------
    @property
    def request_records(self) -> List[dict]:
        """Per-replica records merged in replica order (stable — the
        roll-up quantiles are order-independent, determinism tests
        compare the merged list directly)."""
        merged: List[dict] = []
        for i, ex in enumerate(self.replicas):
            for rec in ex.request_records:
                merged.append(dict(rec, replica=i))
        return merged

    def measured_request_p99(self, metric: str = "ttft_s",
                             slo: Optional[str] = None,
                             window: int = 0) -> Optional[float]:
        """Fleet-level per-class request-latency quantile: the merged
        per-replica completions, each class watched at its own
        quantile — the measured side ``TrainingController.
        observe_fleet`` compares against the proposal's predictions."""
        recs = [r for r in self.request_records
                if r.get("phase") == "finish"
                and (slo is None or r.get("slo") == slo)
                and r.get(metric) is not None]
        if window:
            recs = recs[-window:]
        cls = self.slo_classes.get(slo) if slo else None
        return ContinuousBatchingExecutor._quantile(
            [r[metric] for r in recs], cls.quantile if cls else 0.99)

    def summary(self) -> dict:
        """Fleet roll-up: per-replica executor summaries plus merged
        per-class p50/p99 across the whole fleet."""
        q = ContinuousBatchingExecutor._quantile
        recs = [r for r in self.request_records
                if r.get("phase") == "finish"]
        by_class: Dict[str, List[dict]] = {}
        for r in recs:
            by_class.setdefault(r.get("slo", "standard"), []).append(r)
        out = {
            "replicas": len(self.replicas),
            "routing": {c: list(fr)
                        for c, fr in sorted(self.routing.items())},
            "completed": len(recs),
            "per_replica": [ex.summary() for ex in self.replicas],
            "slo_classes": {
                name: {
                    "completed": len(rs),
                    "ttft_p50_s": q([r["ttft_s"] for r in rs
                                     if r.get("ttft_s") is not None],
                                    0.5),
                    "ttft_p99_s": q([r["ttft_s"] for r in rs
                                     if r.get("ttft_s") is not None],
                                    0.99),
                    "e2e_p50_s": q([r["e2e_s"] for r in rs
                                    if r.get("e2e_s") is not None],
                                   0.5),
                    "e2e_p99_s": q([r["e2e_s"] for r in rs
                                    if r.get("e2e_s") is not None],
                                   0.99),
                }
                for name, rs in sorted(by_class.items())
            },
        }
        return out
