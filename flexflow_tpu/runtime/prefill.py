"""Chunked prefill: the prompt path off the decode loop (ROADMAP
item 2 — "the serving tier's prompt path stops riding the decode
frame").

PR 10's executor admitted prompts token-by-token through the decode
graph: one full decode frame per prompt token, so TTFT paid
``len(prompt)`` frame dispatches.  This module builds the batched KV
writer: the prompt's causal forward runs once per C-token CHUNK (C a
config knob, ``FFConfig.prefill_chunk``) and scatters the chunk's K/V
directly into the sequence's page-pool pages, after which the sequence
joins the decode loop at its LAST prompt token — the first generated
token still comes out of the decode graph, so the chunked path is
token-identical to the prefill-via-decode oracle (test-enforced across
ragged prompt lengths).

The chunk program is derived FROM THE DECODE GRAPH itself, not from a
separately-built prefill model: every decode-family op has a natural
C-token semantics (embeddings/dense/LN/add are position-wise;
``DecodeAttentionOp.forward_chunk`` is the prefix+causal-chunk
attention with the batched scatter), so prefill and decode trivially
share ONE parameter set — the decode model's params — and the caches
are populated under whatever sharding the strategy's
``state_shardings`` placed them with (the chunk update is a jitted
function of the placed state, so XLA keeps the pool's sharding).  The
separately-searched ``build_gpt_prefill`` graph is what the
DISAGGREGATION search places (search/disaggregation.py);
``prefill_weight_bridge`` proves its parameter set corresponds
name-for-name (and shape-for-shape) to the decode graph's, which is
what lets that placement claim a shared parameter set too (SHD165).

Positions past the prompt (the fixed-shape chunk's pad tail) are
clamped into the sequence's own page allotment: a pad write lands at a
FUTURE position, and the decode loop rewrites every position in the
frame that first reads it, so pad garbage is dead by construction — no
masking, no dynamic shapes, one compiled program per chunk size.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flexflow_tpu.core.optype import OperatorType
from flexflow_tpu.ops.base import LoweringContext
from flexflow_tpu.ops.inout import InputOp


def run_chunked_prefill(prefill_fn: Callable, tokens: Sequence[int],
                        pages: Sequence[int], *, chunk: int, cap: int,
                        start: int = 0,
                        trace_id: Optional[str] = None) -> int:
    """Drive the chunk writer over a prompt: write ``tokens[start:-1]``
    into the sequence's pages in ``ceil((len-1-start)/chunk)``
    fixed-shape passes (the decode loop then starts at the LAST
    token).  Returns the number of chunk passes paid.

    ``start`` is the prefix-sharing skip-ahead (runtime/decode.py):
    the first ``start`` tokens already live in pages the admission
    claimed from the trie (or copied on divergence), so the writer
    begins at the first divergent token — its chunk windows simply
    shift, positions stay absolute, and the already-cached pages are
    never touched.

    Pad positions past the prompt clamp into the sequence's own
    allotment (``cap - 1``): a pad write lands at a FUTURE position the
    decode loop rewrites before any frame reads it (see module
    docstring) — no masking, no dynamic shapes.

    When ``trace_id`` names a live request trace, each pass closes as
    one ``prefill.chunk`` child span under the open ``prefill`` span —
    the per-chunk attribution the request span tree renders."""
    n_pre = len(tokens) - 1
    if n_pre - start <= 0:
        return 0
    tracer = None
    if trace_id is not None:
        from flexflow_tpu.obs.tracing import TRACER as tracer
    table = np.asarray(pages, np.int32)[None, :]  # [1, P]
    chunks = 0
    for c0 in range(start, n_pre, chunk):
        if tracer is not None:
            tracer.begin(trace_id, "prefill.chunk", parent="prefill",
                         c0=c0)
        ids = np.zeros((1, chunk), np.int32)
        valid = min(chunk, n_pre - c0)
        ids[0, :valid] = tokens[c0:c0 + valid]
        pos = np.minimum(c0 + np.arange(chunk), cap - 1)
        prefill_fn(ids, pos[None, :].astype(np.int32), table)
        if tracer is not None:
            tracer.end(trace_id, "prefill.chunk", tokens=valid)
        chunks += 1
    return chunks


def _decode_guids(graph) -> List[int]:
    return [n.guid for n in graph.topo_order()
            if n.op.op_type == OperatorType.DECODE_ATTENTION]


def prefill_io_nodes(graph) -> Tuple[int, int, int]:
    """(token_ids, page_table, seq_lens) InputOp guids of a
    decode-family graph, identified structurally from the first decode
    op's own bindings (input 1 = page_table, input 2 = seq_lens) —
    never by name."""
    dec = _decode_guids(graph)
    if not dec:
        raise ValueError("graph has no DecodeAttentionOp — not a "
                         "decode-family graph")
    by_idx = {e.dst_idx: e.src for e in graph.in_edges[dec[0]]}
    pt_guid, sl_guid = by_idx[1], by_idx[2]
    inputs = [n.guid for n in graph.topo_order()
              if isinstance(n.op, InputOp)]
    tok = [g for g in inputs if g not in (pt_guid, sl_guid)]
    if len(tok) != 1:
        raise ValueError(
            f"decode-family graph must have exactly 3 inputs "
            f"(token_ids, page_table, seq_lens); found {len(inputs)}")
    return tok[0], pt_guid, sl_guid


def build_chunk_forward(graph, compute_dtype) -> Callable:
    """A pure function ``(params, state, ids [B, C], positions [B, C],
    page_table [B, P]) -> new_state`` lowering the decode graph for a
    C-token chunk.  Position-wise ops run their ordinary ``forward``;
    the seq_lens->pos_ids reshape becomes identity (positions already
    arrive [B, C]); decode attention takes its chunk twin.  Everything
    downstream of the last cache write (final LN, lm_head) is dead code
    the jit prunes — prefill produces STATE, not logits."""
    tok_guid, pt_guid, sl_guid = prefill_io_nodes(graph)
    dec_guids = set(_decode_guids(graph))
    topo = graph.topo_order()
    for node in topo:  # fail at build time, not inside the jit
        ot = node.op.op_type
        if ot == OperatorType.RESHAPE:
            srcs = {e.src for e in graph.in_edges[node.guid]}
            if srcs != {sl_guid}:
                raise NotImplementedError(
                    f"chunked prefill only supports the seq_lens "
                    f"pos_ids reshape; {node.op.name!r} reshapes "
                    f"something else")

    def fwd(params, state, ids, positions, page_table):
        ctx = LoweringContext(compute_dtype=compute_dtype, train=False,
                              state_in=state)
        values: Dict[Tuple[int, int], object] = {}
        for node in topo:
            op = node.op
            if isinstance(op, InputOp):
                values[(node.guid, 0)] = {
                    tok_guid: ids, pt_guid: page_table,
                    sl_guid: positions}[node.guid]
                continue
            edges = sorted(graph.in_edges[node.guid],
                           key=lambda e: e.dst_idx)
            ins = [values[(e.src, e.src_idx)] for e in edges]
            weights = params.get(op.name, {})
            if node.guid in dec_guids:
                outs = op.forward_chunk(ctx, ins, weights)
            elif op.op_type == OperatorType.RESHAPE:
                outs = [ins[0]]  # positions already [B, C]
            else:
                outs = op.forward(ctx, ins, weights)
            for i, y in enumerate(outs):
                values[(node.guid, i)] = y
        new_state = dict(state)
        new_state.update(ctx.state_out)
        return new_state

    return fwd


def prefill_weight_bridge(prefill_graph, decode_graph) -> Dict[str, str]:
    """The weight-correspondence bridge: prove the separately-built
    prefill graph (models/decode.py ``build_gpt_prefill``) and the
    decode graph share ONE parameter set, weight for weight.  Returns
    ``{"prefill_op/w": "decode_op/w"}`` for every prefill weight, or
    raises ``ValueError`` naming the first break.

    The rule is name correspondence under shape agreement — the same
    rule ``weight_fold_key`` initializes by, so a bridged pair draws
    IDENTICAL values for the same seed.  One deliberate exception: the
    positional table, where the prefill graph's ``seq_len`` rows are a
    PREFIX of the decode graph's ``max_seq_len`` rows (positions are
    positions); the bridge accepts ``prefill_rows <= decode_rows`` with
    agreeing trailing dims there, and exact shape equality everywhere
    else.  The disaggregation lint (SHD165) runs this to refuse
    placements whose two blocks would not actually share parameters."""
    dec_ops = {n.op.name: n.op for n in decode_graph.topo_order()
               if n.op._weight_specs}
    # the decode side's position count — the ONLY row count the prefix
    # rule may target (a vocab mismatch must stay a hard error)
    dec_nodes = [decode_graph.nodes[g] for g in _decode_guids(decode_graph)]
    pos_rows = {n.op.max_seq_len for n in dec_nodes}
    bridge: Dict[str, str] = {}
    for node in prefill_graph.topo_order():
        op = node.op
        if not op._weight_specs:
            continue
        twin = dec_ops.get(op.name)
        if twin is None:
            raise ValueError(
                f"prefill op {op.name!r} has no same-named decode twin "
                f"— the graphs cannot share a parameter set")
        dec_ws = {w.name: w for w in twin._weight_specs}
        for ws in op._weight_specs:
            tw = dec_ws.get(ws.name)
            if tw is None:
                raise ValueError(
                    f"prefill weight {op.name}/{ws.name} missing on the "
                    f"decode twin")
            ok = tuple(ws.shape) == tuple(tw.shape)
            if not ok and len(ws.shape) == len(tw.shape) == 2 \
                    and ws.shape[1] == tw.shape[1] \
                    and ws.shape[0] <= tw.shape[0] \
                    and tw.shape[0] in pos_rows:
                # positional-table prefix rule (see docstring): only a
                # decode-side table with exactly max_seq_len rows
                # qualifies — a vocab mismatch stays a hard error
                ok = True
            if not ok:
                raise ValueError(
                    f"weight {op.name}/{ws.name} shape mismatch: "
                    f"prefill {tuple(ws.shape)} vs decode "
                    f"{tuple(tw.shape)}")
            bridge[f"{op.name}/{ws.name}"] = f"{twin.name}/{ws.name}"
    return bridge
