"""FFModel — the public model-building and training API.

Mirrors the surface of the reference's FFModel
(reference: include/flexflow/model.h:316-700 layer methods;
python/flexflow/core/flexflow_cffi.py:784-1900): ``create_tensor`` +
layer methods build a lazy graph; ``compile`` turns it into a PCG,
picks a parallelization strategy, and lowers to one jitted SPMD
program; ``fit``/``eval`` run the training loop.

Differences by design (TPU-native):
* no init/forward/backward/update verbs per op — one fused train step;
* the parallelization strategy is sharding degrees over a global mesh,
  searched by flexflow_tpu.search (Unity algorithm) or data-parallel;
* NHWC conv layout.
"""

from __future__ import annotations

import math as _math
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.graph import Graph, Node
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.core.optype import OperatorType
from flexflow_tpu.core.ptensor import DataType, ParallelTensorShape, Tensor
from flexflow_tpu.initializers import Initializer
from flexflow_tpu.losses import LossType
from flexflow_tpu.metrics import MetricsType, PerfMetrics
from flexflow_tpu import ops as O
from flexflow_tpu.optimizers import Optimizer, SGDOptimizer


def _merge_matching(new, old):
    """Recursively keep ``new``'s structure, copying ``old``'s values at
    key paths present in both with matching array shapes."""
    if isinstance(new, dict) and isinstance(old, dict):
        return {
            k: _merge_matching(v, old[k]) if k in old else v
            for k, v in new.items()
        }
    if hasattr(new, "shape") and hasattr(old, "shape") and new.shape == old.shape:
        return old
    return new


def _adopt_kv_dtype(graph, dtype) -> None:
    """Retype the graph's decode-attention page pools IN PLACE to the
    ``__meta__.kv`` dtype (searched or imported, both SHD168/169-gated
    before this runs).  Called strictly AFTER the strategy export's
    digest computation — exported artifacts stay keyed to the attr-free
    frontend graph, so the import-side digest gate still passes — and
    before lowering, so ``state_specs``/``state_shardings`` build the
    quantized pool (+ per-(page, slot) scales under int8) the pricing
    chose.  fp32 is the attr-free default: nothing to adopt, the
    lowered program stays bit-identical to history."""
    if dtype in (None, "fp32"):
        return
    from flexflow_tpu.core.graph import Node
    from flexflow_tpu.core.optype import OperatorType

    changed = False
    for guid, node in list(graph.nodes.items()):
        op = node.op
        if op.op_type != OperatorType.DECODE_ATTENTION:
            continue
        if op.attrs.get("kv_dtype", "fp32") == dtype:
            continue
        a = op.attrs
        clone = type(op)(
            op.name, op.input_shapes,
            embed_dim=a["embed_dim"], num_heads=a["num_heads"],
            page_size=a["page_size"], pages_per_seq=a["pages_per_seq"],
            num_pages=a["num_pages"], use_kernel=a["use_kernel"],
            kv_dtype=dtype, kernel_initializer=op._kernel_init,
        )
        graph.nodes[guid] = Node(guid, clone)
        changed = True
    if changed:
        graph._invalidate()


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self.graph = Graph()
        self._producer: Dict[int, Tuple[Node, int]] = {}  # tensor.guid -> (node, out_idx)
        self._input_tensors: List[Tensor] = []
        self._name_counts: Dict[str, int] = {}
        self.compiled = None
        self.strategy = None  # chosen parallelization, set by compile()
        self.pipeline_proposal = None  # staged-pipeline candidate for
        # graphs the stacked executor can't run (StagedPipelineProposal)
        self.disaggregation = None  # prefill/decode disaggregation
        # proposal, set by compile() under the serve objective
        self.fleet = None  # serving-fleet proposal (search/fleet.py),
        # set by compile() under serve_fleet="search"; the controller's
        # elastic re-search hot-swaps it (research_fleet)
        self.fleet_base_graph = None  # pre-rewrite graph the fleet
        # re-search solves narrow blocks on (research_fleet)
        self.params = None
        self.opt_state = None
        self.state = None
        self.optimizer: Optional[Optimizer] = None
        self._rng_counter = 0

    # ------------------------------------------------------------------
    def _fresh_name(self, base: str, name: Optional[str]) -> str:
        if name:
            return name
        i = self._name_counts.get(base, 0)
        self._name_counts[base] = i + 1
        return f"{base}_{i}"

    def _shape_of(self, t: Tensor) -> ParallelTensorShape:
        return ParallelTensorShape.make(t.sizes, t.dtype)

    def _add_op(self, op: O.Operator, inputs: Sequence[Tensor]) -> List[Tensor]:
        node = self.graph.new_node(op)
        for i, t in enumerate(inputs):
            src_node, src_idx = self._producer[t.guid]
            self.graph.add_edge(src_node, node, src_idx, i)
        outs = []
        for i, shape in enumerate(op.output_shapes):
            t = Tensor(shape.sizes, shape.dtype, owner_layer=node, owner_idx=i,
                       name=f"{op.name}:{i}")
            self._producer[t.guid] = (node, i)
            outs.append(t)
        return outs

    # ------------------------------------------------------------------
    def create_tensor(self, dims: Sequence[int], dtype="float32", name=None) -> Tensor:
        """Frontend input tensor (reference: FFModel::create_tensor)."""
        name = self._fresh_name("input", name)
        t = Tensor(dims, dtype, name=name)
        op = O.InputOp(name, ParallelTensorShape.make(t.sizes, t.dtype), tensor_guid=t.guid)
        node = self.graph.new_node(op)
        self._producer[t.guid] = (node, 0)
        self._input_tensors.append(t)
        return t

    def create_constant(self, value, dtype=None, name=None) -> Tensor:
        """Compile-time constant tensor (baked into the program; XLA
        folds it).  Serves imported frontend graphs whose buffers —
        position ids, token-type ids — are constants, a case the
        reference routes through host-initialized Legion regions."""
        arr = np.asarray(value)
        if dtype is not None:
            arr = arr.astype(DataType.from_any(dtype).to_numpy())
        name = self._fresh_name("constant", name)
        dt = str(arr.dtype)
        t = Tensor(list(arr.shape), dt, name=name)
        op = O.ConstantOp(
            name, ParallelTensorShape.make(t.sizes, t.dtype), value=arr
        )
        node = self.graph.new_node(op)
        self._producer[t.guid] = (node, 0)
        return t

    # ---- layers (reference: model.h layer-method block) ----------------
    def dense(self, input: Tensor, out_dim: int, activation=None, use_bias=True,
              kernel_initializer=None, bias_initializer=None, name=None) -> Tensor:
        op = O.LinearOp(self._fresh_name("dense", name), [self._shape_of(input)],
                        out_dim=out_dim, activation=activation, use_bias=use_bias,
                        kernel_initializer=kernel_initializer,
                        bias_initializer=bias_initializer)
        return self._add_op(op, [input])[0]

    def conv2d(self, input: Tensor, out_channels: int, kernel_h: int, kernel_w: int,
               stride_h: int = 1, stride_w: int = 1, padding_h: int = 0,
               padding_w: int = 0, activation=None, groups: int = 1, use_bias=True,
               kernel_initializer=None, bias_initializer=None, name=None) -> Tensor:
        op = O.Conv2DOp(self._fresh_name("conv2d", name), [self._shape_of(input)],
                        out_channels=out_channels, kernel_h=kernel_h, kernel_w=kernel_w,
                        stride_h=stride_h, stride_w=stride_w, padding_h=padding_h,
                        padding_w=padding_w, groups=groups, activation=activation,
                        use_bias=use_bias, kernel_initializer=kernel_initializer,
                        bias_initializer=bias_initializer)
        return self._add_op(op, [input])[0]

    def pool2d(self, input: Tensor, kernel_h: int, kernel_w: int, stride_h: int = 1,
               stride_w: int = 1, padding_h: int = 0, padding_w: int = 0,
               pool_type: str = "max", activation=None, name=None) -> Tensor:
        op = O.Pool2DOp(self._fresh_name("pool2d", name), [self._shape_of(input)],
                        kernel_h=kernel_h, kernel_w=kernel_w, stride_h=stride_h,
                        stride_w=stride_w, padding_h=padding_h, padding_w=padding_w,
                        pool_type=pool_type, activation=activation)
        return self._add_op(op, [input])[0]

    def batch_norm(self, input: Tensor, relu: bool = True, momentum: float = 0.9,
                   name=None) -> Tensor:
        op = O.BatchNormOp(self._fresh_name("batchnorm", name), [self._shape_of(input)],
                           relu=relu, momentum=momentum)
        return self._add_op(op, [input])[0]

    def layer_norm(self, input: Tensor, axes=(-1,), elementwise_affine=True,
                   eps=1e-5, name=None) -> Tensor:
        op = O.LayerNormOp(self._fresh_name("layernorm", name), [self._shape_of(input)],
                           axes=tuple(axes), elementwise_affine=elementwise_affine, eps=eps)
        return self._add_op(op, [input])[0]

    def embedding(self, input: Tensor, num_entries: int, out_dim: int,
                  aggr: str = "none", kernel_initializer=None, name=None) -> Tensor:
        op = O.EmbeddingOp(self._fresh_name("embedding", name), [self._shape_of(input)],
                           num_entries=num_entries, out_dim=out_dim, aggr=aggr,
                           kernel_initializer=kernel_initializer)
        return self._add_op(op, [input])[0]

    def multihead_attention(self, query: Tensor, key: Tensor, value: Tensor,
                            embed_dim: int, num_heads: int, kdim: int = 0,
                            vdim: int = 0, dropout: float = 0.0, bias: bool = False,
                            causal: bool = False, sp_mode: str = "ring",
                            kernel_initializer=None,
                            name=None) -> Tensor:
        op = O.MultiHeadAttentionOp(
            self._fresh_name("attention", name),
            [self._shape_of(query), self._shape_of(key), self._shape_of(value)],
            embed_dim=embed_dim, num_heads=num_heads, kdim=kdim, vdim=vdim,
            dropout=dropout, use_bias=bias, causal=causal, sp_mode=sp_mode,
            kernel_initializer=kernel_initializer)
        return self._add_op(op, [query, key, value])[0]

    def decode_attention(self, hidden: Tensor, page_table: Tensor,
                         seq_lens: Tensor, embed_dim: int, num_heads: int,
                         page_size: int = 16, pages_per_seq: int = 8,
                         num_pages: int = 0, use_kernel: bool = True,
                         kernel_initializer=None, name=None) -> Tensor:
        """Single-token decode attention over this layer's paged KV
        cache (ops/decode_attention.py — the serving-side sibling of
        multihead_attention; no reference equivalent)."""
        op = O.DecodeAttentionOp(
            self._fresh_name("decode_attention", name),
            [self._shape_of(hidden), self._shape_of(page_table),
             self._shape_of(seq_lens)],
            embed_dim=embed_dim, num_heads=num_heads, page_size=page_size,
            pages_per_seq=pages_per_seq, num_pages=num_pages,
            use_kernel=use_kernel, kernel_initializer=kernel_initializer)
        return self._add_op(op, [hidden, page_table, seq_lens])[0]

    def batch_matmul(self, A: Tensor, B: Tensor, a_seq_length_dim: int = -1,
                     b_seq_length_dim: int = -1, name=None) -> Tensor:
        op = O.BatchMatmulOp(self._fresh_name("bmm", name),
                             [self._shape_of(A), self._shape_of(B)],
                             a_seq_length_dim=a_seq_length_dim,
                             b_seq_length_dim=b_seq_length_dim)
        return self._add_op(op, [A, B])[0]

    def dropout(self, input: Tensor, rate: float = 0.5, seed: int = 0, name=None) -> Tensor:
        op = O.DropoutOp(self._fresh_name("dropout", name), [self._shape_of(input)],
                         rate=rate, seed=seed)
        return self._add_op(op, [input])[0]

    def softmax(self, input: Tensor, axis: int = -1, name=None) -> Tensor:
        op = O.SoftmaxOp(self._fresh_name("softmax", name), [self._shape_of(input)], axis=axis)
        return self._add_op(op, [input])[0]

    def concat(self, tensors: Sequence[Tensor], axis: int, name=None) -> Tensor:
        op = O.ConcatOp(self._fresh_name("concat", name),
                        [self._shape_of(t) for t in tensors], axis=axis)
        return self._add_op(op, list(tensors))[0]

    def split(self, input: Tensor, sizes: Union[int, Sequence[int]], axis: int,
              name=None) -> List[Tensor]:
        if isinstance(sizes, int):
            total = input.sizes[axis]
            assert total % sizes == 0
            sizes = [total // sizes] * sizes
        op = O.SplitOp(self._fresh_name("split", name), [self._shape_of(input)],
                       sizes=tuple(sizes), axis=axis)
        return self._add_op(op, [input])

    def flat(self, input: Tensor, name=None) -> Tensor:
        op = O.FlatOp(self._fresh_name("flat", name), [self._shape_of(input)])
        return self._add_op(op, [input])[0]

    def reshape(self, input: Tensor, shape: Sequence[int], name=None) -> Tensor:
        op = O.ReshapeOp(self._fresh_name("reshape", name), [self._shape_of(input)],
                         shape=tuple(shape))
        return self._add_op(op, [input])[0]

    def transpose(self, input: Tensor, perm: Sequence[int], name=None) -> Tensor:
        op = O.TransposeOp(self._fresh_name("transpose", name), [self._shape_of(input)],
                           perm=tuple(perm))
        return self._add_op(op, [input])[0]

    def reverse(self, input: Tensor, axis: int, name=None) -> Tensor:
        op = O.ReverseOp(self._fresh_name("reverse", name), [self._shape_of(input)], axis=axis)
        return self._add_op(op, [input])[0]

    def cast(self, input: Tensor, dtype, name=None) -> Tensor:
        op = O.CastOp(self._fresh_name("cast", name), [self._shape_of(input)], dtype=dtype)
        return self._add_op(op, [input])[0]

    def mean(self, input: Tensor, dims: Sequence[int], keepdims: bool = False,
             name=None) -> Tensor:
        op = O.MeanOp(self._fresh_name("mean", name), [self._shape_of(input)],
                      dims=tuple(dims), keepdims=keepdims)
        return self._add_op(op, [input])[0]

    def top_k(self, input: Tensor, k: int, sorted: bool = True, name=None) -> Tuple[Tensor, Tensor]:
        op = O.TopKOp(self._fresh_name("topk", name), [self._shape_of(input)], k=k, sorted=sorted)
        outs = self._add_op(op, [input])
        return outs[0], outs[1]

    def gather(self, input: Tensor, indices: Tensor, axis: int = 0, name=None) -> Tensor:
        op = O.GatherOp(self._fresh_name("gather", name),
                        [self._shape_of(input), self._shape_of(indices)], axis=axis)
        return self._add_op(op, [input, indices])[0]

    def group_by(self, data: Tensor, assign: Tensor, n_experts: int, alpha: float = 1.0,
                 name=None) -> List[Tensor]:
        op = O.GroupByOp(self._fresh_name("group_by", name),
                         [self._shape_of(data), self._shape_of(assign)],
                         n_experts=n_experts, alpha=alpha)
        return self._add_op(op, [data, assign])

    def aggregate(self, gates: Tensor, expert_idx: Tensor, pos: Tensor, valid: Tensor,
                  expert_out: Tensor, lambda_bal: float = 0.0, name=None) -> Tensor:
        op = O.AggregateOp(
            self._fresh_name("aggregate", name),
            [self._shape_of(t) for t in (gates, expert_idx, pos, valid, expert_out)],
            lambda_bal=lambda_bal)
        return self._add_op(op, [gates, expert_idx, pos, valid, expert_out])[0]

    def aggregate_spec(self, gates, expert_idx, pos, valid, expert_out,
                       lambda_bal: float = 0.0, name=None) -> Tensor:
        op = O.AggregateSpecOp(
            self._fresh_name("aggregate_spec", name),
            [self._shape_of(t) for t in (gates, expert_idx, pos, valid, expert_out)],
            lambda_bal=lambda_bal)
        return self._add_op(op, [gates, expert_idx, pos, valid, expert_out])[0]

    def cache(self, input: Tensor, use_cached: bool = False, name=None) -> Tensor:
        op = O.CacheOp(self._fresh_name("cache", name), [self._shape_of(input)],
                       use_cached=use_cached)
        return self._add_op(op, [input])[0]

    # parallel ops (reference: src/parallel_ops/*; inserted by the search
    # or placed manually for hand-written strategies) -------------------
    def repartition(self, input: Tensor, dim: int, degree: int, name=None) -> Tensor:
        from flexflow_tpu.parallel.parallel_ops import RepartitionOp

        op = RepartitionOp(self._fresh_name("repartition", name),
                           [self._shape_of(input)], dim=dim, degree=degree)
        return self._add_op(op, [input])[0]

    def combine(self, input: Tensor, dim: int, degree: int = 1, name=None) -> Tensor:
        from flexflow_tpu.parallel.parallel_ops import CombineOp

        op = CombineOp(self._fresh_name("combine", name),
                       [self._shape_of(input)], dim=dim, degree=degree)
        return self._add_op(op, [input])[0]

    def replicate(self, input: Tensor, degree: int, name=None) -> Tensor:
        from flexflow_tpu.parallel.parallel_ops import ReplicateOp

        op = ReplicateOp(self._fresh_name("replicate", name),
                         [self._shape_of(input)], degree=degree)
        return self._add_op(op, [input])[0]

    def reduction(self, input: Tensor, degree: int, name=None) -> Tensor:
        from flexflow_tpu.parallel.parallel_ops import ReductionOp

        op = ReductionOp(self._fresh_name("reduction", name),
                         [self._shape_of(input)], degree=degree)
        return self._add_op(op, [input])[0]

    def node_by_name(self, name: str) -> Node:
        for node in self.graph.nodes.values():
            if node.op.name == name:
                return node
        raise KeyError(name)

    # elementwise -------------------------------------------------------
    def _unary(self, t: OperatorType, input: Tensor, name=None, scalar=0.0,
               base=None, approximate=True):
        op = O.ElementUnaryOp(self._fresh_name(base or t.value, name),
                              [self._shape_of(input)], unary_type=t,
                              scalar=scalar, approximate=approximate)
        return self._add_op(op, [input])[0]

    def _binary(self, t: OperatorType, a: Tensor, b: Tensor, name=None):
        op = O.ElementBinaryOp(self._fresh_name(t.value, name),
                               [self._shape_of(a), self._shape_of(b)], binary_type=t)
        return self._add_op(op, [a, b])[0]

    def relu(self, x, name=None):
        return self._unary(OperatorType.RELU, x, name)

    def sigmoid(self, x, name=None):
        return self._unary(OperatorType.SIGMOID, x, name)

    def tanh(self, x, name=None):
        return self._unary(OperatorType.TANH, x, name)

    def elu(self, x, name=None):
        return self._unary(OperatorType.ELU, x, name)

    def gelu(self, x, name=None, approximate=True):
        """tanh-approximate by default (the TPU-friendly form); pass
        approximate=False for the exact erf GELU that tf.keras and
        torch default to."""
        return self._unary(OperatorType.GELU, x, name, approximate=approximate)

    def exp(self, x, name=None):
        return self._unary(OperatorType.EXP, x, name)

    def log(self, x, name=None):
        return self._unary(OperatorType.LOG, x, name)

    def identity(self, x, name=None):
        return self._unary(OperatorType.IDENTITY, x, name)

    def rsqrt(self, x, name=None):
        return self._unary(OperatorType.RSQRT, x, name)

    def pow(self, x, exponent: float, name=None):
        return self._unary(OperatorType.POW, x, name, scalar=exponent)

    def scalar_add(self, x, scalar: float, name=None):
        return self._unary(OperatorType.SCALAR_ADD, x, name, scalar=scalar)

    def scalar_sub(self, x, scalar: float, name=None):
        return self._unary(OperatorType.SCALAR_SUB, x, name, scalar=scalar)

    def scalar_multiply(self, x, scalar: float, name=None):
        return self._unary(OperatorType.SCALAR_MUL, x, name, scalar=scalar)

    def scalar_true_divide(self, x, scalar: float, name=None):
        return self._unary(OperatorType.SCALAR_TRUE_DIV, x, name, scalar=scalar)

    def add(self, a, b, name=None):
        return self._binary(OperatorType.EW_ADD, a, b, name)

    def subtract(self, a, b, name=None):
        return self._binary(OperatorType.EW_SUB, a, b, name)

    def multiply(self, a, b, name=None):
        return self._binary(OperatorType.EW_MUL, a, b, name)

    def divide(self, a, b, name=None):
        return self._binary(OperatorType.EW_DIV, a, b, name)

    def max(self, a, b, name=None):
        return self._binary(OperatorType.EW_MAX, a, b, name)

    def min(self, a, b, name=None):
        return self._binary(OperatorType.EW_MIN, a, b, name)

    # ------------------------------------------------------------------
    def compile(
        self,
        optimizer: Optional[Optimizer] = None,
        loss_type="sparse_categorical_crossentropy",
        metrics=("accuracy",),
        comp_mode: str = "training",
        strategy: Optional[Dict[int, MachineView]] = None,
        pipeline=None,
        block_of: Optional[Dict[int, int]] = None,
        mesh=None,
    ):
        """Pick a parallelization strategy and lower
        (reference: FFModel::compile model.cc:2587).  ``pipeline`` — a
        flexflow_tpu.parallel.pipeline.PipelineConfig enables the
        S-stage microbatched pipeline over a ``pp`` mesh axis (a
        capability the reference only stubbed: OP_PIPELINE,
        ffconst.h:148)."""
        from flexflow_tpu.compiler.lowering import CompiledModel, data_parallel_strategy

        if comp_mode not in ("training", "inference"):
            raise ValueError(
                f"comp_mode must be 'training' or 'inference', got {comp_mode!r}"
            )
        self.config.comp_mode = comp_mode
        if self.config.verify:
            # prove the frontend-built graph well-formed before anything
            # consumes it (flexflow_tpu/analysis).  The per-rewrite hook
            # inside the search is armed by optimize_strategy's own
            # scoped_verify — config.verify never becomes a sticky
            # process-wide latch.
            from flexflow_tpu.analysis import assert_graph_ok

            assert_graph_ok(self.graph, context="at compile entry")
        if self.config.obs_log_file:
            # FFConfig-gated unified telemetry (flexflow_tpu/obs): the
            # search, compile, and fit paths below all emit through the
            # same bus once it is armed
            from flexflow_tpu.obs.events import BUS as _obs_bus

            _obs_bus.configure(self.config.obs_log_file)
        self.pipeline_proposal = None  # a stale proposal from an earlier
        # compile must not hijack this one's lowering
        self.disaggregation = None  # prefill/decode disaggregation
        # proposal (search/disaggregation.py DisaggregationProposal):
        # searched under objective="serve" +
        # serve_disaggregation="search", persisted when adopted
        self.fleet = None  # serving-fleet proposal (search/fleet.py
        # FleetProposal): searched under objective="serve" +
        # serve_fleet="search", persisted when adopted
        self.fleet_base_graph = None
        self.optimizer = optimizer or SGDOptimizer(
            lr=self.config.learning_rate, weight_decay=self.config.weight_decay
        )
        if pipeline is not None and (
            pipeline.num_stages < 1
            or self.config.num_devices % pipeline.num_stages != 0
        ):
            raise ValueError(
                f"pipeline.num_stages={pipeline.num_stages} must divide "
                f"num_devices={self.config.num_devices}"
            )
        if pipeline is not None and mesh is not None:
            raise ValueError(
                "mesh= is not supported with pipeline= (the pipelined "
                "lowering builds its own pp-leading mesh)"
            )
        if pipeline is not None and self.config.zero_dp_shard:
            raise NotImplementedError(
                "zero_dp_shard is not supported with pipeline= yet — the "
                "pipelined lowering manages its own per-stage placement; "
                "silently ignoring the flag would leave optimizer state "
                "replicated while the user expects 1/N memory"
            )
        searched_strategy = False  # did the joint search pick it?
        searched_strategy_obj = None  # the exact strategy the search
        # returned (a placement proposal may replace `strategy` below)
        imported_sync_schedule = None  # __meta__.sync_schedule of an
        # imported strategy file (already behind the digest gate)
        imported_zero_groups = None  # __meta__.zero_groups likewise
        kv_adopt_dtype = None  # pool dtype the decode ops ADOPT right
        # before lowering (searched __meta__.kv or an imported one,
        # both SHD168/169-gated).  Adoption is deliberately deferred
        # past the strategy export: exported digests stay keyed to the
        # attr-free frontend graph, so the import-side digest gate
        # still passes and the kv block re-lints there instead.
        if strategy is None:
            if pipeline is not None:
                # dp over the devices left after the pp axis is carved off
                strategy = data_parallel_strategy(
                    self.graph, self.config.num_devices // pipeline.num_stages
                )
            elif self.config.import_strategy_file:
                from flexflow_tpu.search.strategy_io import import_strategy

                # an imported strategy bypasses the search's always-on
                # gate — provenance is checked by import_strategy and
                # the views are linted below, so an illegal file fails
                # at compile with a finding, not inside XLA
                from flexflow_tpu.analysis import (
                    AnalysisError,
                    emit_findings,
                    errors_only,
                    lint_strategy,
                )

                try:
                    strategy = import_strategy(
                        self.config.import_strategy_file, self.graph,
                        allow_partial=self.config.import_strategy_partial)
                except AnalysisError as e:
                    err = AnalysisError(
                        f"{e}\n(hint: a strategy exported after a "
                        f"REWRITING search is keyed to the rewritten "
                        f"graph and cannot re-apply to a fresh frontend "
                        f"build — use the persistent cost cache "
                        f"(--cost-cache-file) for cross-process reuse of "
                        f"rewritten searches, or "
                        f"--import-strategy-partial / "
                        f"FFConfig.import_strategy_partial for a "
                        f"best-effort partial apply)")
                    err.findings = list(e.findings)
                    raise err from e

                bad = errors_only(lint_strategy(
                    self.graph, strategy, self.config.num_devices))
                if bad:
                    emit_findings(bad)
                    raise AnalysisError(
                        f"imported strategy "
                        f"{self.config.import_strategy_file!r} is illegal "
                        f"for this graph/mesh", bad)
                from flexflow_tpu.search.strategy_io import read_meta

                _imeta = read_meta(self.config.import_strategy_file)
                imported_sync_schedule = _imeta.get("sync_schedule")
                imported_zero_groups = _imeta.get("zero_groups")
                # pipeline/placement proposal provenance rides the same
                # digest gate — re-lint against THIS graph/strategy so
                # a hand-edited proposal block fails with a finding at
                # import, not inside the placed/staged lowering
                # (analysis/placement.py SHD150-155)
                if _imeta.get("placement") is not None:
                    from flexflow_tpu.analysis import (
                        lint_placement,
                        placement_meta,
                    )

                    bad = errors_only(lint_placement(
                        self.graph, strategy, self.config))
                    if not bad and placement_meta(
                            self.graph, strategy, self.config
                    ) != _imeta["placement"]:
                        from flexflow_tpu.analysis import Finding

                        bad = [Finding(
                            code="SHD153", pass_name="placement",
                            message=(
                                "imported __meta__.placement block frame "
                                "disagrees with the device blocks the "
                                "strategy's start_part views actually "
                                "form"))]
                    if bad:
                        emit_findings(bad)
                        raise AnalysisError(
                            "imported placement proposal is illegal for "
                            "this graph/strategy", bad)
                _ispec = None  # the imported ServingSpec, shared with
                # the __meta__.kv re-lint below
                if _imeta.get("serving") is not None:
                    # imported serving provenance re-lints against THIS
                    # graph/strategy (SHD16x): a hand-edited or
                    # re-targeted serve artifact fails with findings,
                    # not inside the executor
                    from flexflow_tpu.analysis import lint_serving
                    from flexflow_tpu.search.machine_model import (
                        CostModel as _SCM,
                    )
                    from flexflow_tpu.search.serving import ServingSpec

                    _sv = _imeta["serving"]
                    try:
                        _spec = ServingSpec(
                            max_seqs=int(_sv["max_seqs"]),
                            page_size=int(_sv["page_size"]),
                            pages_per_seq=int(_sv["pages_per_seq"]),
                            p99_budget_ms=float(
                                _sv.get("p99_budget_ms", 0.0)),
                            quantile=float(_sv.get("quantile", 0.99)),
                            # residency was ranked under the kv block's
                            # prefix sharing (when present): the SHD161
                            # re-proof must price the same pool
                            shared_prefix_pages=int(
                                (_imeta.get("kv") or {}).get(
                                    "shared_prefix_pages", 0) or 0),
                        )
                    except (KeyError, TypeError, ValueError) as e:
                        raise AnalysisError(
                            f"imported strategy file carries a malformed "
                            f"__meta__.serving block: {e}", []) from e
                    # inference=... must MATCH the producing gate's cost
                    # model (the search ran under comp_mode=inference):
                    # a training-mode CostModel counts activations 2x
                    # and would SHD161-reject legal near-capacity
                    # artifacts the search-time gate passed; serving=
                    # arms the same shared-residency discount
                    bad = errors_only(lint_serving(
                        self.graph, strategy, _spec,
                        _SCM(self.config.machine_spec,
                             num_devices=self.config.search_devices,
                             inference=comp_mode == "inference",
                             serving=_spec)))
                    if bad:
                        emit_findings(bad)
                        raise AnalysisError(
                            "imported serving provenance is illegal for "
                            "this graph/strategy", bad)
                    _ispec = _spec
                if _imeta.get("kv") is not None:
                    # imported KV-lane provenance re-lints against THIS
                    # graph/strategy (SHD168/169) BEFORE the pool dtype
                    # is adopted onto the decode ops: a hand-edited or
                    # re-targeted __meta__.kv fails with findings at
                    # import, never inside the lowering or the kernel
                    from flexflow_tpu.analysis import lint_kv

                    bad = errors_only(lint_kv(
                        self.graph, strategy, _imeta["kv"],
                        serving=_ispec))
                    if bad:
                        emit_findings(bad)
                        raise AnalysisError(
                            "imported __meta__.kv block is illegal for "
                            "this graph/strategy", bad)
                    kv_adopt_dtype = _imeta["kv"].get("dtype")
                if _imeta.get("disaggregation") is not None:
                    # imported disaggregation provenance re-lints
                    # against THIS graph (SHD164/165): the persisted
                    # pool geometry must agree with the target's decode
                    # ops and the shared-parameter-set bridge must
                    # still hold — a hand-edited or re-targeted
                    # artifact fails with findings at import
                    from flexflow_tpu.analysis import lint_disaggregation

                    bad = errors_only(lint_disaggregation(
                        self.graph, _imeta["disaggregation"],
                        self.config))
                    if bad:
                        emit_findings(bad)
                        raise AnalysisError(
                            "imported disaggregation proposal is "
                            "illegal for this graph", bad)
                if _imeta.get("fleet") is not None:
                    # imported fleet provenance re-lints against THIS
                    # graph (SHD166/167): replica blocks must tile the
                    # mesh disjointly, routing must cover every SLO
                    # class, and the persisted pool geometry must agree
                    # with the target's decode ops
                    from flexflow_tpu.analysis import lint_fleet

                    bad = errors_only(lint_fleet(
                        self.graph, _imeta["fleet"], self.config))
                    if bad:
                        emit_findings(bad)
                        raise AnalysisError(
                            "imported fleet proposal is illegal for "
                            "this graph", bad)
                if _imeta.get("pipeline") is not None:
                    from flexflow_tpu.analysis import (
                        Finding,
                        lint_pipeline_stages,
                    )

                    _pmeta = _imeta["pipeline"]
                    bad = []
                    stage_guids = None
                    _ns = _nm = 0
                    # a hand-edited meta block may carry ANY JSON type:
                    # malformed shapes must become findings, never a
                    # bare TypeError out of the gate itself
                    if not isinstance(_pmeta, dict):
                        bad = [Finding(
                            code="SHD150", pass_name="placement",
                            message="imported __meta__.pipeline is not "
                                    "an object")]
                    else:
                        _ns = _pmeta.get("num_stages", 0)
                        _nm = _pmeta.get("num_microbatches", 0)
                        _stages = _pmeta.get("stages")
                        if (not isinstance(_ns, int)
                                or not isinstance(_nm, int)
                                or isinstance(_ns, bool)
                                or isinstance(_nm, bool)):
                            bad = [Finding(
                                code="SHD150", pass_name="placement",
                                message=(
                                    f"imported __meta__.pipeline has "
                                    f"non-integer num_stages/"
                                    f"num_microbatches ({_ns!r}, "
                                    f"{_nm!r})"))]
                        elif _stages is not None and not (
                                isinstance(_stages, list)
                                and all(isinstance(s, list)
                                        and all(isinstance(op, str)
                                                for op in s)
                                        for s in _stages)):
                            bad = [Finding(
                                code="SHD150", pass_name="placement",
                                message=(
                                    "imported __meta__.pipeline stages "
                                    "is not a list of op-name lists"))]
                        elif _stages is not None:
                            by_name = {n.op.name: n.guid
                                       for n in self.graph.topo_order()}
                            stage_guids = [
                                [by_name.get(op, -1) for op in stage]
                                for stage in _stages
                            ]
                    if not bad:
                        bad = errors_only(lint_pipeline_stages(
                            self.graph, stage_guids, _ns, _nm,
                            self.config))
                    if bad:
                        emit_findings(bad)
                        raise AnalysisError(
                            "imported pipeline proposal is illegal for "
                            "this graph/strategy", bad)
                    # the validated proposal is ADOPTED, not just
                    # checked: an export whose compile ran the staged
                    # executor must round-trip to the staged executor
                    # (an import that re-lints but silently lowers
                    # flat would defeat the proposal it validated —
                    # e.g. the HBM-infeasible regime staged pipelining
                    # exists for)
                    if stage_guids is not None:
                        from flexflow_tpu.search.pipeline_search import (
                            StagedPipelineProposal,
                        )

                        self.pipeline_proposal = StagedPipelineProposal(
                            num_stages=_ns, num_microbatches=_nm,
                            stage_guids=stage_guids,
                            cost=float("nan"),  # not re-simulated here
                            executable=False,
                        )
                    elif pipeline is None:
                        # S x M without explicit stages = the
                        # stacked-block shape; adopt it exactly as if
                        # the user had passed compile(pipeline=...)
                        from flexflow_tpu.parallel.pipeline import (
                            PipelineConfig,
                        )

                        if self.config.zero_dp_shard:
                            # the early compile(pipeline=) guard has
                            # already run by this point — re-raise its
                            # contract rather than silently leaving
                            # optimizer state replicated
                            raise NotImplementedError(
                                "zero_dp_shard is not supported with "
                                "an imported pipeline proposal")
                        pipeline = PipelineConfig(
                            num_stages=_ns, num_microbatches=_nm)
            elif self.config.only_data_parallel:
                strategy = data_parallel_strategy(self.graph, self.config.num_devices)
            else:
                # the Unity joint search IS the default compile path
                # (reference: FFModel::compile -> graph_optimize,
                # model.cc:2587-2655): graph rewrites compete with view
                # assignment and the best REWRITTEN graph gets lowered —
                # self.graph is replaced the same way the reference
                # deserializes the optimized PCG into its operator list
                # (convert_graph_to_operators, substitution.cc:3014)
                from flexflow_tpu.search.driver import optimize_strategy

                # the pre-search graph: the disaggregation proposal's
                # narrow-block solves run on it (rewrites bake
                # full-mesh repartition views narrow blocks can't host)
                _disagg_base_graph = self.graph
                best_graph, strategy = optimize_strategy(
                    self.graph, self.config, return_graph=True
                )
                self.graph = best_graph
                searched_strategy = True
                from flexflow_tpu.search import driver as _kvdriver

                if _kvdriver.LAST_KV_META:
                    # the searched pool dtype (SHD168/169-gated inside
                    # the driver); adopted onto the decode ops right
                    # before lowering, AFTER the strategy export's
                    # digest computation
                    kv_adopt_dtype = _kvdriver.LAST_KV_META.get("dtype")
                # the strategy object the driver's sync-schedule gate
                # ran against — a pipeline/placement proposal below may
                # REPLACE `strategy`, and the gated schedule must not
                # follow it onto a strategy it was never linted for
                searched_strategy_obj = strategy
                # the search also costs pipelined candidates for
                # stacked-block graphs (reference gap: OP_PIPELINE is an
                # enum stub, ffconst.h:148) — a winning PipelineConfig
                # is adopted exactly as if the user had passed it
                if (
                    pipeline is None
                    and mesh is None
                    and (self.config.enable_pipeline_search
                         or self.config.enable_placement_search)
                    and not self.config.zero_dp_shard
                    and comp_mode == "training"
                ):
                    from flexflow_tpu.search.driver import (
                        coherent_calibration,
                    )
                    from flexflow_tpu.search.pipeline_search import (
                        propose_pipeline,
                    )
                    from flexflow_tpu.search.simulator import Simulator

                    # same cost currency as the flat search that just
                    # ran: measured calibration included when coherent
                    sim = Simulator.for_config(
                        self.config,
                        calibration=coherent_calibration(self.config),
                    )
                    baseline = sim.simulate(self.graph, strategy)
                    prop = (
                        propose_pipeline(
                            self.graph, self.config, sim, baseline
                        )
                        if self.config.enable_pipeline_search else None
                    )
                    if prop is not None and (
                        self.config.num_devices % prop.num_stages == 0
                        and self.config.batch_size % prop.num_microbatches
                        == 0
                    ):
                        pipeline = prop
                        strategy = data_parallel_strategy(
                            self.graph,
                            self.config.num_devices // pipeline.num_stages,
                        )
                    elif self.config.enable_placement_search:
                        # no pipeline won: cost 2-block inter-op placed
                        # candidates in the placed executor's schedule
                        # (reference: VERTICAL splits + mapper placement,
                        # graph.cc:161-295, mapper.cc:371-475); a
                        # margin-beating placeable winner replaces the
                        # flat strategy and lowers via the placed path
                        from flexflow_tpu.search.placement_search import (
                            propose_placement,
                        )

                        placed = propose_placement(
                            self.graph, self.config, baseline,
                            calibration=coherent_calibration(self.config),
                        )
                        if placed is not None:
                            strategy = placed
                        elif not _math.isfinite(baseline):
                            # nothing executable fits: cost the GENERAL
                            # staged-pipeline shape (any graph cut,
                            # reference graph.cc:161-295); a winning
                            # proposal lowers via the heterogeneous
                            # staged executor
                            # (compiler/staged_pipeline_lowering.py)
                            from flexflow_tpu.search.pipeline_search import (
                                propose_pipeline_general,
                            )

                            self.pipeline_proposal = (
                                propose_pipeline_general(
                                    self.graph, self.config, sim, baseline
                                )
                            )
                            if self.pipeline_proposal is not None:
                                from flexflow_tpu.utils.logging import (
                                    SEARCH_LOG,
                                )

                                p = self.pipeline_proposal
                                SEARCH_LOG.log(
                                    f"staged-pipeline candidate: S="
                                    f"{p.num_stages} M="
                                    f"{p.num_microbatches} modeled "
                                    f"{p.cost * 1e3:.3f} ms/iter "
                                    f"(flat is infeasible)"
                                )
        # the chosen strategy is public state: tooling (bench_search,
        # strategy introspection) reads it back after compile
        self.strategy = strategy
        # prefill/decode disaggregation (search/disaggregation.py):
        # under the serve objective, also price placing the prompt
        # graph and this decode graph on disjoint submeshes — the
        # two-block placement with the KV handoff as a cross-block
        # transfer.  The proposal (adopted or honest zero) is public
        # state; adopted winners persist as __meta__.disaggregation.
        if (
            searched_strategy
            and strategy
            and pipeline is None
            and mesh is None
            and comp_mode == "inference"
            and getattr(self.config, "objective", "train") == "serve"
            and getattr(self.config, "serve_disaggregation", "off")
            == "search"
        ):
            from flexflow_tpu.search.disaggregation import (
                propose_disaggregation,
            )
            from flexflow_tpu.search.driver import coherent_calibration

            self.disaggregation = propose_disaggregation(
                self.graph, strategy, self.config,
                calibration=coherent_calibration(self.config),
                base_graph=(_disagg_base_graph
                            if _disagg_base_graph is not self.graph
                            else None),
            )
        # serving fleet (search/fleet.py): under the serve objective,
        # also price partitioning the mesh into N replica blocks with
        # per-replica strategies and per-SLO-class routing — the
        # N-block generalization of the disaggregation pass.  Public
        # state like the disaggregation proposal; adopted winners
        # persist as __meta__.fleet.
        if (
            searched_strategy
            and strategy
            and pipeline is None
            and mesh is None
            and comp_mode == "inference"
            and getattr(self.config, "objective", "train") == "serve"
            and getattr(self.config, "serve_fleet", "off") == "search"
        ):
            from flexflow_tpu.search.driver import coherent_calibration
            from flexflow_tpu.search.fleet import propose_fleet

            # the controller's elastic re-search needs the SAME
            # pre-rewrite graph for its narrow-block solves (rewrites
            # bake full-mesh views narrow blocks can't host)
            self.fleet_base_graph = (
                _disagg_base_graph
                if _disagg_base_graph is not self.graph else None)
            self.fleet = propose_fleet(
                self.graph, strategy, self.config,
                calibration=coherent_calibration(self.config),
                base_graph=self.fleet_base_graph,
            )
        # sync-precision dimension of the strategy (EQuARX compressed
        # gradient collectives): build the per-weight-group wire map
        # with the SAME cost model the search ranked with, so execution
        # runs exactly what the simulation priced.  Public state like
        # the strategy itself (bench_search reads it back).
        self.sync_precision_map: Dict[str, str] = {}
        _sync_sim = None  # shared by the precision map + schedule
        # builders below: one Simulator.for_config per compile, not three
        if (
            comp_mode == "training"
            and strategy
            and getattr(self.config, "sync_precision", "fp32") != "fp32"
        ):
            from flexflow_tpu.search.driver import coherent_calibration
            from flexflow_tpu.search.simulator import Simulator
            from flexflow_tpu.search.sync_precision import (
                choose_sync_precision,
            )

            _sync_sim = Simulator.for_config(
                self.config, calibration=coherent_calibration(self.config)
            )
            self.sync_precision_map = choose_sync_precision(
                self.graph, strategy, _sync_sim.cost
            )
        # gradient-sync SCHEDULE (search/sync_schedule.py): bucketed,
        # issue-ordered collectives the lowering executes inside the
        # backward (comm/bucketed.py).  The joint search already chose
        # and legality-gated one for ITS result (driver
        # _build_sync_schedule); other strategy sources (forced DP,
        # caller-supplied, imported without one) run the same choice +
        # always-on gate here.  Public state like the strategy itself.
        self.sync_schedule = None
        if (
            comp_mode == "training"
            and strategy
            and pipeline is None
            and getattr(self.config, "sync_schedule", "off") == "search"
        ):
            if imported_sync_schedule is not None:
                # a schedule persisted next to an imported strategy
                # (digest gate already passed) — re-lint against THIS
                # graph before adopting: a hand-edited file must fail
                # with a finding, not inside XLA
                from flexflow_tpu.analysis import (
                    AnalysisError,
                    emit_findings,
                    errors_only,
                    lint_sync_schedule,
                )
                from flexflow_tpu.search.sync_schedule import SyncSchedule

                try:
                    sched = SyncSchedule.from_jsonable(imported_sync_schedule)
                except ValueError as e:
                    raise AnalysisError(
                        f"imported strategy file carries a malformed "
                        f"sync_schedule: {e}", []) from e
                from flexflow_tpu.analysis import lint_reduction_plan
                from flexflow_tpu.search.machine_model import CostModel

                _lint_cm = CostModel(
                    self.config.machine_spec,
                    num_devices=self.config.search_devices)
                bad = errors_only(
                    lint_sync_schedule(
                        self.graph, strategy, sched,
                        self.sync_precision_map)
                    + lint_reduction_plan(
                        self.graph, strategy, sched, _lint_cm))
                if bad:
                    emit_findings(bad)
                    raise AnalysisError(
                        "imported sync_schedule is illegal for this "
                        "graph/strategy", bad)
                self.sync_schedule = sched
            elif searched_strategy and strategy is searched_strategy_obj:
                from flexflow_tpu.search import driver as _driver

                self.sync_schedule = _driver.LAST_SYNC_SCHEDULE
            else:
                # caller-supplied / forced-DP strategies, and searched
                # strategies later REPLACED by a placement proposal:
                # run the same choice + always-on gate against the
                # strategy actually being lowered
                from flexflow_tpu.search.driver import (
                    _build_sync_schedule,
                    coherent_calibration,
                )
                from flexflow_tpu.search.simulator import Simulator

                if _sync_sim is None:
                    _sync_sim = Simulator.for_config(
                        self.config,
                        calibration=coherent_calibration(self.config),
                    )
                self.sync_schedule = _build_sync_schedule(
                    self.graph, strategy, _sync_sim, self.config
                )
        # per-group optimizer-state sharding (the co-searched ZeRO-1
        # dimension, search/comm_plan.py): adopted from the search
        # (LAST_ZERO_GROUPS — already gated by the driver's always-on
        # SHD140/141 lint) or from an imported strategy file's
        # __meta__.zero_groups (re-linted against THIS graph/strategy
        # here).  The global config.zero_dp_shard flag is untouched and
        # keeps arming every op; the per-group map is ignored under it.
        self.zero_groups: tuple = ()
        if (
            comp_mode == "training"
            and strategy
            and pipeline is None
            and not self.config.zero_dp_shard
        ):
            if imported_zero_groups is not None:
                from flexflow_tpu.analysis import (
                    AnalysisError,
                    emit_findings,
                    errors_only,
                    lint_zero_map,
                )
                from flexflow_tpu.search.machine_model import CostModel

                if (not isinstance(imported_zero_groups, list)
                        or any(not isinstance(z, str)
                               for z in imported_zero_groups)):
                    raise AnalysisError(
                        "imported strategy file carries a malformed "
                        "zero_groups map (expected a list of op names)",
                        [])
                _zcm = CostModel(
                    self.config.machine_spec,
                    num_devices=self.config.search_devices)
                bad = errors_only(lint_zero_map(
                    self.graph, strategy, imported_zero_groups, _zcm))
                if bad:
                    emit_findings(bad)
                    raise AnalysisError(
                        "imported zero_groups map is illegal for this "
                        "graph/strategy", bad)
                self.zero_groups = tuple(imported_zero_groups)
            elif searched_strategy and strategy is searched_strategy_obj:
                from flexflow_tpu.search import driver as _driver

                self.zero_groups = tuple(_driver.LAST_ZERO_GROUPS)
        # predicted step breakdown + strategy-explanation telemetry —
        # the predicted half of the DriftReport fit() completes.  Only
        # computed when something will consume it (profiling, the obs
        # bus, a strategy/trace export): one extra simulate per compile
        # is cheap but not free.
        from flexflow_tpu.obs.events import BUS as _obs_bus

        self.predicted_breakdown = None
        self.drift_report = None
        self.lane_drift_report = None  # filled by fit's device-trace
        # capture (config.device_trace_dir) via obs/trace_ingest.py
        _pred_cal = None  # the coherent table the prediction was priced
        # under — the export block digests THIS object (STR210) instead
        # of re-parsing the file a second time
        if (
            strategy
            and pipeline is None
            and self.pipeline_proposal is None
            and (
                self.config.profiling
                or _obs_bus.enabled
                or self.config.export_strategy_file
                or self.config.obs_trace_file
                # a calibrated compile must ALWAYS record its prediction:
                # the drift/healthy-reset loop (fit tail, re-probe
                # allowance) closes on it even when neither profiling nor
                # the obs bus is armed — without this, the allowance
                # reset rode the drift-report path only
                or self.config.calibration_file
            )
        ):
            from flexflow_tpu.search.driver import coherent_calibration
            from flexflow_tpu.search.simulator import Simulator as _Sim

            try:
                _pred_cal = coherent_calibration(self.config)
                _psim = _Sim.for_config(
                    self.config, calibration=_pred_cal
                )
                bd: Dict = {}
                _sched: list = []
                _comm: list = []
                _psim.simulate(self.graph, strategy, breakdown=bd,
                               schedule=_sched, comm_schedule=_comm,
                               sync_schedule=self.sync_schedule)
                bd["calibrated"] = _psim.cost.calibration is not None
                bd["machine"] = self.config.machine_spec.name
                self.predicted_breakdown = bd
                if _obs_bus.enabled:
                    _obs_bus.emit(
                        "strategy.table",
                        rows=_psim.strategy_table_rows(
                            self.graph, strategy,
                            self.sync_precision_map,
                        ),
                        predicted_s=bd.get("total_s"),
                        devices=self.config.search_devices,
                        comp_mode=comp_mode,
                        # searched=False marks forced-DP / imported /
                        # caller-supplied strategies so report tooling
                        # can prefer the joint-search table when both
                        # were compiled in one run
                        searched=searched_strategy,
                    )
                if self.config.obs_trace_file:
                    _psim.export_chrome_trace(
                        self.graph, strategy, self.config.obs_trace_file,
                        schedule=_sched, comm_schedule=_comm,
                        total_s=bd.get("total_s"))
            except Exception:  # telemetry must never fail a compile
                self.predicted_breakdown = None
        _placed_lint_cache: list = []

        def _placed_lint_errors():
            """Error findings of the placed-cut legality lint for the
            strategy about to lower — computed ONCE per compile (the
            per-segment sub-lints rebuild block subgraphs) and shared
            by the export decision and the placed-lowering gate."""
            if not _placed_lint_cache:
                from flexflow_tpu.analysis import (
                    errors_only,
                    lint_placement,
                )

                _placed_lint_cache.append(errors_only(lint_placement(
                    self.graph, strategy, self.config)))
            return _placed_lint_cache[0]

        if self.config.export_strategy_file:
            from flexflow_tpu.search.strategy_io import export_strategy

            _meta = {}
            if self.predicted_breakdown:
                _meta["predicted"] = self.predicted_breakdown
            # the calibration signature the strategy was ranked under
            # (content digest of the coherent measured table): fflint
            # strategy compares it against the LIVE CALIBRATION.json
            # (STR210) so a re-probed table flags every strategy file
            # it orphans as stale.  The prediction block above already
            # loaded the table; digest that exact object — it is BOTH
            # the cheaper path and the honest one (the signature
            # describes the table the predicted numbers were priced
            # under).
            from flexflow_tpu.search.cost_cache import calibration_digest

            if _pred_cal is None and self.config.calibration_file:
                from flexflow_tpu.search.driver import (
                    coherent_calibration as _cc,
                )

                _pred_cal = _cc(self.config)
            _cal_sig = calibration_digest(_pred_cal)
            if _cal_sig is not None:
                _meta["calibration_signature"] = _cal_sig
            if self.sync_schedule is not None:
                # the searched comm plan persists NEXT to the strategy,
                # behind the same graph-digest gate import enforces
                _meta["sync_schedule"] = self.sync_schedule.to_jsonable()
            if self.zero_groups:
                # the co-searched per-group optimizer-sharding map
                # rides the same digest gate (fflint checks it, STR207)
                _meta["zero_groups"] = sorted(self.zero_groups)
            if (searched_strategy
                    and getattr(self.config, "objective", "train")
                    == "serve"):
                # the serve objective's SHD16x-gated provenance
                # (objective + SLO budget + frame geometry + predicted
                # p99 + KV residency) persists behind the same digest
                # gate; fflint strategy checks it stdlib-only (STR209)
                from flexflow_tpu.search import driver as _sdriver

                if _sdriver.LAST_SERVING_META:
                    _meta["serving"] = dict(_sdriver.LAST_SERVING_META)
                if _sdriver.LAST_KV_META:
                    # the KV-lane provenance (pool dtype + scale layout
                    # + prefix-sharing residency accounting, SHD168/169
                    # gated in the driver; fflint checks the frame
                    # stdlib-only, STR213).  Persisted BEFORE the dtype
                    # is adopted onto the decode ops, so the exported
                    # digests stay keyed to the attr-free frontend
                    # graph and import's digest gate still passes.
                    _meta["kv"] = dict(_sdriver.LAST_KV_META)
                if (self.disaggregation is not None
                        and self.disaggregation.adopted):
                    # the ADOPTED two-block prefill/decode placement
                    # (search/disaggregation.py — already SHD164/165
                    # gated at proposal); import re-lints against the
                    # target graph, fflint checks the frame stdlib-only
                    # (STR211).  Honest zeros persist nothing.
                    _meta["disaggregation"] = \
                        self.disaggregation.to_meta()
                if self.fleet is not None and self.fleet.adopted:
                    # the ADOPTED N-replica fleet (search/fleet.py —
                    # already SHD166/167 gated at proposal); import
                    # re-lints against the target graph, fflint checks
                    # the frame stdlib-only (STR212)
                    _meta["fleet"] = self.fleet.to_meta()
            # pipeline/placement proposals persist NEXT to the strategy
            # behind the same digest gate (the lint already gated them
            # at proposal time; fflint strategy re-checks the frame
            # stdlib-only, STR208)
            from flexflow_tpu.analysis import placement_meta as _pmeta_fn
            from flexflow_tpu.compiler.placement_lowering import (
                placeable as _placeable,
            )

            # only a cut the placed executor will actually run is a
            # placement proposal: the lowering decision below requires
            # pipeline/mesh unset AND placeable, and the frame must
            # pass the same legality gate the placed branch enforces —
            # a compile that will fail that gate (or run flat under
            # mesh=) must not leave a placement artifact on disk.
            # Inert multi-block strategies (the historical
            # flat-lowering fallback) persist no meta either.
            _pl = (
                _pmeta_fn(self.graph, strategy, self.config)
                if (strategy and pipeline is None and mesh is None
                    and _placeable(self.graph, strategy, self.config)
                    and not _placed_lint_errors())
                else None
            )
            if _pl is not None:
                _meta["placement"] = _pl
            if self.pipeline_proposal is not None:
                _pp = self.pipeline_proposal
                _meta["pipeline"] = {
                    "num_stages": _pp.num_stages,
                    "num_microbatches": _pp.num_microbatches,
                    "stages": [
                        [self.graph.nodes[g].op.name for g in stage]
                        for stage in _pp.stage_guids
                    ],
                }
            elif pipeline is not None:
                _meta["pipeline"] = {
                    "num_stages": pipeline.num_stages,
                    "num_microbatches": pipeline.num_microbatches,
                }
            export_strategy(
                self.config.export_strategy_file, self.graph, strategy,
                meta=_meta or None,
            )
        if self.config.export_strategy_computation_graph_file:
            self.graph.write_dot(
                self.config.export_strategy_computation_graph_file, strategy
            )
        if self.config.export_strategy_task_graph_file:
            from flexflow_tpu.search.simulator import Simulator

            # for_config: search_devices + comp_mode/zero flags match
            # what the search itself costed
            Simulator.for_config(self.config).export_task_graph_dot(
                self.graph, strategy, self.config.export_strategy_task_graph_file
            )

        # KV-lane adoption (searched or imported __meta__.kv, both
        # SHD168/169-gated above): the decode ops take the chosen pool
        # dtype NOW — after every export computed its digests against
        # the attr-free graph, before any lowering builds state
        _adopt_kv_dtype(self.graph, kv_adopt_dtype)

        from flexflow_tpu.compiler.placement_lowering import placeable

        if pipeline is None and mesh is None and strategy and placeable(
                self.graph, strategy, self.config):
            # mesh is None: a user-supplied mesh commits the whole graph
            # to one submesh program, which a 2-block placed strategy
            # cannot honor — fall through to the flat lowering (which
            # respects mesh=) instead of silently ignoring it
            # disjoint start_part device blocks that the placed lowering
            # can express: EXECUTED inter-op placement (reference:
            # mapper.cc:371-475 places ops on disjoint device sets and
            # Legion runs them).  Multi-block strategies OUTSIDE its
            # support (>2 blocks, multi-tensor cuts, grad accumulation)
            # keep the historical behavior: offsets are inert and the
            # single SPMD program replicates small-degree ops.
            from flexflow_tpu.compiler.placement_lowering import (
                PlacedCompiledModel,
            )

            # always-on legality gate on the cut about to execute
            # (search proposals were gated at proposal time; this also
            # covers caller-supplied placed strategies with findings
            # instead of opaque lowering errors).  Shares the export
            # path's one-shot lint cache.
            from flexflow_tpu.analysis import (
                AnalysisError,
                emit_findings,
            )

            _bad = _placed_lint_errors()
            if _bad:
                emit_findings(_bad)
                raise AnalysisError(
                    "placed strategy is illegal for this graph/mesh",
                    _bad)
            self.compiled = PlacedCompiledModel(
                self.graph,
                strategy,
                self.config,
                LossType.from_any(loss_type),
                list(metrics),
                self.optimizer,
            )
        elif pipeline is not None:
            from flexflow_tpu.compiler.pipeline_lowering import PipelinedCompiledModel

            self.compiled = PipelinedCompiledModel(
                self.graph,
                strategy,
                self.config,
                LossType.from_any(loss_type),
                list(metrics),
                self.optimizer,
                pipeline=pipeline,
                block_of=block_of,
            )
        elif (
            self.pipeline_proposal is not None
            and mesh is None
            and comp_mode == "training"
        ):
            # (multi-process raises inside the constructor and falls
            # back to flat via the except below)
            # flat is infeasible and the general staged proposal won:
            # lower it via the heterogeneous staged executor (GPipe over
            # arbitrary graph cuts — compiler/staged_pipeline_lowering)
            from flexflow_tpu.compiler.staged_pipeline_lowering import (
                StagedPipelinedModel,
            )

            try:
                self.compiled = StagedPipelinedModel(
                    self.graph,
                    self.pipeline_proposal.stage_guids,
                    self.pipeline_proposal.num_microbatches,
                    self.config,
                    LossType.from_any(loss_type),
                    list(metrics),
                    self.optimizer,
                )
            except (NotImplementedError, ValueError):
                # stateful stages etc.: keep the flat lowering (the
                # proposal stays surfaced on self.pipeline_proposal)
                self.compiled = None
            if self.compiled is None:
                self.compiled = CompiledModel(
                    self.graph, strategy, self.config,
                    LossType.from_any(loss_type), list(metrics),
                    self.optimizer, mesh=mesh,
                    sync_precision=self.sync_precision_map,
                    sync_schedule=self.sync_schedule,
                    zero_groups=self.zero_groups,
                )
        else:
            self.compiled = CompiledModel(
                self.graph,
                strategy,
                self.config,
                LossType.from_any(loss_type),
                list(metrics),
                self.optimizer,
                mesh=mesh,
                sync_precision=self.sync_precision_map,
                sync_schedule=self.sync_schedule,
                zero_groups=self.zero_groups,
            )
        from flexflow_tpu.compiler.staged_pipeline_lowering import (
            StagedPipelinedModel as _Staged,
        )

        if self.sync_precision_map and not getattr(
                self.compiled, "sync_precision", None):
            # placed/pipelined lowerings manage their own grad paths and
            # do not run _sync_grads yet — say so rather than silently
            # training at fp32 while the user expects compression
            from flexflow_tpu.utils.logging import SEARCH_LOG

            SEARCH_LOG.log(
                f"sync_precision={self.config.sync_precision!r} chose "
                f"{len(self.sync_precision_map)} compressed groups but "
                f"this lowering ({type(self.compiled).__name__}) cannot "
                f"execute them; gradients sync at fp32"
            )
            self.sync_precision_map = {}
        if self.zero_groups and getattr(
                self.compiled, "zero_groups", None) is None:
            # same honesty rule for the per-group optimizer sharding:
            # placed/pipelined lowerings manage their own placement and
            # cannot execute the map — say so instead of silently
            # leaving optimizer state replicated
            from flexflow_tpu.utils.logging import SEARCH_LOG

            SEARCH_LOG.log(
                f"co-search chose {len(self.zero_groups)} "
                f"optimizer-sharded group(s) but this lowering "
                f"({type(self.compiled).__name__}) cannot execute the "
                f"per-group map; optimizer state stays replicated"
            )
            self.zero_groups = ()
        if self.sync_schedule is not None and getattr(
                self.compiled, "sync_schedule", None) is None:
            # same honesty rule for the sync schedule: placed/pipelined
            # lowerings do not run _sync_grads, so the searched comm
            # plan cannot execute there — say so instead of silently
            # falling back to the monolithic sync
            from flexflow_tpu.utils.logging import SEARCH_LOG

            SEARCH_LOG.log(
                f"sync_schedule chose {len(self.sync_schedule.buckets)} "
                f"buckets but this lowering "
                f"({type(self.compiled).__name__}) cannot execute them; "
                f"gradients sync monolithically"
            )
            self.sync_schedule = None

        self._compile_ctx = dict(
            strategy=strategy, loss_type=LossType.from_any(loss_type),
            metrics=list(metrics), pipeline=pipeline, block_of=block_of,
            mesh=mesh,
            sync_precision=dict(self.sync_precision_map),
            sync_schedule=self.sync_schedule,
            zero_groups=self.zero_groups,
            staged=(self.pipeline_proposal
                    if isinstance(self.compiled, _Staged) else None),
        )
        self.params, self.state = self.compiled.init_params(self.config.seed)
        self.opt_state = self.optimizer.init_state(self.params)
        self.opt_state = self.compiled.shard_opt_state(self.opt_state)
        return self.compiled

    def recompile(self):
        """Re-lower the (possibly altered) graph into a fresh XLA
        program, carrying params / optimizer state / model state over
        (reference: dynamic re-optimization, recompile_state.cc — ops
        altered in place; here the program is rebuilt instead)."""
        from flexflow_tpu.compiler.lowering import CompiledModel

        ctx = self._compile_ctx
        if ctx["pipeline"] is not None:
            from flexflow_tpu.compiler.pipeline_lowering import PipelinedCompiledModel

            self.compiled = PipelinedCompiledModel(
                self.graph, ctx["strategy"], self.config, ctx["loss_type"],
                ctx["metrics"], self.optimizer,
                pipeline=ctx["pipeline"], block_of=ctx["block_of"],
            )
        elif ctx.get("staged") is not None:
            # a staged-pipelined model must RE-lower staged: the flat
            # strategy it replaced was HBM-infeasible by construction
            from flexflow_tpu.compiler.staged_pipeline_lowering import (
                StagedPipelinedModel,
            )

            staged = ctx["staged"]
            self.compiled = StagedPipelinedModel(
                self.graph, staged.stage_guids, staged.num_microbatches,
                self.config, ctx["loss_type"], ctx["metrics"],
                self.optimizer,
            )
        else:
            from flexflow_tpu.compiler.placement_lowering import (
                PlacedCompiledModel,
                placeable,
            )

            if ctx.get("mesh") is None and ctx["strategy"] and placeable(
                    self.graph, ctx["strategy"], self.config):
                # a placed model must RE-lower placed: flat re-lowering
                # would silently drop the inter-op placement and carry
                # submesh-committed params into a global-mesh program
                self.compiled = PlacedCompiledModel(
                    self.graph, ctx["strategy"], self.config,
                    ctx["loss_type"], ctx["metrics"], self.optimizer,
                )
            else:
                self.compiled = CompiledModel(
                    self.graph, ctx["strategy"], self.config,
                    ctx["loss_type"], ctx["metrics"], self.optimizer,
                    mesh=ctx.get("mesh"),
                    sync_precision=ctx.get("sync_precision"),
                    sync_schedule=ctx.get("sync_schedule"),
                    zero_groups=ctx.get("zero_groups"),
                )
        old_params, old_state, old_opt = self.params, self.state, self.opt_state
        self.params, self.state = self.compiled.init_params(self.config.seed)
        # shape-checked carry-over: an alter() that changes a weight's
        # shape keeps the fresh init for that weight
        self.params = _merge_matching(self.params, old_params or {})
        self.state = _merge_matching(self.state, old_state or {})
        # optimizer state must match the NEW param tree structure; re-init
        # and carry over leaves whose key paths survived the alteration
        self.opt_state = self.optimizer.init_state(self.params)
        self.opt_state = _merge_matching(self.opt_state, old_opt)
        self.opt_state = self.compiled.shard_opt_state(self.opt_state)
        return self.compiled

    def swap_strategy(self, strategy: Dict[int, MachineView],
                      graph: Optional[Graph] = None, config=None) -> dict:
        """HOT-swap the parallelization strategy between training steps
        (the always-on loop's core mechanism, runtime/controller.py):
        the full live training state — params, optimizer slots, mutable
        op state including EF residuals and KV page pools — is
        checkpointed in memory, the model re-lowers under the new
        (graph, strategy), and every value is re-sharded live onto the
        new strategy's views (``jax.device_put`` onto the fresh
        shardings — a value-identity operation at fp32, test-enforced
        bit-exact).  ``config=`` additionally swaps the FFConfig, which
        is how elastic mesh-size changes (preemption / added capacity)
        re-home the state onto a different device set.

        Gated always-on by the swap-legality lint (analysis/swap.py,
        SHD170-172 + the flat SHD1xx strategy lint).  The searched comm
        plan is rebuilt for the new pair and must re-pass its own
        legality gates; when it does not, the swap falls back to the
        monolithic fp32 sync path instead of failing the run.  Returns
        ``{"fallback", "fresh", "dropped", "swap_seconds"}``."""
        assert self.compiled is not None, "compile() before swap_strategy"
        import time as _time

        from flexflow_tpu.analysis import (
            AnalysisError,
            emit_findings,
            errors_only,
            lint_swap,
        )
        from flexflow_tpu.runtime.checkpoint import snapshot_in_memory

        t0 = _time.perf_counter()
        ctx = self._compile_ctx
        from flexflow_tpu.compiler.placement_lowering import (
            PlacedCompiledModel as _Placed,
        )

        if (ctx.get("pipeline") is not None or ctx.get("staged") is not None
                or ctx.get("mesh") is not None
                # a placed model's ctx has none of the three markers —
                # gate on the lowering itself, or a live inter-op
                # placement would silently re-lower FLAT mid-run
                or isinstance(self.compiled, _Placed)):
            raise NotImplementedError(
                "swap_strategy supports the flat SPMD lowering only — "
                "placed/pipelined/staged/user-mesh models manage their "
                "own placement and cannot re-shard live state this way")
        new_config = config if config is not None else self.config
        new_graph = graph if graph is not None else self.graph
        bad = errors_only(lint_swap(
            self.graph, new_graph, strategy, new_config.num_devices))
        if bad:
            emit_findings(bad)
            raise AnalysisError(
                "hot-swap target is illegal for the live training state",
                bad)
        snap = snapshot_in_memory(self)
        rollback = dict(
            config=self.config, graph=self.graph, strategy=self.strategy,
            compiled=self.compiled, params=self.params,
            opt_state=self.opt_state, state=self.state,
            sync_precision_map=self.sync_precision_map,
            sync_schedule=self.sync_schedule, zero_groups=self.zero_groups,
        )
        try:
            return self._swap_strategy_inner(
                snap, new_config, new_graph, strategy, ctx, t0)
        except Exception:
            # a failed swap (e.g. an elastic GROW past the available
            # device count rejected by mesh construction, or a corrupt
            # cost cache) must leave the model exactly as it was — the
            # OLD program with the OLD state — never half-swapped with
            # config/graph describing a program that does not exist
            for k, v in rollback.items():
                setattr(self, k, v)
            raise

    def _swap_strategy_inner(self, snap, new_config, new_graph, strategy,
                             ctx, t0) -> dict:
        import time as _time

        from flexflow_tpu.analysis import AnalysisError, errors_only
        from flexflow_tpu.compiler.lowering import CompiledModel
        from flexflow_tpu.runtime.checkpoint import restore_in_memory
        from flexflow_tpu.search.driver import coherent_calibration
        from flexflow_tpu.search.simulator import Simulator
        from flexflow_tpu.utils.logging import SEARCH_LOG

        self.config = new_config
        self.graph = new_graph
        self.strategy = strategy
        # ONE calibration load + at most one Simulator per swap (the
        # compile-path discipline): swap latency is a headline number
        _cal = coherent_calibration(self.config)
        _sim = None

        def sim():
            nonlocal _sim
            if _sim is None:
                _sim = Simulator.for_config(self.config, calibration=_cal)
            return _sim

        # rebuild the comm plan for the new pair.  Every piece re-runs
        # its always-on legality gate against what is ACTUALLY being
        # lowered; a searched plan that fails post-swap costs the run
        # its overlap/compression win, never its life — graceful
        # fallback to the monolithic fp32 sync path.
        fallback = False
        pmap: Dict[str, str] = {}
        schedule = None
        zero: tuple = ()
        training = self.config.comp_mode == "training"
        try:
            if training and getattr(
                    self.config, "sync_precision", "fp32") != "fp32":
                from flexflow_tpu.search.sync_precision import (
                    choose_sync_precision,
                )

                pmap = choose_sync_precision(
                    new_graph, strategy, sim().cost)
            if training and getattr(
                    self.config, "sync_schedule", "off") == "search":
                from flexflow_tpu.search.driver import _build_sync_schedule

                schedule = _build_sync_schedule(
                    new_graph, strategy, sim(), self.config)
            if (training and self.zero_groups
                    and not self.config.zero_dp_shard):
                # the co-searched per-group optimizer-sharding map rides
                # along only while it still lints for the new pair —
                # remapping the per-group ZeRO shards is the restore's
                # job, keeping an illegal map is nobody's
                from flexflow_tpu.analysis import lint_zero_map
                from flexflow_tpu.search.machine_model import CostModel

                _zcm = CostModel(
                    self.config.machine_spec,
                    num_devices=self.config.search_devices)
                if not errors_only(lint_zero_map(
                        new_graph, strategy, sorted(self.zero_groups),
                        _zcm)):
                    zero = tuple(self.zero_groups)
        except AnalysisError as e:
            fallback, pmap, schedule, zero = True, {}, None, ()
            SEARCH_LOG.log(
                f"hot swap: searched comm plan failed its legality gate "
                f"post-swap ({e}); falling back to the monolithic fp32 "
                f"sync path")
        self.sync_precision_map = pmap
        self.sync_schedule = schedule
        self.zero_groups = zero
        self.compiled = CompiledModel(
            new_graph, strategy, self.config, ctx["loss_type"],
            ctx["metrics"], self.optimizer,
            sync_precision=pmap, sync_schedule=schedule, zero_groups=zero,
        )
        self.params, self.state = self.compiled.init_params(self.config.seed)
        self.opt_state = self.optimizer.init_state(self.params)
        self.opt_state = self.compiled.shard_opt_state(self.opt_state)
        report = restore_in_memory(self, snap)
        if report["dropped"]:
            SEARCH_LOG.log(
                f"hot swap: {len(report['dropped'])} state entr(ies) "
                f"have no home under the new comm plan and were dropped "
                f"(e.g. {report['dropped'][:3]})")
        ctx.update(
            strategy=strategy, sync_precision=dict(pmap),
            sync_schedule=schedule, zero_groups=zero,
        )
        # refresh the predicted side of the drift loop for the NEW
        # strategy (same consumers and same never-fail rule as compile)
        from flexflow_tpu.obs.events import BUS as _obs_bus

        if (self.config.profiling or _obs_bus.enabled
                or self.config.calibration_file):
            try:
                bd: Dict = {}
                sim().simulate(new_graph, strategy, breakdown=bd,
                               sync_schedule=schedule)
                bd["calibrated"] = sim().cost.calibration is not None
                bd["machine"] = self.config.machine_spec.name
                self.predicted_breakdown = bd
            except Exception:  # telemetry must never fail a swap
                self.predicted_breakdown = None
        report["fallback"] = fallback
        report["swap_seconds"] = _time.perf_counter() - t0
        return report

    # ------------------------------------------------------------------
    def fit(self, x=None, y=None, batch_size: Optional[int] = None,
            epochs: Optional[int] = None, shuffle: bool = True, verbose: bool = True,
            callbacks: Sequence = (), recompile_state=None,
            validation_data=None, validation_split: float = 0.0,
            checkpoint_dir: Optional[str] = None, checkpoint_every: int = 1,
            resume: bool = False):
        """Training loop (reference: flexflow_cffi.py:1832 fit).

        ``callbacks`` follow the keras callback protocol (duck-typed:
        on_train_begin/end, on_epoch_begin, on_epoch_end(epoch, logs) —
        return False from on_epoch_end to stop early).

        ``recompile_state`` — a runtime.recompile.RecompileState checked
        once per iteration (reference: recompile_on_condition,
        model.cc:2273); its alter() may mutate op attrs, after which the
        model re-lowers with params/state carried over.

        ``validation_data=(vx, vy)`` — evaluated after each epoch;
        ``val_*`` keys join the epoch logs/history so callbacks can
        monitor them (keras semantics; the reference's keras frontend
        verifies metrics only on the training set, callbacks.py
        VerifyMetrics).  ``validation_split=f`` holds out the LAST
        fraction of (x, y) — taken before any shuffling, keras's exact
        split formula — as validation_data; mutually exclusive with it.

        ``checkpoint_dir`` — snapshot the full training state (params,
        optimizer state, rng counter) every ``checkpoint_every`` epochs;
        with ``resume=True`` training continues from the latest
        snapshot's next epoch.  Beyond the reference, which has no
        model checkpointing (SURVEY.md §5); runtime/checkpoint.py."""
        import jax

        from flexflow_tpu.runtime.dataloader import SingleDataLoader

        assert self.compiled is not None, "call compile() first"
        if self.config.comp_mode == "inference":
            raise RuntimeError(
                "model was compiled with comp_mode='inference' (forward-"
                "only strategy search, reference COMP_MODE_INFERENCE) — "
                "recompile with comp_mode='training' to fit()"
            )
        if validation_split:
            # keras semantics: the LAST fraction of the data (before any
            # shuffling) becomes the validation set
            if validation_data is not None:
                raise ValueError(
                    "pass either validation_data or validation_split, not both"
                )
            if not 0.0 < validation_split < 1.0:
                raise ValueError(f"validation_split={validation_split} not in (0, 1)")
            xs_all = x if isinstance(x, (list, tuple)) else [x]
            xs_all = [np.asarray(a) for a in xs_all]
            y_all = np.asarray(y)
            n_all = len(y_all)
            cut = int(n_all * (1.0 - validation_split))  # keras's exact formula
            if cut == n_all or cut == 0:
                raise ValueError(
                    f"validation_split={validation_split} of {n_all} samples "
                    "leaves an empty train or validation set"
                )
            validation_data = ([a[cut:] for a in xs_all]
                               if len(xs_all) > 1 else xs_all[0][cut:],
                               y_all[cut:])
            x = [a[:cut] for a in xs_all] if len(xs_all) > 1 else xs_all[0][:cut]
            y = y_all[:cut]
        if validation_data is not None:
            # fail BEFORE training, not after a wasted epoch
            if not isinstance(validation_data, (tuple, list)) or len(
                validation_data
            ) != 2:
                raise ValueError(
                    "validation_data must be an (x, y) pair "
                    "(sample weights are not supported)"
                )
            _vy = np.asarray(validation_data[1])
            _bs = batch_size or self.config.batch_size
            if len(_vy) < _bs:
                raise ValueError(
                    f"validation set ({len(_vy)} samples) is smaller than "
                    f"batch_size ({_bs}) — evaluate() runs full batches "
                    "only, so no validation metric could ever be computed"
                )
            if len(_vy) % _bs:
                print(
                    f"# warning: validation tail of {len(_vy) % _bs} samples "
                    f"(< batch_size {_bs}) is dropped each epoch"
                )
        ckpt_mgr = None
        start_epoch = 0
        if checkpoint_dir is not None:
            # multi-process runs go down CheckpointManager's coordinated
            # orbax multihost path (every process calls save/restore on
            # the same directory; orbax synchronizes the shard writes)
            from flexflow_tpu.runtime.checkpoint import CheckpointManager

            ckpt_mgr = CheckpointManager(checkpoint_dir)
            if resume and ckpt_mgr.latest_step() is not None:
                start_epoch = ckpt_mgr.restore(self) + 1
        elif resume:
            raise ValueError("resume=True requires checkpoint_dir")
        for cb in callbacks:
            # keras callback protocol: bind the model before training
            # (works for both FFModel.fit and the keras Model.fit path,
            # which re-binds with the keras wrapper afterwards)
            if hasattr(cb, "set_model") and getattr(cb, "model", None) is None:
                cb.set_model(self)
        xs = x if isinstance(x, (list, tuple)) else [x]
        batch_size = batch_size or self.config.batch_size
        epochs = epochs or self.config.epochs
        loader = SingleDataLoader(
            self.compiled, [np.asarray(a) for a in xs], np.asarray(y),
            batch_size, shuffle=shuffle, seed=self.config.seed,
        )
        if start_epoch and shuffle:
            # fast-forward the shuffle stream: a resumed epoch N must see
            # the N-th permutation, not replay epoch 0's order
            ff_order = np.arange(loader.num_samples)
            for _ in range(start_epoch):
                loader.rng.shuffle(ff_order)
        if loader.num_batches == 0:
            raise ValueError(
                f"no full batch: {loader.num_samples} samples < batch_size {batch_size}"
            )
        for cb in callbacks:
            cb.on_train_begin()
        profiler = None
        if self.config.profiling:
            from flexflow_tpu.runtime.profiler import StepProfiler

            profiler = StepProfiler()
        # real device-trace capture (obs/annotate.py + trace_ingest.py):
        # the post-compile steps are captured under jax.profiler with
        # the step annotated and the sync buckets lane-stamped (the
        # lowering threaded the markers because device_trace_dir was
        # set at compile); after the run the capture is ingested and
        # tag-matched against the predicted comm lanes.
        capture_dir = self.config.device_trace_dir
        trace_active = False
        self.lane_drift_report = None
        metrics = PerfMetrics()
        history = []
        t_start = None
        steps_done = 0
        steps_at_t0 = 0
        stop = False
        # iteration tracing: run config.trace_steps optimizer steps per
        # compiled call (train_steps scan) — the Legion begin/end_trace
        # analogue.  Incompatible with per-step profiling/recompile
        # checks, which need host control between steps.
        trace_n = max(1, int(getattr(self.config, "trace_steps", 1)))
        use_trace = (
            trace_n > 1
            and profiler is None
            and recompile_state is None
            and jax.process_count() == 1
            and loader.num_batches >= trace_n
            # multi-mesh compositions (inter-op placement) have no
            # single traced program — fall back to per-step calls
            and getattr(self.compiled, "supports_trace", True)
        )
        for epoch in range(start_epoch, epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            metrics.reset()
            acc = None  # device-side metric accumulation; host sync once/epoch
            batch_iter = (
                loader.iter_traced(trace_n) if use_trace else
                (("single", i, l) for i, l in loader)
            )
            for kind, inputs, labels in batch_iter:
                self._rng_counter += 1
                rng = jax.random.key(self._rng_counter)
                step_span = None
                if trace_active:
                    from flexflow_tpu.obs import annotate as _annot

                    # one ff.phase/step annotation per optimizer step:
                    # the window trace_ingest assigns lane markers to
                    step_span = _annot.phase_span(_annot.STEP_PHASE)
                    step_span.__enter__()
                if profiler is not None:
                    profiler.start_step()
                    profiler.start_phase("dispatch")
                if kind == "stack":
                    (self.params, self.opt_state, self.state, losses, ms) = (
                        self.compiled.train_steps(
                            self.params, self.opt_state, self.state, rng,
                            inputs, labels
                        )
                    )
                    loss = losses[-1]
                    # summing the stacked per-step metric trees equals
                    # the single-step accumulation below
                    m = jax.tree.map(lambda a: a.sum(axis=0), ms)
                    n_this = len(losses)
                else:
                    (self.params, self.opt_state, self.state, loss, m) = (
                        self.compiled.train_step(
                            self.params, self.opt_state, self.state, rng,
                            inputs, labels
                        )
                    )
                    n_this = 1
                if profiler is not None:
                    # host phases: enqueue (dispatch) vs device
                    # completion (wait) — the measured side of the
                    # DriftReport; the fence makes the step time real
                    profiler.end_phase("dispatch")
                    profiler.start_phase("wait")
                    float(loss)
                    profiler.end_phase("wait")
                    profiler.end_step()
                elif step_span is not None:
                    # the step annotation must cover the device work,
                    # so a capture without profiling still fences
                    float(loss)
                if step_span is not None:
                    step_span.__exit__(None, None, None)
                if recompile_state is not None and recompile_state.check(self):
                    # drop the accumulator AND this step's metrics: the
                    # re-lowered program may emit a different metric tree
                    acc = None
                else:
                    acc = m if acc is None else jax.tree.map(
                        lambda a, b: a + b, acc, m)
                steps_done += n_this
                if t_start is None:
                    float(loss)  # readback fence (block_until_ready does
                    # not reliably fence through remote-device tunnels)
                    t_start = time.perf_counter()  # skip compile time
                    steps_at_t0 = steps_done
                    if capture_dir and not trace_active:
                        # start the capture AFTER the compile step so
                        # the trace holds steady-state steps only
                        try:
                            import os as _os

                            from flexflow_tpu.obs import annotate as _annot

                            _os.makedirs(capture_dir, exist_ok=True)
                            jax.profiler.start_trace(capture_dir)
                            _annot.arm()
                            _annot.LANES.clear()
                            trace_active = True
                        except Exception:
                            pass  # telemetry must never fail a fit
            if acc is not None:  # None if a recompile landed on the last batch
                metrics.update(acc)
            if verbose:
                print(f"epoch {epoch}: loss={float(loss):.4f} {metrics}")
            logs = metrics.report()
            logs["loss"] = float(loss)
            if validation_data is not None:
                vx, vy = validation_data
                val = self.evaluate(x=vx, y=vy, batch_size=batch_size)
                for k, v in val.items():
                    if k != "samples":
                        logs[f"val_{k}"] = v
                if verbose:
                    parts = " ".join(
                        f"{k}: {v:.4f}" for k, v in logs.items()
                        if k.startswith("val_")
                    )
                    print(f"  validation: {parts}")
            history.append(logs)
            for cb in callbacks:
                if cb.on_epoch_end(epoch, logs) is False:
                    stop = True
            if ckpt_mgr is not None and (
                (epoch + 1) % max(1, checkpoint_every) == 0
                or epoch == epochs - 1 or stop
            ):
                ckpt_mgr.save(epoch, self)
            if stop:
                break
        for cb in callbacks:
            cb.on_train_end()
        if trace_active:
            from flexflow_tpu.obs import annotate as _annot

            _annot.disarm()
            try:
                float(loss)  # fence: the last step must land in-trace
                jax.profiler.stop_trace()
            except Exception:
                trace_active = False
        if steps_done == 0:
            return history
        float(loss)  # readback fence before reading the clock
        elapsed = time.perf_counter() - (t_start or time.perf_counter())
        if steps_done > steps_at_t0 and elapsed > 0:
            thr = (steps_done - steps_at_t0) * batch_size / elapsed
            if verbose:
                print(f"ELAPSED TIME = {elapsed:.4f}s, THROUGHPUT = {thr:.2f} samples/s")
            self.last_throughput = thr
        if profiler is not None:
            self._report_profile(profiler, verbose)
        if trace_active:
            self._ingest_device_trace(capture_dir, verbose)
        if profiler is None and steps_done > steps_at_t0 and elapsed > 0:
            # re-probe-allowance bugfix: a HEALTHY calibrated fit must
            # reset MAX_AUTO_REPROBES even when neither profiling nor
            # the obs bus armed the full drift-report path — fit's own
            # fenced post-compile timer is evidence enough to CLEAR
            # staleness (stale-MARKING stays on the profiler's
            # measurement: a false "stale" poisons the cost cache, a
            # false "healthy" merely re-grants a re-probe)
            self._healthy_calibration_reset(
                elapsed / (steps_done - steps_at_t0))
        return history

    def _ingest_device_trace(self, capture_dir: str, verbose: bool) -> None:
        """Close the measured side of the lane loop: parse the capture
        fit just stopped, tag-match it against the compile-time
        predicted comm lanes, and fill the per-bucket DriftReport
        measured fields that stayed ``None`` while no real trace
        existed.  The report lands on ``self.lane_drift_report`` and
        (when exporting) in the strategy file's ``__meta__``."""
        try:
            from flexflow_tpu.obs.events import BUS
            from flexflow_tpu.obs.trace_ingest import (
                apply_lane_measurements,
                build_lane_drift_report,
            )

            report = build_lane_drift_report(
                capture_dir, getattr(self, "predicted_breakdown", None),
                threshold=self.config.drift_threshold)
            self.lane_drift_report = report
            if report is None:
                return
            apply_lane_measurements(self.drift_report, report)
            if verbose:
                print(f"LANES {report}")
            if self.config.export_strategy_file:
                from flexflow_tpu.search.strategy_io import attach_meta

                try:
                    attach_meta(self.config.export_strategy_file,
                                lane_drift=report.to_dict())
                except (OSError, ValueError):
                    pass
            BUS.flush()
        except Exception:  # telemetry must never fail a fit
            self.lane_drift_report = None

    def _healthy_calibration_reset(self, measured_step_s: float) -> None:
        pred = getattr(self, "predicted_breakdown", None)
        if (not pred or not pred.get("calibrated")
                or not self.config.calibration_file):
            return
        from flexflow_tpu.obs.drift import build_drift_report

        report = build_drift_report(
            pred, measured_step_s=measured_step_s,
            threshold=self.config.drift_threshold, calibrated=True)
        if report is None or report.stale:
            return
        from flexflow_tpu.search.calibration import CalibrationTable

        CalibrationTable.mark_healthy_file(self.config.calibration_file)

    def _report_profile(self, profiler, verbose: bool) -> None:
        """Step-profile reporting through the obs metrics registry +
        event bus (replacing the ad-hoc ``print(f"PROFILE ...")``-only
        path), plus the predicted-vs-measured DriftReport when
        compile() recorded a prediction."""
        from flexflow_tpu.obs.drift import build_drift_report
        from flexflow_tpu.obs.events import BUS
        from flexflow_tpu.obs.metrics import METRICS

        s = profiler.summary()
        if s.get("steps") and not s.get("includes_compile"):
            # compile-contaminated stats stay out of the registry the
            # same way the drift path declines them — a gauge has no
            # honesty flag to carry the caveat
            METRICS.gauge("fit.step_mean_s").set(s["mean_s"])
            METRICS.gauge("fit.step_p95_s").set(s["p95_s"])
            METRICS.counter("fit.steps").inc(int(s["steps"]))
            hist = METRICS.histogram("fit.step_s")
            for t in profiler.step_times[1:]:
                hist.observe(t)
        BUS.emit("profile.summary", **s)
        if verbose:
            print(f"PROFILE {profiler}")
        pred = getattr(self, "predicted_breakdown", None)
        if not pred or not s.get("steps") or s.get("includes_compile"):
            # a compile-only measurement would compare apples to the
            # compile step; decline rather than report fiction
            return
        report = build_drift_report(
            pred,
            measured_step_s=s["mean_s"],
            measured_phases=profiler.phase_summary(),
            threshold=self.config.drift_threshold,
            calibrated=bool(pred.get("calibrated")),
        )
        if report is None:
            return
        self.drift_report = report
        BUS.emit("drift.report", **report.to_dict())
        METRICS.gauge("fit.drift_ratio").set(report.ratio)
        if report.calibration_stale:
            BUS.emit("calibration.staleness", ratio=report.ratio,
                     threshold=report.threshold)
            from flexflow_tpu.utils.logging import SEARCH_LOG

            lo = 1.0 / (1.0 + report.threshold)
            hi = 1.0 + report.threshold
            SEARCH_LOG.log(
                f"calibration staleness: measured step is "
                f"{report.ratio:.2f}x the calibrated prediction, "
                f"outside [{lo:.2f}x, {hi:.2f}x]"
            )
            # mark the persisted TABLE stale so the next
            # optimize_strategy re-probes the drifted records
            # automatically (driver re-probe policy) instead of ranking
            # with measurements execution just falsified
            if self.config.calibration_file:
                from flexflow_tpu.search.calibration import (
                    CalibrationTable,
                )

                if CalibrationTable.mark_stale_file(
                        self.config.calibration_file, report.ratio):
                    SEARCH_LOG.log(
                        f"calibration table "
                        f"{self.config.calibration_file} marked stale: "
                        f"the next search re-probes it on the modeled "
                        f"backend (or falls back to the roofline)"
                    )
            # a stale table must also stop seeding future searches: mark
            # the persistent cost cache, which then refuses to serve its
            # rows/results until a recalibration rotates the signature
            from flexflow_tpu.search.cost_cache import (
                mark_calibration_stale,
                resolve_cost_cache_path,
            )

            cache_path = resolve_cost_cache_path(self.config)
            if cache_path and mark_calibration_stale(cache_path):
                SEARCH_LOG.log(
                    f"cost cache {cache_path} marked calibration-stale: "
                    f"recalibrate or pass --no-cost-cache"
                )
        elif report.calibrated and self.config.calibration_file:
            # drift cleared on a calibrated fit: reset the persisted
            # staleness state and the auto-re-probe allowance, so the
            # driver's re-probe cap only counts CONSECUTIVE failures
            from flexflow_tpu.search.calibration import CalibrationTable

            CalibrationTable.mark_healthy_file(self.config.calibration_file)
        if verbose:
            print(f"DRIFT {report}")
        if self.config.export_strategy_file:
            from flexflow_tpu.search.strategy_io import attach_meta

            try:
                attach_meta(self.config.export_strategy_file,
                            drift=report.to_dict())
            except (OSError, ValueError):
                pass
        BUS.flush()  # writes are block-buffered; a fit boundary is
        # where tooling tails the log

    def evaluate(self, x=None, y=None, batch_size: Optional[int] = None):
        """reference: flexflow_cffi.py:1876 eval."""
        from flexflow_tpu.runtime.dataloader import SingleDataLoader

        xs = x if isinstance(x, (list, tuple)) else [x]
        batch_size = batch_size or self.config.batch_size
        loader = SingleDataLoader(
            self.compiled, [np.asarray(a) for a in xs], np.asarray(y),
            batch_size, shuffle=False,
        )
        metrics = PerfMetrics()
        total_loss, batches = 0.0, 0
        for inputs, labels in loader:
            loss, m = self.compiled.eval_step(
                self.params, self.state, inputs, labels
            )
            total_loss += float(loss)
            batches += 1
            metrics.update(m)
        rep = metrics.report()
        if batches:  # equal-sized batches: mean of batch means is exact
            rep["loss"] = total_loss / batches
        return rep

    def predict(self, x, batch_size: Optional[int] = None) -> np.ndarray:
        """Batched forward pass: one output row per input row (a short
        tail batch is padded to batch_size and trimmed — the compiled
        program has static shapes).  The inference verb pairing with
        compile(comp_mode='inference'); reference models predict via
        their eval path only."""
        assert self.compiled is not None, "call compile() first"
        batch_size = batch_size or self.config.batch_size
        xs = x if isinstance(x, (list, tuple)) else [x]
        xs = [np.asarray(a) for a in xs]
        n = xs[0].shape[0]
        fwd = self.compiled.forward_fn()
        outs = []
        for i in range(0, n, batch_size):
            batch = [a[i:i + batch_size] for a in xs]
            got = batch[0].shape[0]
            if got < batch_size:
                batch = [
                    np.concatenate(
                        [b, np.repeat(b[-1:], batch_size - got, axis=0)],
                        axis=0,
                    )
                    for b in batch
                ]
            y = np.asarray(fwd(self.params, self.state, batch))
            outs.append(y[:got])
        if outs:
            return np.concatenate(outs, axis=0)
        import jax

        zero_batch = [
            jax.ShapeDtypeStruct((batch_size,) + a.shape[1:], a.dtype)
            for a in xs
        ]
        spec = jax.eval_shape(fwd, self.params, self.state, zero_batch)
        return np.empty((0,) + tuple(spec.shape[1:]), spec.dtype)

    # ------------------------------------------------------------------
    def get_weight(self, op_name: str, weight_name: str = "kernel") -> np.ndarray:
        """reference: ParallelTensorBase::get_tensor (parallel_tensor.h:157)."""
        return np.asarray(self.params[op_name][weight_name])

    def set_weight(self, op_name: str, weight_name: str, value: np.ndarray) -> None:
        import jax

        old = self.params[op_name][weight_name]
        assert tuple(old.shape) == tuple(value.shape)
        self.params[op_name][weight_name] = jax.device_put(
            value.astype(old.dtype), old.sharding
        )

    def set_state_var(self, key: str, value: np.ndarray) -> None:
        """Overwrite one model-state entry (e.g. a batch-norm running
        statistic, key ``"<op>/running_mean"``)."""
        import jax

        old = self.state[key]
        assert tuple(old.shape) == tuple(value.shape), (key, old.shape, value.shape)
        self.state[key] = jax.device_put(value.astype(old.dtype), old.sharding)
