"""Bucketed, issue-ordered gradient sync — the execution half of the
sync SCHEDULE (search/sync_schedule.py).

The monolithic ``_sync_grads`` fires every weight group's collective
after the whole backward; GSPMD-style compilers hide reduction latency
by issuing collectives asynchronously under the remaining backward
compute (arXiv:2105.04663), and the weight-update/sync tail is where
data-parallel steps lose their time (arXiv:2004.13336).  This module
executes a searched ``SyncSchedule`` for real:

* **Fused wire payload** — a compressed bucket's member grads flatten
  into ONE buffer per replication group and ride a single
  ``quantized_allreduce`` round trip (int8/bf16 chunk-scaled wire,
  comm/quantized.py): k collectives' latency floors collapse into one,
  exactly the amortization the cost model prices
  (``CostModel.bucket_sync_cost``).
* **Issue ordering** — buckets chain through
  ``lax.optimization_barrier``: bucket k+1's payload is data-dependent
  on bucket k's result, so XLA must issue the collectives in schedule
  order (reverse-topological = backward grad-readiness order) instead
  of clumping them after the last use, and its latency-hiding scheduler
  may overlap each one with backward compute that does not feed it.
* **fp32 buckets are bit-exact** — their gradients were already reduced
  by GSPMD's own backward psum (the fp32 "wire" is that psum); the
  bucket contributes only its ordering barrier, which is a value
  identity, so an all-fp32 schedule produces bitwise the same step as
  the monolithic lowering (test-enforced).  Sub-floor weights inside a
  compressed bucket pass through untouched (``MIN_COMPRESS_ELEMS``),
  mirroring ``quantized_grad_sync`` and the cost model exactly.

Composition: the round trip runs before the optimizer update, so
ZeRO-1's reduce-scatter/all-gather placement (``_constrain_update``)
and grad accumulation (sync of the averaged grads) are untouched.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
from jax import lax

from flexflow_tpu.comm.quantized import (
    DEFAULT_CHUNK,
    MIN_COMPRESS_ELEMS,
    quantized_allreduce,
    quantized_allreduce_ef,
    replication_axes,
)


def _ordered(arrays: List[jax.Array], token) -> Tuple[List[jax.Array], object]:
    """Tie ``arrays`` to the previous bucket's completion token: every
    output of one optimization_barrier depends on every input, so the
    collectives consuming the returned arrays cannot issue before the
    token's producer — the schedule's serialization, with zero value
    change."""
    if token is None or not arrays:
        return arrays, token
    tied = lax.optimization_barrier(tuple(arrays) + (token,))
    return list(tied[:-1]), tied[-1]


def bucketed_grad_sync(
    grads: Dict[str, Dict[str, jax.Array]],
    mesh,
    param_shardings: Dict[str, Dict[str, "jax.sharding.NamedSharding"]],
    schedule,
    chunk: int = DEFAULT_CHUNK,
    machine=None,
    residuals: Optional[Dict[str, Dict[str, jax.Array]]] = None,
    lane_stamps: bool = False,
):
    """Run ``schedule``'s buckets in issue order over ``grads`` (the
    already-GSPMD-reduced gradient tree) — call inside the jitted step,
    before the optimizer update.  Ops absent from the schedule (or
    whose params consume the whole mesh) pass through untouched, as do
    fp32 buckets' values and sub-floor weights of compressed buckets.

    ``machine`` (a MachineSpec) arms the staged execution of buckets
    carrying a reduction PLAN (search/reduction_plan.py): their
    compressed wire runs the hierarchical RS → cross-slice exchange →
    AG shape (comm/hierarchical.py) over the plan's nested axis
    groupings instead of one flat collective.  All-fp32 plans stay
    value-identity anchors — bit-exact with the monolithic path.

    ``residuals`` — error-feedback state (op → weight → residual,
    sharded like the param) for ``int8_ef`` buckets: their fused
    payload rides ``quantized_allreduce_ef`` — the residuals flatten
    into the SAME fused buffer as the grads, so the feedback composes
    with coalescing — and the call returns ``(merged, new_residuals)``
    for the training loop to persist.  Staged (plan-carrying) buckets
    execute their cross stage at the plain int8 wire and skip EF
    (exactly how the cost model priced them); with ``residuals=None``
    int8_ef degrades to plain int8 and the legacy return shape is
    kept.

    ``lane_stamps`` (``FFConfig.device_trace_dir`` consumers only)
    brackets each bucket's collectives with ordered host-callback
    markers carrying the bucket's STABLE lane id
    (``bucket:<name>:sync`` — the simulator's comm_schedule name), so
    a live ``device_trace`` capture records when the runtime actually
    issued and finished each lane (obs/annotate.py; matched back to
    the predicted lanes by obs/trace_ingest.py).  Off (the default)
    the lowered program is byte-identical to history."""
    from flexflow_tpu.comm.compat import shard_map
    from flexflow_tpu.comm.hierarchical import (
        plan_axis_groups,
        plan_cross_precision,
        staged_allreduce,
    )

    merged = {op: dict(ws) for op, ws in grads.items()}
    new_res: Dict[str, Dict[str, jax.Array]] = {}
    token = None
    for bucket in getattr(schedule, "buckets", schedule):
        prec = getattr(bucket, "precision", "fp32")
        plan = getattr(bucket, "plan", None)
        cross_prec = plan_cross_precision(plan)
        # a plan whose every stage is fp32 has no explicit wire work
        # (GSPMD's own psum reduced the grads; the priced stages model
        # XLA's hierarchical psum) — its members all pass through
        wire = prec in ("bf16", "int8", "int8_ef") and (
            plan is None or cross_prec is not None)
        # EF rides every group that executes the FLAT collective —
        # including the within-slice groups of a plan-carrying bucket
        # (pricing charges them the EF passes, bucket_sync_cost);
        # groups the plan actually STAGES skip EF on both sides (the
        # cross stage carries already-reduced shards the residual
        # never sees), decided per group below once `staged` is known
        ef = prec == "int8_ef" and residuals is not None
        # bucket members' replicated grads, grouped by replication axes
        # — one fused payload per (axes, n, has-residual) group (EF and
        # residual-less members must not share a collective: the fused
        # buffer either threads feedback or it does not)
        groups: Dict[Tuple, List[Tuple]] = {}
        plain: List[Tuple[str, str, jax.Array]] = []
        for op_name in bucket.ops:
            for w_name, g in grads.get(op_name, {}).items():
                sh = param_shardings.get(op_name, {}).get(w_name)
                if sh is None:
                    continue
                rep, n = replication_axes(sh, mesh)
                if not rep:
                    continue
                if wire and g.size >= MIN_COMPRESS_ELEMS:
                    r = (residuals or {}).get(op_name, {}).get(w_name) \
                        if ef else None
                    groups.setdefault((rep, n, r is not None), []).append(
                        (op_name, w_name, g, sh.spec, r))
                else:
                    # fp32 wire = GSPMD's own backward psum (already
                    # happened); the bucket only anchors issue order
                    plain.append((op_name, w_name, g))
        lane = f"bucket:{bucket.name}:sync"
        if lane_stamps and (groups or plain):
            from flexflow_tpu.obs import annotate

            # the issue marker depends on every member grad (fires once
            # the bucket's payload is ready) and its 0.0 result is
            # folded into the first member's PAYLOAD — the collectives
            # consume it, so the marker both precedes them and stays
            # live (XLA prunes optimization-barrier operands whose
            # outputs are unused, so the token chain alone is not a
            # liveness anchor).  The marker's trace timestamp IS the
            # lane's host-observed issue point.
            deps = [m[2].ravel()[0] for ms in groups.values()
                    for m in ms]
            deps += [g.ravel()[0] for _o, _w, g in plain]
            d = deps[0]
            for x in deps[1:]:
                d = d + x.astype(d.dtype)
            z = annotate.lane_stamp(lane, "issue", d)
            if groups:
                key = next(iter(groups))
                m0 = groups[key][0]
                groups[key][0] = m0[:2] + (
                    m0[2] + z.astype(m0[2].dtype),) + m0[3:]
            else:
                o0, w0, g0 = plain[0]
                plain[0] = (o0, w0, g0 + z.astype(g0.dtype))
        toks: List[jax.Array] = []
        for (rep, n, has_res), members in groups.items():
            gs = [g for _o, _w, g, _s, _r in members]
            gs, token = _ordered(gs, token)
            specs = [s for _o, _w, _g, s, _r in members]
            # per-group reduction: the plan's staged shape when its
            # cross stage has axes to ride on this group, the flat
            # quantized collective otherwise (a within-slice group of a
            # staged bucket runs flat at the bucket precision — exactly
            # how the cost model priced it)
            staged = None
            if plan is not None and cross_prec is not None \
                    and machine is not None:
                st_axes, st_sizes = plan_axis_groups(
                    rep, mesh, machine, plan.cross_level)
                if st_axes[-1]:
                    staged = (st_axes, st_sizes)
            # int8_ef's wire IS int8 — EF changes what is quantized
            wire_prec = "int8" if prec == "int8_ef" else prec

            def reduce_flat(flat, _rep=rep, _n=n, _staged=staged):
                if _staged is not None:
                    return staged_allreduce(
                        flat, _staged[0], _staged[1], cross_prec,
                        chunk=chunk, mean=True)
                return quantized_allreduce(
                    flat, _rep, precision=wire_prec, chunk=chunk,
                    mean=True, axis_size=_n,
                )

            def fused(*local, _red=reduce_flat):
                # flatten the bucket into ONE wire payload: the fused
                # collective pays a single latency floor for the whole
                # bucket (what coalescing buys)
                sizes = [x.size for x in local]
                flat = (
                    local[0].reshape(-1) if len(local) == 1 else
                    jax.numpy.concatenate([x.reshape(-1) for x in local])
                )
                red = _red(flat)
                out, off = [], 0
                for x, sz in zip(local, sizes):
                    out.append(red[off:off + sz].reshape(x.shape))
                    off += sz
                return tuple(out)

            def fused_ef(*local, _rep=rep, _n=n):
                # EF variant: grads then residuals, each flattened into
                # one fused buffer — feedback rides the SAME coalesced
                # collective the schedule priced
                k = len(local) // 2
                gs_loc, rs_loc = local[:k], local[k:]
                sizes = [x.size for x in gs_loc]
                cat = (lambda xs: xs[0].reshape(-1) if len(xs) == 1 else
                       jax.numpy.concatenate([x.reshape(-1) for x in xs]))
                red, nr = quantized_allreduce_ef(
                    cat(gs_loc), cat(rs_loc), _rep, precision="int8",
                    chunk=chunk, mean=True, axis_size=_n,
                )
                out, rout, off = [], [], 0
                for x, sz in zip(gs_loc, sizes):
                    out.append(red[off:off + sz].reshape(x.shape))
                    rout.append(nr[off:off + sz].reshape(x.shape))
                    off += sz
                return tuple(out) + tuple(rout)

            if has_res and staged is not None:
                # the plan stages this group: the cross-slice exchange
                # carries already-reduced shards the residual never
                # sees, so EF is off for it — exactly how
                # bucket_sync_cost priced it (staged stages at the
                # plain wire, no EF passes); the residual is left
                # untouched, not advanced with stale feedback
                has_res = False
            if has_res:
                rs = [r for _o, _w, _g, _s, r in members]
                outs = shard_map(
                    fused_ef, mesh=mesh,
                    in_specs=tuple(specs) + tuple(specs),
                    out_specs=tuple(specs) + tuple(specs),
                )(*gs, *rs)
                synced, res_out = outs[:len(members)], outs[len(members):]
                for (op_name, w_name, _g, _s, _r), nr in zip(
                        members, res_out):
                    new_res.setdefault(op_name, {})[w_name] = nr
            else:
                synced = shard_map(
                    fused, mesh=mesh, in_specs=tuple(specs),
                    out_specs=tuple(specs),
                )(*gs)
            for (op_name, w_name, _g, _s, _r), y in zip(members, synced):
                merged[op_name][w_name] = y
            # one completion scalar PER fused collective: the next
            # bucket must order after every one of this bucket's
            # replication-group collectives, not just the first
            toks.append(synced[0].ravel()[0])
        if plain:
            gs = [g for _o, _w, g in plain]
            gs, token = _ordered(gs, token)
            for (op_name, w_name, _g), y in zip(plain, gs):
                merged[op_name][w_name] = y
            toks.append(gs[0].ravel()[0])
        if toks:
            # completion token for the NEXT bucket's barrier — summing
            # makes it data-dependent on ALL of this bucket's
            # collectives, so bucket k+1 cannot issue before any of
            # bucket k's groups
            token = toks[0]
            for t in toks[1:]:
                token = token + t
            if lane_stamps:
                from flexflow_tpu.obs import annotate

                # the done marker depends on every collective of this
                # bucket (the summed token) — its trace timestamp is
                # the lane's host-observed completion.  Its 0.0 result
                # is tied into one of the bucket's LIVE outputs: the
                # last bucket's token feeds nothing downstream, and an
                # unused pure_callback is dead code XLA may eliminate
                z = annotate.lane_stamp(lane, "done", token)
                token = token + z
                o, w = (next(iter(groups.values()))[0][:2] if groups
                        else plain[0][:2])
                merged[o][w] = merged[o][w] + z.astype(
                    merged[o][w].dtype)
    if residuals is None:
        return merged
    return merged, new_res
