"""EQuARX-style quantized allreduce (arXiv:2506.17615).

The weight-gradient allreduce is the dominant term of sync-bound data
parallelism (per-device batch 1, full widths — the regime
bench_search.py's BERT exec tier targets).  EQuARX shows a
block-scaled int8 allreduce inside XLA cuts that wire time ~2-4x; the
cross-replica weight-update sharding paper (arXiv:2004.13336, our
ZeRO-1 path) already treats sync cost as a first-class lever.  This
module is the execution half: a quantized allreduce built from
``psum_scatter``/``all_gather`` with per-chunk scales, an exact-fp32
fallback, and an error-bound contract the tests assert.

Shape of the collective (both compressed precisions):

    quantize(local) → all_to_all of the COMPRESSED payload
    → dequantize+sum the owned shard → requantize
    → all_gather of the COMPRESSED reduced shards → dequantize

The reduce phase is an all_to_all of int8 chunks (+ their fp32
scales): each device ships shard j of its quantized addend to device
j — the same (n-1)/n·bytes a reduce-scatter moves, but the wire
genuinely carries the compressed format (psum_scatter would force a
dequantized fp32 operand, silently un-realizing the priced win).  The
owner dequantizes its n received shards and accumulates in fp32 —
EQuARX's per-hop dequant-accumulate — then requantizes for the
all-gather phase, whose payload is int8 too.  Exactly the two
compressed wire phases the cost model prices
(search/machine_model.py ``allreduce(precision=...)``).  fp32 is a
plain ``lax.psum``: bit-exact with the uncompressed lowering.

Honesty note: under GSPMD the backward's own psum has already reduced
the gradient by the time the optimizer sees it, so execution routes the
*reduced* gradient through this collective round-trip over the
replication axes — on top of, not instead of, XLA's internal reduce.
Numerics and wire format are real; the net step-time win is the priced
number, and a CPU-mesh executed ratio measures the compression
overhead, not the ICI saving.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SYNC_PRECISIONS = ("fp32", "bf16", "int8")

# elements per int8 scale block.  256 keeps the scale overhead at
# 4/256 = 1.6% of the compressed payload while bounding the blast
# radius of one outlier element to its own chunk (EQuARX block scaling)
DEFAULT_CHUNK = 256

# weight groups below this many elements never compress: their sync is
# latency-bound (nothing to win) and bias/scale vectors are exactly
# these.  THE shared floor — the search's safety heuristic
# (search/sync_precision.py) and the execution path (quantized_grad_sync
# skips sub-floor leaves even inside a compressed op) both import it,
# as does the cost model's per-weight pricing.
MIN_COMPRESS_ELEMS = 1 << 16

_AxisNames = Union[str, Tuple[str, ...]]


def quantize_chunked(x: jax.Array, chunk: int = DEFAULT_CHUNK):
    """Flatten ``x`` and quantize per-chunk to symmetric int8.

    Returns ``(q [nchunks, chunk] int8, scale [nchunks, 1] fp32)``.
    The tail is zero-padded to a whole chunk; all-zero chunks get scale
    1 so their round trip is exact.  |q| <= 127 by construction (the
    scale is amax/127, so the largest magnitude maps to ±127)."""
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % chunk
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, chunk)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(blocks / scale).astype(jnp.int8)
    return q, scale


def dequantize_chunked(
    q: jax.Array, scale: jax.Array, size: int, shape: Tuple[int, ...]
):
    """Inverse of quantize_chunked: drop the tail padding and restore
    ``shape`` (``size`` = number of real elements)."""
    blocks = q.astype(jnp.float32) * scale
    return blocks.reshape(-1)[:size].reshape(shape)


def quantized_allreduce(
    x: jax.Array,
    axis_name: _AxisNames,
    precision: str = "fp32",
    chunk: int = DEFAULT_CHUNK,
    mean: bool = False,
    axis_size: Optional[int] = None,
) -> jax.Array:
    """Allreduce of ``x`` over ``axis_name`` — call inside shard_map.

    ``precision`` one of SYNC_PRECISIONS.  fp32 is an exact
    ``lax.psum``.  bf16/int8 compress both wire phases (see module
    docstring); the result satisfies the ``allreduce_error_bound``
    contract.  ``axis_size`` (product of the named axes' sizes) is
    required for the compressed precisions and for ``mean`` — it shapes
    the scatter and must be static."""
    if precision not in SYNC_PRECISIONS:
        raise ValueError(
            f"precision must be one of {SYNC_PRECISIONS}, got {precision!r}"
        )
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if precision == "fp32":
        y = lax.psum(x, axes)
        if mean:
            if axis_size is None:
                raise ValueError("mean=True requires axis_size")
            y = y / axis_size
        return y
    if axis_size is None:
        raise ValueError(f"precision={precision!r} requires axis_size")
    n = int(axis_size)
    orig_shape, size, orig_dtype = x.shape, x.size, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    # pad so every device's owned share is a whole number of chunks
    pad = (-flat.shape[0]) % (n * chunk)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    if precision == "int8":
        # stage 1: quantize locally, then EXCHANGE THE INT8 PAYLOAD —
        # shard j of every device's addend lands on device j
        # (all_to_all moves the same (n-1)/n·bytes a reduce-scatter
        # would, in the compressed format the cost model prices)
        q, s = quantize_chunked(flat, chunk)          # [C, chunk], [C, 1]
        qn = q.reshape(n, -1, chunk)
        sn = s.reshape(n, -1, 1)
        q_recv = lax.all_to_all(qn, axes, split_axis=0, concat_axis=0,
                                tiled=True).reshape(n, -1, chunk)
        s_recv = lax.all_to_all(sn, axes, split_axis=0, concat_axis=0,
                                tiled=True).reshape(n, -1, 1)
        # owner-side dequantize + fp32 accumulate (EQuARX's per-hop
        # dequant-accumulate), then requantize for the gather phase
        part = jnp.sum(q_recv.astype(jnp.float32) * s_recv, axis=0)
        q2, s2 = quantize_chunked(part, chunk)
        # stage 2: all-gather of the still-compressed reduced shards
        full_q = lax.all_gather(q2, axes, axis=0, tiled=True)
        full_s = lax.all_gather(s2, axes, axis=0, tiled=True)
        full = (full_q.astype(jnp.float32) * full_s).reshape(-1)
    else:
        bn = flat.astype(jnp.bfloat16).reshape(n, -1)
        b_recv = lax.all_to_all(bn, axes, split_axis=0, concat_axis=0,
                                tiled=True).reshape(n, -1)
        part = jnp.sum(b_recv.astype(jnp.float32), axis=0)
        full = lax.all_gather(
            part.astype(jnp.bfloat16), axes, axis=0, tiled=True
        ).astype(jnp.float32)
    out = full[:size].reshape(orig_shape)
    if mean:
        out = out / n
    return out.astype(orig_dtype)


def quantized_allreduce_ef(
    x: jax.Array,
    residual: jax.Array,
    axis_name: _AxisNames,
    precision: str = "int8",
    chunk: int = DEFAULT_CHUNK,
    mean: bool = False,
    axis_size: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Error-feedback (residual) variant of ``quantized_allreduce``:
    each device transmits ``quantize(x + residual)`` and carries the
    local quantization error forward — ``residual' = (x + residual) -
    dequantize(quantize(x + residual))`` — so the compression error is
    re-injected instead of lost (EF-SGD; what keeps int8 sync safe at
    large replica counts, where n independent per-step roundings would
    otherwise accumulate a bias the lone-step error bound does not
    see).  Returns ``(reduced, new_residual)``; the caller threads the
    residual across steps like optimizer state.  fp32 is the exact
    psum with a zero residual.  The feedback compensates the entry
    (stage-1) quantization — the per-addend error EF-SGD corrects; the
    reduced-shard requantize of stage 2 remains bounded by the
    one-step contract (``allreduce_error_bound``)."""
    if precision not in SYNC_PRECISIONS:
        raise ValueError(
            f"precision must be one of {SYNC_PRECISIONS}, got {precision!r}"
        )
    if precision == "fp32":
        return (
            quantized_allreduce(x, axis_name, "fp32", chunk, mean,
                                axis_size),
            jnp.zeros_like(x, dtype=jnp.float32),
        )
    carry = x.astype(jnp.float32) + residual.astype(jnp.float32)
    if precision == "int8":
        q, s = quantize_chunked(carry, chunk)
        approx = dequantize_chunked(q, s, carry.size, carry.shape)
    else:
        approx = carry.astype(jnp.bfloat16).astype(jnp.float32)
    new_residual = carry - approx
    out = quantized_allreduce(
        carry, axis_name, precision=precision, chunk=chunk, mean=mean,
        axis_size=axis_size,
    ).astype(x.dtype)
    return out, new_residual


def allreduce_error_bound(
    per_device_inputs, precision: str, chunk: int = DEFAULT_CHUNK
) -> float:
    """Max-abs error bound of ``quantized_allreduce`` vs the exact fp32
    psum of ``per_device_inputs`` (a sequence of the n local addends).

    int8: stage 1 rounds each addend to its chunk scale (half-ulp error
    <= amax_i/254 per element, summed over addends); stage 2 rounds the
    reduced value once more (<= amax(sum)/254 <= sum_i amax_i/254).
    Global-amax form — per-chunk scales only tighten it.  bf16: same
    two stages at half-ulp relative error 2^-8 for an 8-bit
    significand.  A 5% headroom absorbs the fp32 accumulation rounding
    of the reduction itself."""
    if precision not in SYNC_PRECISIONS:
        raise ValueError(f"unknown precision {precision!r}")
    if precision == "fp32":
        return 0.0
    total = float(
        sum(np.max(np.abs(np.asarray(x))) for x in per_device_inputs)
    )
    per_stage = total / 254.0 if precision == "int8" else total * 2.0 ** -8
    return 1.05 * 2.0 * per_stage + 1e-12


def replication_axes(sharding, mesh) -> Tuple[Tuple[str, ...], int]:
    """The mesh axes a param's PartitionSpec does NOT consume (its
    gradient is replicated — and psummed by GSPMD — across exactly
    these), plus their total extent.  THE shared rule between the
    per-group quantized sync below and the bucketed fused sync
    (comm/bucketed.py)."""
    used = set()
    for entry in sharding.spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            used.add(a)
    rep = tuple(
        a for a, s in mesh.shape.items() if a not in used and s > 1
    )
    n = 1
    for a in rep:
        n *= mesh.shape[a]
    return rep, n


def quantized_grad_sync(
    grads: Dict[str, Dict[str, jax.Array]],
    mesh,
    param_shardings: Dict[str, Dict[str, "jax.sharding.NamedSharding"]],
    precision_map: Dict[str, str],
    chunk: int = DEFAULT_CHUNK,
    residuals: Optional[Dict[str, Dict[str, jax.Array]]] = None,
):
    """Route the weight groups named by ``precision_map`` (op name →
    bf16/int8/int8_ef) through the quantized collective over their
    replication axes — the mesh axes the param's PartitionSpec does not
    consume.

    Gradients arrive already reduced (replicated across those axes), so
    the round trip sums n identical addends and divides by n: the value
    is preserved up to the two quantization stages, which run for real.
    Groups whose params consume the whole mesh (nothing replicated),
    fp32 groups, and sub-MIN_COMPRESS_ELEMS weights (the bias/scale
    vectors of an otherwise-compressed op — latency-bound sync, nothing
    to win) pass through untouched — with an empty map the function is
    an identity and the lowering is bit-exact with history.

    ``residuals`` — the error-feedback state tree (op → weight →
    residual array, sharded like the param) for ``int8_ef`` groups:
    each is threaded through ``quantized_allreduce_ef`` and the call
    then returns ``(merged_grads, new_residuals)`` so the training loop
    can persist the updated residuals (compiler/lowering.py carries
    them in the model-state dict).  With ``residuals=None`` (legacy
    callers) the signature and return value are unchanged and
    ``int8_ef`` degrades to the plain int8 wire — EF without its state
    would silently re-zero the residual every step."""
    from jax.sharding import PartitionSpec

    from flexflow_tpu.comm.compat import shard_map

    sel: Dict[str, Dict[str, jax.Array]] = {}
    res_sel: Dict[str, Dict[str, jax.Array]] = {}
    specs: Dict[str, Dict[str, PartitionSpec]] = {}
    res_specs: Dict[str, Dict[str, PartitionSpec]] = {}
    plan: Dict[str, Dict[str, Tuple[Tuple[str, ...], str, int]]] = {}
    for op_name, prec in precision_map.items():
        if prec == "fp32":
            continue
        for w_name, g in grads.get(op_name, {}).items():
            if g.size < MIN_COMPRESS_ELEMS:
                continue
            sh = param_shardings.get(op_name, {}).get(w_name)
            if sh is None:
                continue
            rep, n = replication_axes(sh, mesh)
            if not rep:
                continue
            p = prec
            if p == "int8_ef":
                r = (residuals or {}).get(op_name, {}).get(w_name)
                if r is None:
                    p = "int8"  # no state to thread — plain wire
                else:
                    res_sel.setdefault(op_name, {})[w_name] = r
                    res_specs.setdefault(op_name, {})[w_name] = sh.spec
            sel.setdefault(op_name, {})[w_name] = g
            specs.setdefault(op_name, {})[w_name] = sh.spec
            plan.setdefault(op_name, {})[w_name] = (rep, p, n)
    if not sel:
        return grads if residuals is None else (grads, {})

    def local(gs, rs):
        out: Dict[str, Dict[str, jax.Array]] = {}
        rout: Dict[str, Dict[str, jax.Array]] = {}
        for op_name, ws in gs.items():
            for w_name, g in ws.items():
                rep, prec, n = plan[op_name][w_name]
                if prec == "int8_ef":
                    y, nr = quantized_allreduce_ef(
                        g, rs[op_name][w_name], rep, precision="int8",
                        chunk=chunk, mean=True, axis_size=n,
                    )
                    out.setdefault(op_name, {})[w_name] = y
                    rout.setdefault(op_name, {})[w_name] = nr
                else:
                    out.setdefault(op_name, {})[w_name] = (
                        quantized_allreduce(
                            g, rep, precision=prec, chunk=chunk,
                            mean=True, axis_size=n,
                        ))
        return out, rout

    synced, new_res = shard_map(
        local, mesh=mesh, in_specs=(specs, res_specs),
        out_specs=(specs, res_specs),
    )(sel, res_sel)
    merged = {op: dict(ws) for op, ws in grads.items()}
    for op_name, ws in synced.items():
        for w_name, g in ws.items():
            merged[op_name][w_name] = g
    if residuals is None:
        return merged
    return merged, new_res
