from flexflow_tpu.parallel.mesh import (
    annot_partition_spec,
    build_mesh,
    prime_factors,
    view_slot_axes,
)

__all__ = [
    "annot_partition_spec",
    "build_mesh",
    "prime_factors",
    "view_slot_axes",
]
