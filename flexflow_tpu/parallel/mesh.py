"""Device-mesh construction and canonical axis assignment.

The TPU replacement for the reference's device-placement machinery
(MachineView strided boxes + FFMapper decoding,
reference: src/mapper/mapper.cc:371-475): build ONE global
``jax.sharding.Mesh`` whose axes are the *prime factors* of the device
count, then map every op's abstract partition degrees onto concrete
axis names with one deterministic rule.  Because the rule is
deterministic, two ops that split the same logical dim by the same
degree land on the same axes — so a data-parallel chain compiles with
zero resharding, exactly like same-MachineView ops sharing a Legion
index space in the reference.

Physical placement within the mesh (which chip is neighbour to which)
is delegated to jax's device ordering, which already lays slices out
along the ICI torus.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.ops.base import REPLICA_SLOT, ShardAnnot


def prime_factors(n: int) -> List[int]:
    out: List[int] = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


def mesh_axis_sizes(num_devices: int) -> List[Tuple[str, int]]:
    factors = prime_factors(num_devices) or [1]
    return [(f"x{i}", f) for i, f in enumerate(factors)]


def build_mesh(devices: Optional[Sequence] = None):
    """Build the global mesh over ``devices`` (default: all)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    axes = mesh_axis_sizes(len(devices))
    names = tuple(n for n, _ in axes)
    shape = tuple(s for _, s in axes)
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, names)


def view_slot_axes(
    mv: MachineView, axis_pool: Sequence[Tuple[str, int]]
) -> Dict[int, Tuple[str, ...]]:
    """Assign mesh axes to the view's slots (output dims + replica slot).

    Deterministic: slots are visited in order (0..ndim-1 then
    REPLICA_SLOT); each slot of degree d consumes, for every prime
    factor of d, the first unused pool axis of that size.  Raises if
    the view does not factor into the pool (the search only generates
    views whose total parts divide the device count).
    """
    used = [False] * len(axis_pool)
    slots: Dict[int, Tuple[str, ...]] = {}

    def take(degree: int) -> Tuple[str, ...]:
        taken: List[str] = []
        for p in prime_factors(degree):
            for i, (name, size) in enumerate(axis_pool):
                if not used[i] and size == p:
                    used[i] = True
                    taken.append(name)
                    break
            else:
                raise ValueError(
                    f"degree {degree} does not factor into mesh axes {axis_pool}"
                )
        return tuple(taken)

    for i, d in enumerate(mv.dim_degrees):
        slots[i] = take(d) if d > 1 else ()
    r = mv.replica_degree
    slots[REPLICA_SLOT] = take(r) if r > 1 else ()
    return slots


def annot_partition_spec(annot: ShardAnnot, slot_axes: Dict[int, Tuple[str, ...]]):
    """Lower a ShardAnnot to a PartitionSpec using the op's slot→axes map."""
    from jax.sharding import PartitionSpec

    entries = []
    for dim, (deg, slot) in enumerate(zip(annot.degrees, annot.parallel_idx())):
        if deg <= 1 or slot == -1:
            entries.append(None)
            continue
        axes = slot_axes.get(slot, ())
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)
