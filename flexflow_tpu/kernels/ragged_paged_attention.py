"""Ragged paged decode attention — Pallas TPU kernel + XLA fallback.

The serving-side sibling of ``kernels/flash_attention``: one decode
step attends a single fresh query token per sequence against that
sequence's KV cache, which lives in a PAGED pool (PagedAttention /
"Ragged Paged Attention", arXiv:2604.15464 — PAPERS.md) instead of a
dense [B, S_max] buffer:

* ``k_pages``/``v_pages`` — [num_pages, page_size, H, D]: one global
  page pool shared by every sequence; a sequence owns the pages its
  row of ``page_table`` names, so HBM residency tracks the RAGGED
  total of live tokens, not B × S_max.
* ``page_table`` — [B, pages_per_seq] int32 page ids (rows padded with
  any valid id past the sequence's last live page — masked off).
* ``seq_lens`` — [B] int32 live token counts; position ``seq_lens[b]``
  is exclusive (lengths, not indices).

The Pallas kernel runs a flash-style online softmax with the PAGE as
the KV block: grid (B, H, pages_per_seq), pages innermost so the
(m, l, acc) scratch accumulators carry across a sequence's pages, and
the page indirection rides the BlockSpec index_map — the scalar-
prefetched ``page_table`` picks which pool page each grid step loads,
so only the sequence's OWN pages ever move HBM→VMEM (the ragged win;
a dense layout would stream B × S_max tokens).  Pages past
``ceil(len/page_size)`` are skipped with ``pl.when`` (they still DMA —
the index map pins them to page 0 — but cost no FLOPs; the tail page's
dead rows are masked at NEG_INF exactly like flash attention's causal
mask).  On non-TPU backends the kernel runs in interpreter mode; any
failure falls back to the gather/masked XLA path so CPU-mesh tests
cover the same call sites.

``dense_decode_reference`` is the oracle: materialize every sequence's
KV densely, mask past ``seq_lens``, plain softmax — the parity target
for both the kernel and the fallback (tests/test_serving.py).

Pool dtype (the searched KV-precision lane, ops/decode_attention.py):
the plain entry points accept fp32 or bf16 pools — every dot casts its
operands to fp32, a no-op on the fp32 path, so the historical numerics
are bit-identical.  An int8 pool carries per-(page, slot) fp32 scales
and enters through ``ragged_paged_attention_quant``: the Pallas
variant dequantizes INSIDE the page loop (the scales ride the same
scalar-prefetched page indirection as the payload, one [page_size]
row per grid step), so only quantized bytes ever stream HBM→VMEM —
that smaller stream is the whole point of the lane.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:  # pallas may be unavailable on some backends; the XLA paths in
    # this module must stay importable without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pl = None
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# dense masked reference (the oracle)
# ---------------------------------------------------------------------------
def dense_decode_reference(q, k_dense, v_dense, seq_lens, scale=None):
    """Single-token decode attention against dense per-sequence KV.

    q [B, H, D], k_dense/v_dense [B, S_max, H, D], seq_lens [B] int32
    -> [B, H, D].  Positions >= seq_lens[b] are masked out.  Pure XLA,
    numerically the plain (not online) softmax — the reference both
    the paged kernel and the gather fallback must match."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                   k_dense.astype(jnp.float32)) * scale
    pos = jnp.arange(k_dense.shape[1], dtype=jnp.int32)
    mask = pos[None, None, :] < seq_lens[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", p, v_dense.astype(jnp.float32))
    return out.astype(q.dtype)


def gather_kv_pages(pages, page_table):
    """[P, page_size, H, D] pool + [B, pages_per_seq] table -> dense
    [B, pages_per_seq * page_size, H, D] per-sequence KV (the fallback
    path's gather; also how tests densify a paged cache for the
    oracle)."""
    g = pages[page_table]  # [B, pages_per_seq, page_size, H, D]
    b, npp, ps, h, d = g.shape
    return g.reshape(b, npp * ps, h, d)


def gather_kv_pages_quant(pages, scales, page_table):
    """Densify + DEQUANTIZE an int8 pool: pages [P, page_size, H, D]
    int8, scales [P, page_size] fp32 (per-(page, slot), shared across
    heads) -> dense fp32 [B, pages_per_seq * page_size, H, D].  The
    fallback/chunk-prefill sibling of the in-kernel page-loop
    dequant."""
    dense = gather_kv_pages(pages, page_table).astype(jnp.float32)
    s = scales[page_table]  # [B, pages_per_seq, page_size]
    b, npp, ps = s.shape
    return dense * s.reshape(b, npp * ps)[:, :, None, None]


# ---------------------------------------------------------------------------
# pure-XLA fallback: gather pages, mask, dense softmax
# ---------------------------------------------------------------------------
def _xla_ragged_paged(q, k_pages, v_pages, page_table, seq_lens, scale):
    k_dense = gather_kv_pages(k_pages, page_table)
    v_dense = gather_kv_pages(v_pages, page_table)
    return dense_decode_reference(q, k_dense, v_dense, seq_lens, scale)


def _xla_ragged_paged_quant(q, k_pages, v_pages, k_scale, v_scale,
                            page_table, seq_lens, scale):
    k_dense = gather_kv_pages_quant(k_pages, k_scale, page_table)
    v_dense = gather_kv_pages_quant(v_pages, v_scale, page_table)
    return dense_decode_reference(q, k_dense, v_dense, seq_lens, scale)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------
def _rpa_kernel(
    page_table_ref, seq_lens_ref,  # scalar-prefetch operands
    q_ref, k_ref, v_ref, o_ref,
    m_scratch, l_scratch, acc_scratch,
    *, page_size: int, scale: float,
):
    """Grid (B, H, pages_per_seq), pages innermost (sequential on TPU)
    so the online-softmax scratch carries across one sequence's pages.
    The k/v BlockSpec index maps already routed THIS grid step's block
    to pool page ``page_table[b, j]`` — the kernel only masks the
    ragged tail and skips fully-dead pages."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    npp = pl.num_programs(2)
    n = seq_lens_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # a page whose first slot is already past the ragged length holds
    # no live token for this sequence
    @pl.when(j * page_size < n)
    def _step():
        q = q_ref[0]        # [1, D] — the lone decode token's row
        # fp32 casts are no-ops on the historical fp32 pool (numerics
        # bit-identical) and make the SAME kernel serve a bf16 pool
        k = k_ref[0, :, 0].astype(jnp.float32)  # [page_size, D]
        v = v_ref[0, :, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [1, page_size] fp32
        cols = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols < n, s, NEG_INF)
        m_prev = m_scratch[:]  # [1, 1]
        l_prev = l_scratch[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scratch[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scratch[:] = m_new

    @pl.when(j == npp - 1)
    def _finish():
        l = jnp.maximum(l_scratch[:], 1e-30)
        o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def _pallas_ragged_paged(q, k_pages, v_pages, page_table, seq_lens, scale,
                         interpret: bool):
    b, h, d = q.shape
    num_pages, page_size, hp, dp = k_pages.shape
    assert (hp, dp) == (h, d), (k_pages.shape, q.shape)
    pages_per_seq = page_table.shape[1]
    grid = (b, h, pages_per_seq)

    def kv_map(bi, hi, j, pt_ref, sl_ref):
        # dead pages (page slot past ceil(len/page_size)) pin to pool
        # page 0 — the DMA still runs but pl.when skips the math and
        # the tail mask kills any live-page partial rows
        live = (j * page_size) < sl_ref[bi]
        page = jnp.where(live, pt_ref[bi, j], 0)
        return (page, 0, hi, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda bi, hi, j, pt, sl: (bi, hi, 0)),
            pl.BlockSpec((1, page_size, 1, d), kv_map),
            pl.BlockSpec((1, page_size, 1, d), kv_map),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, d), lambda bi, hi, j, pt, sl: (bi, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _rpa_kernel, page_size=page_size, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q, k_pages, v_pages)
    return out


def _rpa_kernel_quant(
    page_table_ref, seq_lens_ref,  # scalar-prefetch operands
    q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
    m_scratch, l_scratch, acc_scratch,
    *, page_size: int, scale: float,
):
    """The int8-pool twin of ``_rpa_kernel``: identical online softmax,
    but the page's K/V arrive quantized and are DEQUANTIZED here, in
    the page loop — ``ks_ref``/``vs_ref`` hold this page's
    per-(page, slot) fp32 scale rows, routed by the same
    scalar-prefetched page indirection as the payload.  HBM→VMEM moves
    1 byte per element + 8 scale bytes per token; the fp32 values
    exist only in registers/VMEM."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    npp = pl.num_programs(2)
    n = seq_lens_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    @pl.when(j * page_size < n)
    def _step():
        q = q_ref[0]  # [1, D]
        k = k_ref[0, :, 0].astype(jnp.float32) * ks_ref[0][:, None]
        v = v_ref[0, :, 0].astype(jnp.float32) * vs_ref[0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        cols = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols < n, s, NEG_INF)
        m_prev = m_scratch[:]
        l_prev = l_scratch[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scratch[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scratch[:] = m_new

    @pl.when(j == npp - 1)
    def _finish():
        l = jnp.maximum(l_scratch[:], 1e-30)
        o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def _pallas_ragged_paged_quant(q, k_pages, v_pages, k_scale, v_scale,
                               page_table, seq_lens, scale,
                               interpret: bool):
    b, h, d = q.shape
    num_pages, page_size, hp, dp = k_pages.shape
    assert (hp, dp) == (h, d), (k_pages.shape, q.shape)
    pages_per_seq = page_table.shape[1]
    grid = (b, h, pages_per_seq)

    def kv_map(bi, hi, j, pt_ref, sl_ref):
        live = (j * page_size) < sl_ref[bi]
        page = jnp.where(live, pt_ref[bi, j], 0)
        return (page, 0, hi, 0)

    def scale_map(bi, hi, j, pt_ref, sl_ref):
        # the scale rows ride the SAME page indirection as the payload
        live = (j * page_size) < sl_ref[bi]
        page = jnp.where(live, pt_ref[bi, j], 0)
        return (page, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda bi, hi, j, pt, sl: (bi, hi, 0)),
            pl.BlockSpec((1, page_size, 1, d), kv_map),
            pl.BlockSpec((1, page_size, 1, d), kv_map),
            pl.BlockSpec((1, page_size), scale_map),
            pl.BlockSpec((1, page_size), scale_map),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, d), lambda bi, hi, j, pt, sl: (bi, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _rpa_kernel_quant, page_size=page_size, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q, k_pages, v_pages, k_scale, v_scale)
    return out


def ragged_paged_attention_quant(
    q, k_pages, v_pages, k_scale, v_scale, page_table, seq_lens,
    scale=None,
):
    """Paged-KV decode attention over an INT8 pool: like
    ``ragged_paged_attention`` but ``k_pages``/``v_pages`` are int8 and
    ``k_scale``/``v_scale`` [P, page_size] fp32 carry each token's
    symmetric per-(page, slot) scale (shared across heads).  Same
    kernel gating and fallback contract as the fp32 entry point."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    d = q.shape[-1]
    page_size = k_pages.shape[1]
    if _HAS_PLTPU and d % 8 == 0 and page_size % 8 == 0:
        interpret = jax.default_backend() != "tpu"
        try:
            return _pallas_ragged_paged_quant(
                q, k_pages, v_pages, k_scale, v_scale, page_table,
                seq_lens, float(scale), interpret)
        except Exception:
            pass  # fall through to the XLA path (e.g. unsupported jax)
    return _xla_ragged_paged_quant(q, k_pages, v_pages, k_scale, v_scale,
                                   page_table, seq_lens, float(scale))


def ragged_paged_attention(
    q, k_pages, v_pages, page_table, seq_lens, scale=None,
):
    """Paged-KV decode attention: q [B, H, D] (one fresh token per
    sequence), k_pages/v_pages [P, page_size, H, D], page_table
    [B, pages_per_seq] int32, seq_lens [B] int32 -> [B, H, D].

    Takes the Pallas kernel when available (interpreter mode off-TPU,
    like flash_attention), falling back to the gather/masked XLA path
    on any failure so the CPU mesh exercises identical call sites.
    Decode is forward-only (no gradients flow into a serving step), so
    no custom VJP is defined — autodiff through the fallback works for
    the tests that want it."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    d = q.shape[-1]
    page_size = k_pages.shape[1]
    # the kernel wants MXU/VPU-friendly tails: head_dim a multiple of 8
    # and at least one full lane-worth of page; anything else (tiny CPU
    # test shapes) is served by the fallback, same contract
    if _HAS_PLTPU and d % 8 == 0 and page_size % 8 == 0:
        interpret = jax.default_backend() != "tpu"
        try:
            return _pallas_ragged_paged(
                q, k_pages, v_pages, page_table, seq_lens, float(scale),
                interpret)
        except Exception:
            pass  # fall through to the XLA path (e.g. unsupported jax)
    return _xla_ragged_paged(q, k_pages, v_pages, page_table, seq_lens,
                             float(scale))
