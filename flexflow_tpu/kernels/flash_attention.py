"""Flash attention — Pallas TPU kernel.

Replaces the reference's cuDNN multi-head attention kernel
(reference: src/ops/attention.cu cudnnMultiHeadAttnForward) with an
online-softmax blocked kernel that never materializes the [Sq, Sk]
score matrix in HBM: the canonical TPU formulation with a sequential
grid over KV blocks and VMEM scratch accumulators (m, l, acc) that
persist across grid steps.

Layout: q, k, v are [B, S, H, D] ("bshd", matching the MHA op).  The
kernel runs per (batch*head, q-block) with KV blocks innermost.

Backward: custom_vjp with an XLA recompute backward (standard
einsum-based gradients).  A fully-blocked Pallas backward is future
work; the forward already gives the memory win where it matters for
long-context inference/training forward activations.

On non-TPU backends the kernel runs in interpreter mode so tests cover
the same code path.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp

try:  # pallas may be unavailable on some backends; the XLA paths in
    # this module must stay importable without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pl = None
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *refs,
    scale: float, causal: bool, block_q: int, block_k: int, q_k_offset: int,
    partial_out: bool = False,
):
    """Grid: (BH, num_q_blocks, num_k_blocks) — k innermost (sequential
    on TPU), so scratch accumulators carry across k steps.
    ``q_k_offset`` = Sk - Sq aligns the causal diagonal at the sequence
    END (query i attends to keys <= i + offset), matching tril(k=sk-sq).
    With ``partial_out`` the kernel emits UNNORMALIZED (acc, m, l) so
    callers (ring attention) can merge partials across devices."""
    if partial_out:
        m_out, l_out, m_scratch, l_scratch, acc_scratch = refs
    else:
        m_scratch, l_scratch, acc_scratch = refs
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    qb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    run = True
    if causal:
        # skip blocks strictly above the (end-aligned) diagonal
        run = (kb * block_k) <= (qb * block_q + block_q - 1 + q_k_offset)

    @pl.when(run if causal else True)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale  # [bq, D]
        k = k_ref[0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0].astype(jnp.float32)  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows + q_k_offset >= cols, s, NEG_INF)
        m_prev = m_scratch[:]  # [bq, 1]
        l_prev = l_scratch[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scratch[:] = m_new
        l_scratch[:] = l_new

    @pl.when(kb == nk - 1)
    def _finish():
        if partial_out:
            o_ref[0] = acc_scratch[:].astype(o_ref.dtype)
            m_out[0] = m_scratch[:].astype(m_out.dtype)
            l_out[0] = l_scratch[:].astype(l_out.dtype)
        else:
            l = jnp.maximum(l_scratch[:], 1e-30)
            o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, scale: float,
                   block_q: int, block_k: int, interpret: bool):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # [B, S, H, D] -> [B*H, S, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    grid = (b * h, sq // block_q, sk // block_k)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, q_k_offset=sk - sq,
    )
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, d), jnp.float32),
    ]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _xla_attention(q, k, v, causal, scale, dropout_rate=0.0, dropout_rng=None):
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(dropout_rng, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def _xla_attention_partial(q, k, v, causal, scale):
    """Unnormalized blockwise partials (acc, m, l) in fp32, layout
    acc [B,H,Sq,D], m/l [B,H,Sq,1] — the XLA fallback twin of the
    partial-out Pallas path, and its recompute-backward reference."""
    qf = q.astype(jnp.float32) * scale
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return acc, m, l


def _flash_forward_partial(q, k, v, causal, scale, block_q, block_k, interpret):
    """Pallas partial-out forward: returns (acc, m, l) shaped
    [B,H,Sq,D] / [B,H,Sq,1] fp32."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    grid = (b * h, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, q_k_offset=sk - sq,
        partial_out=True,
    )
    qspec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    sspec = pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0))
    acc, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            qspec,
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[qspec, sspec, sspec],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return (
        acc.reshape(b, h, sq, d),
        m.reshape(b, h, sq, 1),
        l.reshape(b, h, sq, 1),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_partial_vjp(q, k, v, causal, scale, block_q, block_k):
    return _fap_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def flash_attention_partial(
    q, k, v, causal: bool = False, scale: float | None = None,
    block_q: int = 128, block_k: int = 128,
):
    """Blocked attention partials for cross-device merging (ring
    attention): q,k,v [B,S,H,D] -> (acc [B,H,Sq,D], m, l [B,H,Sq,1]),
    all fp32 and unnormalized (out = acc/l after merging)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_partial_vjp(q, k, v, causal, scale, block_q, block_k)


def _fap_fwd(q, k, v, causal, scale, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    sq, sk = q.shape[1], k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if not _HAS_PLTPU or sq % bq != 0 or sk % bk != 0 or q.shape[-1] % 8 != 0:
        out = _xla_attention_partial(q, k, v, causal, scale)
    else:
        out = _flash_forward_partial(q, k, v, causal, scale, bq, bk, interpret)
    return out, (q, k, v)


def _fap_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res

    def f(q, k, v):
        return _xla_attention_partial(q, k, v, causal, scale)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


_flash_partial_vjp.defvjp(_fap_fwd, _fap_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_vjp(q, k, v, causal, scale, block_q, block_k):
    return _fa_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def flash_attention(
    q, k, v, causal: bool = False, scale: float | None = None,
    block_q: int = 128, block_k: int = 128,
):
    """q, k, v: [B, S, H, D] -> [B, Sq, H, D]."""
    return _flash_attention_vjp(q, k, v, causal, scale, block_q, block_k)


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    interpret = jax.default_backend() != "tpu"
    sq, sk = q.shape[1], k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if not _HAS_PLTPU or sq % bq != 0 or sk % bk != 0 or q.shape[-1] % 8 != 0:
        out = _xla_attention(q, k, v, causal, scale)  # shape fallback
    else:
        out = _flash_forward(q, k, v, causal, scale, bq, bk, interpret)
    return out, (q, k, v)


def _fa_bwd(causal, scale, block_q, block_k, res, g):
    """Recompute backward via XLA (standard attention gradients)."""
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    def f(q, k, v):
        return _xla_attention(q, k, v, causal, scale)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


_flash_attention_vjp.defvjp(_fa_fwd, _fa_bwd)
