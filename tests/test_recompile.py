"""runtime/recompile.py tier-1 coverage: RecompileState.check drives
model.recompile() carrying params/optimizer/model state across the
re-lower — the MoE cache-flip path (reference: recompile_state.cc +
examples/cpp/mixture_of_experts/moe.cc:73-92)."""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.runtime.recompile import RecompileState, cache_score


def _cache_model(num_devices=2):
    cfg = ff.FFConfig(batch_size=8, num_devices=num_devices,
                      only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16])
    h = m.dense(x, 32, activation="relu", name="d0")
    c = m.cache(h, name="gate_cache")
    m.dense(c, 4, name="d1")
    m.compile(optimizer=ff.AdamOptimizer(alpha=1e-2),
              loss_type="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    return m


def _data(n=24, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 16).astype(np.float32),
            rng.randint(0, 4, size=(n,)).astype(np.int32))


def test_check_fires_alter_exactly_once():
    m = _cache_model()
    calls = []

    def alter(model):
        calls.append(1)
        model.node_by_name("gate_cache").op.attrs["use_cached"] = True

    rs = RecompileState(trigger=lambda model: True, alter=alter)
    assert rs.check(m) is True
    assert rs.altered and calls == [1]
    # alter_flag semantics: at most once, no matter how often checked
    assert rs.check(m) is False
    assert calls == [1]


def test_trigger_false_never_alters():
    m = _cache_model()
    rs = RecompileState(trigger=lambda model: False,
                        alter=lambda model: pytest.fail("must not fire"))
    for _ in range(3):
        assert rs.check(m) is False
    assert rs.altered is False


def test_recompile_carries_params_opt_and_model_state():
    """model.recompile() after an alter(): weights, Adam slots, and the
    cache op's mutable state survive the re-lower bit-for-bit (the
    reference mutates operators in place; here the program is rebuilt
    and the state carried)."""
    import jax

    m = _cache_model()
    X, Y = _data()
    m.fit(X, Y, batch_size=8, epochs=2, verbose=False)
    w_before = m.get_weight("d0")
    cached_before = np.asarray(m.state["gate_cache/cached"])
    opt_before = [np.asarray(v) for v in jax.tree.leaves(m.opt_state)]
    assert np.abs(cached_before).sum() > 0  # the cache saw live values

    m.node_by_name("gate_cache").op.attrs["use_cached"] = True
    m.recompile()
    np.testing.assert_array_equal(w_before, m.get_weight("d0"))
    np.testing.assert_array_equal(
        cached_before, np.asarray(m.state["gate_cache/cached"]))
    opt_after = [np.asarray(v) for v in jax.tree.leaves(m.opt_state)]
    assert len(opt_before) == len(opt_after)
    for a, b in zip(opt_before, opt_after):
        np.testing.assert_array_equal(a, b)
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)  # still trains


def test_cache_flip_e2e_through_fit():
    """The documented MoE path end-to-end: fit(recompile_state=...)
    flips the CacheOp to its cached values mid-training, the score
    state keeps updating, and training completes."""
    m = _cache_model()
    X, Y = _data()
    seen = []

    def trigger(model):
        # examples/moe.py discipline: consult the live cache score
        if "gate_cache/score" in (model.state or {}):
            seen.append(cache_score(model, "gate_cache"))
        return len(seen) >= 2

    def alter(model):
        model.node_by_name("gate_cache").op.attrs["use_cached"] = True

    rs = RecompileState(trigger=trigger, alter=alter)
    hist = m.fit(X, Y, batch_size=8, epochs=3, verbose=False,
                 recompile_state=rs)
    assert rs.altered is True
    assert m.node_by_name("gate_cache").op.attrs["use_cached"] is True
    assert len(hist) == 3 and np.isfinite(hist[-1]["loss"])
    assert all(np.isfinite(s) for s in seen)


def test_merge_matching_keeps_fresh_init_on_shape_change():
    """The carry-over rule recompile() applies (_merge_matching): a
    weight whose shape changed across the alter keeps its FRESH init,
    every shape-stable leaf carries the old value."""
    from flexflow_tpu.model import _merge_matching

    new = {"d0": {"kernel": np.zeros((2, 2)), "bias": np.zeros(3)},
           "d2": {"kernel": np.zeros(5)}}
    old = {"d0": {"kernel": np.ones((2, 2)), "bias": np.ones(4)},
           "d1": {"kernel": np.ones(7)}}
    out = _merge_matching(new, old)
    assert (out["d0"]["kernel"] == 1).all()  # carried
    assert (out["d0"]["bias"] == 0).all()    # shape changed: fresh
    assert (out["d2"]["kernel"] == 0).all()  # new op: fresh
    assert "d1" not in out                   # dropped op: gone
