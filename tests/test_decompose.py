"""Series-parallel decomposition + off-critical-path matching (PR 12).

The gates ROADMAP item 4 names: chain-shaped graphs route through the
generalized SP path as the width-1 degenerate case BIT-IDENTICALLY to
the retained PR 7 chain oracle (digests + per-node views + exact
sim-cost floats); bottleneck-free graphs decompose instead of
degenerating to binary recursion, with the decision observable on the
``search.decompose`` event; stamped segment solves stay SHD1xx-linted;
finished segment solves persist as guid-free sp-memo rows a cold
process serves (and an unknown sp_schema drops the layer LOUDLY); the
vectorized matcher filters and the opt-in match-worker pool are
serial-identical.
"""

import json
import os
import time

import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.compiler.lowering import data_parallel_strategy
from flexflow_tpu.models import build_gpt, build_moe_trunk, build_multibranch
from flexflow_tpu.search import decompose
from flexflow_tpu.search.driver import (
    CHAIN_MIN_NODES,
    LAST_SEARCH_STATS,
    _load_xfers,
    _UnityOptimizer,
    optimize_strategy,
)
from flexflow_tpu.search.dp import SearchHelper
from flexflow_tpu.search.simulator import Simulator


def _gpt_chain(cfg):
    return build_gpt(cfg, vocab=4000, num_layers=40, hidden=256,
                     num_heads=4, ff_dim=512, seq_len=64)


# ---------------------------------------------------------------------------
# decompose.py units


def test_frontier_widths_matches_bruteforce():
    """frontier_widths' incremental sweep == the O(n^2) definition on a
    branchy graph (diamond + skip)."""
    m = ff.FFModel(ff.FFConfig(num_devices=8))
    x = m.create_tensor([16, 8])
    a = m.dense(x, 8, name="a")
    b = m.dense(x, 8, name="b")
    c = m.add(a, b, name="c")
    d = m.add(c, x, name="d")  # skip keeps x live across the graph
    m.dense(d, 4, name="head")
    g = m.graph
    topo, widths = decompose.frontier_widths(g)
    pos = {n.guid: i for i, n in enumerate(topo)}
    for i in range(len(topo)):
        prefix = {n.guid for n in topo[: i + 1]}
        expect = len({
            e.src for guid in prefix for e in g.out_edges[guid]
            if e.dst not in prefix
        })
        assert widths[i] == expect, (i, widths[i], expect)


def test_chain_cuts_reproduce_bottleneck_rule():
    """On a chain-shaped graph the cut selector returns mode='chain'
    with width-1 cuts at exactly the PR 7 bottleneck spacing."""
    cfg = ff.FFConfig(batch_size=8, num_devices=8)
    g = _gpt_chain(cfg).graph
    cuts, mode = decompose.find_series_cuts(g, {}, 10)
    assert mode == "chain"
    assert all(c.width == 1 for c in cuts)
    # reproduce chain_optimize's own selection
    order = {n.guid: i for i, n in enumerate(g.topo_order())}
    expect, last = [], 0
    for bn in g.bottlenecks():
        at = order[bn.guid]
        if at - last >= 10 and at < len(order) - 1:
            expect.append(bn.guid)
            last = at
    assert [c.crossing[0] for c in cuts] == expect


def test_split_series_covers_graph_exactly_once():
    cfg = ff.FFConfig(batch_size=8, num_devices=8)
    g = build_moe_trunk(cfg, num_blocks=12).graph
    cuts, mode = decompose.find_series_cuts(g, {}, 8)
    assert cuts is not None
    segments = decompose.split_series(g, cuts)
    assert segments is not None
    interior_seen = set()
    for seg, in_cross, out_cross in segments:
        interior = set(seg.nodes) - set(in_cross)
        assert not (interior & interior_seen)
        interior_seen |= interior
        # every in-crossing node is a source inside the segment
        for gd in in_cross:
            assert not seg.in_edges[gd]
    assert interior_seen == set(g.nodes)


def test_boundary_tuples_carry_pins_shared_nodes():
    views = {1: ["a", "b"], 2: ["c", "d"]}
    out = decompose.boundary_tuples(views, (1, 2), carry={1: "b"})
    assert out == [("b", "c"), ("b", "d")]
    # width-1, no carry: degenerates to the per-node view list
    assert decompose.boundary_tuples(views, (1,)) == [("a",), ("b",)]


# ---------------------------------------------------------------------------
# the chain bit-identity regression gate (width-1 degenerate case)


def test_sp_path_bit_identical_to_chain_oracle():
    """sp_optimize on a chain-shaped production graph == the retained
    PR 7 chain_optimize oracle: same rewritten-graph digest, same
    per-node views, same exact sim-cost float.  Separate optimizers so
    neither serves the other's segment cache."""
    cfg = ff.FFConfig(batch_size=8, num_devices=8, cost_cache_file="")
    g = _gpt_chain(cfg).graph
    assert g.num_nodes > CHAIN_MIN_NODES
    xfers = _load_xfers(cfg, 8)

    def run(fn):
        helper = SearchHelper(Simulator(cfg.machine_spec, num_devices=8), 8)
        opt = _UnityOptimizer(helper, cfg, xfers)
        return getattr(opt, fn)(g, {})

    ga, ca, sa = run("sp_optimize")
    gb, cb, sb = run("chain_optimize")
    assert ca == cb  # exact float, not approx
    assert ga.hash() == gb.hash()
    assert sorted((k, repr(v)) for k, v in sa.items()) == \
        sorted((k, repr(v)) for k, v in sb.items())


def test_chain_shaped_graph_routes_through_sp_as_chain_mode():
    cfg = ff.FFConfig(batch_size=8, num_devices=8, cost_cache_file="")
    g = _gpt_chain(cfg).graph
    optimize_strategy(g, cfg, return_graph=True)
    assert LAST_SEARCH_STATS.get("decompose_mode") == "chain"
    assert LAST_SEARCH_STATS.get("decompose_max_width") == 1
    assert LAST_SEARCH_STATS.get("segments_stamped", 0) > 0


# ---------------------------------------------------------------------------
# bottleneck-free graphs decompose (the pre-PR silent degradation)


def test_sp_decomposes_bottleneck_free_trunk():
    """A persistent-skip MoE trunk past CHAIN_MIN_NODES has (near-)no
    bottleneck chain; pre-PR it fell into the binary recursion's
    whole-graph brute force.  It must now decompose via bounded-width
    frontier cuts, stamp isomorphic segments, finish fast, beat pure
    DP, and pass the strategy lint."""
    cfg = ff.FFConfig(batch_size=8, num_devices=8, cost_cache_file="")
    m = build_moe_trunk(cfg, num_blocks=30)
    g = m.graph
    assert g.num_nodes > CHAIN_MIN_NODES
    assert len(g.bottlenecks()) < 8  # no usable chain
    t0 = time.monotonic()
    bg, strategy = optimize_strategy(g, cfg, return_graph=True)
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, f"sp search took {elapsed:.1f}s"
    assert LAST_SEARCH_STATS.get("decompose_mode") == "sp"
    assert LAST_SEARCH_STATS.get("decompose_cuts", 0) >= 2
    assert LAST_SEARCH_STATS.get("segments_stamped", 0) > 0
    sim = Simulator(cfg.machine_spec, num_devices=8)
    c_se = sim.simulate(bg, strategy)
    c_dp = sim.simulate(g, data_parallel_strategy(g, 8))
    assert c_se <= c_dp * 1.001, (c_se, c_dp)
    from flexflow_tpu.analysis import errors_only, lint_strategy

    assert errors_only(lint_strategy(bg, strategy, 8)) == []


def test_sp_decomposes_multibranch_with_wide_cuts():
    cfg = ff.FFConfig(batch_size=8, num_devices=8, cost_cache_file="")
    m = build_multibranch(cfg, num_branches=3, depth=90)
    g = m.graph
    assert g.num_nodes > CHAIN_MIN_NODES
    bg, strategy = optimize_strategy(g, cfg, return_graph=True)
    assert LAST_SEARCH_STATS.get("decompose_mode") == "sp"
    assert LAST_SEARCH_STATS.get("decompose_max_width", 0) >= 2
    assert len(strategy) == bg.num_nodes


def test_decompose_event_emitted_and_valid(tmp_path):
    """The search.decompose obs event names the chosen decomposition
    (satellite: the silent binary-recursion degradation is now an
    observable decision) and validates against the registered schema."""
    from flexflow_tpu.obs.events import BUS, validate_event

    log = tmp_path / "obs.jsonl"
    cfg = ff.FFConfig(batch_size=8, num_devices=8, cost_cache_file="")
    g = build_moe_trunk(cfg, num_blocks=22).graph
    BUS.configure(str(log))
    try:
        optimize_strategy(g, cfg, return_graph=True)
    finally:
        BUS.flush()
        BUS.close()
    events = [json.loads(ln) for ln in log.read_text().splitlines()]
    for e in events:
        assert validate_event(e) == [], e
    decos = [e for e in events if e["kind"] == "search.decompose"]
    assert decos and decos[0]["mode"] == "sp"
    assert decos[0]["cuts"] >= 2
    dones = [e for e in events if e["kind"] == "search.decompose_done"]
    assert dones and np.isfinite(dones[-1]["cost_s"])


# ---------------------------------------------------------------------------
# stamped solves stay lint-gated


def test_stamp_serve_rejected_when_lint_fails(monkeypatch):
    """A stamped (remapped) segment serve that fails the SHD1xx lint
    must be DROPPED (costs one re-search, never an illegal serve) —
    and the lint-memo must remember the verdict per entry."""
    import flexflow_tpu.analysis as analysis
    from flexflow_tpu.analysis.findings import Finding

    cfg = ff.FFConfig(batch_size=8, num_devices=8, cost_cache_file="")
    g = _gpt_chain(cfg).graph
    xfers = _load_xfers(cfg, 8)
    helper = SearchHelper(Simulator(cfg.machine_spec, num_devices=8), 8)
    opt = _UnityOptimizer(helper, cfg, xfers)

    bad = Finding(code="SHD199", pass_name="sharding",
                  message="forced failure", severity="error")
    real_lint = analysis.lint_strategy
    calls = {"n": 0}

    def failing_lint(graph, strategy, n, **kw):
        calls["n"] += 1
        return [bad]

    monkeypatch.setattr(analysis, "lint_strategy", failing_lint)
    try:
        res = opt.sp_optimize(g, {})
    finally:
        monkeypatch.setattr(analysis, "lint_strategy", real_lint)
    # every remapped serve was rejected, so the search re-solved each
    # segment fresh — slower but LEGAL, and the gate provably ran
    assert calls["n"] > 0
    assert helper.segments_stamped == 0
    assert res is None or np.isfinite(res[1])


# ---------------------------------------------------------------------------
# persistent sp-memo rows: cold/warm serve + loud unknown-schema drop


def test_sp_rows_cold_write_warm_serve(tmp_path):
    """Cold search persists sp-segment memo rows; a warm search of a
    DIFFERENT graph with isomorphic segments (so the whole-result
    layer misses on the new graph digest) serves whole segment solves
    from them — the guid-free cross-graph reuse the layer exists
    for."""
    cache = str(tmp_path / "sp_cache.json")
    cfg = ff.FFConfig(batch_size=8, num_devices=8, cost_cache_file=cache,
                      search_budget=16)
    g_cold = build_moe_trunk(cfg, num_blocks=30).graph
    optimize_strategy(g_cold, cfg, return_graph=True)
    assert LAST_SEARCH_STATS.get("sp_rows_served", 0) == 0  # cold: inert
    data = json.load(open(cache))
    assert data.get("sp_schema") == 1
    assert data.get("sp_rows"), "cold search persisted no sp rows"
    # warm: a deeper trunk — same block structure, new graph digest
    cfg2 = ff.FFConfig(batch_size=8, num_devices=8,
                       cost_cache_file=cache, search_budget=16)
    g_warm = build_moe_trunk(cfg2, num_blocks=34).graph
    optimize_strategy(g_warm, cfg2, return_graph=True)
    assert not LAST_SEARCH_STATS.get("result_cache_hit")
    assert LAST_SEARCH_STATS.get("sp_rows_served", 0) > 0


def test_sp_rows_unknown_schema_dropped_loudly(tmp_path, capsys):
    from flexflow_tpu.search.cost_cache import CostCache

    cache = str(tmp_path / "sp_cache.json")
    cfg = ff.FFConfig(batch_size=8, num_devices=8, cost_cache_file=cache,
                      search_budget=16)
    g = build_moe_trunk(cfg, num_blocks=30).graph
    optimize_strategy(g, cfg, return_graph=True)
    data = json.load(open(cache))
    assert data["sp_rows"]
    sig = data["signature"]
    data["sp_schema"] = 99
    json.dump(data, open(cache, "w"))
    capsys.readouterr()
    cc = CostCache(cache, sig)
    err = capsys.readouterr().err
    assert "unknown sp_schema" in err
    assert not cc.sp_loaded and not cc.sp_rows
    # the still-valid layers survive the drop
    assert cc.dp_loaded or cc.rows or cc.results


def test_fflint_cache_sp_row_corruptions(tmp_path):
    """fflint cache: CCH409 for an unknown sp_schema, CCH410 for
    malformed sp rows, clean for a well-formed layer."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import fflint

    def lint(payload):
        p = tmp_path / "c.json"
        p.write_text(json.dumps(payload))
        return fflint.lint_cache_file(str(p))

    base = {"schema": 1, "signature": "0" * 16,
            "calibration_stale": False, "rows": []}
    ok_row = {"cost": 1e-3,
              "strategy": [["ab12", [2, 1], 1, 0], ["cd34", [1, 1], 1, 0]]}
    clean = lint({**base, "sp_schema": 1, "sp_rows": {"d:k": ok_row}})
    assert [f for f in clean if f[0] == "error"] == []
    bad_schema = lint({**base, "sp_schema": 99,
                       "sp_rows": {"d:k": ok_row}})
    assert any(c == "CCH409" for _s, c, _m in bad_schema)
    for corrupt in (
        {"cost": -1.0, "strategy": ok_row["strategy"]},   # negative cost
        {"cost": 1e-3, "strategy": []},                   # no rows
        {"cost": 1e-3, "strategy": [["zz", [0], 0, -1]]},  # bad entry
        "not-an-object",
    ):
        got = lint({**base, "sp_schema": 1, "sp_rows": {"d:k": corrupt}})
        assert any(c == "CCH410" for _s, c, _m in got), corrupt


# ---------------------------------------------------------------------------
# matching off the critical path


def test_vec_filters_identical_to_full_scan():
    """Every factory xfer with a vec_filter finds EXACTLY the matches
    of the unindexed full scan on a graph rich in parallel-op motifs
    (the soundness contract: the filter is a superset, the matcher
    confirms)."""
    from flexflow_tpu.search.substitution import generate_all_pcg_xfers

    m = ff.FFModel(ff.FFConfig(num_devices=8))
    x = m.create_tensor([16, 8])
    t = m.relu(x, name="act")
    for i in range(3):
        p = m.repartition(t, dim=0, degree=4, name=f"p{i}")
        m.dense(p, 8, name=f"fc{i}")
    a = m.dense(x, 32, name="fc_a")
    a = m.relu(a)
    b = m.repartition(a, dim=0, degree=2, name="rp")
    b = m.combine(b, dim=0, degree=1, name="cb")
    m.dense(b, 4, name="head")
    g = m.graph
    # force the vectorized path even on this small graph
    import flexflow_tpu.search.substitution as subst

    old = subst.VEC_MIN_CANDS
    subst.VEC_MIN_CANDS = 1
    try:
        for xf in generate_all_pcg_xfers(8):
            if getattr(xf, "vec_filter", None) is None:
                continue
            got = [n.guid for n in xf.find_matches(g)]
            full = [n.guid for n in g.topo_order()
                    if xf.matcher(g, n)]
            assert got == full, xf.name
    finally:
        subst.VEC_MIN_CANDS = old


def test_match_worker_pool_identical_to_serial(monkeypatch):
    """The opt-in process pool returns exactly the serial matches for
    every xfer (guids for node matchers, binding dicts for group
    matchers), and degrades to None when off."""
    from flexflow_tpu.search import match_workers

    cfg = ff.FFConfig(batch_size=8, num_devices=8)
    g = build_multibranch(cfg, num_branches=4, depth=12).graph
    xfers = _load_xfers(cfg, 8)
    # off by default
    assert match_workers.find_all_matches(xfers, g, cfg, 8) is None
    monkeypatch.setenv("FLEXFLOW_TPU_MATCH_WORKERS", "2")
    monkeypatch.setattr(match_workers, "MIN_POOL_NODES", 8)
    monkeypatch.setattr(match_workers, "_DISABLED", False)
    try:
        pooled = match_workers.find_all_matches(xfers, g, cfg, 8)
        assert pooled is not None
        assert match_workers.BATCHES.value > 0
        for xf, ms in zip(xfers, pooled):
            serial = xf.find_matches(g)
            a = [m.guid if hasattr(m, "guid") else m for m in ms]
            b = [m.guid if hasattr(m, "guid") else m for m in serial]
            assert a == b, getattr(xf, "name", xf)
    finally:
        match_workers.shutdown()


# ---------------------------------------------------------------------------
# pattern-graph instantiator (the EQV306 remainder)


def test_pattern_instantiator_proves_multi_node_rule():
    """A multi-node JSON PatternRule outside the motif families is
    proven on a graph instantiated FROM ITS OWN source pattern instead
    of being EQV306-reported."""
    from flexflow_tpu.analysis.proofgen import (
        instantiate_pattern_graph,
        verify_registry_generated,
    )
    from flexflow_tpu.search.substitution_loader import _parse_rule

    rule = _parse_rule({
        "name": "swap_linear_twins",
        "srcOp": [
            {"type": "OP_LINEAR",
             "input": [{"opId": -1, "tsId": 0}, {"opId": -2, "tsId": 0}],
             "para": [{"key": "PM_ACTI", "value": 0}]},
            {"type": "OP_RELU",
             "input": [{"opId": 0, "tsId": 0}], "para": []},
        ],
        "dstOp": [
            {"type": "OP_LINEAR",
             "input": [{"opId": -1, "tsId": 0}, {"opId": -2, "tsId": 0}],
             "para": [{"key": "PM_ACTI", "value": 2}]},
        ],
        "mappedOutput": [
            {"srcOpId": 1, "srcTsId": 0, "dstOpId": 0, "dstTsId": 0}],
    })
    assert rule is not None
    g = instantiate_pattern_graph(rule, 8)
    assert g is not None
    matches = rule.find_matches(g)
    assert matches, "instantiated pattern graph does not match its rule"
    findings, stats = verify_registry_generated(8, xfers=[rule])
    assert not any(f.code == "EQV306" for f in findings), findings
    assert stats["unproven"] == 0


def test_pattern_instantiator_declines_unsupported_families():
    from flexflow_tpu.analysis.proofgen import instantiate_pattern_graph
    from flexflow_tpu.search.substitution_loader import _parse_rule

    rule = _parse_rule({
        "name": "conv_rule",
        "srcOp": [
            {"type": "OP_CONV2D",
             "input": [{"opId": -1, "tsId": 0}, {"opId": -2, "tsId": 0}],
             "para": []},
        ],
        "dstOp": [
            {"type": "OP_CONV2D",
             "input": [{"opId": -1, "tsId": 0}, {"opId": -2, "tsId": 0}],
             "para": []},
        ],
        "mappedOutput": [
            {"srcOpId": 0, "srcTsId": 0, "dstOpId": 0, "dstTsId": 0}],
    })
    assert rule is not None
    assert instantiate_pattern_graph(rule, 8) is None  # honest decline
