"""Serving workload: ragged paged decode attention, KV-cache-aware
search, the serve (p99/SLO) objective, and the continuous-batching
executor (ISSUE 10 / ROADMAP item 4).

Contract highlights:

* the ragged paged kernel (Pallas-interpret AND the XLA fallback)
  matches the dense masked reference across ragged shapes, including
  the single-token and full-page boundaries;
* per-device KV residency enters the simulator's memory check: a
  strategy that cannot hold the page pool is rejected INSIDE the
  search, never at OOM;
* on the serving-regime decode config the serve objective selects a
  DIFFERENT strategy than the throughput objective and wins on
  simulated p99 (the acceptance scenario BENCH_SEARCH records);
* with objective="train" (the default) the serving machinery is
  structurally inert — a poisoned spec builder proves the default
  path never touches it, and cache signatures only extend under serve;
* the executor's continuous batching is semantically invisible:
  serving requests batched with admission/eviction yields EXACTLY the
  tokens of serving each request alone.
"""

import json
import math

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.machine import MachineSpec, MachineView
from flexflow_tpu.core.optype import OperatorType

N_DEV = 8


def _trivial_strategy(graph):
    return {
        n.guid: (n.op.fixed_machine_view()
                 or MachineView.trivial(n.op.output_shapes[0].ndim))
        for n in graph.topo_order()
    }


def _decode_views(graph, strategy):
    return [
        (tuple(strategy[n.guid].dim_degrees),
         strategy[n.guid].replica_degree)
        for n in graph.topo_order()
        if n.op.op_type == OperatorType.DECODE_ATTENTION
    ]


# ---------------------------------------------------------------------------
# kernel parity vs the dense masked reference
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "B,H,D,page_size,pages_per_seq,lens",
    [
        (4, 2, 16, 8, 3, (1, 8, 17, 24)),   # single-token + full-page
        (2, 4, 32, 16, 2, (16, 32)),        # exact page boundaries
        (3, 1, 8, 8, 4, (1, 9, 31)),        # ragged mid-page
        (2, 2, 8, 4, 2, (3, 7)),            # sub-lane tiny pages
    ],
)
def test_ragged_kernel_matches_dense_reference(B, H, D, page_size,
                                               pages_per_seq, lens):
    import jax.numpy as jnp

    from flexflow_tpu.kernels.ragged_paged_attention import (
        _pallas_ragged_paged,
        _xla_ragged_paged,
        dense_decode_reference,
        gather_kv_pages,
        ragged_paged_attention,
    )

    rng = np.random.default_rng(0)
    P = B * pages_per_seq + 2  # pool larger than the allotment
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(P, page_size, H, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(P, page_size, H, D)), jnp.float32)
    pt = jnp.asarray(
        rng.permutation(P)[:B * pages_per_seq].reshape(B, pages_per_seq),
        jnp.int32)
    sl = jnp.asarray(lens, jnp.int32)
    scale = 1.0 / math.sqrt(D)
    ref = dense_decode_reference(
        q, gather_kv_pages(kp, pt), gather_kv_pages(vp, pt), sl)
    got = ragged_paged_attention(q, kp, vp, pt, sl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    fb = _xla_ragged_paged(q, kp, vp, pt, sl, scale)
    np.testing.assert_allclose(np.asarray(fb), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    if D % 8 == 0 and page_size % 8 == 0:
        pk = _pallas_ragged_paged(q, kp, vp, pt, sl, scale, True)
        np.testing.assert_allclose(np.asarray(pk), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_decode_op_incremental_matches_dense():
    """Stepping DecodeAttentionOp token by token must equal dense
    attention over every token cached so far — the cache scatter, the
    page indirection, and the +1 fresh-token length all proven against
    plain softmax."""
    import jax.numpy as jnp

    from flexflow_tpu.core.ptensor import ParallelTensorShape
    from flexflow_tpu.kernels.ragged_paged_attention import (
        dense_decode_reference,
    )
    from flexflow_tpu.ops.base import LoweringContext
    from flexflow_tpu.ops.decode_attention import DecodeAttentionOp

    B, E, H, ps, pps = 2, 32, 4, 4, 3
    op = DecodeAttentionOp(
        "dec",
        [ParallelTensorShape.make((B, 1, E), "float32"),
         ParallelTensorShape.make((B, pps), "int32"),
         ParallelTensorShape.make((B,), "int32")],
        embed_dim=E, num_heads=H, page_size=ps, pages_per_seq=pps)
    rng = np.random.default_rng(1)
    weights = {
        ws.name: jnp.asarray(rng.normal(size=ws.shape) * 0.1, jnp.float32)
        for ws in op._weight_specs
    }
    state = {}
    for name, shape, dtype, fill in op.state_specs():
        state[f"dec/{name}"] = jnp.full(shape, fill, dtype)
    # non-trivial page assignment (pages deliberately interleaved)
    pt = jnp.asarray([[1, 3, 5], [0, 2, 4]], jnp.int32)
    steps = ps * pps - 1
    xs = rng.normal(size=(steps, B, 1, E)).astype(np.float32)
    hist = []  # per-step hidden inputs, to rebuild dense K/V
    for t in range(steps):
        ctx = LoweringContext(compute_dtype=jnp.float32, train=False)
        ctx.state_in = state
        hidden = jnp.asarray(xs[t])
        lens = jnp.full((B,), t, jnp.int32)
        (out,) = op.forward(ctx, [hidden, pt, lens], weights)
        state = dict(state)
        state.update(ctx.state_out)
        hist.append(xs[t])
        # dense reference over every token so far
        x_all = jnp.asarray(np.stack(hist, axis=1)[:, :, 0, :])  # [B,t+1,E]
        qh = jnp.einsum("be,ehd->bhd", jnp.asarray(xs[t][:, 0, :]),
                        weights["wq"])
        kh = jnp.einsum("bse,ehd->bshd", x_all, weights["wk"])
        vh = jnp.einsum("bse,ehd->bshd", x_all, weights["wv"])
        ref_attn = dense_decode_reference(
            qh, kh, vh, jnp.full((B,), t + 1, jnp.int32))
        ref = jnp.einsum("bhd,hde->be", ref_attn, weights["wo"])[:, None, :]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# KV-cache-aware memory accounting
# ---------------------------------------------------------------------------
def _decode_model(batch=16, **overrides):
    from flexflow_tpu.models import GPT_DECODE_KW, build_gpt_decode

    kw = dict(GPT_DECODE_KW)
    kw.update(overrides)
    cfg = ff.FFConfig(batch_size=batch, num_devices=N_DEV,
                      comp_mode="inference", cost_cache_file="",
                      search_budget=8, search_timeout_s=30.0)
    return build_gpt_decode(cfg, **kw), cfg


def test_kv_residency_enters_memory_accounting():
    from flexflow_tpu.search.machine_model import CostModel

    m, cfg = _decode_model()
    cm = CostModel(cfg.machine_spec, num_devices=N_DEV, inference=True)
    node = next(n for n in m.graph.topo_order()
                if n.op.op_type == OperatorType.DECODE_ATTENTION)
    triv = MachineView.trivial(3)
    dp = MachineView(dim_degrees=(8, 1, 1))
    tp = MachineView(dim_degrees=(1, 1, 1), replica_degree=8)
    kv_triv = node.op.kv_cache_bytes(triv)
    assert kv_triv == pytest.approx(
        node.op.attrs["num_pages"] * node.op.attrs["page_size"]
        * node.op.kv_bytes_per_token())
    # both batch and head splits genuinely divide residency
    assert node.op.kv_cache_bytes(dp) == pytest.approx(kv_triv / 8)
    assert node.op.kv_cache_bytes(tp) == pytest.approx(kv_triv / 8)
    # and op_memory carries the pool (strictly more than the weight
    # + activation memory of the same op with the hook detached)
    with_kv = cm.op_memory(node.op, triv)
    assert with_kv > kv_triv


def test_capacity_edge_rejected_inside_search():
    """On a machine whose HBM fits the page pool only when sharded,
    the unsharded strategy simulates to inf (the memory check) and the
    SEARCH returns a sharded strategy that fits — rejection happens at
    strategy-selection time, not at runtime OOM."""
    import dataclasses

    from flexflow_tpu.search.driver import optimize_strategy
    from flexflow_tpu.search.serving import kv_residency_bytes
    from flexflow_tpu.search.simulator import Simulator

    m, cfg = _decode_model()
    triv = _trivial_strategy(m.graph)
    sim0 = Simulator(cfg.machine_spec, num_devices=N_DEV, inference=True)
    need = sim0.peak_memory(m.graph, triv)
    # capacity window: the replicated pool blows it, 1/8 residency fits
    tight = dataclasses.replace(cfg.machine_spec, hbm_capacity=need / 2)
    cfg_tight = ff.FFConfig(
        batch_size=16, num_devices=N_DEV, comp_mode="inference",
        machine_spec=tight, cost_cache_file="", search_budget=8,
        search_timeout_s=30.0)
    sim = Simulator(tight, num_devices=N_DEV, inference=True)
    assert sim.simulate(m.graph, triv) == math.inf
    g, s = optimize_strategy(m.graph, cfg_tight, return_graph=True)
    cost = Simulator(tight, num_devices=N_DEV, inference=True).simulate(g, s)
    assert math.isfinite(cost), "search returned an HBM-infeasible strategy"
    assert kv_residency_bytes(g, s, N_DEV) < need / 2


# ---------------------------------------------------------------------------
# serve objective: divergence + inertness
# ---------------------------------------------------------------------------
def _search(objective, batch, kw):
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.search.driver import optimize_strategy

    cfg = ff.FFConfig(batch_size=batch, num_devices=N_DEV,
                      search_budget=8, search_timeout_s=45.0,
                      objective=objective, comp_mode="inference",
                      cost_cache_file="")
    m = build_gpt_decode(cfg, **kw)
    g, s = optimize_strategy(m.graph, cfg, return_graph=True)
    return cfg, g, s


def test_serve_objective_diverges_and_wins_p99():
    """THE acceptance scenario (also recorded in BENCH_SEARCH.md
    "Inference serving"): on the serving-regime decode config the serve
    objective picks a different strategy than throughput and wins on
    simulated p99 under the same arrival-model currency."""
    from flexflow_tpu.models import GPT_DECODE_SERVE_KW, SERVE_FRAME_SLOTS
    from flexflow_tpu.search import driver
    from flexflow_tpu.search.serving import serve_latency_quantiles

    cfg_t, g_t, s_t = _search("train", SERVE_FRAME_SLOTS,
                              GPT_DECODE_SERVE_KW)
    assert driver.LAST_SERVING_META is None  # train run leaves no meta
    cfg_s, g_s, s_s = _search("serve", SERVE_FRAME_SLOTS,
                              GPT_DECODE_SERVE_KW)
    assert _decode_views(g_t, s_t) != _decode_views(g_s, s_s)
    p99_t = serve_latency_quantiles(g_t, s_t, cfg_s)["p99"]
    p99_s = serve_latency_quantiles(g_s, s_s, cfg_s)["p99"]
    assert p99_s < p99_t, (p99_s, p99_t)
    meta = driver.LAST_SERVING_META
    assert meta is not None and meta["objective"] == "serve"
    assert meta["predicted_p99_step_ms"] > 0
    assert meta["kv_bytes_per_device"] > 0


def test_load_factor_monotone_in_batch_degree():
    from flexflow_tpu.search.serving import ServingSpec

    spec = ServingSpec(max_seqs=32, page_size=32, pages_per_seq=128)
    f = [spec.load_factor(d) for d in (1, 2, 4, 8, 16, 32)]
    assert all(0 < x <= 1.0 for x in f)
    # fewer sequences per shard = less averaging = fatter relative p99
    assert all(a <= b + 1e-9 for a, b in zip(f, f[1:])), f
    assert f[0] < f[-1]  # the imbalance amplification is non-trivial


def test_train_objective_is_structurally_inert(monkeypatch):
    """The default objective must never touch the serving machinery
    (the poisoned-builder discipline of test_co_search): a zoo search
    with objective='train' completes with serving_spec_for booby-
    trapped, and the cost/search cache keys are byte-identical to keys
    that predate the serving dimension."""
    from flexflow_tpu.models import build_mlp_unify
    from flexflow_tpu.search import serving as serving_mod
    from flexflow_tpu.search.cost_cache import cost_signature, CostCache
    from flexflow_tpu.search.driver import optimize_strategy
    from flexflow_tpu.search.machine_model import CostModel

    def _boom(*a, **k):  # pragma: no cover - must never run
        raise AssertionError("serving machinery touched under train")

    monkeypatch.setattr(serving_mod, "serving_spec_for", _boom)
    monkeypatch.setattr(serving_mod.ServingSpec, "load_factor", _boom)
    cfg = ff.FFConfig(batch_size=16, num_devices=N_DEV, search_budget=4,
                      search_timeout_s=20.0, cost_cache_file="")
    m = build_mlp_unify(cfg, in_dim=64, hidden=(64, 64))
    g, s = optimize_strategy(m.graph, cfg, return_graph=True)
    assert s
    # signature inertness: serving=None adds no key material
    cm = CostModel(cfg.machine_spec, num_devices=N_DEV)
    sig = cost_signature(cm)
    cm_no_attr = CostModel(cfg.machine_spec, num_devices=N_DEV)
    del cm_no_attr.__dict__["serving"]  # a pre-PR cost model shape
    assert cost_signature(cm_no_attr) == sig
    k_train = CostCache.search_key(m.graph, cfg)
    cfg2 = ff.FFConfig(batch_size=16, num_devices=N_DEV, search_budget=4,
                       search_timeout_s=20.0, cost_cache_file="")
    assert CostCache.search_key(m.graph, cfg2) == k_train
    cfg_serve = ff.FFConfig(batch_size=16, num_devices=N_DEV,
                            search_budget=4, search_timeout_s=20.0,
                            cost_cache_file="", objective="serve")
    assert CostCache.search_key(m.graph, cfg_serve) != k_train


def test_serve_objective_without_decode_ops_degenerates():
    from flexflow_tpu.models import build_mlp_unify
    from flexflow_tpu.search import driver
    from flexflow_tpu.search.driver import optimize_strategy

    cfg = ff.FFConfig(batch_size=16, num_devices=N_DEV, search_budget=4,
                      search_timeout_s=20.0, cost_cache_file="",
                      objective="serve", comp_mode="inference")
    m = build_mlp_unify(cfg, in_dim=64, hidden=(64, 64))
    g, s = optimize_strategy(m.graph, cfg, return_graph=True)
    assert s and driver.LAST_SERVING_META is None


def test_serve_objective_requires_inference_mode():
    """A decode step has no backward: pricing the p99 currency with
    training costs would mint an SLO for a step that never runs — the
    driver refuses loudly instead (review finding)."""
    from flexflow_tpu.models import GPT_DECODE_KW, build_gpt_decode
    from flexflow_tpu.search.driver import optimize_strategy

    cfg = ff.FFConfig(batch_size=16, num_devices=N_DEV, search_budget=4,
                      search_timeout_s=20.0, cost_cache_file="",
                      objective="serve")  # comp_mode left at "training"
    m = build_gpt_decode(cfg, **GPT_DECODE_KW)
    with pytest.raises(ValueError, match="comp_mode='inference'"):
        optimize_strategy(m.graph, cfg, return_graph=True)


def test_co_search_refuses_serve_objective():
    with pytest.raises(ValueError, match="does not compose"):
        ff.FFConfig(objective="serve", co_search=True)


# ---------------------------------------------------------------------------
# SHD16x serving lints + STR209
# ---------------------------------------------------------------------------
def test_lint_serving_codes():
    import dataclasses

    from flexflow_tpu.analysis import errors_only, lint_serving
    from flexflow_tpu.search.machine_model import CostModel
    from flexflow_tpu.search.serving import ServingSpec, serving_spec_for

    m, cfg = _decode_model()
    strategy = _trivial_strategy(m.graph)
    cm = CostModel(cfg.machine_spec, num_devices=N_DEV, inference=True)
    spec = serving_spec_for(m.graph, cfg)
    assert not errors_only(lint_serving(m.graph, strategy, spec, cm))
    # SHD160: geometry disagreement with the decode ops
    wrong = dataclasses.replace(spec, page_size=spec.page_size * 2,
                                _factors={})
    codes = [f.code for f in lint_serving(m.graph, strategy, wrong, cm)]
    assert "SHD160" in codes
    # SHD160: missing spec entirely
    assert [f.code for f in lint_serving(m.graph, strategy, None, cm)] \
        == ["SHD160"]
    # SHD161: pool larger than HBM
    tiny = CostModel(
        dataclasses.replace(cfg.machine_spec, hbm_capacity=1e6),
        num_devices=N_DEV, inference=True)
    codes = [f.code for f in lint_serving(m.graph, strategy, spec, tiny)]
    assert "SHD161" in codes
    # SHD162: head split that does not divide the heads
    bad = dict(strategy)
    for n in m.graph.topo_order():
        if n.op.op_type == OperatorType.DECODE_ATTENTION:
            bad[n.guid] = MachineView(dim_degrees=(1, 1, 1),
                                      replica_degree=3)
    codes = [f.code for f in lint_serving(m.graph, bad, spec, cm)]
    assert "SHD162" in codes
    # SHD163: predicted p99 over the declared budget → warn, not error
    budget = dataclasses.replace(spec, p99_budget_ms=1e-6, _factors={})
    findings = lint_serving(m.graph, strategy, budget, cm,
                            predicted_p99_s=1.0)
    assert any(f.code == "SHD163" and f.severity == "warn"
               for f in findings)
    assert not errors_only(findings)
    # driver behavior when NO strategy can hold the pool: the search's
    # memory check prices everything inf, the result is returned for
    # compile's fallback machinery (the train-objective contract), and
    # no serving meta is minted for the infeasible artifact
    import dataclasses as _dc

    from flexflow_tpu.search import driver
    from flexflow_tpu.search.driver import optimize_strategy
    from flexflow_tpu.search.simulator import Simulator

    floor_bytes = sum(
        n.op.kv_cache_bytes(MachineView(dim_degrees=(8, 1, 1)))
        for n in m.graph.topo_order()
        if n.op.op_type == OperatorType.DECODE_ATTENTION)
    hopeless = _dc.replace(cfg.machine_spec, hbm_capacity=floor_bytes / 2)
    cfg_bad = ff.FFConfig(
        batch_size=16, num_devices=N_DEV, comp_mode="inference",
        machine_spec=hopeless, cost_cache_file="", search_budget=4,
        search_timeout_s=20.0, objective="serve")
    g_bad, s_bad = optimize_strategy(m.graph, cfg_bad, return_graph=True)
    assert driver.LAST_SERVING_META is None
    assert Simulator(hopeless, num_devices=N_DEV,
                     inference=True).simulate(g_bad, s_bad) == math.inf


def test_str209_serving_meta_lint(tmp_path):
    import sys

    sys.path.insert(0, "tools")
    try:
        from fflint import lint_strategy_file
    finally:
        sys.path.pop(0)

    good_meta = {
        "graph_digest": "d" * 32,
        "serving": {"objective": "serve", "max_seqs": 16,
                    "page_size": 16, "pages_per_seq": 16,
                    "quantile": 0.99, "p99_budget_ms": 0.0,
                    "predicted_p99_step_ms": 0.05,
                    "kv_bytes_per_device": 2.1e6},
    }
    base = {"lm_head": {"dims": [8, 1, 1], "replica": 1, "start": 0}}

    def write(meta):
        p = tmp_path / "strategy.json"
        p.write_text(json.dumps({**base, "__meta__": meta}))
        return str(p)

    assert not [f for f in lint_strategy_file(write(good_meta))
                if f[1] == "STR209"]
    corruptions = [
        ("not-an-object", {**good_meta, "serving": [1, 2]}),
        ("wrong objective", {**good_meta, "serving": {
            **good_meta["serving"], "objective": "train"}}),
        ("zero max_seqs", {**good_meta, "serving": {
            **good_meta["serving"], "max_seqs": 0}}),
        ("bool page_size", {**good_meta, "serving": {
            **good_meta["serving"], "page_size": True}}),
        ("quantile 1.5", {**good_meta, "serving": {
            **good_meta["serving"], "quantile": 1.5}}),
        ("negative budget", {**good_meta, "serving": {
            **good_meta["serving"], "p99_budget_ms": -1}}),
        ("nan p99", {**good_meta, "serving": {
            **good_meta["serving"], "predicted_p99_step_ms": float("nan")}}),
        ("negative kv", {**good_meta, "serving": {
            **good_meta["serving"], "kv_bytes_per_device": -5}}),
    ]
    for label, meta in corruptions:
        found = [f for f in lint_strategy_file(write(meta))
                 if f[1] == "STR209" and f[0] == "error"]
        assert found, f"corruption {label!r} not caught by STR209"


def test_serving_meta_round_trip(tmp_path):
    """compile(objective=serve) persists __meta__.serving behind the
    digest gate; import re-lints it (SHD16x) against the target graph."""
    from flexflow_tpu.models import GPT_DECODE_KW, build_gpt_decode
    from flexflow_tpu.search.strategy_io import read_meta

    path = str(tmp_path / "serve_strategy.json")
    kw = dict(GPT_DECODE_KW)
    cfg = ff.FFConfig(batch_size=8, num_devices=N_DEV, search_budget=0,
                      objective="serve", cost_cache_file="",
                      export_strategy_file=path)
    m = build_gpt_decode(cfg, **kw)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              comp_mode="inference")
    meta = read_meta(path)
    assert meta.get("serving", {}).get("objective") == "serve"
    assert meta["serving"]["max_seqs"] == 8
    # re-import: the serving block re-lints against THIS graph
    cfg2 = ff.FFConfig(batch_size=8, num_devices=N_DEV,
                       import_strategy_file=path, cost_cache_file="")
    m2 = build_gpt_decode(cfg2, **kw)
    m2.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
               comp_mode="inference")
    assert m2.strategy
    # a corrupted geometry must fail the import gate with findings
    from flexflow_tpu.analysis import AnalysisError

    data = json.load(open(path))
    data["__meta__"]["serving"]["page_size"] = 64
    bad_path = str(tmp_path / "bad.json")
    json.dump(data, open(bad_path, "w"))
    cfg3 = ff.FFConfig(batch_size=8, num_devices=N_DEV,
                       import_strategy_file=bad_path, cost_cache_file="")
    m3 = build_gpt_decode(cfg3, **kw)
    with pytest.raises(AnalysisError):
        m3.compile(loss_type="sparse_categorical_crossentropy",
                   metrics=[], comp_mode="inference")


# ---------------------------------------------------------------------------
# continuous-batching executor
# ---------------------------------------------------------------------------
def _synthetic_step(vocab=97):
    """Deterministic model stand-in: the next token is a pure function
    of (current token, position) — enough structure that scheduling
    bugs (wrong slot, wrong position, corrupted cache) change the
    output stream."""

    def step(ids, table, lens):
        ids = np.asarray(ids)
        lens = np.asarray(lens)
        nxt = (ids[:, 0] * 7 + lens * 13 + 5) % vocab
        logits = np.zeros((ids.shape[0], 1, vocab), np.float32)
        logits[np.arange(ids.shape[0]), 0, nxt] = 1.0
        return logits

    return step


def test_executor_batched_equals_solo():
    """Continuous batching must be semantically invisible: each
    request's generated tokens equal serving it ALONE."""
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
    )

    reqs = [
        DecodeRequest(rid=f"r{i}", prompt=[3 + i, 11, 2 * i + 1],
                      max_new_tokens=3 + (i % 3))
        for i in range(7)
    ]
    solo = {}
    for r in reqs:
        ex = ContinuousBatchingExecutor(
            _synthetic_step(), max_seqs=1, page_size=4, pages_per_seq=4)
        solo.update(ex.run([DecodeRequest(rid=r.rid, prompt=list(r.prompt),
                                          max_new_tokens=r.max_new_tokens)]))
    # 3 slots, pages for only 2 concurrent sequences: admission waits
    ex = ContinuousBatchingExecutor(
        _synthetic_step(), max_seqs=3, page_size=4, pages_per_seq=4,
        num_pages=8)
    batched = ex.run(reqs, max_frames=400)
    assert batched == solo
    s = ex.summary()
    assert s["completed"] == len(reqs)
    assert s["admitted"] == len(reqs) and s["evicted"] == len(reqs)
    # every sequence page returned; only the oversubscribed pool's
    # permanently reserved scratch page stays out
    assert ex.allocator.pages_in_use == 1 and not ex.slot_aligned


def test_executor_exhausted_pool_never_corrupts_live_cache():
    """Review-finding regression: an OVERSUBSCRIBED pool fully
    exhausted by one live sequence while other slots sit idle — the
    idle rows' unavoidable scatter must land on the reserved scratch
    page, never on the live sequence's page 0 (whose slot 0 holds its
    FIRST cached token).  Proven end-to-end on the compiled decode
    graph: batched tokens equal serving the request alone."""
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
        compiled_decode_step,
    )

    kw = dict(vocab=128, num_layers=1, hidden=32, num_heads=2,
              ff_dim=32, page_size=2, pages_per_seq=2, num_pages=3)
    req = DecodeRequest(rid="a", prompt=[7, 11], max_new_tokens=2)

    def run(num_pages):
        cfg = ff.FFConfig(batch_size=2, num_devices=1, cost_cache_file="")
        m = build_gpt_decode(cfg, **kw)
        m.compile(loss_type="sparse_categorical_crossentropy",
                  metrics=[], comp_mode="inference",
                  strategy=_trivial_strategy(m.graph))
        ex = ContinuousBatchingExecutor(
            compiled_decode_step(m), max_seqs=2, page_size=2,
            pages_per_seq=2, num_pages=num_pages)
        return ex.run([DecodeRequest(rid="a", prompt=list(req.prompt),
                                     max_new_tokens=2)], max_frames=40)

    # pool 3: scratch reserved -> 2 usable -> the live sequence holds
    # EVERY allocatable page while slot 1 idles (the corruption regime)
    assert run(3) == run(4)  # 4 = slot-aligned, trivially safe


def test_executor_page_accounting_and_caps():
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
        PageAllocator,
    )

    pa = PageAllocator(4)
    got = pa.alloc(3)
    assert pa.free_pages == 1 and pa.pages_in_use == 3
    assert pa.alloc(2) is None  # refuse partial allotments
    pa.free(got)
    assert pa.free_pages == 4
    ex = ContinuousBatchingExecutor(
        _synthetic_step(), max_seqs=2, page_size=4, pages_per_seq=2)
    with pytest.raises(AssertionError):  # request longer than a sequence
        ex.submit([DecodeRequest(rid="x", prompt=[1] * 7,
                                 max_new_tokens=9)])


def test_executor_on_compiled_decode_model():
    """End-to-end: the executor drives the COMPILED decode graph (KV
    caches threaded as model state) and emits schema-valid obs
    events + a decode DriftReport."""
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.obs.events import BUS, validate_event
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
        compiled_decode_step,
    )

    kw = dict(vocab=256, num_layers=1, hidden=64, num_heads=4,
              ff_dim=64, page_size=4, pages_per_seq=4)
    cfg = ff.FFConfig(batch_size=4, num_devices=1, cost_cache_file="")
    m = build_gpt_decode(cfg, **kw)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              comp_mode="inference",
              strategy=_trivial_strategy(m.graph))
    import tempfile

    log = tempfile.mktemp(suffix=".jsonl")
    BUS.configure(log)
    try:
        ex = ContinuousBatchingExecutor(
            compiled_decode_step(m), max_seqs=4, page_size=4,
            pages_per_seq=4, num_pages=8, predicted_step_s=1e-4)
        out = ex.run([DecodeRequest(rid=f"r{i}", prompt=[1 + i, 2],
                                    max_new_tokens=3) for i in range(5)],
                     max_frames=120)
        assert len(out) == 5
        assert all(len(v) == 3 for v in out.values())
        rep = ex.decode_drift_report()
        assert rep is not None and "decode" in rep.phases
        BUS.flush()
        with open(log) as f:
            for line in f:
                assert validate_event(json.loads(line)) == []
    finally:
        BUS.close()
        import os

        os.remove(log)


def test_decode_graph_searched_strategy_executes():
    """A SEARCHED multi-device decode strategy lowers and steps on the
    host mesh — the state-sharded KV cache path is executable, not
    just priced."""
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
        compiled_decode_step,
    )

    kw = dict(vocab=256, num_layers=1, hidden=64, num_heads=4,
              ff_dim=64, page_size=4, pages_per_seq=4)
    cfg = ff.FFConfig(batch_size=8, num_devices=N_DEV,
                      search_budget=4, search_timeout_s=20.0,
                      cost_cache_file="",
                      machine_spec=MachineSpec.host_cpu(N_DEV))
    m = build_gpt_decode(cfg, **kw)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              comp_mode="inference")
    ex = ContinuousBatchingExecutor(
        compiled_decode_step(m), max_seqs=8, page_size=4,
        pages_per_seq=4)
    out = ex.run([DecodeRequest(rid="a", prompt=[5, 6, 7],
                                max_new_tokens=4)], max_frames=60)
    assert len(out["a"]) == 4
