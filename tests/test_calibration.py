"""Cost-model calibration: measured per-(op, view) costs override the
roofline and change search decisions (reference: ProfilingRecord cache,
src/runtime/simulator.cc:515-554; on-device timing model.cu:38-74)."""

import math

import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.search.calibration import (
    CalibrationTable,
    calibrate_graph,
    measure_op_view,
)
from flexflow_tpu.search.dp import SearchHelper
from flexflow_tpu.search.simulator import Simulator


def mlp_model(batch=64, in_dim=128, hidden=256, classes=16):
    cfg = ff.FFConfig(batch_size=batch, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([batch, in_dim])
    t = m.dense(x, hidden, activation="relu", name="fc1")
    t = m.dense(t, classes, name="head")
    return m


def test_table_roundtrip(tmp_path):
    m = mlp_model()
    op = m.node_by_name("fc1").op
    table = CalibrationTable()
    table.put(op, MachineView.data_parallel(2, 8), 1.5e-4)
    table.put(op, MachineView.trivial(2), 9e-4)
    p = str(tmp_path / "calib.json")
    table.save(p)
    loaded = CalibrationTable.load(p)
    assert len(loaded) == 2
    assert loaded.get(op, MachineView.data_parallel(2, 8)) == pytest.approx(1.5e-4)
    assert loaded.get(op, MachineView.trivial(2)) == pytest.approx(9e-4)


def test_injected_measurements_flip_search_ranking():
    """The VERDICT r2 contract: a search decision must be reversible by
    measurements alone.  For this small dense layer the roofline keeps
    fc1 UNSHARDED (compute is tiny; any sharding pays sync/xfer).
    Inject measurements saying the unsharded kernel is pathologically
    slow on real hardware while every sharded variant is fast, and the
    search must start sharding that op."""
    m = mlp_model()
    g = m.graph
    n_dev = 8

    def searched_parts(calibration):
        sim = Simulator(m.config.machine_spec, num_devices=n_dev,
                        calibration=calibration)
        helper = SearchHelper(sim, n_dev)
        _, strategy = helper.graph_cost(g)
        fc1 = m.node_by_name("fc1")
        return strategy[fc1.guid].num_parts

    assert searched_parts(None) == 1  # roofline: trivial wins

    fc1_op = m.node_by_name("fc1").op
    table = CalibrationTable()
    from flexflow_tpu.search.views import boundary_views, candidate_views

    views = list(candidate_views(fc1_op, n_dev)) + list(
        boundary_views(fc1_op, n_dev)
    )
    for mv in views:
        table.put(fc1_op, mv, 5e-2 if mv.num_parts == 1 else 1e-6)
    assert searched_parts(table) > 1  # measurements flipped the ranking


def test_measure_and_calibrate_graph_smoke():
    """measure_op_view probes a sharded dense layer on the live backend
    (CPU mesh in tests; the real chip under bench) and calibrate_graph
    fills a table for a small graph within its budget."""
    # shapes large enough that one forward clears timer noise on a CPU
    # backend — sub-noise probes now decline (return None) by design
    m = mlp_model(batch=512, in_dim=512, hidden=1024, classes=64)
    op = m.node_by_name("fc1").op
    t_full = measure_op_view(op, MachineView.trivial(2), warmup=1, repeats=2)
    assert t_full is not None and math.isfinite(t_full) and t_full > 0
    t_shard = measure_op_view(op, MachineView.data_parallel(2, 8),
                              warmup=1, repeats=2)
    assert t_shard is not None and t_shard > 0

    table = calibrate_graph(m.graph, 8, time_budget_s=20.0, repeats=1)
    assert len(table) > 0
    # the search consumes the table through the simulator
    sim = Simulator(m.config.machine_spec, num_devices=8, calibration=table)
    helper = SearchHelper(sim, 8)
    cost, strategy = helper.graph_cost(m.graph)
    assert math.isfinite(cost) and strategy


def test_calibrate_graph_fills_caller_table_in_place():
    """Regression: an EMPTY CalibrationTable is falsy (__len__ == 0), so a
    `table or CalibrationTable()` default silently discarded the caller's
    table — bench_search passed a fresh table, calibrate_graph filled a
    private one, and the artifact reported 'calibrated 0 records'."""
    m = mlp_model(batch=512, in_dim=512, hidden=1024, classes=64)
    mine = CalibrationTable()
    assert not mine  # the precondition that triggered the bug
    out = calibrate_graph(m.graph, 8, mine, time_budget_s=20.0, repeats=1)
    assert out is mine
    assert len(mine) > 0


def test_compile_time_calibration_probes_and_persists(tmp_path):
    """FFConfig(calibrate=True) makes the default compile path probe
    this graph's (op, view) costs on the live backend and rank with
    them — the reference's default behavior (simulator.cc:515-554,
    model.cu:38-74) — persisting to calibration_file for later runs."""
    import json
    import os

    from flexflow_tpu.core.machine import MachineSpec

    path = str(tmp_path / "cal.json")
    # machine model must describe the live backend for probes to be
    # coherent (the driver declines to probe otherwise)
    cfg = ff.FFConfig(batch_size=512, num_devices=8, search_budget=2,
                      calibrate=True, calibration_file=path,
                      calibration_budget_s=25.0,
                      machine_spec=MachineSpec.host_cpu(8))
    m = ff.FFModel(cfg)
    x = m.create_tensor([512, 512])
    t = m.dense(x, 1024, activation="relu", name="fc1")
    t = m.dense(t, 64, name="head")
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    assert os.path.exists(path)
    with open(path) as f:
        data = json.load(f)
    assert len(data["records"]) > 0
    assert data["backend"] == "cpu"  # tests run on the CPU mesh

    # second compile resumes from the persisted table (no growth needed,
    # just correctness of the load path through FFConfig)
    cfg2 = ff.FFConfig(batch_size=512, num_devices=8, search_budget=2,
                       calibration_file=path,
                       machine_spec=MachineSpec.host_cpu(8))
    m2 = ff.FFModel(cfg2)
    x2 = m2.create_tensor([512, 512])
    t2 = m2.dense(x2, 1024, activation="relu", name="fc1")
    t2 = m2.dense(t2, 64, name="head")
    m2.compile(loss_type="sparse_categorical_crossentropy", metrics=[])


def test_mismatched_backend_calibration_ignored(tmp_path):
    """A table probed on a backend the machine model does not describe
    must not override the roofline (TPU-probed milliseconds are
    incoherent with a CPU-modeled simulator and vice versa): the driver
    discards it and ranks analytically.  A TPU table WITH a TPU machine
    model on a CPU host stays valid — the reference's
    search-on-small-machine pattern (graph.cc:1535-1540)."""
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.driver import optimize_strategy
    from flexflow_tpu.search.views import boundary_views, candidate_views

    m = mlp_model()
    fc1 = m.node_by_name("fc1")
    views = list(candidate_views(fc1.op, 8)) + list(
        boundary_views(fc1.op, 8))

    def tpu_table(path, punish_unsharded):
        t = CalibrationTable()
        for mv in views:
            slow = (mv.num_parts == 1) if punish_unsharded \
                else (mv.num_parts > 1)
            t.put(fc1.op, mv, 5e-2 if slow else 1e-6)
        t.backend = "tpu"
        t.save(path)
        return path

    # the CPU roofline SHARDS this layer (low peak flops -> compute
    # dominates); a consulted table punishing sharding would flip it to
    # unsharded.  With a cpu machine model the tpu-probed table must be
    # discarded, so the sharded roofline pick survives.
    path_ps = tpu_table(str(tmp_path / "punish_shard.json"),
                        punish_unsharded=False)
    cfg = ff.FFConfig(batch_size=64, num_devices=8, search_budget=0,
                      calibration_file=path_ps,
                      machine_spec=MachineSpec.host_cpu(8))
    strategy = optimize_strategy(m.graph, cfg)
    assert strategy[fc1.guid].num_parts > 1

    # the TPU roofline keeps this layer UNSHARDED; the same-backend
    # table punishing unsharded IS consulted and flips the ranking —
    # even though tests run on a CPU host (the reference's
    # search-on-small-machine pattern)
    path_pu = tpu_table(str(tmp_path / "punish_unsharded.json"),
                        punish_unsharded=True)
    cfg_tpu = ff.FFConfig(batch_size=64, num_devices=8, search_budget=0,
                          calibration_file=path_pu)
    assert cfg_tpu.machine_spec.platform == "tpu"  # the default model
    strategy2 = optimize_strategy(m.graph, cfg_tpu)
    assert strategy2[fc1.guid].num_parts > 1
    # and the punishing-sharded table, consulted on the tpu model,
    # keeps it unsharded — proving consultation, not coincidence
    cfg_tpu2 = ff.FFConfig(batch_size=64, num_devices=8, search_budget=0,
                           calibration_file=path_ps)
    strategy3 = optimize_strategy(m.graph, cfg_tpu2)
    assert strategy3[fc1.guid].num_parts == 1


# ---------------------------------------------------------------------------
# adaptive probes for sub-noise ops + fusion-cluster measurements (round-4)
# ---------------------------------------------------------------------------


def test_cheap_ops_are_measurable():
    """softmax/layernorm/pool-class ops used to fall below timer noise
    and stay unmeasured — the adaptive scan length must resolve them."""
    cfg = ff.FFConfig(batch_size=8, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 32, 64])
    t = m.layer_norm(x, name="ln")
    t = m.softmax(t, name="sm")
    table = calibrate_graph(m.graph, 8, time_budget_s=60.0, repeats=2)
    kinds = {eval(k[0])[0] for k in table._t}
    assert "layernorm" in kinds, kinds
    assert "softmax" in kinds, kinds


def test_cluster_probe_and_simulator_override(tmp_path):
    """A linear+gelu+softmax chain gets a fused measurement; the
    simulator must then price the chain at (or below) its lone-op sum,
    and the record must survive a save/load round trip."""
    from flexflow_tpu.compiler.lowering import data_parallel_strategy
    from flexflow_tpu.search.calibration import (
        calibrate_clusters,
        find_clusters,
    )

    cfg = ff.FFConfig(batch_size=32, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([32, 128])
    t = m.dense(x, 256, name="fc")
    t = m.gelu(t, name="act")
    t = m.softmax(t, name="sm")

    chains = find_clusters(m.graph)
    assert len(chains) == 1
    producer, chain = chains[0]
    assert producer.op.name == "fc"
    assert [c.op.name for c in chain] == ["act", "sm"]

    table = CalibrationTable()
    calibrate_clusters(m.graph, 8, table, time_budget_s=60.0, repeats=2)
    assert table.num_clusters >= 1

    p = str(tmp_path / "calib.json")
    table.save(p)
    loaded = CalibrationTable.load(p)
    assert loaded.num_clusters == table.num_clusters

    strat = dict(data_parallel_strategy(m.graph, 8))
    base_sim = Simulator(cfg.machine_spec, num_devices=8)
    base = base_sim.simulate(m.graph, strat)
    fused = Simulator(cfg.machine_spec, num_devices=8,
                      calibration=loaded).simulate(m.graph, strat)
    assert math.isfinite(fused) and fused > 0
    # a fused measurement is a refinement with ratio clamped at 1.0, so
    # total simulated cost can never increase
    assert fused <= base * (1.0 + 1e-9)

    # deterministic check that the override actually engages: inject a
    # cluster record saying the fused chain costs 10% of the lone sum
    # and the simulated total must drop strictly below the baseline
    ops = [producer.op] + [c.op for c in chain]
    mv = strat[producer.guid]
    lone = sum(base_sim.cost.op_cost(op, mv, backward=False) for op in ops)
    injected = CalibrationTable()
    injected.put_cluster(ops, mv, lone * 0.1)
    cheap = Simulator(cfg.machine_spec, num_devices=8,
                      calibration=injected).simulate(m.graph, strat)
    assert cheap < base


def test_cluster_reservation_only_when_unmeasured(monkeypatch):
    """The 25% cluster-budget reservation must key on MISSING cluster
    probes, not on mere cluster presence: a resumed run whose clusters
    are fully measured would otherwise stop op probing at 75% of the
    budget and return the reserved time unused.  Deterministic via a
    fake clock + fake probes (each op probe 'costs' 10s), so the budget
    arithmetic — not host speed — decides what gets measured."""
    from flexflow_tpu.search import calibration as cal

    cfg = ff.FFConfig(batch_size=64, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([64, 128])
    t = m.dense(x, 256, name="fc")
    t = m.gelu(t, name="act")
    g = m.graph

    clusters = cal.find_clusters(g)
    assert clusters
    clock = [0.0]
    monkeypatch.setattr(cal.time, "monotonic", lambda: clock[0])

    def fake_op_probe(op, mv, repeats=3, **kw):
        clock[0] += 10.0
        return 0.001

    def fake_cluster_probe(producer, chain, mv, repeats=3):
        clock[0] += 10.0
        return 0.002

    monkeypatch.setattr(cal, "measure_op_view", fake_op_probe)
    monkeypatch.setattr(cal, "measure_cluster", fake_cluster_probe)

    # learn the full queue size with an effectively unlimited budget
    probe_all = cal.calibrate_graph(g, 8, CalibrationTable(),
                                    time_budget_s=1e9)
    n_ops, n_cl = len(probe_all), probe_all.num_clusters
    # the budget arithmetic below only discriminates with >=6 queued op
    # probes (0.75*n + 1 < n); guard the regime, not just non-emptiness
    assert n_ops >= 6 and n_cl >= 1

    # Case 1: clusters fully pre-measured -> NO reservation; a budget of
    # exactly 10s/op must measure every queued op probe.  Under the
    # keyed-on-presence regression op probing would stop at 75% of the
    # budget and strand the rest (0.75*n + 1 < n for n > 4).
    pre = CalibrationTable()
    pre._clusters = dict(probe_all._clusters)
    assert not cal._any_cluster_unmeasured(pre, clusters, 8)
    clock[0] = 0.0
    cal.calibrate_graph(g, 8, pre, time_budget_s=10.0 * n_ops + 5.0)
    assert len(pre) == n_ops, (
        f"full budget must reach all {n_ops} op probes when no cluster "
        f"probe is missing; got {len(pre)}"
    )

    # Case 2: clusters unmeasured -> reservation applies; the same
    # budget stops op probing early and spends the tail on clusters.
    fresh = CalibrationTable()
    clock[0] = 0.0
    cal.calibrate_graph(g, 8, fresh, time_budget_s=10.0 * n_ops + 5.0)
    assert len(fresh) < n_ops, "reservation should starve some op probes"
    assert fresh.num_clusters >= 1, "reserved budget must reach clusters"


def test_cluster_probe_dedup_across_identical_chains(monkeypatch):
    """N identical chains share one cluster_key: the probe queue must
    hold each (cluster_key, view) ONCE, not N times — a tight budget
    would otherwise buy N copies of the same measurement."""
    from flexflow_tpu.search import calibration as cal

    cfg = ff.FFConfig(batch_size=64, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([64, 128])
    for i in range(3):  # three IDENTICAL dense+gelu chains
        t = m.dense(x, 32, name=f"fc{i}")
        m.gelu(t, name=f"act{i}")

    calls = []
    monkeypatch.setattr(
        cal, "measure_cluster",
        lambda producer, chain, mv, repeats=3: calls.append(
            cal.CalibrationTable.cluster_key(
                [producer.op] + [c.op for c in chain], mv)) or 0.001)
    table = CalibrationTable()
    cal.calibrate_clusters(m.graph, 8, table, time_budget_s=1e9)
    assert len(calls) == len(set(calls)), (
        "identical chains must not be probed repeatedly")
    assert table.num_clusters == len(set(calls))


# ---------------------------------------------------------------------------
# satellite: drift-staleness -> automatic re-probe policy


def test_stale_table_reprobed_when_live_backend_matches(tmp_path):
    """A DriftReport-marked table must make the NEXT optimize_strategy
    re-probe (live backend == machine target) instead of only warning:
    fresh records, stale flag cleared on disk."""
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.driver import optimize_strategy

    path = str(tmp_path / "cal.json")
    cfg = ff.FFConfig(batch_size=16, num_devices=8,
                      machine_spec=MachineSpec.host_cpu(8),
                      calibration_file=path, search_budget=0,
                      calibration_budget_s=15.0, cost_cache_file="")
    m = ff.FFModel(cfg)
    x = m.create_tensor([16, 32])
    m.dense(m.dense(x, 64, name="fc1"), 8, name="head")
    table = CalibrationTable()
    calibrate_graph(m.graph, 8, table, time_budget_s=15.0)
    table.save(path)
    assert CalibrationTable.mark_stale_file(path, 2.5)
    loaded = CalibrationTable.load(path)
    assert loaded.stale and loaded.stale_ratio == 2.5
    optimize_strategy(m.graph, cfg, return_graph=False)
    after = CalibrationTable.load(path)
    assert not after.stale, "re-probe must clear the stale flag"
    assert len(after) > 0, "re-probe must produce fresh records"


def test_stale_table_discarded_when_backend_cannot_reprobe(tmp_path):
    """Stale table for a TPU machine model on a CPU host: the search
    must fall back to the roofline (table ignored) rather than rank
    with measurements execution falsified — and must NOT clear the
    on-disk stale flag (the re-probe still owes)."""
    from flexflow_tpu.search.driver import load_calibration, optimize_strategy

    path = str(tmp_path / "cal.json")
    cfg = ff.FFConfig(batch_size=16, num_devices=8,
                      calibration_file=path, search_budget=0,
                      cost_cache_file="")  # default machine: tpu_v5e
    m = ff.FFModel(cfg)
    x = m.create_tensor([16, 32])
    m.dense(m.dense(x, 64, name="fc1"), 8, name="head")
    table = CalibrationTable()
    table.backend = "tpu"
    for node in m.graph.topo_order():
        from flexflow_tpu.core.machine import MachineView

        table.put(node.op, MachineView.trivial(
            node.op.output_shapes[0].ndim), 1e-4)
    table.stale = True
    table.stale_ratio = 3.0
    table.save(path)
    optimize_strategy(m.graph, cfg, return_graph=False)
    after = CalibrationTable.load(path)
    assert after.stale, "deferred re-probe must keep the flag"
    assert len(after) == len(table), "records must survive untouched"
    assert load_calibration(cfg).stale  # and loading still sees it


def test_auto_reprobe_capped_on_persistent_drift(tmp_path):
    """Re-probing that keeps reproducing the drift is a cost-MODEL gap:
    past MAX_AUTO_REPROBES the driver must stop burning the calibration
    budget (records kept on disk, roofline used), and a healthy
    calibrated fit resets the allowance (mark_healthy_file)."""
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.driver import optimize_strategy

    path = str(tmp_path / "cal.json")
    cfg = ff.FFConfig(batch_size=16, num_devices=8,
                      machine_spec=MachineSpec.host_cpu(8),
                      calibration_file=path, search_budget=0,
                      calibration_budget_s=15.0, cost_cache_file="")
    m = ff.FFModel(cfg)
    x = m.create_tensor([16, 32])
    m.dense(m.dense(x, 64, name="fc1"), 8, name="head")
    table = CalibrationTable()
    calibrate_graph(m.graph, 8, table, time_budget_s=15.0)
    table.stale = True
    table.stale_ratio = 2.0
    table.reprobes = CalibrationTable.MAX_AUTO_REPROBES
    n_records = len(table)
    table.save(path)
    optimize_strategy(m.graph, cfg, return_graph=False)
    after = CalibrationTable.load(path)
    # capped: no re-probe ran — flag and records untouched on disk
    assert after.stale and len(after) == n_records
    assert after.reprobes == CalibrationTable.MAX_AUTO_REPROBES
    # a healthy calibrated fit resets the allowance
    assert CalibrationTable.mark_healthy_file(path)
    healthy = CalibrationTable.load(path)
    assert not healthy.stale and healthy.reprobes == 0
    # and the counter climbs through begin_reprobe on a fresh cycle
    healthy.stale = True
    healthy.begin_reprobe()
    assert healthy.reprobes == 1 and not healthy.stale


def test_healthy_calibrated_fit_resets_allowance_without_obs(tmp_path):
    """Regression (always-on loop satellite): the re-probe-allowance
    reset must NOT ride the drift-report path alone — a healthy
    calibrated fit with profiling OFF and the obs bus OFF still resets
    ``reprobes`` via mark_healthy_file (fit's own post-compile step
    timer is the evidence; staleness within the configured threshold
    counts as healthy)."""
    import json

    import numpy as np

    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.obs.events import BUS

    path = str(tmp_path / "cal.json")
    cfg = ff.FFConfig(batch_size=8, num_devices=2,
                      machine_spec=MachineSpec.host_cpu(2),
                      only_data_parallel=True, calibration_file=path,
                      cost_cache_file="",
                      # a CPU-host step never lands within a real drift
                      # band; the threshold is config — what this test
                      # pins is the RESET PATH, not the band
                      drift_threshold=1e9)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16])
    m.dense(m.dense(x, 32, name="fc1"), 4, name="head")
    table = CalibrationTable()
    for node in m.graph.topo_order():
        table.put(node.op, MachineView.trivial(
            node.op.output_shapes[0].ndim), 1e-4)
    table.reprobes = CalibrationTable.MAX_AUTO_REPROBES  # spent allowance
    table.save(path)
    assert not BUS.enabled  # the whole point: no obs bus in play
    m.compile(loss_type="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    # the calibrated compile recorded its prediction even with the bus
    # off (the gate the bugfix widened)
    assert m.predicted_breakdown and m.predicted_breakdown["calibrated"]
    rng = np.random.RandomState(0)
    X = rng.randn(16, 16).astype(np.float32)
    Y = rng.randint(0, 4, size=(16,)).astype(np.int32)
    m.fit(X, Y, batch_size=8, epochs=2, verbose=False)
    with open(path) as f:
        assert json.load(f)["reprobes"] == 0, (
            "healthy calibrated fit must reset the re-probe allowance "
            "even with profiling and the obs bus disabled")
