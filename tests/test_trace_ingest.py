"""The measured side of the loop (ISSUE 14): device-trace ingestion +
lane matching (obs/trace_ingest.py + obs/annotate.py), per-request
serving telemetry (runtime/decode.py), the Prometheus exposition
(obs/exposition.py), and the seeded-reservoir histogram fix.

The committed fixture ``tests/data/device_trace_fixture.trace.json``
exercises the parser and tag matcher without a live capture; the
tier-1 smoke at the bottom runs the REAL pipeline — a short fit with
``device_trace_dir`` on the 8-dev CPU mesh, a decode serve with obs
on, ingest → match → ``LaneDriftReport`` — and asserts ``ffobs
report`` renders it, ``ffobs validate`` exits 0, and ``ffobs
metrics`` renders the Prometheus exposition from the snapshot JSONL
offline.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.obs.annotate import lane_tag, parse_tag
from flexflow_tpu.obs.drift import build_drift_report
from flexflow_tpu.obs.events import BUS, validate_event
from flexflow_tpu.obs.exposition import render_prometheus
from flexflow_tpu.obs.metrics import Histogram, MetricsRegistry
from flexflow_tpu.obs.trace_ingest import (
    apply_lane_measurements,
    build_lane_drift_report,
    ingest,
    match_lanes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "data",
                       "device_trace_fixture.trace.json")


@pytest.fixture(autouse=True)
def _bus_teardown():
    yield
    BUS.close()


# ---------------------------------------------------------------------------
# annotation tag vocabulary
def test_lane_tag_roundtrip():
    assert lane_tag("bucket:b0:sync") == "ff.lane/bucket:b0:sync"
    assert parse_tag("ff.lane/bucket:b0:sync#issue") == \
        ("bucket:b0:sync", "issue")
    assert parse_tag("ff.lane/bucket:b0:sync#done") == \
        ("bucket:b0:sync", "done")
    assert parse_tag("ff.lane/x:sync") == ("x:sync", None)
    assert parse_tag("dot.4") is None


# ---------------------------------------------------------------------------
# fixture: parser + pairing
def test_fixture_ingest_parses_and_pairs():
    result = ingest(FIXTURE, emit=False)
    assert result is not None
    assert result.events > 10
    # two annotated step windows, in time order
    assert result.step_spans == [(1000.0, 2000.0), (3000.0, 3800.0)]
    # issue/done pairs per lane: the out-of-window b0 pair still pairs
    # here (windows apply at MATCH time); the unpaired trailing b1
    # issue is dropped
    assert sorted(result.lanes) == [
        "bucket:b0:sync", "bucket:b1:sync", "bucket:zz:sync"]
    assert len(result.lanes["bucket:b0:sync"]) == 3
    assert len(result.lanes["bucket:b1:sync"]) == 2
    # non-step phase spans are collected with their durations
    assert result.phases["ff.phase/decode_frame"] == [300.0 / 1e6]


def _predicted(total_s=0.001, b1_sync=0.0003):
    """A Simulator.simulate(breakdown=...)-shaped prediction whose
    lanes mirror the fixture: b0 issues at 20% of the step for 30%,
    b1 at 60% for 30% — matching the fixture's measured fractions."""
    return {
        "total_s": total_s,
        "sync_buckets": [
            {"name": "b0", "lane": "bucket:b0:sync", "ops": ["x"],
             "start_s": 0.0002, "sync_s": 0.0003, "exposed_s": 0.0},
            {"name": "b1", "lane": "bucket:b1:sync", "ops": ["y"],
             "start_s": 0.0006, "sync_s": b1_sync, "exposed_s": 0.0},
        ],
    }


def test_fixture_lane_match_by_tag():
    result = ingest(FIXTURE, emit=False)
    report = match_lanes(result, _predicted(), threshold=0.5,
                         emit=False)
    assert report is not None
    assert report.steps == 2
    assert report.matched_all and report.matched == 2
    by = {r["lane"]: r for r in report.lanes}
    b0 = by["bucket:b0:sync"]
    # only the two IN-WINDOW occurrences count (the 6000us pair sits
    # outside every step span)
    assert b0["samples"] == 2
    # window 1: issue 200us/dur 300us of a 1000us step; window 2:
    # issue 160us/dur 240us of 800us — means over both
    assert b0["measured_issue_s"] == pytest.approx(180e-6)
    assert b0["measured_sync_s"] == pytest.approx(270e-6)
    assert b0["measured_issue_frac"] == pytest.approx(0.2, rel=1e-6)
    assert b0["measured_sync_frac"] == pytest.approx(0.3, rel=1e-6)
    # the prediction put b0 at the same fractions: ratio 1.0
    assert b0["issue_frac_ratio"] == pytest.approx(1.0, rel=1e-6)
    assert b0["sync_frac_ratio"] == pytest.approx(1.0, rel=1e-6)
    assert report.stale_lanes == []
    # the lane the prediction does not know is reported, not silently
    # absorbed into a fuzzy match
    assert report.unmatched_trace == ["bucket:zz:sync"]


def test_fixture_lane_drift_flags_stale_lane():
    """A lane whose measured step share is far off its predicted share
    lands in stale_lanes — the per-lane drift signal."""
    result = ingest(FIXTURE, emit=False)
    report = match_lanes(result, _predicted(b1_sync=0.00001),
                         threshold=0.5, emit=False)
    assert report.stale_lanes == ["bucket:b1:sync"]


def test_fixture_unmatched_predicted_lane():
    pred = _predicted()
    pred["sync_buckets"].append(
        {"name": "b9", "lane": "bucket:b9:sync", "ops": ["z"],
         "start_s": 0.0008, "sync_s": 0.0001, "exposed_s": 0.0})
    report = match_lanes(ingest(FIXTURE, emit=False), pred, emit=False)
    assert not report.matched_all
    assert report.unmatched_predicted == ["bucket:b9:sync"]


def test_apply_lane_measurements_fills_drift_report():
    """The previously-None measured bucket fields of the DriftReport
    are populated from a matched capture."""
    pred = _predicted()
    drift = build_drift_report(pred, measured_step_s=0.0011)
    assert all(b["measured_s"] is None for b in drift.sync_buckets)
    report = match_lanes(ingest(FIXTURE, emit=False), pred, emit=False)
    filled = apply_lane_measurements(drift, report)
    assert filled == 2
    by = {b["lane"]: b for b in drift.sync_buckets}
    assert by["bucket:b0:sync"]["measured_s"] == pytest.approx(270e-6)
    assert by["bucket:b0:sync"]["measured_issue_s"] == \
        pytest.approx(180e-6)
    assert by["bucket:b0:sync"]["measured_source"] == "host_trace"


def test_ingest_emits_schema_valid_events(tmp_path):
    log = str(tmp_path / "log.jsonl")
    BUS.configure(log)
    build_lane_drift_report(FIXTURE, _predicted(), threshold=0.5)
    BUS.close()
    events = [json.loads(x) for x in open(log)]
    kinds = [e["kind"] for e in events]
    assert "trace.ingest" in kinds
    assert kinds.count("trace.lane_match") == 2
    for e in events:
        assert validate_event(e) == [], e


# ---------------------------------------------------------------------------
# satellite: seeded reservoir histogram
def test_histogram_reservoir_tracks_whole_stream():
    """The old first-N sampling froze percentiles on the first 4096
    observations — a long-running server reported its warm-up p99
    forever.  The reservoir keeps tracking: a stream whose second half
    is 10x slower must raise the reported p99 accordingly."""
    frozen_like = Histogram("t", max_samples=512)
    for _ in range(2000):
        frozen_like.observe(1.0)
    for _ in range(2000):
        frozen_like.observe(10.0)
    s = frozen_like.summary()
    # exact aggregates never sampled
    assert s["count"] == 4000
    assert s["sum"] == pytest.approx(2000 * 1.0 + 2000 * 10.0)
    assert s["min"] == 1.0 and s["max"] == 10.0
    # ~half the reservoir is late observations: p95/p99 must see them
    assert s["p99"] == 10.0
    assert s["p50"] in (1.0, 10.0)


def test_histogram_reservoir_deterministic():
    """Same metric name + same stream => identical reservoir (the
    seed derives from the name), including across reset()."""
    rng = np.random.default_rng(3)
    stream = rng.normal(10.0, 2.0, size=5000).tolist()
    a, b = Histogram("x", max_samples=256), Histogram("x", max_samples=256)
    for v in stream:
        a.observe(v)
        b.observe(v)
    assert a.summary() == b.summary()
    reg = MetricsRegistry()
    h = reg.histogram("x")
    h.max_samples = 256
    for v in stream:
        h.observe(v)
    first = h.summary()
    reg.reset()
    for v in stream:
        h.observe(v)
    assert h.summary() == first


# ---------------------------------------------------------------------------
# satellite: Prometheus exposition
def test_render_prometheus_families():
    reg = MetricsRegistry()
    reg.counter("fit.steps").inc(7)
    reg.gauge("fit.drift_ratio").set(1.25)
    h = reg.histogram("decode.ttft_s")
    for v in (0.01, 0.02, 0.03):
        h.observe(v)
    text = render_prometheus(reg.snapshot())
    assert "# TYPE flexflow_tpu_fit_steps counter" in text
    assert "flexflow_tpu_fit_steps 7" in text
    assert "# TYPE flexflow_tpu_fit_drift_ratio gauge" in text
    assert "flexflow_tpu_fit_drift_ratio 1.25" in text
    assert "# TYPE flexflow_tpu_decode_ttft_s summary" in text
    assert 'flexflow_tpu_decode_ttft_s{quantile="0.99"}' in text
    assert "flexflow_tpu_decode_ttft_s_count 3" in text
    assert "flexflow_tpu_decode_ttft_s_sum" in text


def test_metrics_http_endpoint():
    """The stdlib endpoint serves the live registry at /metrics; an
    ephemeral port keeps the test hermetic."""
    import urllib.request

    from flexflow_tpu.obs.exposition import MetricsServer

    reg = MetricsRegistry()
    reg.counter("serve.requests").inc(3)
    srv = MetricsServer(0, registry=reg)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read()
        assert b"flexflow_tpu_serve_requests 3" in body
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=5)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# satellite: per-request decode telemetry (+ the one-check contract is
# in tests/test_obs.py next to the bus-overhead test)
def _synthetic_step(vocab=97):
    def step(ids, table, lens):
        ids = np.asarray(ids)
        lens = np.asarray(lens)
        nxt = (ids[:, 0] * 7 + lens * 13 + 5) % vocab
        logits = np.zeros((ids.shape[0], 1, vocab), np.float32)
        logits[np.arange(ids.shape[0]), 0, nxt] = 1.0
        return logits

    return step


def test_decode_request_lifecycle_telemetry(tmp_path):
    from flexflow_tpu.obs.metrics import METRICS
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
    )

    log = str(tmp_path / "log.jsonl")
    BUS.configure(log)
    base = METRICS.histogram("decode.ttft_s").count
    ex = ContinuousBatchingExecutor(
        _synthetic_step(), max_seqs=2, page_size=4, pages_per_seq=4,
        predicted_step_s=1e-4)
    reqs = [DecodeRequest(rid=f"r{i}", prompt=[3 + i, 11],
                          max_new_tokens=3) for i in range(4)]
    out = ex.run(reqs, max_frames=200)
    assert len(out) == 4
    # one lifecycle record per completed request
    assert len(ex.request_records) == 4
    for rec in ex.request_records:
        assert rec["tokens"] == 3
        assert rec["e2e_s"] > 0 and rec["ttft_s"] > 0
        assert rec["queue_s"] >= 0
        assert rec["tpot_s"] is not None  # 3 tokens => steady TPOT
        assert rec["ttft_s"] <= rec["e2e_s"]
    # the last two requests queued behind the first two: their queue
    # wait includes real frames
    s = ex.summary()
    assert s["requests_recorded"] == 4
    assert s["ttft_p99_s"] >= s["ttft_p50_s"] > 0
    assert s["tpot_p99_s"] > 0 and s["e2e_p99_s"] > 0
    # TTFT/TPOT histograms in the metrics registry grew
    assert METRICS.histogram("decode.ttft_s").count == base + 4
    # the continuous p99 drift signal
    assert ex.measured_p99() > 0
    assert ex.measured_p99(window=2) > 0
    rep = ex.decode_drift_report(window=3)
    assert rep is not None and rep.phases["decode"]["ratio"] == rep.ratio
    BUS.close()
    events = [json.loads(x) for x in open(log)]
    reqs_ev = [e for e in events if e["kind"] == "decode.request"]
    assert len(reqs_ev) == 4
    for e in events:
        assert validate_event(e) == [], e


# ---------------------------------------------------------------------------
# tier-1 smoke: the full measured-lane pipeline on the 8-dev CPU mesh
def test_lane_capture_smoke_e2e(tmp_path, mesh8):
    """fit with device_trace_dir: a REAL capture on the CPU mesh
    round-trips into a LaneDriftReport with every annotated sync
    bucket tag-matched, the DriftReport's measured bucket fields
    populated; a decode serve with obs on rides the same log; ffobs
    report renders lane + request sections, validate exits 0, and
    metrics renders the Prometheus exposition offline."""
    from flexflow_tpu.models import build_transformer
    from flexflow_tpu.obs.metrics import METRICS
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
    )

    log = str(tmp_path / "obs.jsonl")
    tdir = str(tmp_path / "device_trace")
    BUS.close()
    BUS.configure(log)
    cfg = ff.FFConfig(batch_size=8, num_devices=8, epochs=2,
                      only_data_parallel=True, compute_dtype="float32",
                      sync_schedule="search", profiling=True,
                      obs_log_file=log, device_trace_dir=tdir)
    m = build_transformer(cfg, num_layers=1, hidden=512, num_heads=4,
                          ff_dim=2048, seq_len=8)
    m.compile(loss_type="mean_squared_error", metrics=[])
    assert m.sync_schedule is not None and m.sync_schedule.buckets
    rng = np.random.default_rng(0)
    x = rng.normal(size=(24, 8, 512)).astype(np.float32)
    y = rng.normal(size=(24, 8, 512)).astype(np.float32)
    m.fit(x=x, y=y, verbose=False, shuffle=False)

    report = m.lane_drift_report
    assert report is not None, "capture did not ingest"
    # every annotated sync bucket tag-matched — no fuzzy-name matching
    assert report.matched_all, report.to_dict()
    assert len(report.lanes) == len(m.sync_schedule.buckets)
    assert report.steps >= 2
    for lane in report.lanes:
        assert lane["samples"] >= 1
        assert lane["measured_issue_s"] > 0
        assert lane["measured_sync_s"] > 0
    # the previously-None measured bucket fields are populated
    assert m.drift_report is not None
    for b in m.drift_report.sync_buckets:
        assert b["measured_s"] is not None
        assert b["measured_source"] == "host_trace"

    # decode serve with obs on, feeding the same log + registry
    ex = ContinuousBatchingExecutor(
        _synthetic_step(), max_seqs=2, page_size=4, pages_per_seq=4,
        predicted_step_s=1e-4)
    ex.run([DecodeRequest(rid=f"q{i}", prompt=[2 + i, 5],
                          max_new_tokens=2) for i in range(3)],
           max_frames=100)
    ex.decode_drift_report()
    METRICS.emit_snapshot()
    BUS.close()

    # every line schema-valid, the new kinds present
    kinds = set()
    with open(log) as f:
        for line in f:
            obj = json.loads(line)
            assert validate_event(obj) == [], (validate_event(obj), line)
            kinds.add(obj["kind"])
    assert {"trace.ingest", "trace.lane_match", "decode.request",
            "metrics.snapshot"} <= kinds

    ffobs = os.path.join(REPO, "tools", "ffobs.py")
    rep = subprocess.run([sys.executable, ffobs, "report", log],
                        capture_output=True, text=True)
    assert rep.returncode == 0, rep.stderr
    assert "Measured lanes (device-trace capture)" in rep.stdout
    assert "bucket:b0:sync" in rep.stdout
    assert "Per-request telemetry" in rep.stdout
    val = subprocess.run([sys.executable, ffobs, "validate", log],
                        capture_output=True, text=True)
    assert val.returncode == 0, val.stdout + val.stderr
    met = subprocess.run([sys.executable, ffobs, "metrics", log],
                        capture_output=True, text=True)
    assert met.returncode == 0, met.stdout + met.stderr
    assert "flexflow_tpu_decode_ttft_s_count" in met.stdout
    assert "# TYPE" in met.stdout
