"""Tests for the cost model, simulator, DP search, substitutions, MCMC —
role of the reference's search unit tests (tests/unit/test_dominators.cc
etc.) plus strategy-quality checks the reference does via osdi22ae."""

import math

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.compiler.lowering import data_parallel_strategy
from flexflow_tpu.core.machine import MachineSpec, MachineView
from flexflow_tpu.search.dp import SearchHelper
from flexflow_tpu.search.driver import mcmc_optimize, optimize_strategy
from flexflow_tpu.search.simulator import Simulator
from flexflow_tpu.search.substitution import generate_all_pcg_xfers
from flexflow_tpu.search.views import candidate_views


def mlp_model(batch=64, in_dim=128, hidden=256, classes=16):
    cfg = ff.FFConfig(batch_size=batch, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([batch, in_dim])
    t = m.dense(x, hidden, activation="relu", name="fc1")
    t = m.dense(t, hidden, activation="relu", name="fc2")
    t = m.dense(t, classes, name="head")
    return m


def big_weight_model(batch=8, dim=2048):
    """Tiny batch, huge weights: data parallelism must lose to TP
    (grad allreduce dominates) — the Unity headline scenario."""
    cfg = ff.FFConfig(batch_size=batch, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([batch, dim])
    t = m.dense(x, dim, activation="relu", name="fc1")
    t = m.dense(t, dim, activation="relu", name="fc2")
    t = m.dense(t, 16, name="head")
    return m


def test_candidate_views_divisibility():
    m = mlp_model()
    node = m.node_by_name("fc1")
    views = candidate_views(node.op, 8)
    assert MachineView.trivial(2) in views
    assert MachineView.data_parallel(2, 8) in views
    assert any(v.dim_degrees[1] > 1 for v in views)  # TP column split
    assert any(v.replica_degree > 1 for v in views)  # row-parallel
    for v in views:
        assert 8 % v.num_parts == 0


def conv_model(batch=256):
    """Conv net: heavy per-sample compute, small weights — the regime
    where data parallelism wins (grad sync hides under backward)."""
    cfg = ff.FFConfig(batch_size=batch, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([batch, 32, 32, 64])
    t = m.conv2d(x, 64, 3, 3, 1, 1, 1, 1, activation="relu", name="c1")
    t = m.conv2d(t, 64, 3, 3, 1, 1, 1, 1, activation="relu", name="c2")
    t = m.flat(t)
    t = m.dense(t, 16, name="head")
    return m


def test_simulator_prefers_parallel():
    m = conv_model()
    sim = Simulator(MachineSpec.tpu_v5e(8), num_devices=8)
    trivial = {n.guid: MachineView.trivial(n.op.output_shapes[0].ndim)
               for n in m.graph.topo_order()}
    dp = data_parallel_strategy(m.graph, 8)
    c_triv = sim.simulate(m.graph, trivial)
    c_dp = sim.simulate(m.graph, dp)
    assert 0 < c_dp < c_triv


def test_simulator_invalid_strategy_is_inf():
    m = mlp_model()
    sim = Simulator(MachineSpec.tpu_v5e(8), num_devices=8)
    bad = data_parallel_strategy(m.graph, 8)
    # concat-free model: break a Linear by replicating beyond max heads etc.
    # use an inconsistent replicate view on a parallel op instead:
    cfg = ff.FFConfig(num_devices=8)
    m2 = ff.FFModel(cfg)
    x = m2.create_tensor([16, 8])
    t = m2.replicate(x, degree=4, name="rep")
    m2.dense(t, 8, name="fc")
    s = {n.guid: MachineView.trivial(n.op.output_shapes[0].ndim)
         for n in m2.graph.topo_order()}  # violates rep's fixed degree
    assert sim.simulate(m2.graph, s) == math.inf


def test_dp_search_beats_or_matches_dp():
    m = mlp_model()
    sim = Simulator(MachineSpec.tpu_v5e(8), num_devices=8)
    helper = SearchHelper(sim, 8)
    cost, strategy = helper.graph_cost(m.graph)
    dp_cost = sim.simulate(m.graph, data_parallel_strategy(m.graph, 8))
    assert cost <= dp_cost * 1.001
    assert len(strategy) == m.graph.num_nodes
    assert len(helper.memo) > 0


def test_search_finds_tp_for_big_weights():
    m = big_weight_model()
    sim = Simulator(MachineSpec.tpu_v5e(8), num_devices=8)
    helper = SearchHelper(sim, 8)
    cost, strategy = helper.graph_cost(m.graph)
    dp_cost = sim.simulate(m.graph, data_parallel_strategy(m.graph, 8))
    assert cost < dp_cost, (cost, dp_cost)
    # the searched strategy should shard at least one big weight
    fc_views = [strategy[m.node_by_name(n).guid] for n in ("fc1", "fc2")]
    assert any(v.dim_degrees[1] > 1 or v.replica_degree > 1 for v in fc_views)


def test_optimize_strategy_end_to_end_training():
    cfg = ff.FFConfig(batch_size=32, epochs=2, num_devices=8,
                      only_data_parallel=False, compute_dtype="float32",
                      search_budget=4)
    m = ff.FFModel(cfg)
    x = m.create_tensor([32, 16])
    t = m.dense(x, 64, activation="relu")
    t = m.dense(t, 4)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=["accuracy"])
    rng = np.random.default_rng(0)
    y = rng.integers(0, 4, 128).astype(np.int32)
    xd = (rng.normal(size=(4, 16))[y] * 3 + rng.normal(size=(128, 16))).astype(np.float32)
    hist = m.fit(x=xd, y=y, verbose=False)
    assert hist[-1]["accuracy"] > 0.5


def test_mcmc_optimize_runs():
    m = mlp_model()
    cfg = m.config
    s = mcmc_optimize(m.graph, cfg, iterations=50, seed=1)
    sim = Simulator(cfg.machine_spec, num_devices=8)
    assert sim.simulate(m.graph, s) < math.inf


def test_substitutions_apply_and_cancel():
    m = mlp_model()
    xfers = generate_all_pcg_xfers(8)
    part = next(x for x in xfers if x.name.startswith("partition_linear_combine_d2"))
    matches = part.find_matches(m.graph)
    assert matches
    g2 = part.apply(m.graph, matches[0])
    assert g2 is not None
    assert g2.num_nodes == m.graph.num_nodes + 2
    g2.topo_order()  # still a DAG
    cancel = next(x for x in xfers if x.name == "cancel_repartition_combine")
    # cancel only fires when combine directly follows repartition
    m3 = ff.FFModel(ff.FFConfig(num_devices=8))
    x3 = m3.create_tensor([16, 8])
    t3 = m3.repartition(x3, dim=0, degree=4)
    t3 = m3.combine(t3, dim=0, degree=1)
    m3.dense(t3, 8)
    c_matches = cancel.find_matches(m3.graph)
    assert len(c_matches) == 1
    g3 = cancel.apply(m3.graph, c_matches[0])
    assert g3.num_nodes == m3.graph.num_nodes - 2
    g3.topo_order()


def test_strategy_export_import_roundtrip(tmp_path):
    from flexflow_tpu.search.strategy_io import export_strategy, import_strategy

    m = mlp_model()
    dp = data_parallel_strategy(m.graph, 8)
    p = str(tmp_path / "strategy.json")
    export_strategy(p, m.graph, dp)
    back = import_strategy(p, m.graph)
    assert back == dp


def test_linear_activation_fusion_xfer():
    """reference: the generated linear_relu fusion xfer
    (substitution.cc:1619-1758)."""
    import flexflow_tpu as ff
    from flexflow_tpu.core.optype import OperatorType
    from flexflow_tpu.search.substitution import make_linear_activation_fusion_xfer

    cfg = ff.FFConfig(batch_size=8, num_devices=8, compute_dtype="float32")
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16])
    t = m.dense(x, 32, name="fc")
    t = m.relu(t)
    t = m.dense(t, 4, name="out")

    xf = make_linear_activation_fusion_xfer()
    matches = xf.find_matches(m.graph)
    assert len(matches) == 1 and matches[0].op.name == "fc"
    g2 = xf.apply(m.graph, matches[0])
    assert g2.num_nodes == m.graph.num_nodes - 1
    fused = [n for n in g2.topo_order()
             if n.op.op_type is OperatorType.LINEAR
             and n.op.attrs.get("activation") == "relu"]
    assert len(fused) == 1
    # rewritten graph still topologically valid and costable
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.simulator import Simulator
    from flexflow_tpu.compiler.lowering import data_parallel_strategy

    sim = Simulator(MachineSpec.tpu_v5e(8))
    c = sim.simulate(g2, data_parallel_strategy(g2, 8))
    assert c > 0 and c != float("inf")
