"""Test configuration: force an 8-virtual-device CPU platform.

The reference tests multi-GPU behaviour with real GPUs
(tests/multi_gpu_tests.sh); we instead exercise the identical SPMD code
paths on a virtual CPU mesh — XLA compiles the same collectives, so
sharding correctness transfers to real TPU slices.

NOTE: in this environment jax is pre-imported at interpreter startup
with the axon/TPU platform selected, so env vars are too late — we
override via jax.config before any backend is initialized.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from flexflow_tpu.parallel.mesh import build_mesh

    return build_mesh(jax.devices()[:8])
