"""Test configuration: force an 8-virtual-device CPU platform.

The reference tests multi-GPU behaviour with real GPUs
(tests/multi_gpu_tests.sh); we instead exercise the identical SPMD code
paths on a virtual CPU mesh — XLA compiles the same collectives, so
sharding correctness transfers to real TPU slices.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from flexflow_tpu.parallel.mesh import build_mesh

    return build_mesh(jax.devices()[:8])
