"""Chunked prefill + prefill/decode disaggregation + SLO classes
(ISSUE 15 — the serving tier's prompt path off the decode loop).

Contract highlights:

* the chunked prefill lane (runtime/prefill.py) is TOKEN-IDENTICAL to
  the prefill-via-decode oracle across ragged prompt lengths,
  including single-token prompts and exact chunk boundaries;
* TTFT decomposes exactly into queue + prefill + first-decode-frame
  spans (the attribution the ffobs report renders);
* SLO classes: priority admission order, deadline expiry instead of
  late service, preemption by strictly-higher priority — all
  deterministic under a seeded arrival trace;
* the disaggregation search prices colocated vs two-block placement in
  the phase-split serve currency, adopts only past the margin
  (honest zero on the small config), is lint-gated (SHD164/165),
  persists as __meta__.disaggregation behind the digest gate, and
  re-lints on import (corrupt artifacts fail with findings);
* fflint STR211 catches file-level corruption of the persisted
  disaggregation/SLO meta stdlib-only.
"""

import json

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.runtime.decode import (
    ContinuousBatchingExecutor,
    DecodeRequest,
    SLOClass,
    compiled_decode_step,
)

N_DEV = 8

# the short-prompt interactive regime where disaggregation genuinely
# wins on the stock machine model (bench_search.py GPT_DECODE_CHAT_KW)
CHAT_KW = dict(vocab=4096, num_layers=2, hidden=2048, num_heads=16,
               ff_dim=4096, page_size=16, pages_per_seq=32)
CHAT_ARRIVAL = dict(serve_prompt_tokens_mean=128,
                    serve_decode_tokens_mean=32)

SMALL_KW = dict(vocab=256, num_layers=2, hidden=64, num_heads=4,
                ff_dim=64, page_size=4, pages_per_seq=8)


def _trivial_strategy(graph):
    return {
        n.guid: (n.op.fixed_machine_view()
                 or MachineView.trivial(n.op.output_shapes[0].ndim))
        for n in graph.topo_order()
    }


def _compiled_small(num_devices=1, batch=4, **overrides):
    from flexflow_tpu.models import build_gpt_decode

    kw = dict(SMALL_KW)
    kw.update(overrides)
    cfg = ff.FFConfig(batch_size=batch, num_devices=num_devices,
                      cost_cache_file="")
    m = build_gpt_decode(cfg, **kw)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              comp_mode="inference",
              strategy=_trivial_strategy(m.graph))
    return m


@pytest.fixture(scope="module")
def small_model():
    """One compiled small decode model shared by the executor-level
    tests: each ``compiled_decode_step`` call snapshots ``model.state``
    into its own box, so every lane starts from the same fresh caches
    without recompiling the model."""
    return _compiled_small()


# ---------------------------------------------------------------------------
# chunked prefill: token identity with the prefill-via-decode oracle
# ---------------------------------------------------------------------------
def _serve(model, chunk, prompts, max_new=4, slots=4):
    step = compiled_decode_step(model, prefill_chunk=chunk)
    ex = ContinuousBatchingExecutor(
        step, max_seqs=slots, page_size=SMALL_KW["page_size"],
        pages_per_seq=SMALL_KW["pages_per_seq"],
        prefill_fn=getattr(step, "prefill", None), prefill_chunk=chunk)
    reqs = [DecodeRequest(rid=f"r{i}", prompt=list(p),
                          max_new_tokens=max_new)
            for i, p in enumerate(prompts)]
    out = ex.run(reqs, max_frames=600)
    return out, ex


def test_chunked_prefill_token_identity_ragged(small_model):
    """THE acceptance contract: the chunked lane's generated tokens
    equal the token-by-token oracle's for ragged prompt lengths
    including single-token (nothing to prefill), chunk-boundary
    (len-1 a multiple of the chunk), and cross-chunk prompts."""
    rng = np.random.default_rng(3)
    chunk = 8
    # 1 = single-token; 9 = exactly one full chunk of prefill (8 = L-1);
    # 17 = two full chunks; 5/12/23 = ragged tails
    lengths = (1, 2, 5, 9, 12, 17, 23)
    prompts = [list(map(int, rng.integers(1, 255, size=L)))
               for L in lengths]
    out_oracle, ex0 = _serve(small_model, 0, prompts)
    out_chunk, ex1 = _serve(small_model, chunk, prompts)
    assert out_oracle == out_chunk
    # the lane genuinely ran and genuinely saved frames
    assert ex1.prefill_tokens == sum(L - 1 for L in lengths)
    assert ex1.prefill_chunks == sum(
        -(-(L - 1) // chunk) for L in lengths if L > 1)
    assert ex1.frame < ex0.frame


@pytest.mark.slow
def test_chunked_prefill_on_searched_multidevice_strategy():
    """The lane composes with a SEARCHED sharded strategy on the host
    mesh: the chunk writer updates the placed KV state (the
    state_shardings discipline), still token-identical."""
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.models import build_gpt_decode

    kw = dict(vocab=256, num_layers=1, hidden=64, num_heads=4,
              ff_dim=64, page_size=4, pages_per_seq=4)

    def build():
        cfg = ff.FFConfig(batch_size=8, num_devices=N_DEV,
                          search_budget=4, search_timeout_s=20.0,
                          cost_cache_file="",
                          machine_spec=MachineSpec.host_cpu(N_DEV))
        m = build_gpt_decode(cfg, **kw)
        m.compile(loss_type="sparse_categorical_crossentropy",
                  metrics=[], comp_mode="inference")
        return m

    prompts = [[5, 6, 7, 8, 9, 10], [3], [11, 12, 13]]

    def run(chunk):
        m = build()
        step = compiled_decode_step(m, prefill_chunk=chunk)
        ex = ContinuousBatchingExecutor(
            step, max_seqs=8, page_size=4, pages_per_seq=4,
            prefill_fn=getattr(step, "prefill", None),
            prefill_chunk=chunk)
        return ex.run([DecodeRequest(rid=f"r{i}", prompt=list(p),
                                     max_new_tokens=4)
                       for i, p in enumerate(prompts)], max_frames=200)

    assert run(0) == run(4)


def test_chunk_forward_rejects_non_decode_graph():
    from flexflow_tpu.models import build_mlp_unify
    from flexflow_tpu.runtime.prefill import build_chunk_forward

    cfg = ff.FFConfig(batch_size=4, num_devices=1, cost_cache_file="")
    m = build_mlp_unify(cfg, in_dim=16, hidden=(16,))
    with pytest.raises(ValueError, match="no DecodeAttentionOp"):
        build_chunk_forward(m.graph, np.float32)


def test_prefill_weight_bridge():
    """The weight-correspondence bridge: build_gpt_prefill and
    build_gpt_decode share one parameter set name-for-name (the
    positional table as a prefix); a vocab mismatch is a hard error."""
    from flexflow_tpu.models import (
        build_gpt_decode,
        build_gpt_prefill,
        derive_prefill_model,
    )
    from flexflow_tpu.runtime.prefill import prefill_weight_bridge

    cfg = ff.FFConfig(batch_size=4, num_devices=1, cost_cache_file="")
    dec = build_gpt_decode(cfg, **SMALL_KW)
    pre, _ = derive_prefill_model(dec.graph, cfg, seq_len=16)
    bridge = prefill_weight_bridge(pre.graph, dec.graph)
    # every prefill weight maps to a same-named decode weight
    assert all(k.split("/")[0] == v.split("/")[0]
               for k, v in bridge.items())
    assert "lm_head/kernel" in bridge and "tok_embed/table" in bridge
    # positional prefix rule: prefill pos table (16 rows) maps onto the
    # decode table (page_size * pages_per_seq = 32 rows)
    assert "pos_embed/table" in bridge
    # vocab mismatch must NOT ride the prefix rule
    wrong = build_gpt_prefill(
        cfg, **{**{k: v for k, v in SMALL_KW.items()
                   if k not in ("page_size", "pages_per_seq")},
                "vocab": 128}, seq_len=16)
    with pytest.raises(ValueError, match="shape mismatch"):
        prefill_weight_bridge(wrong.graph, dec.graph)


# ---------------------------------------------------------------------------
# TTFT split telemetry
# ---------------------------------------------------------------------------
def test_ttft_splits_into_queue_prefill_first_frame(tmp_path,
                                                    small_model):
    from flexflow_tpu.obs.events import BUS, validate_event

    log = str(tmp_path / "obs.jsonl")
    BUS.configure(log)
    try:
        out, ex = _serve(small_model, 4, [[1, 2, 3, 4, 5, 6, 7], [9]])
        s = ex.summary()
        assert s["requests_recorded"] == 2
        for r in ex.request_records:
            assert r["phase"] == "finish"
            # the split sums to TTFT exactly (same stamps, no gaps)
            assert r["ttft_s"] == pytest.approx(
                r["queue_s"] + r["prefill_s"] + r["first_frame_s"],
                rel=1e-6, abs=1e-9)
        assert s["prefill_p50_s"] is not None
        assert s["first_frame_p99_s"] is not None
        BUS.flush()
        with open(log) as f:
            events = [json.loads(line) for line in f]
        for e in events:
            assert validate_event(e) == []
        kinds = {e["kind"] for e in events}
        assert "decode.prefill" in kinds  # the lane emitted its event
    finally:
        BUS.close()


# ---------------------------------------------------------------------------
# SLO classes: priority admission, deadline expiry, preemption
# ---------------------------------------------------------------------------
def _synthetic_step(vocab=97):
    def step(ids, table, lens):
        ids = np.asarray(ids)
        lens = np.asarray(lens)
        nxt = (ids[:, 0] * 7 + lens * 13 + 5) % vocab
        logits = np.zeros((ids.shape[0], 1, vocab), np.float32)
        logits[np.arange(ids.shape[0]), 0, nxt] = 1.0
        return logits

    return step


SLO_TABLE = (
    SLOClass("interactive", priority=2, deadline_frames=0),
    SLOClass("standard", priority=1, deadline_frames=0),
    SLOClass("batch", priority=0, deadline_frames=0),
)


def test_priority_admission_order():
    """With one open slot and a full queue, the higher-priority class
    admits first regardless of submission order."""
    ex = ContinuousBatchingExecutor(
        _synthetic_step(), max_seqs=1, page_size=4, pages_per_seq=4,
        slo_classes=SLO_TABLE)
    ex.submit([DecodeRequest(rid="batch", prompt=[1], max_new_tokens=2,
                             slo="batch"),
               DecodeRequest(rid="inter", prompt=[2], max_new_tokens=2,
                             slo="interactive")])
    ex.step()
    live = [s for s in ex.slots if s is not None]
    assert live and live[0].req.rid == "inter"
    ex.run(max_frames=50)
    assert set(ex.finished) == {"batch", "inter"}


def test_deadline_expiry_refuses_late_service():
    """A queued request whose deadline_frames passes is EXPIRED (never
    served late): recorded in .expired, absent from .finished."""
    ex = ContinuousBatchingExecutor(
        _synthetic_step(), max_seqs=1, page_size=4, pages_per_seq=4)
    ex.submit([DecodeRequest(rid="long", prompt=[1], max_new_tokens=10),
               DecodeRequest(rid="dead", prompt=[2], max_new_tokens=2,
                             deadline_frames=3)])
    out = ex.run(max_frames=100)
    assert "dead" not in out and "dead" in ex.expired
    assert ex.total_expired == 1
    assert len(out["long"]) == 10


def test_preemption_by_higher_priority_continues_stream():
    """A strictly-higher-priority arrival preempts the lowest-priority
    live sequence; the victim re-queues with its tokens so far and —
    regeneration being deterministic — finishes with EXACTLY the
    tokens of an unpreempted run."""
    solo = ContinuousBatchingExecutor(
        _synthetic_step(), max_seqs=1, page_size=4, pages_per_seq=4)
    expect = solo.run([DecodeRequest(rid="low", prompt=[3, 4],
                                     max_new_tokens=6)], max_frames=60)

    ex = ContinuousBatchingExecutor(
        _synthetic_step(), max_seqs=1, page_size=4, pages_per_seq=4,
        slo_classes=SLO_TABLE)
    ex.submit([DecodeRequest(rid="low", prompt=[3, 4], max_new_tokens=6,
                             slo="batch")])
    ex.step()  # low admitted and running
    assert ex.slots[0] is not None and ex.slots[0].req.rid == "low"
    ex.submit([DecodeRequest(rid="hi", prompt=[9], max_new_tokens=2,
                             slo="interactive")])
    out = ex.run(max_frames=100)
    assert ex.total_preempted == 1
    assert out["low"] == expect["low"]  # the stream survived preemption
    assert len(out["hi"]) == 2


def test_slo_scheduling_deterministic_under_seeded_trace():
    """The acceptance determinism gate: a seeded ragged arrival trace
    with mixed classes, deadlines, and pool pressure produces
    IDENTICAL admissions, expirations, preemptions and token streams
    across runs."""

    def run():
        rng = np.random.default_rng(11)
        ex = ContinuousBatchingExecutor(
            _synthetic_step(), max_seqs=2, page_size=4, pages_per_seq=4,
            num_pages=8, slo_classes=SLO_TABLE)
        outs = {}
        for wave in range(4):
            reqs = []
            for j in range(3):
                cls = ("interactive", "standard", "batch")[
                    int(rng.integers(0, 3))]
                L = int(rng.integers(1, 6))
                reqs.append(DecodeRequest(
                    rid=f"w{wave}r{j}",
                    prompt=list(map(int, rng.integers(1, 96, size=L))),
                    max_new_tokens=int(rng.integers(1, 5)),
                    slo=cls,
                    deadline_frames=(6 if cls == "interactive"
                                     else None)))
            ex.submit(reqs)
            for _ in range(3):
                ex.step()
        outs = ex.run(max_frames=300)
        return (outs, dict(ex.expired), ex.total_preempted,
                ex.total_expired, ex.total_admitted)

    assert run() == run()


def test_measured_request_p99_per_class(tmp_path):
    from flexflow_tpu.obs.events import BUS

    BUS.configure(str(tmp_path / "obs.jsonl"))
    try:
        ex = ContinuousBatchingExecutor(
            _synthetic_step(), max_seqs=2, page_size=4, pages_per_seq=4,
            slo_classes=SLO_TABLE)
        reqs = [DecodeRequest(rid=f"r{i}", prompt=[1 + i],
                              max_new_tokens=2,
                              slo=("interactive" if i % 2 else "batch"))
                for i in range(6)]
        ex.run(reqs, max_frames=60)
        s = ex.summary()
        assert set(s["slo_classes"]) == {"interactive", "batch"}
        for name in ("interactive", "batch"):
            v = ex.measured_request_p99("ttft_s", slo=name)
            assert v is not None and v > 0
        assert ex.measured_request_p99("ttft_s") is not None
    finally:
        BUS.close()


# ---------------------------------------------------------------------------
# disaggregation: search, lints, persistence, import
# ---------------------------------------------------------------------------
def _chat_cfg(**overrides):
    kw = dict(batch_size=32, num_devices=N_DEV, search_budget=8,
              search_timeout_s=60.0, objective="serve",
              comp_mode="inference", cost_cache_file="",
              **CHAT_ARRIVAL)
    kw.update(overrides)
    return ff.FFConfig(**kw)


@pytest.fixture(scope="module")
def chat_search():
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.search.driver import optimize_strategy

    cfg = _chat_cfg()
    m = build_gpt_decode(cfg, **CHAT_KW)
    g, s = optimize_strategy(m.graph, cfg, return_graph=True)
    return cfg, m.graph, g, s


def test_disaggregation_adopts_where_handoff_is_cheap(chat_search):
    """THE acceptance scenario (recorded in BENCH_SEARCH
    "Prefill/decode disaggregation"): on the short-prompt interactive
    config — the weight-streaming-bound prefill regime, where a
    prompt's KV handoff is cheap relative to the phase interference
    colocation pays — the search PICKS disaggregation."""
    from flexflow_tpu.search.disaggregation import propose_disaggregation

    cfg, base, g, s = chat_search
    prop = propose_disaggregation(
        g, s, cfg, base_graph=base if g is not base else None)
    assert prop is not None and prop.adopted
    assert prop.disagg_step_s < prop.colocated_step_s
    assert prop.handoff_s > 0
    assert prop.prefill_devices + prop.decode_devices <= N_DEV
    assert prop.prefill_strategy and prop.decode_strategy


def test_disaggregation_honest_zero_on_long_cache_config():
    """The long-cache serving-regime config keeps colocation (its
    decode phase wants every device and its handoff payload is fat):
    the proposal is still returned — both prices recorded — but NOT
    adopted.  The search does not manufacture divergence."""
    from flexflow_tpu.models import (
        GPT_DECODE_SERVE_KW,
        SERVE_FRAME_SLOTS,
        build_gpt_decode,
    )
    from flexflow_tpu.search.disaggregation import propose_disaggregation
    from flexflow_tpu.search.driver import optimize_strategy

    cfg = ff.FFConfig(batch_size=SERVE_FRAME_SLOTS, num_devices=N_DEV,
                      search_budget=4, search_timeout_s=45.0,
                      objective="serve", comp_mode="inference",
                      cost_cache_file="")
    m = build_gpt_decode(cfg, **GPT_DECODE_SERVE_KW)
    g, s = optimize_strategy(m.graph, cfg, return_graph=True)
    prop = propose_disaggregation(
        g, s, cfg, base_graph=m.graph if g is not m.graph else None)
    assert prop is not None and not prop.adopted
    assert prop.colocated_step_s < prop.disagg_step_s


def test_lint_disaggregation_codes(chat_search):
    from flexflow_tpu.analysis import errors_only, lint_disaggregation
    from flexflow_tpu.search.disaggregation import propose_disaggregation

    cfg, base, g, s = chat_search
    prop = propose_disaggregation(
        g, s, cfg, base_graph=base if g is not base else None)
    meta = prop.to_meta()
    graph = base  # un-rewritten: the import-path shape
    assert not errors_only(lint_disaggregation(graph, meta, cfg))
    # SHD164: overflowing blocks
    bad = dict(meta, prefill_devices=N_DEV)
    codes = [f.code for f in lint_disaggregation(graph, bad, cfg)]
    assert "SHD164" in codes
    # SHD164: zero-width block / bad chunk
    codes = [f.code for f in lint_disaggregation(
        graph, dict(meta, decode_devices=0), cfg)]
    assert "SHD164" in codes
    codes = [f.code for f in lint_disaggregation(
        graph, dict(meta, chunk=0), cfg)]
    assert "SHD164" in codes
    # SHD165: pool geometry disagreement across the handoff
    codes = [f.code for f in lint_disaggregation(
        graph, dict(meta, page_size=meta["page_size"] * 2), cfg)]
    assert "SHD165" in codes
    # SHD165: malformed SLO classes
    codes = [f.code for f in lint_disaggregation(
        graph, dict(meta, slo_classes=[{"name": "a", "quantile": 2.0}]),
        cfg)]
    assert "SHD165" in codes
    codes = [f.code for f in lint_disaggregation(
        graph, dict(meta, slo_classes=[{"name": "a"}, {"name": "a"}]),
        cfg)]
    assert "SHD165" in codes


@pytest.mark.slow
def test_disaggregation_meta_round_trip(tmp_path):
    """compile(serve_disaggregation=search) persists
    __meta__.disaggregation behind the digest gate; import re-lints it
    (SHD164/165) against the target graph; corrupt pool geometry fails
    the gate with findings."""
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.search.strategy_io import read_meta

    path = str(tmp_path / "disagg_strategy.json")
    # budget 0: a rewriting search keys its export to the rewritten
    # graph, which deliberately cannot re-import onto a fresh build
    # (STR201) — the round trip is the un-rewritten artifact's story.
    # Half-width chat geometry (still the adopting short-prompt
    # regime) keeps the three compiles in this test cheap.
    kw = dict(CHAT_KW, hidden=1024, num_heads=8, ff_dim=2048)
    cfg = _chat_cfg(serve_disaggregation="search",
                    serve_slo_classes="interactive:2:64,batch:0:0:0.9",
                    export_strategy_file=path, search_budget=0,
                    search_timeout_s=30.0)
    m = build_gpt_decode(cfg, **kw)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              comp_mode="inference")
    assert m.disaggregation is not None and m.disaggregation.adopted
    meta = read_meta(path)
    dm = meta.get("disaggregation")
    assert dm and dm["prefill_devices"] + dm["decode_devices"] <= N_DEV
    assert [c["name"] for c in dm["slo_classes"]] == ["interactive",
                                                      "batch"]
    # geometry agrees with the sibling serving block (STR211's rule)
    assert dm["page_size"] == meta["serving"]["page_size"]

    # clean re-import
    cfg2 = ff.FFConfig(batch_size=32, num_devices=N_DEV,
                       cost_cache_file="", import_strategy_file=path)
    m2 = build_gpt_decode(cfg2, **kw)
    m2.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
               comp_mode="inference")
    assert m2.strategy

    # corrupt geometry -> import gate fails with findings
    from flexflow_tpu.analysis import AnalysisError

    data = json.load(open(path))
    data["__meta__"]["disaggregation"]["pages_per_seq"] = 999
    bad_path = str(tmp_path / "bad.json")
    json.dump(data, open(bad_path, "w"))
    cfg3 = ff.FFConfig(batch_size=32, num_devices=N_DEV,
                       cost_cache_file="",
                       import_strategy_file=bad_path)
    m3 = build_gpt_decode(cfg3, **kw)
    with pytest.raises(AnalysisError):
        m3.compile(loss_type="sparse_categorical_crossentropy",
                   metrics=[], comp_mode="inference")


def test_str211_disagg_meta_lint(tmp_path):
    import sys

    sys.path.insert(0, "tools")
    try:
        from fflint import lint_strategy_file
    finally:
        sys.path.pop(0)

    good = {
        "graph_digest": "d" * 32,
        "serving": {"objective": "serve", "max_seqs": 32,
                    "page_size": 16, "pages_per_seq": 32,
                    "quantile": 0.99, "p99_budget_ms": 0.0},
        "disaggregation": {
            "num_devices": 8, "prefill_devices": 4,
            "decode_devices": 4, "chunk": 32, "prefill_seq_len": 128,
            "max_seqs": 32, "page_size": 16, "pages_per_seq": 32,
            "colocated_step_ms": 0.4, "disagg_step_ms": 0.35,
            "handoff_ms": 0.09, "prefill_tokens_per_frame": 128.0,
            "spans_dcn": False,
            "slo_classes": [{"name": "interactive", "priority": 2,
                             "deadline_frames": 64, "quantile": 0.99}],
        },
    }
    base = {"lm_head": {"dims": [8, 1, 1], "replica": 1, "start": 0}}

    def write(meta):
        p = tmp_path / "strategy.json"
        p.write_text(json.dumps({**base, "__meta__": meta}))
        return str(p)

    assert not [f for f in lint_strategy_file(write(good))
                if f[1] == "STR211"]
    dg = good["disaggregation"]
    corruptions = [
        ("not-an-object", {**good, "disaggregation": [1]}),
        ("zero block", {**good, "disaggregation": {
            **dg, "prefill_devices": 0}}),
        ("overflow", {**good, "disaggregation": {
            **dg, "decode_devices": 7}}),
        ("bool chunk", {**good, "disaggregation": {**dg, "chunk": True}}),
        ("geometry vs serving", {**good, "disaggregation": {
            **dg, "page_size": 64}}),
        ("nan price", {**good, "disaggregation": {
            **dg, "handoff_ms": float("nan")}}),
        ("dup slo", {**good, "disaggregation": {
            **dg, "slo_classes": [{"name": "a"}, {"name": "a"}]}}),
        ("bad quantile", {**good, "disaggregation": {
            **dg, "slo_classes": [{"name": "a", "quantile": 1.5}]}}),
        ("negative deadline", {**good, "disaggregation": {
            **dg, "slo_classes": [{"name": "a",
                                   "deadline_frames": -1}]}}),
    ]
    for label, meta in corruptions:
        found = [f for f in lint_strategy_file(write(meta))
                 if f[1] == "STR211" and f[0] == "error"]
        assert found, f"corruption {label!r} not caught by STR211"


def test_serving_spec_signature_unchanged_by_phase_fields():
    """Bit-identity guard: the phase-split arrival fields must NOT
    enter the cost-row signature — serve cost rows keyed before this
    PR must keep serving."""
    from flexflow_tpu.search.serving import ServingSpec

    a = ServingSpec(max_seqs=16, page_size=16, pages_per_seq=16)
    b = ServingSpec(max_seqs=16, page_size=16, pages_per_seq=16,
                    prompt_tokens_mean=128, decode_tokens_mean=32)
    assert a.signature() == b.signature()
    assert b.prefill_tokens_per_frame() == 16 * (128.0 / 32.0)
