"""Request-scoped tracing, the always-on flight recorder, and SLO
burn-rate signals (ISSUE 17 — the serving fleet's observability spine).

Contract highlights:

* every request served by a traced executor/fleet yields a WELL-FORMED
  span tree: one root, a ``route`` decision stamp, ``queue``/
  ``prefill``/``decode`` phase children that nest inside the root and
  sum to the measured e2e within tolerance; preemption re-opens the
  queue span so the tree narrates the re-queue;
* the tracer is off by default and one-boolean cheap on the decode hot
  path (the ``BUS.enabled`` read-count contract in test_obs.py already
  pins the bus side; here the tracer side must add NO bus reads);
* ``export_chrome_trace`` writes the ``ph:"X"``/``ph:"M"`` µs shape
  Perfetto loads — one thread row per trace, slices carrying
  span/parent ids;
* the flight recorder rides EVERY emit (armed bus or not) into a
  bounded ring; fault injections dump the ring plus the in-flight
  requests' open spans as a post-mortem JSONL;
* the multi-window burn-rate computer fires on persistent moderate SLO
  violations BEFORE (or while never) the raw p99-drift trigger, and a
  lone spike under a loose error budget stays quiet;
* ``TrainingController.observe_burn_rate`` arms a ``burn_rate``
  re-search trigger from a fleet's finished-request records.
"""

import json
import os
from types import SimpleNamespace

import numpy as np
import pytest

from flexflow_tpu.obs.events import BUS
from flexflow_tpu.obs.flight import FLIGHT, FlightRecorder
from flexflow_tpu.obs.slo import burn_rates, first_fire_indices
from flexflow_tpu.obs.tracing import (
    REQUEST_PHASES,
    TRACER,
    Tracer,
    forest_stats,
    span_forest,
)
from flexflow_tpu.runtime.decode import (
    ContinuousBatchingExecutor,
    DecodeRequest,
    SLOClass,
)
from flexflow_tpu.runtime.fleet import FleetExecutor

SLO_TABLE = (
    SLOClass("interactive", priority=2, deadline_frames=0),
    SLOClass("standard", priority=1, deadline_frames=0),
    SLOClass("batch", priority=0, deadline_frames=0, quantile=0.9),
)


@pytest.fixture(autouse=True)
def _obs_teardown():
    yield
    BUS.close()
    TRACER.reset()
    TRACER.enabled = False
    FLIGHT.reset()
    FLIGHT.dump_dir = None
    FLIGHT.enabled = True


def _synthetic_step(vocab=97):
    def step(ids, table, lens):
        ids = np.asarray(ids)
        lens = np.asarray(lens)
        nxt = (ids[:, 0] * 7 + lens * 13 + 5) % vocab
        logits = np.zeros((ids.shape[0], 1, vocab), np.float32)
        logits[np.arange(ids.shape[0]), 0, nxt] = 1.0
        return logits

    return step


def _mk_executor(**kw):
    args = dict(max_seqs=4, page_size=4, pages_per_seq=4,
                slo_classes=SLO_TABLE)
    args.update(kw)
    return ContinuousBatchingExecutor(_synthetic_step(), **args)


# ---------------------------------------------------------------------------
# span trees from the traced runtime
# ---------------------------------------------------------------------------
def test_fleet_request_span_trees_validate(tmp_path):
    """THE acceptance property: every request routed through a traced
    fleet yields a well-formed span tree — single root, route stamp
    with the replica tag, queue/prefill/decode children nesting inside
    the root, phase durations summing to the measured e2e."""
    BUS.configure(str(tmp_path / "obs.jsonl"))
    TRACER.reset()
    TRACER.enabled = True
    fl = FleetExecutor(
        [_mk_executor(replica_label=str(i)) for i in range(2)],
        {c.name: [0.5, 0.5] for c in SLO_TABLE},
        slo_classes=SLO_TABLE, seed=7)
    reqs = [DecodeRequest(rid=f"r{i}", prompt=[2 + i, 3 + i, 4 + i],
                          max_new_tokens=3 + i % 3,
                          slo=SLO_TABLE[i % 3].name)
            for i in range(8)]
    fl.run(reqs)
    recs = {r["rid"]: r for r in fl.request_records
            if r.get("phase") == "finish"}
    assert len(recs) == 8
    assert TRACER.open_spans() == []
    seen = 0
    for tid in TRACER.trace_ids():
        rid = tid.split("#", 1)[0]
        rec = recs[rid]
        assert TRACER.validate_trace(tid, e2e_s=rec["e2e_s"]) == []
        spans = TRACER.trace_spans(tid)
        root = [s for s in spans if s.parent_id is None]
        assert len(root) == 1 and root[0].name == "request"
        names = {s.name for s in spans}
        assert {"route", "queue", "prefill", "decode"} <= names
        route = next(s for s in spans if s.name == "route")
        assert route.attrs["replica"] == fl.assignments[rid]
        seen += 1
    assert seen == 8


def test_preemption_reopens_queue_span(tmp_path):
    """A preempted request's tree narrates the re-queue: queue →
    prefill → decode → queue (requeue) → prefill → decode, and still
    validates against the measured e2e."""
    BUS.configure(str(tmp_path / "obs.jsonl"))
    TRACER.reset()
    TRACER.enabled = True
    ex = _mk_executor(max_seqs=1)
    ex.submit([DecodeRequest(rid="victim", prompt=[2, 3],
                             max_new_tokens=8, slo="batch")])
    ex.step()  # admit + first frame
    ex.submit([DecodeRequest(rid="vip", prompt=[4, 5],
                             max_new_tokens=2, slo="interactive")])
    ex.run(max_frames=100)
    recs = {r["rid"]: r for r in ex.request_records
            if r.get("phase") == "finish"}
    vt = [t for t in TRACER.trace_ids() if t.startswith("victim#")][0]
    assert TRACER.validate_trace(vt, e2e_s=recs["victim"]["e2e_s"]) == []
    names = [s.name for s in TRACER.trace_spans(vt)]
    assert names.count("queue") == 2  # the requeue re-opened it
    requeues = [s for s in TRACER.trace_spans(vt)
                if s.name == "queue" and s.attrs.get("requeue")]
    assert len(requeues) == 1
    root = [s for s in TRACER.trace_spans(vt) if s.parent_id is None][0]
    assert root.attrs.get("preempted") == 1


def test_tracer_disabled_adds_nothing(tmp_path):
    """Default-off: an untraced run mints no spans and no rid maps —
    the runtime edits must be invisible when the flag is down."""
    BUS.configure(str(tmp_path / "obs.jsonl"))
    assert not TRACER.enabled
    _mk_executor().run([DecodeRequest(rid="r0", prompt=[2, 3],
                                      max_new_tokens=2)])
    assert TRACER.trace_ids() == []
    assert TRACER.open_spans() == []


def test_validate_trace_flags_defects():
    t = Tracer()
    t.enabled = True
    tid = t.request_root("r0")
    t.begin(tid, "queue", parent="request")
    # still-open spans are a defect
    assert any("still open" in p for p in t.validate_trace(tid))
    t.end(tid, "queue")
    t.finish_request("r0")
    # a wildly wrong measured e2e trips the phase-sum check
    assert any("phase spans" in p
               for p in t.validate_trace(tid, e2e_s=1e6))
    # orphan detection is the forest helpers' job (dump/log replay)
    forest = span_forest([
        {"kind": "trace.span", "trace_id": "x", "span_id": 1,
         "parent_id": None, "span": "request"},
        {"kind": "trace.span", "trace_id": "x", "span_id": 2,
         "parent_id": 99, "span": "queue"},
    ])
    total, _depth, orphans = forest_stats(forest)
    assert (total, orphans) == (2, 1)


def test_rid_reuse_mints_fresh_trace():
    t = Tracer()
    t.enabled = True
    a = t.request_root("r0")
    t.finish_request("r0")
    b = t.request_root("r0")
    assert a != b and t.trace_of("r0") == b


# ---------------------------------------------------------------------------
# chrome export
# ---------------------------------------------------------------------------
def test_chrome_trace_export_shape(tmp_path):
    t = Tracer()
    t.enabled = True
    tid = t.request_root("r0", slo="standard")
    t.annotate(tid, "route", parent="request", replica=1)
    t.begin(tid, "queue", parent="request")
    t.end(tid, "queue")
    t.finish_request("r0")
    eid = t.episode_root(trigger="burn_rate")
    t.begin(eid, "research", parent="controller.episode")  # left OPEN
    path = str(tmp_path / "trace.json")
    n = t.export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert len(slices) == n == 5
    # one process row + one thread row per trace, named by trace id
    assert {m["name"] for m in metas} == {"process_name", "thread_name"}
    threads = {m["args"]["name"] for m in metas
               if m["name"] == "thread_name"}
    assert threads == {tid, eid}
    for e in slices:
        assert e["ts"] >= 0 and e["dur"] > 0
        assert {"trace_id", "span_id", "parent_id", "open"} \
            <= set(e["args"])
    open_slices = [e for e in slices if e["args"]["open"]]
    assert {e["name"] for e in open_slices} \
        == {"controller.episode", "research"}


def test_span_bound_evicts_oldest():
    t = Tracer(max_spans=4)
    t.enabled = True
    for i in range(6):
        tid = t.request_root(f"r{i}")
        t.finish_request(f"r{i}")
    assert len(t.spans) == 4 and t.dropped == 2


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def test_flight_ring_records_disabled_bus_and_bounds(tmp_path):
    """The post-mortem point: the ring sees every emit even while the
    bus is OFF, stays bounded, and the dump carries the last-N events
    plus the open spans of the in-flight requests."""
    assert not BUS.enabled
    FLIGHT.reset()
    FLIGHT.configure(capacity=16)
    try:
        for i in range(50):
            BUS.emit("search.log", msg=f"m{i}")
        assert FLIGHT.recorded == 50 and len(FLIGHT.ring) == 16
        TRACER.reset()
        TRACER.enabled = True
        tid = TRACER.request_root("inflight", slo="standard")
        TRACER.begin(tid, "queue", parent="request")
        path = str(tmp_path / "dump.jsonl")
        assert FLIGHT.dump(path, reason="test") == path
        rows = [json.loads(ln) for ln in open(path)]
        meta = rows[0]
        assert meta["kind"] == "flight.meta" and meta["reason"] == "test"
        assert meta["events"] == 16 and meta["dropped"] == 34
        kinds = [r["kind"] for r in rows[1:]]
        assert kinds[:16] == ["search.log"] * 16
        opens = [r for r in rows if r["kind"] == "trace.open"]
        assert {r["span"] for r in opens} == {"request", "queue"}
        assert all(r["trace_id"] == tid for r in opens)
    finally:
        FLIGHT.configure(capacity=512)


def test_flight_disabled_is_a_true_noop(tmp_path):
    FLIGHT.reset()
    FLIGHT.enabled = False
    BUS.emit("search.log", msg="x")
    assert FLIGHT.recorded == 0
    assert FLIGHT.dump(str(tmp_path / "d.jsonl")) is None


def test_fault_injection_dumps_post_mortem(tmp_path):
    """Every fault injector writes the flight post-mortem when a dump
    dir is armed — the injected failure rehearses the unplanned one."""
    from flexflow_tpu.runtime.faults import FaultPlan

    FLIGHT.reset()
    FLIGHT.configure(dump_dir=str(tmp_path))
    BUS.emit("search.log", msg="before-fault")
    plan = FaultPlan.parse("p99_drift@0", seed=7)
    ratio = plan.inject_p99_drift(plan.due(0)[0])
    assert ratio > 1.5
    path = FLIGHT.last_dump_path
    assert path is not None and os.path.exists(path)
    rows = [json.loads(ln) for ln in open(path)]
    assert rows[0]["reason"] == "fault-p99_drift-step0"
    assert any(r.get("msg") == "before-fault" for r in rows)


def test_flight_dump_without_destination_is_none():
    rec = FlightRecorder(capacity=4)
    rec.record("x", {})
    assert rec.dump(reason="nowhere") is None  # opt-in by destination


# ---------------------------------------------------------------------------
# burn rate
# ---------------------------------------------------------------------------
def test_burn_fires_before_p99_drift():
    target = 0.1
    # persistent moderate violation: every completion at 1.3x target —
    # the budget torches while raw p99 sits under the 1.5x threshold
    burn_at, drift_at = first_fire_indices([0.13] * 48, target)
    assert burn_at == 8 and drift_at is None
    # load ramp: burn leads the raw p99 trigger by many completions
    ramp = [0.08 + i * (0.12 / 47.0) for i in range(48)]
    burn_at, drift_at = first_fire_indices(ramp, target)
    assert burn_at is not None and drift_at is not None
    assert burn_at < drift_at
    # a healthy stream fires neither
    assert first_fire_indices([0.05] * 48, target) == (None, None)


def test_burn_rate_spike_robust_under_loose_budget():
    lat = [0.05] * 20 + [0.4] + [0.05] * 20
    burn_at, _ = first_fire_indices(lat, 0.1, budget=0.1)
    assert burn_at is None  # one spike inside a 10% budget stays quiet


def test_burn_rates_per_class_map(tmp_path):
    BUS.configure(str(tmp_path / "obs.jsonl"))
    recs = ([{"phase": "finish", "slo": "standard", "ttft_s": 0.13}] * 12
            + [{"phase": "finish", "slo": "batch", "ttft_s": 0.05}] * 12)
    rates = burn_rates(recs, {"standard": 0.1, "batch": 0.1},
                       budgets={"standard": 0.01, "batch": 0.01})
    assert rates["standard"]["fired"] and not rates["batch"]["fired"]
    assert rates["standard"]["completions"] == 12


def test_controller_observe_burn_rate_arms_trigger(tmp_path):
    from flexflow_tpu.runtime.controller import TrainingController

    BUS.configure(str(tmp_path / "obs.jsonl"))
    model = SimpleNamespace(
        compiled=object(),
        fleet=SimpleNamespace(per_class_p99_s={"standard": 0.1}))
    ctl = TrainingController(model)
    source = SimpleNamespace(
        request_records=[{"phase": "finish", "slo": "standard",
                          "ttft_s": 0.13}] * 12,
        slo_classes={"standard": SLOClass("standard", priority=1,
                                          deadline_frames=0)})
    rates = ctl.observe_burn_rate(source)
    assert rates["standard"]["fired"]
    assert ctl._burn_trigger == "standard"
    BUS.flush()
    evs = [json.loads(ln)
           for ln in open(str(tmp_path / "obs.jsonl"))]
    burns = [e for e in evs if e["kind"] == "controller.burn_rate"]
    assert burns and burns[-1]["slo"] == "standard" \
        and burns[-1]["fired"]
    # no fleet proposal on the model -> honest None, no trigger
    ctl._burn_trigger = None
    ctl.model = SimpleNamespace(compiled=object())
    assert ctl.observe_burn_rate(source) is None
    assert ctl._burn_trigger is None


# ---------------------------------------------------------------------------
# ffobs trace rendering
# ---------------------------------------------------------------------------
def test_ffobs_trace_renders_and_flags_orphans(tmp_path):
    import subprocess
    import sys

    log = tmp_path / "trace.jsonl"
    rows = [
        {"ts": 1.0, "kind": "trace.span", "trace_id": "r0#1",
         "span": "request", "span_id": 1, "parent_id": None,
         "start_s": 0.0, "end_s": 1.0, "dur_s": 1.0},
        {"ts": 1.0, "kind": "trace.span", "trace_id": "r0#1",
         "span": "queue", "span_id": 2, "parent_id": 1,
         "start_s": 0.0, "end_s": 0.4, "dur_s": 0.4},
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in rows))
    ffobs = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "ffobs.py")
    proc = subprocess.run(
        [sys.executable, ffobs, "trace", str(log)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "trace r0#1" in proc.stdout and "queue" in proc.stdout
    assert "0 orphan span(s)" in proc.stdout
    # an orphan flips the exit code — validation failure, not cosmetics
    rows.append({"ts": 1.0, "kind": "trace.span", "trace_id": "r0#1",
                 "span": "ghost", "span_id": 3, "parent_id": 77,
                 "start_s": 0.0, "end_s": 0.1, "dur_s": 0.1})
    log.write_text("".join(json.dumps(r) + "\n" for r in rows))
    proc = subprocess.run(
        [sys.executable, ffobs, "trace", str(log)],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "ORPHAN" in proc.stdout
