"""Delta simulation + persistent cost cache.

The delta layer's contract is EXACT equivalence: a delta-served
``simulate`` returns the same float the full event-driven walk would
(reference: simulator.h SIMULATE_DELTA re-simulates only perturbed
tasks).  These tests drive randomized substitution sequences over zoo
graphs and assert bit equality, cap the number of full simulations a
canned search may run (counter-based — no wall-clock flakiness), and
exercise the cost cache's signature/staleness invalidation rules.
"""

import math
import random

import pytest

import flexflow_tpu as ff
from flexflow_tpu.compiler.lowering import data_parallel_strategy
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.search.calibration import CalibrationTable, find_clusters
from flexflow_tpu.search.cost_cache import (
    CostCache,
    cost_signature,
    mark_calibration_stale,
    resolve_cost_cache_path,
    stable_graph_digest,
)
from flexflow_tpu.search.driver import LAST_SEARCH_STATS, optimize_strategy
from flexflow_tpu.search.simulator import Simulator
from flexflow_tpu.search.substitution import generate_all_pcg_xfers
from flexflow_tpu.search.views import candidate_views


def _dlrm_graph(cfg=None):
    from flexflow_tpu.models import build_dlrm

    cfg = cfg or ff.FFConfig(batch_size=64, num_devices=8)
    return build_dlrm(
        cfg, embedding_sizes=(1000,) * 4, embedding_dim=16,
        bot_mlp=(32, 16), top_mlp=(32, 1)).graph


def _bert_graph(cfg=None):
    from flexflow_tpu.models import build_transformer

    cfg = cfg or ff.FFConfig(batch_size=8, num_devices=8)
    return build_transformer(
        cfg, num_layers=2, hidden=128, num_heads=4, ff_dim=256,
        seq_len=32).graph


def _cnn_graph(cfg=None):
    from flexflow_tpu.models import build_alexnet

    cfg = cfg or ff.FFConfig(batch_size=16, num_devices=8)
    return build_alexnet(cfg, num_classes=10, image=32).graph


def _fake_calibration(graph, n=8) -> CalibrationTable:
    """Deterministic synthetic measurements INCLUDING fusion-cluster
    records, so the delta path's chain-dirty logic is exercised the
    way a real TPU-probed table exercises it."""
    table = CalibrationTable()
    table.backend = "tpu"
    rng = random.Random(7)
    for node in graph.topo_order():
        for mv in candidate_views(node.op, n)[:4]:
            table.put(node.op, mv, 1e-4 * (1 + rng.random()))
    for producer, chain in find_clusters(graph):
        ops = [producer.op] + [c.op for c in chain]
        for mv in candidate_views(producer.op, n)[:4]:
            table.put_cluster(ops, mv, 5e-5 * (1 + rng.random()))
    return table


@pytest.mark.parametrize("builder", [_dlrm_graph, _bert_graph, _cnn_graph])
def test_delta_equals_full_across_random_substitutions(builder):
    """Property: for randomized substitution sequences, the delta-served
    cost is bit-identical (same float, not approximately) to the full
    simulation of the same (graph, strategy)."""
    graph = builder()
    n = 8
    sim = Simulator(ff.FFConfig(num_devices=n).machine_spec, num_devices=n,
                    calibration=_fake_calibration(graph, n))
    xfers = generate_all_pcg_xfers(n)
    rng = random.Random(0)

    parent = graph
    strat = dict(data_parallel_strategy(parent, n))
    checked = 0
    for step in range(40):
        assert sim.set_baseline(parent, strat) is not None
        # a few children per baseline, like one best-first pop
        children = []
        for _ in range(4):
            xf = rng.choice(xfers)
            matches = xf.find_matches(parent)
            if not matches:
                continue
            g2 = xf.apply(parent, rng.choice(matches))
            if g2 is None:
                continue
            # estimate-style strategy: carried views + defaults
            s2 = {}
            for guid, node in g2.nodes.items():
                v = strat.get(guid)
                if v is None:
                    v = node.op.fixed_machine_view() or MachineView.trivial(
                        node.op.output_shapes[0].ndim)
                s2[guid] = v
            delta_cost = sim.simulate(g2, s2)
            full_cost = sim._simulate_full(g2, s2, True)
            assert delta_cost == full_cost or (
                math.isnan(delta_cost) and math.isnan(full_cost)), (
                f"step {step}: delta {delta_cost!r} != full {full_cost!r} "
                f"after {xf.name}")
            checked += 1
            children.append((g2, s2, delta_cost))
        # also perturb a view on the unchanged structure (the re-viewed
        # strategy path of the generic diff)
        node = rng.choice(parent.topo_order())
        views = candidate_views(node.op, n)
        if views and node.op.fixed_machine_view() is None:
            s3 = dict(strat)
            s3[node.guid] = rng.choice(views)
            assert sim.simulate(parent, s3) == sim._simulate_full(
                parent, s3, True)
            checked += 1
        live = [c for c in children if math.isfinite(c[2])]
        if live:
            parent, strat, _ = rng.choice(live)
    assert checked >= 40  # the walk must have really exercised deltas
    assert sim.delta_sims > 0


def test_canned_search_full_sim_budget():
    """Regression: the tier-1 estimates of a canned search must ride
    the delta path — the number of FULL simulate() derivations stays
    capped while delta re-costs dominate.  Counter-based: no wall-clock
    flakiness."""
    from flexflow_tpu.search import simulator as sim_mod

    if sim_mod.DELTA_CHECK:
        pytest.skip("FLEXFLOW_TPU_DELTA_CHECK doubles every delta into "
                    "a full sim; the counter cap is meaningless")
    cfg = ff.FFConfig(batch_size=64, num_devices=8, search_budget=16)
    graph = _dlrm_graph(cfg)
    from flexflow_tpu.search.dp import SearchHelper

    sim = Simulator.for_config(cfg)
    helper = SearchHelper(sim, cfg.search_devices)
    from flexflow_tpu.search.driver import _UnityOptimizer, _load_xfers

    opt = _UnityOptimizer(helper, cfg, _load_xfers(cfg, 8))
    opt._score_edges(graph)
    opt.sequence_optimize(graph, {})
    total = sim.full_sims + sim.delta_sims
    assert sim.delta_sims > 0
    # pre-delta, EVERY candidate estimate was a full simulation; now
    # full sims are snapshots/merges/DP-revalidations only.  The cap is
    # ~3x the observed count — far below the estimate volume.
    assert sim.full_sims <= max(200, total // 3), (
        sim.full_sims, sim.delta_sims)


def test_mcmc_rides_delta():
    from flexflow_tpu.search.driver import mcmc_optimize

    cfg = ff.FFConfig(batch_size=64, num_devices=8)
    graph = _dlrm_graph(cfg)
    import flexflow_tpu.search.driver as drv

    before = None
    strategy = mcmc_optimize(graph, cfg, iterations=60, seed=1)
    assert strategy  # sanity; the delta counters live on the sim inside
    del before, drv


# ---------------------------------------------------------------------------
# persistent cost cache


def _search_cfg(tmp_path, **kw):
    return ff.FFConfig(
        batch_size=64, num_devices=8, search_budget=8,
        cost_cache_file=str(tmp_path / "cost_cache.json"), **kw)


def test_cost_cache_round_trip_and_result_serve(tmp_path):
    cfg = _search_cfg(tmp_path)
    g1 = _dlrm_graph(cfg)
    bg1, s1 = optimize_strategy(g1, cfg, return_graph=True)
    assert LAST_SEARCH_STATS["result_cache_hit"] is False
    cost1 = Simulator.for_config(cfg).simulate(bg1, s1)

    cfg2 = _search_cfg(tmp_path)
    g2 = _dlrm_graph(cfg2)
    bg2, s2 = optimize_strategy(g2, cfg2, return_graph=True)
    assert LAST_SEARCH_STATS["result_cache_hit"] is True
    cost2 = Simulator.for_config(cfg2).simulate(bg2, s2)
    assert cost1 == cost2


def test_cost_cache_rows_survive_and_match(tmp_path):
    cfg = _search_cfg(tmp_path)
    g = _dlrm_graph(cfg)
    sim = Simulator.for_config(cfg)
    assert sim.cost_cache is not None
    node = g.topo_order()[3]
    mv = candidate_views(node.op, 8)[0]
    row = sim._node_costs(node, mv)
    sim.cost_cache.save()

    sim2 = Simulator.for_config(_search_cfg(tmp_path))
    assert sim2.cost_cache.rows, "rows must persist"
    assert sim2._node_costs(node, mv) == row
    assert sim2.cost_cache.row_hits >= 1


def test_cost_cache_invalidated_on_calibration_signature_change(tmp_path):
    """Flipping the calibration signature must force a full recompute:
    the old rows/results are abandoned wholesale."""
    cfg = _search_cfg(tmp_path)
    g = _dlrm_graph(cfg)
    optimize_strategy(g, cfg, return_graph=True)

    # a new measured record changes the table content => new signature
    cal = _fake_calibration(g)
    cfg2 = _search_cfg(tmp_path)
    sim_plain = Simulator.for_config(cfg2)
    sim_cal = Simulator.for_config(cfg2, calibration=cal)
    assert cost_signature(sim_plain.cost) != cost_signature(sim_cal.cost)
    assert sim_cal.cost_cache.invalidated
    assert not sim_cal.cost_cache.rows
    assert sim_cal.cost_cache.get_search_result(g, cfg2) is None


def test_cost_cache_disabled_by_empty_path(tmp_path, monkeypatch):
    monkeypatch.setenv("FLEXFLOW_TPU_COST_CACHE",
                       str(tmp_path / "env_cache.json"))
    # explicit empty string (--no-cost-cache) beats the env default
    cfg = ff.FFConfig(batch_size=64, num_devices=8, search_budget=4,
                      cost_cache_file="")
    assert resolve_cost_cache_path(cfg) is None
    g = _dlrm_graph(cfg)
    optimize_strategy(g, cfg, return_graph=True)
    assert not (tmp_path / "env_cache.json").exists()
    # and the env default applies when the config leaves it unset
    cfg2 = ff.FFConfig(batch_size=64, num_devices=8, search_budget=4)
    assert resolve_cost_cache_path(cfg2) == str(tmp_path / "env_cache.json")


def test_no_cost_cache_flag_parses():
    cfg = ff.FFConfig.parse_args(["--no-cost-cache"])
    assert cfg.cost_cache_file == ""
    assert resolve_cost_cache_path(cfg) is None
    cfg2 = ff.FFConfig.parse_args(["--cost-cache-file", "/tmp/x.json"])
    assert cfg2.cost_cache_file == "/tmp/x.json"


def test_calibration_stale_flag_refuses_to_serve(tmp_path, capsys):
    cfg = _search_cfg(tmp_path)
    g = _dlrm_graph(cfg)
    optimize_strategy(g, cfg, return_graph=True)
    path = resolve_cost_cache_path(cfg)
    assert mark_calibration_stale(path)

    sim = Simulator.for_config(_search_cfg(tmp_path))
    cache = sim.cost_cache
    assert cache.stale
    assert not cache.rows
    assert cache.get_search_result(g, cfg) is None
    node = g.topo_order()[0]
    cache.put(node.op, MachineView.trivial(2), (1.0, 2.0, 0.0, 0.0))
    assert not cache.rows  # refuses new rows too until recalibration
    err = capsys.readouterr().err
    assert "no-cost-cache" in err and "recalibrate" in err.lower()


def test_stable_graph_digest_ignores_tensor_guid_counter():
    g1, g2 = _bert_graph(), _bert_graph()  # global tensor guids differ
    assert stable_graph_digest(g1) == stable_graph_digest(g2)
    assert stable_graph_digest(g1) != stable_graph_digest(_dlrm_graph())


def test_search_key_depends_on_knobs():
    g = _dlrm_graph()
    a = CostCache.search_key(g, ff.FFConfig(num_devices=8, search_budget=8))
    b = CostCache.search_key(g, ff.FFConfig(num_devices=8, search_budget=9))
    assert a != b


# ---------------------------------------------------------------------------
# satellite: pooled-comm breakdown flag


def test_breakdown_pooled_comm_flags():
    from flexflow_tpu.search.taskgraph_sim import LogicalTaskGraphSimulator

    cfg = ff.FFConfig(batch_size=64, num_devices=8)
    g = _dlrm_graph(cfg)
    strat = data_parallel_strategy(g, 8)

    bd = {}
    Simulator.for_config(cfg).simulate(g, strat, breakdown=bd)
    assert bd["pooled_comm"] is False

    bd2 = {}
    lsim = LogicalTaskGraphSimulator(cfg.machine_spec, num_devices=8)
    lsim.simulate(g, strat, breakdown=bd2)
    if lsim.cost.network is not None:
        assert bd2["pooled_comm"] is True
        # the flag says WHY there are no per-collective records
        assert bd2["comm_end_s"] >= 0.0
    else:  # no topology: the event-sim fallback ran instead
        assert bd2["pooled_comm"] is False


# ---------------------------------------------------------------------------
# satellite: delta-aware find_matches (rescan only the dirty region)


def test_delta_find_matches_identical_to_full_scan():
    """Property: for every registered GraphXfer, matches computed
    incrementally from the parent's matches + the changed-guid seeds
    equal the full rescan, in the same topo order — on a graph big
    enough that the dirty region actually shrinks the scan."""
    from flexflow_tpu.models import build_inception_v3
    from flexflow_tpu.search import substitution as S

    cfg = ff.FFConfig(batch_size=8, num_devices=8)
    g = build_inception_v3(cfg).graph
    xfers = generate_all_pcg_xfers(8)
    payload = {}
    for xi, xf in enumerate(xfers):
        if hasattr(xf, "find_matches_delta"):
            payload[xi] = [n.guid for n in xf.find_matches(g)]
    rng = random.Random(3)
    applied = 0
    for xi, xf in enumerate(xfers):
        if not hasattr(xf, "matcher"):
            continue
        ms = xf.find_matches(g)
        if not ms:
            continue
        child = xf.apply(g, rng.choice(ms))
        if child is None:
            continue
        applied += 1
        b0 = (S._DELTA_SCANS.value, S._DELTA_SKIPPED.value)
        for xj, xf2 in enumerate(xfers):
            if not hasattr(xf2, "find_matches_delta"):
                continue
            delta = xf2.find_matches_delta(child, payload.get(xj))
            full = xf2.find_matches(child)
            assert [n.guid for n in delta] == [n.guid for n in full], (
                xf.name, xf2.name)
        b1 = (S._DELTA_SCANS.value, S._DELTA_SKIPPED.value)
        assert b1[0] > b0[0], "dirty region never small enough to pay"
        assert b1[1] > b0[1], "no nodes skipped: region degenerated"
        if applied >= 6:
            break
    assert applied >= 4


def test_delta_find_matches_falls_back_without_seeds():
    g = _bert_graph()
    xfers = [x for x in generate_all_pcg_xfers(8) if hasattr(x, "matcher")]
    xf = next(x for x in xfers if x.find_matches(g))
    # no parent matches and no _changed_vs: identical to the full scan
    assert [n.guid for n in xf.find_matches_delta(g, None)] == \
        [n.guid for n in xf.find_matches(g)]


# ---------------------------------------------------------------------------
# segment reuse (PR 7): incremental native-DP ctx assembly + persistent
# DP memo rows under process-stable digests


def test_ctx_patch_oracle_across_random_substitutions(monkeypatch):
    """Property: every PATCHED native-DP ctx must be indistinguishable
    from a full rebuild (same topo order, packed arrays, edge
    matrices).  CTX_CHECK arms the runtime oracle — _assert_ctx_equal
    raises on any divergence — and the walk must actually take the
    patch path, not fall back to rebuilds."""
    from flexflow_tpu import native as _native
    from flexflow_tpu.search import dp as dp_mod
    from flexflow_tpu.search.dp import SearchHelper

    if _native.get_lib() is None:
        pytest.skip("native library not built (see tests/test_native.py)")
    monkeypatch.setattr(dp_mod, "CTX_CHECK", True)
    n = 8
    for builder in (_bert_graph, _dlrm_graph):
        graph = builder()
        sim = Simulator(ff.FFConfig(num_devices=n).machine_spec,
                        num_devices=n)
        helper = SearchHelper(sim, n)
        assert helper._native_dp_ctx(graph) is not None
        xfers = generate_all_pcg_xfers(n)
        rng = random.Random(11)
        parent = graph
        for step in range(10):
            children = []
            for xf in xfers:
                matches = xf.find_matches(parent)
                if not matches:
                    continue
                child = xf.apply(parent, rng.choice(matches))
                if child is None or child.num_nodes > 256:
                    continue
                # the oracle runs inside: patched ctx asserted == rebuilt
                assert helper._native_dp_ctx(child) is not None
                children.append(child)
                if len(children) >= 3:
                    break
            if not children:
                break
            parent = rng.choice(children)
        assert helper.ctx_patch_hits > 0, (
            "substitution children never took the incremental ctx path")


def test_ctx_patch_falls_back_without_parent_ctx():
    """A graph with no _changed_vs (or a parent that never built a ctx)
    must take the full-rebuild path, not crash."""
    from flexflow_tpu import native as _native
    from flexflow_tpu.search.dp import SearchHelper

    if _native.get_lib() is None:
        pytest.skip("native library not built (see tests/test_native.py)")
    g = _bert_graph()
    sim = Simulator(ff.FFConfig(num_devices=8).machine_spec, num_devices=8)
    helper = SearchHelper(sim, 8)
    assert helper._native_dp_ctx(g) is not None
    assert helper.ctx_patch_hits == 0
    assert helper.ctx_rebuilds == 1


_DIGEST_SCRIPT = r"""
import flexflow_tpu as ff
from flexflow_tpu.models import build_transformer
from flexflow_tpu.search.cost_cache import stable_graph_digest

cfg = ff.FFConfig(batch_size=8, num_devices=8)
g = build_transformer(cfg, num_layers=2, hidden=128, num_heads=4,
                      ff_dim=256, seq_len=32).graph
snh = g.stable_node_digests()
order = {n.guid: i for i, n in enumerate(g.topo_order())}
print("GD", stable_graph_digest(g))
print("NH", ";".join(snh[guid] for guid in sorted(snh, key=order.get)))
"""


def _run_subprocess(script, hash_seed, *argv):
    import os
    import subprocess
    import sys

    env = dict(os.environ, PYTHONHASHSEED=str(hash_seed),
               JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script, *map(str, argv)],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


def test_stable_digests_identical_across_processes():
    """The persistence contract: two FRESH processes — with different
    PYTHONHASHSEED, the thing that randomizes python tuple hashes and
    thus node_hashes() — must produce identical stable node digests and
    graph digest, or no prior run's DP memo rows could ever be served."""
    a = _run_subprocess(_DIGEST_SCRIPT, 101)
    b = _run_subprocess(_DIGEST_SCRIPT, 202)
    lines_a = [ln for ln in a.splitlines() if ln[:3] in ("GD ", "NH ")]
    lines_b = [ln for ln in b.splitlines() if ln[:3] in ("GD ", "NH ")]
    assert lines_a and lines_a == lines_b


_WARM_SCRIPT = r"""
import json
import sys

import flexflow_tpu as ff
from flexflow_tpu.models import build_transformer
from flexflow_tpu.search.driver import LAST_SEARCH_STATS, optimize_strategy

cache, budget = sys.argv[1], int(sys.argv[2])
cfg = ff.FFConfig(batch_size=8, num_devices=8, search_budget=budget,
                  cost_cache_file=cache)
g = build_transformer(cfg, num_layers=2, hidden=128, num_heads=4,
                      ff_dim=256, seq_len=32).graph
bg, strat = optimize_strategy(g, cfg, return_graph=True)
print("STATS " + json.dumps({
    "served": LAST_SEARCH_STATS["dp_rows_served"],
    "result_hit": LAST_SEARCH_STATS["result_cache_hit"],
    "covered": len(strat) == bg.num_nodes,
}))
"""


def test_warm_process_serves_persisted_dp_rows(tmp_path):
    """A COLD process must not touch the dp-row layer (within one run
    the in-process memo supersedes it — the bit-identical gate), and a
    WARM second process (different PYTHONHASHSEED, different search
    budget so the whole-result layer misses) must serve tier-2 DP
    results from the persisted rows."""
    import json as _json

    cache = str(tmp_path / "cc.json")
    out = _run_subprocess(_WARM_SCRIPT, 101, cache, 8)
    cold = _json.loads(out.split("STATS ", 1)[1])
    assert cold["served"] == 0 and not cold["result_hit"]
    assert cold["covered"]
    with open(cache) as f:
        data = _json.load(f)
    from flexflow_tpu.search.cost_cache import DP_SCHEMA

    assert data["dp_schema"] == DP_SCHEMA and data["dp_rows"], (
        "first search persisted no DP memo rows")

    out = _run_subprocess(_WARM_SCRIPT, 202, cache, 9)
    warm = _json.loads(out.split("STATS ", 1)[1])
    assert warm["served"] > 0, warm
    assert not warm["result_hit"]  # budget differs: result layer missed
    assert warm["covered"]


def test_unknown_dp_schema_drops_layer_loudly(tmp_path, capsys):
    """Corrupt/unknown dp_schema: the loader must drop the dp-row layer
    with a stderr warning (one recompute, never a wrong serve) while
    keeping the rest of the cache."""
    import json as _json

    from flexflow_tpu.search.cost_cache import DP_SCHEMA

    path = str(tmp_path / "cc.json")
    sig = "test-signature"
    with open(path, "w") as f:
        _json.dump({"schema": 1, "signature": sig,
                    "calibration_stale": False, "rows": [],
                    "dp_schema": DP_SCHEMA + 99,
                    "dp_rows": {"aa:bb": {"cost": 1.0, "strategy": [
                        ["ab12", [1, 8], 1, 0]]}}}, f)
    cc = CostCache(path, sig)
    assert not cc.dp_rows and not cc.dp_loaded
    assert cc.get_dp_row("aa:bb") is None
    assert "dp_schema" in capsys.readouterr().err


def test_search_perf_reports_match_shrink():
    """The satellite's proof counter: a search over a big graph must
    report dirty-region rescans with most match work skipped."""
    from flexflow_tpu.models import build_inception_v3

    cfg = ff.FFConfig(batch_size=8, num_devices=8, search_budget=4,
                      search_timeout_s=60, base_optimize_threshold=300,
                      cost_cache_file="")
    g = build_inception_v3(cfg).graph
    optimize_strategy(g, cfg, return_graph=True)
    stats = dict(LAST_SEARCH_STATS)
    assert stats["match_delta_scans"] > 0, stats
    # most match work is served from the parent (measured ~90% on
    # inception; 2x is the regression floor, not the typical shrink)
    assert stats["match_nodes_skipped"] > 2 * stats[
        "match_nodes_rescanned"], stats
