"""The always-on loop: hot strategy swap (bit-exact fp32 re-shard),
drift-driven live re-search, elastic-mesh recovery, and the
deterministic fault-injection harness (runtime/controller.py,
runtime/faults.py, analysis/swap.py, FFModel.swap_strategy)."""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.compiler.lowering import data_parallel_strategy
from flexflow_tpu.runtime import (
    FaultPlan,
    TrainingController,
    shrink_config,
)
from flexflow_tpu.search.calibration import CalibrationTable

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_model(num_devices=4, seed=0, with_cache=False, **cfg_kw):
    cfg = ff.FFConfig(batch_size=8, num_devices=num_devices,
                      only_data_parallel=True, seed=seed, **cfg_kw)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16])
    h = m.dense(x, 32, activation="relu", name="d0")
    if with_cache:
        h = m.cache(h, name="c0")
    m.dense(h, 4, name="d1")
    m.compile(optimizer=ff.SGDOptimizer(lr=1e-2),
              loss_type="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    return m


def _data(n=32, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 16).astype(np.float32),
            rng.randint(0, 4, size=(n,)).astype(np.int32))


def _fake_table(path, scale=1.0):
    t = CalibrationTable()
    t._t[("('probe', 16, 32)", (1, 1), 1)] = 1e-4 * scale
    t._t[("('probe', 16, 32)", (2, 1), 1)] = 6e-5 * scale
    t.backend = None  # coherent with any machine model
    t.save(path)
    return t


def _host_trees(m):
    import jax

    out = {}
    for name, tree in (("params", m.params), ("opt_state", m.opt_state),
                       ("state", m.state)):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        out[name] = {repr(p): np.array(leaf, copy=True)
                     for p, leaf in flat}
    return out


def _assert_trees_bit_exact(a, b):
    assert a.keys() == b.keys()
    for name in a:
        assert a[name].keys() == b[name].keys(), name
        for k in a[name]:
            np.testing.assert_array_equal(a[name][k], b[name][k],
                                          err_msg=f"{name}:{k}")


# ---------------------------------------------------------------------------
# hot swap mechanics


def test_swap_strategy_bit_exact_and_trainable():
    """The swap contract: params, optimizer slots and op state are
    value-IDENTICAL across the re-shard (fp32 re-shard is a value
    identity — the in-memory checkpoint is the oracle), and the model
    keeps training under the new strategy."""
    m = _make_model(with_cache=True)
    X, Y = _data()
    m.fit(X, Y, batch_size=8, epochs=2, verbose=False)
    before = _host_trees(m)
    rep = m.swap_strategy(data_parallel_strategy(m.graph, 2))
    assert rep["fallback"] is False and not rep["dropped"]
    _assert_trees_bit_exact(before, _host_trees(m))
    # the cache op's mutable state rode the swap
    assert any("c0/cached" in k for k in before["state"])
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)  # must not raise


def test_swap_matches_direct_device_put_oracle():
    """The swap-step state equals an UNINTERRUPTED fp32 re-shard
    oracle: device_put of the pre-swap host values onto the post-swap
    shardings, leaf by leaf."""
    import jax

    m = _make_model()
    X, Y = _data()
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)
    pre = {op: {w: np.array(a, copy=True) for w, a in ws.items()}
           for op, ws in m.params.items()}
    m.swap_strategy(data_parallel_strategy(m.graph, 2))
    for op, ws in pre.items():
        for w, host in ws.items():
            live = m.params[op][w]
            oracle = jax.device_put(host, live.sharding)
            np.testing.assert_array_equal(np.asarray(live),
                                          np.asarray(oracle))


def test_swap_gate_rejects_weight_and_state_loss():
    """SHD170/SHD171: a target graph that drops (or invents) a weight
    or op state is an illegal swap — the always-on gate refuses it."""
    from flexflow_tpu.analysis import AnalysisError, lint_swap

    m = _make_model(with_cache=True)
    other = ff.FFModel(ff.FFConfig(batch_size=8, num_devices=4,
                                   only_data_parallel=True))
    x = other.create_tensor([8, 16])
    h = other.dense(x, 32, activation="relu", name="d0")
    other.dense(h, 8, name="d1")  # shape change + cache state dropped
    strat = data_parallel_strategy(other.graph, 4)
    codes = {f.code for f in lint_swap(
        m.graph, other.graph, strat, 4)}
    assert "SHD170" in codes and "SHD171" in codes
    with pytest.raises(AnalysisError):
        m.swap_strategy(strat, graph=other.graph)


def test_swap_gate_rejects_uncovered_node():
    from flexflow_tpu.analysis import lint_swap

    m = _make_model()
    strat = data_parallel_strategy(m.graph, 4)
    victim = next(g for g, v in strat.items()
                  if len(m.graph.nodes[g].op._weight_specs))
    del strat[victim]
    codes = {f.code for f in lint_swap(m.graph, m.graph, strat, 4)}
    assert "SHD172" in codes


def test_swap_comm_plan_lint_failure_falls_back_to_fp32(monkeypatch):
    """A searched comm plan that fails its legality gate post-swap
    degrades to the monolithic fp32 sync path instead of failing."""
    from flexflow_tpu.analysis import AnalysisError
    from flexflow_tpu.search import driver as _driver

    m = _make_model(sync_schedule="search")
    X, Y = _data()
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)

    def boom(*a, **kw):
        raise AnalysisError("injected post-swap plan lint failure", [])

    monkeypatch.setattr(_driver, "_build_sync_schedule", boom)
    rep = m.swap_strategy(data_parallel_strategy(m.graph, 4))
    assert rep["fallback"] is True
    assert m.sync_schedule is None and not m.sync_precision_map
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)


def test_elastic_swap_with_zero_sharded_optimizer():
    """Mesh shrink re-homes per-group ZeRO optimizer shards: values
    bit-exact, training continues on the survivors."""
    m = _make_model(num_devices=4, zero_dp_shard=True)
    X, Y = _data()
    m.fit(X, Y, batch_size=8, epochs=2, verbose=False)
    before = _host_trees(m)
    cfg2 = shrink_config(m.config, 2)
    m.swap_strategy(data_parallel_strategy(m.graph, 2), config=cfg2)
    assert m.config.num_devices == 2
    _assert_trees_bit_exact(before, _host_trees(m))
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)


# ---------------------------------------------------------------------------
# fault plan


def test_fault_plan_parse_and_env(monkeypatch):
    plan = FaultPlan.parse("calibration_drift@3, device_loss@6:2", seed=5)
    assert [(f.kind, f.step, f.arg) for f in plan.faults] == [
        ("calibration_drift", 3, None), ("device_loss", 6, 2)]
    monkeypatch.setenv("FLEXFLOW_TPU_FAULTS", "collective_failure@1:4")
    monkeypatch.setenv("FLEXFLOW_TPU_FAULT_SEED", "9")
    env = FaultPlan.from_env()
    assert env.seed == 9 and env.faults[0].arg == 4
    with pytest.raises(ValueError):
        FaultPlan.parse("meteor_strike@1")
    # a zero failure budget / zero survivors is a plan that silently
    # tests nothing — rejected at parse, not discovered mid-run
    with pytest.raises(ValueError, match=">= 1"):
        FaultPlan.parse("collective_failure@3:0")
    with pytest.raises(ValueError, match=">= 1"):
        FaultPlan.parse("device_loss@3:0")
    monkeypatch.delenv("FLEXFLOW_TPU_FAULTS")
    assert FaultPlan.from_env() is None


def test_fault_plan_drift_factor_is_seed_deterministic(tmp_path):
    seen = []
    for _ in range(2):
        cal = str(tmp_path / "CAL.json")
        _fake_table(cal)
        plan = FaultPlan.parse("calibration_drift@0", seed=11)
        seen.append(plan.inject_calibration_drift(plan.faults[0], cal))
        with open(cal) as f:
            assert json.load(f)["stale"] is True
    assert seen[0] == seen[1]


# ---------------------------------------------------------------------------
# end-to-end recovery (the acceptance scenarios)


def test_drift_research_hot_swap_e2e_and_deterministic(tmp_path):
    """Injected calibration drift at step k: the controller re-searches
    warm, hot-swaps between steps, the pre-swap trajectory is
    bit-identical to an unfaulted run, the post-swap trajectory stays
    close (same math, possibly different reduction order), and the
    whole run is bit-reproducible under the fixed fault seed."""
    cal = str(tmp_path / "CALIBRATION.json")
    X, Y = _data()

    def run(faulted):
        _fake_table(cal)
        m = _make_model(calibration_file=cal)
        plan = (FaultPlan.parse("calibration_drift@3", seed=7)
                if faulted else None)
        ctl = TrainingController(m, faults=plan)
        out = ctl.run(X, Y, steps=6)
        return out, m

    out_a, _ = run(faulted=True)
    out_b, _ = run(faulted=True)
    clean, _ = run(faulted=False)
    la = [h["loss"] for h in out_a["history"]]
    lb = [h["loss"] for h in out_b["history"]]
    lc = [h["loss"] for h in clean["history"]]
    assert la == lb  # deterministic under the fixed fault seed
    assert out_a["stats"]["swaps"] == 1
    assert out_a["stats"]["research_seconds"]
    assert la[:3] == lc[:3]  # bit-identical up to the swap step
    np.testing.assert_allclose(la, lc, rtol=1e-4, atol=1e-6)


def test_drift_swap_step_state_bit_exact_vs_oracle(tmp_path):
    """The swap step's full state is bit-exact vs the uninterrupted
    run's state at that step (the swap itself moved no values)."""
    cal = str(tmp_path / "CALIBRATION.json")
    X, Y = _data()

    _fake_table(cal)
    m_clean = _make_model(calibration_file=cal)
    TrainingController(m_clean).run(X, Y, steps=3)
    oracle = _host_trees(m_clean)

    _fake_table(cal)
    m = _make_model(calibration_file=cal)
    ctl = TrainingController(m, faults=FaultPlan.parse(
        "calibration_drift@3", seed=7))
    ctl.run(X, Y, steps=4)
    # rewind the extra step by replaying: instead, compare via a second
    # controller stopped AT the swap step
    _fake_table(cal)
    m2 = _make_model(calibration_file=cal)
    ctl2 = TrainingController(m2, faults=FaultPlan.parse(
        "calibration_drift@3", seed=7))
    out2 = ctl2.run(X, Y, steps=3)
    assert out2["stats"]["swaps"] == 0  # fault fires at step 3 exactly
    _assert_trees_bit_exact(oracle, _host_trees(m2))
    assert ctl.stats["swaps"] == 1


def test_device_loss_recovery_matches_shrunken_mesh_trajectory(tmp_path):
    """Injected device loss: the run resumes on the surviving mesh and
    its loss trajectory matches a shrunken-mesh-from-scratch run within
    tolerance (reduction-order noise only)."""
    X, Y = _data()
    m = _make_model(num_devices=4)
    plan = FaultPlan.parse("device_loss@3:2", seed=7)
    out = TrainingController(m, faults=plan).run(X, Y, steps=8)
    assert m.config.num_devices == 2
    assert out["stats"]["recoveries"] == 1 and out["stats"]["swaps"] == 1

    oracle = _make_model(num_devices=2)
    out_o = TrainingController(oracle).run(X, Y, steps=8)
    la = [h["loss"] for h in out["history"]]
    lo = [h["loss"] for h in out_o["history"]]
    assert all(np.isfinite(la))
    np.testing.assert_allclose(la, lo, rtol=1e-4, atol=1e-6)

    # deterministic under the fixed fault seed
    m2 = _make_model(num_devices=4)
    out2 = TrainingController(m2, faults=FaultPlan.parse(
        "device_loss@3:2", seed=7)).run(X, Y, steps=8)
    assert la == [h["loss"] for h in out2["history"]]


def test_collective_failure_retry_then_monolithic_fallback():
    """Transient collective faults retry within the bounded budget; a
    persistent one degrades to the monolithic fp32 sync path and the
    run completes."""
    X, Y = _data()
    m = _make_model()
    plan = FaultPlan.parse(
        "collective_failure@2:1,collective_failure@4:99", seed=3)
    ctl = TrainingController(m, faults=plan, max_retries=2)
    out = ctl.run(X, Y, steps=6)
    assert len(out["history"]) == 6
    assert out["stats"]["retries"] >= 3
    assert out["stats"]["fallbacks"] == 1
    assert m.sync_schedule is None and not m.sync_precision_map
    assert m.config.sync_schedule == "off"


def test_corrupt_checkpoint_restore_drill(tmp_path):
    """A torn newest snapshot triggers the restore drill: fall back to
    the newest COMPLETE step, rewind, and replay deterministically."""
    X, Y = _data()
    d = str(tmp_path / "ck")
    m = _make_model()
    plan = FaultPlan.parse("corrupt_checkpoint@5", seed=1)
    ctl = TrainingController(m, faults=plan, checkpoint_dir=d,
                             checkpoint_every=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = ctl.run(X, Y, steps=8)
    assert out["stats"]["restores"] == 1
    assert [h["step"] for h in out["history"]] == list(range(8))

    clean = _make_model()
    out_c = TrainingController(clean, checkpoint_dir=str(tmp_path / "c2"),
                               checkpoint_every=2).run(X, Y, steps=8)
    # the replayed tail is bit-identical to the unfaulted run (the rng
    # counter rode the checkpoint)
    assert ([h["loss"] for h in out["history"]]
            == [h["loss"] for h in out_c["history"]])


# ---------------------------------------------------------------------------
# telemetry


def test_controller_events_validate_and_render(tmp_path):
    from flexflow_tpu.obs.events import BUS, validate_event

    log = str(tmp_path / "obs.jsonl")
    cal = str(tmp_path / "CALIBRATION.json")
    _fake_table(cal)
    BUS.configure(log)
    try:
        m = _make_model(calibration_file=cal)
        plan = FaultPlan.parse(
            "calibration_drift@2,collective_failure@4:99", seed=7)
        TrainingController(m, faults=plan, max_retries=1).run(
            *_data(), steps=6)
        BUS.flush()
    finally:
        BUS.close()
    kinds = set()
    with open(log) as f:
        for line in f:
            evt = json.loads(line)
            assert validate_event(evt) == [], (evt, validate_event(evt))
            kinds.add(evt["kind"])
    assert {"fault.injected", "controller.research", "controller.swap",
            "controller.retry", "controller.fallback",
            "controller.summary"} <= kinds
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "ffobs.py"),
         "report", log],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "Always-on controller" in proc.stdout
    assert "Hot swap at step" in proc.stdout


def test_corrupt_checkpoint_before_first_save_degrades_gracefully(
        tmp_path):
    """Review fix: the fault firing before any snapshot exists (or
    after truncating the ONLY one) must not kill the run — the live
    in-memory state is intact, so the drill is skipped and training
    continues."""
    X, Y = _data()
    m = _make_model()
    plan = FaultPlan.parse("corrupt_checkpoint@1", seed=1)
    ctl = TrainingController(m, faults=plan,
                             checkpoint_dir=str(tmp_path / "ck"),
                             checkpoint_every=4)
    out = ctl.run(X, Y, steps=6)
    assert len(out["history"]) == 6
    assert out["stats"]["restores"] == 0


def test_monolithic_fallback_drops_zero_groups():
    """Review fix: the fp32 fallback drops the WHOLE searched comm
    plan — the per-group ZeRO map included, not just the schedule and
    wire precision."""
    X, Y = _data()
    m = _make_model()
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)
    # stand in for a co-searched map (any lint-passing content would
    # otherwise be carried forward by swap_strategy BY DESIGN)
    m.zero_groups = ("d0",)
    plan = FaultPlan.parse("collective_failure@1:99", seed=3)
    ctl = TrainingController(m, faults=plan, max_retries=1)
    out = ctl.run(X, Y, steps=3)
    assert out["stats"]["fallbacks"] == 1
    assert m.zero_groups == () and m.compiled.zero_groups == ()


def test_snapshot_shape_mismatch_keeps_fresh_init():
    """Review fix: a saved state entry whose shape no longer matches
    the template keeps the template's fresh init — the stale buffer
    must not ride the grown-state carry back in."""
    from flexflow_tpu.runtime.checkpoint import (
        restore_in_memory,
        snapshot_in_memory,
    )

    m = _make_model(with_cache=True)
    X, Y = _data()
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)
    snap = snapshot_in_memory(m)
    good = np.asarray(m.state["c0/cached"])
    snap["trees"]["state"]["c0/cached"] = np.zeros((1, 1),
                                                   dtype=np.float32)
    report = restore_in_memory(m, snap)
    assert tuple(np.asarray(m.state["c0/cached"]).shape) == good.shape
    assert "state/c0/cached" in report["fresh"]


def test_shrink_config_preserves_machine_family():
    """Review fix: shrinking must not change WHAT machine the model
    describes — a host_cpu spec stays host_cpu (platform included: the
    calibration coherence rule keys on it), a custom spec keeps its
    constants, and only the default tpu_v5e family is re-derived."""
    import dataclasses

    from flexflow_tpu.core.machine import MachineSpec

    cpu_cfg = ff.FFConfig(batch_size=8, num_devices=8,
                          machine_spec=MachineSpec.host_cpu(8))
    small = shrink_config(cpu_cfg, 4)
    assert small.machine_spec == MachineSpec.host_cpu(4)
    assert small.machine_spec.platform == "cpu"

    default_cfg = ff.FFConfig(batch_size=8, num_devices=8)
    assert shrink_config(default_cfg, 4).machine_spec == \
        MachineSpec.tpu_v5e(4)

    custom = dataclasses.replace(MachineSpec.tpu_v5e(8),
                                 peak_flops=1.23e14, name="custom")
    custom_cfg = ff.FFConfig(batch_size=8, num_devices=8,
                             machine_spec=custom)
    shrunk = shrink_config(custom_cfg, 4).machine_spec
    assert shrunk.num_devices == 4
    assert shrunk.peak_flops == 1.23e14 and shrunk.name == "custom"


def test_failed_swap_rolls_back_to_old_program(monkeypatch):
    """Review fix: a swap that fails PAST the gate (a non-AnalysisError
    out of the re-lowering itself) leaves the model exactly as it was
    — old program, old config/strategy, old state — and training
    continues."""
    import flexflow_tpu.compiler.lowering as lowering

    m = _make_model(num_devices=4)
    X, Y = _data()
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)
    before = _host_trees(m)
    old = (m.compiled, m.strategy, m.config, m.graph)

    def boom(*a, **kw):
        raise RuntimeError("injected lowering failure")

    monkeypatch.setattr(lowering, "CompiledModel", boom)
    with pytest.raises(RuntimeError, match="injected"):
        m.swap_strategy(data_parallel_strategy(m.graph, 2),
                        config=shrink_config(m.config, 2))
    assert (m.compiled, m.strategy, m.config, m.graph) == old
    assert m.config.num_devices == 4
    _assert_trees_bit_exact(before, _host_trees(m))
    monkeypatch.undo()
    m.fit(X, Y, batch_size=8, epochs=1, verbose=False)  # still alive


def test_swap_refuses_placed_lowering():
    """Review fix: a live inter-op-placed model must be REFUSED by
    swap_strategy (its _compile_ctx carries none of the pipeline/
    staged/mesh markers) — never silently re-lowered flat mid-run."""
    from flexflow_tpu.compiler.placement_lowering import (
        PlacedCompiledModel,
    )
    from flexflow_tpu.core.machine import MachineView

    cfg = ff.FFConfig(batch_size=8, num_devices=8,
                      compute_dtype="float32")
    m = ff.FFModel(cfg)
    ids = m.create_tensor([8, 4], dtype="int32", name="ids")
    e = m.embedding(ids, 16, 8, name="emb")
    h = m.flat(e, name="flatten")
    h = m.dense(h, 32, activation="relu", name="mlp1")
    m.dense(h, 4, name="head")
    strat = {}
    for node in m.graph.topo_order():
        nd = node.op.output_shapes[0].ndim
        if node.op.name in ("mlp1", "head"):
            strat[node.guid] = MachineView(
                dim_degrees=(4,) + (1,) * (nd - 1), start_part=4)
        else:
            strat[node.guid] = (
                node.op.fixed_machine_view()
                or MachineView(dim_degrees=(4,) + (1,) * (nd - 1)))
    m.compile(loss_type="sparse_categorical_crossentropy",
              metrics=["accuracy"], strategy=strat)
    assert isinstance(m.compiled, PlacedCompiledModel)
    with pytest.raises(NotImplementedError, match="placed"):
        m.swap_strategy(data_parallel_strategy(m.graph, 8))


def test_research_fallback_degrades_to_dp_past_chain_threshold(
        monkeypatch):
    """Review fix: when the swap gate refuses the rewritten winner on a
    graph past the chain threshold, the fallback must NOT run the flat
    whole-graph DP (documented not to terminate at production scale) —
    it degrades to plain data parallelism and the swap proceeds."""
    import flexflow_tpu.analysis as analysis
    from flexflow_tpu.analysis import Finding
    from flexflow_tpu.search import driver as _driver

    m = _make_model()
    X, Y = _data()

    def reject_all(*a, **kw):
        return [Finding(code="SHD170", pass_name="swap",
                        message="forced rejection")]

    monkeypatch.setattr(analysis, "lint_swap", reject_all)
    monkeypatch.setattr(_driver, "CHAIN_MIN_NODES", 1)
    ctl = TrainingController(m)
    g, s = ctl._research(m.config, "calibration_drift", step=0)
    monkeypatch.undo()  # the swap below must run the REAL gate
    assert g is m.graph
    detail = ctl.stats["research_detail"][-1]
    assert detail["dp_fallback"] is True and detail["searches"] == 1
    # the DP strategy is immediately swappable
    ctl._swap(0, s)
    ctl.run(X, Y, steps=2)


# ---------------------------------------------------------------------------
# measured-drift triggers (ISSUE 14): serving p99 + device-trace lanes
def test_p99_drift_fault_triggers_research(tmp_path):
    """A seeded measured-p99 drift past threshold (the p99_drift fault
    kind) must trigger a controller re-search with the "p99_drift"
    trigger — the serve currency joining the calibration-signature
    watch as a first-class re-search signal."""
    from flexflow_tpu.obs.events import BUS

    log = str(tmp_path / "obs.jsonl")
    BUS.configure(log)
    try:
        # profiling arms compile's predicted breakdown — the searched
        # prediction the measured p99 is judged against
        m = _make_model(profiling=True)
        assert m.predicted_breakdown is not None
        X, Y = _data()
        ctl = TrainingController(
            m, faults=FaultPlan.parse("p99_drift@2", seed=7))
        out = ctl.run(X, Y, steps=5)
        triggers = [d["trigger"] for d in ctl.stats["research_detail"]]
        assert "p99_drift" in triggers
        assert ctl.stats["swaps"] >= 1
        assert all(np.isfinite(h["loss"]) for h in out["history"])
    finally:
        BUS.close()
    events = [json.loads(line) for line in open(log)]
    p99 = [e for e in events if e["kind"] == "controller.p99_drift"]
    assert len(p99) == 1 and p99[0]["drifted"] is True
    assert p99[0]["ratio"] > 1.5  # the seeded draw is 1.5x-3.5x
    from flexflow_tpu.obs.events import validate_event

    for e in events:
        assert validate_event(e) == [], e
    # determinism: the same seed pre-draws the same ratio (the full
    # controller replay is covered by the calibration-drift e2e test —
    # no need to pay a second 5-step run here)
    plan_a = FaultPlan.parse("p99_drift@2", seed=7)
    plan_b = FaultPlan.parse("p99_drift@2", seed=7)
    assert plan_a._draws[id(plan_a.faults[0])] == \
        plan_b._draws[id(plan_b.faults[0])] == pytest.approx(
            p99[0]["ratio"])


def test_observe_p99_below_threshold_is_inert():
    m = _make_model(profiling=True)
    ctl = TrainingController(m)
    pred = m.predicted_breakdown["total_s"]
    ratio = ctl.observe_p99(pred * 1.1, step=0)
    assert ratio == pytest.approx(1.1)
    assert ctl._p99_trigger is None
    # missing either side declines instead of inventing a ratio
    assert ctl.observe_p99(0.0, step=0) is None


def test_lane_drift_report_triggers_research():
    """A matched LaneDriftReport with a stale lane (the device-trace
    measured side) arms a "lane_drift" re-search at the next step
    boundary; a clean report stays inert, and the SAME report object
    never fires twice."""
    from flexflow_tpu.obs.trace_ingest import LaneDriftReport

    m = _make_model(profiling=True)
    X, Y = _data()
    ctl = TrainingController(m)
    clean = LaneDriftReport(
        steps=2, predicted_total_s=1e-3, measured_step_s=1e-3,
        threshold=0.5,
        lanes=[{"lane": "bucket:b0:sync", "matched": True,
                "sync_frac_ratio": 1.0}])
    m.lane_drift_report = clean
    ctl.run(X, Y, steps=2)
    assert not any(d["trigger"] == "lane_drift"
                   for d in ctl.stats["research_detail"])
    drifted = LaneDriftReport(
        steps=2, predicted_total_s=1e-3, measured_step_s=1e-3,
        threshold=0.5,
        lanes=[{"lane": "bucket:b0:sync", "matched": True,
                "sync_frac_ratio": 9.0}])
    assert drifted.stale_lanes == ["bucket:b0:sync"]
    m.lane_drift_report = drifted
    ctl.run(X, Y, steps=3)
    lane_triggers = [d for d in ctl.stats["research_detail"]
                     if d["trigger"] == "lane_drift"]
    assert len(lane_triggers) == 1  # consumed once, not every step
