"""Searched serving fleet: N replica blocks x per-replica strategies
x SLO-aware routing (ISSUE 16 — the serving tier priced in one
per-class p99 currency, elastically re-sized by the controller).

Contract highlights:

* the fleet search (search/fleet.py) partitions the mesh into replica
  blocks with per-block searched strategies and per-SLO-class routing
  fractions, priced per class; on the host machine model it PICKS a
  heterogeneous fleet that beats the single-replica baseline, adopts
  only past the margin (honest zero under an extreme margin), and
  never fakes a fleet when the replica bound forbids one;
* offered load re-sizes N: the same searched graph proposes more
  replicas at higher load — the elastic lever the controller pulls;
* SHD166/167 lint the proposal/artifact frame (disjoint blocks,
  routing coherence, pool geometry) and fflint STR212 re-checks the
  persisted ``__meta__.fleet`` stdlib-only;
* the FleetExecutor's deficit router follows the searched fractions
  deterministically under a seed, rolls per-replica records up into
  fleet per-class p99, and emits ``fleet.route`` events;
* ``TrainingController.observe_fleet`` compares measured per-class p99
  to the proposal's predictions, and a drift episode re-searches and
  HOT-APPLIES a re-sized fleet (``fleet.scale``);
* bit-identity: fleet knobs stay out of serve_fleet=off search keys,
  and partial-occupancy pricing never perturbs a full-frame signature.
"""

import json

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.runtime.decode import (
    ContinuousBatchingExecutor,
    DecodeRequest,
    SLOClass,
)
from flexflow_tpu.runtime.fleet import FleetExecutor

N_DEV = 8

# name:priority:deadline_frames:quantile:weight — the mixed-SLO table
# the bench fleet sweep records (bench_search.py FLEET_SLO)
FLEET_SLO = ("interactive:2:64:0.99:1,standard:1:0:0.99:2,"
             "batch:0:0:0.9:5")

# the small decode config whose searched host fleet the bench measures
FLEET_KW = dict(vocab=256, num_layers=2, hidden=64, num_heads=4,
                ff_dim=128, page_size=8, pages_per_seq=8)


def _fleet_cfg(**overrides):
    """Serve-objective config on the CPU-host machine model —
    max_replicas=3 keeps unequal widths in the partition space, the
    regime where the searched fleet is genuinely heterogeneous."""
    kw = dict(batch_size=8, num_devices=N_DEV, search_budget=4,
              search_timeout_s=30.0, objective="serve",
              comp_mode="inference", cost_cache_file="",
              serve_slo_classes=FLEET_SLO, serve_fleet_max_replicas=3,
              machine_spec=MachineSpec.host_cpu(N_DEV))
    kw.update(overrides)
    return ff.FFConfig(**kw)


@pytest.fixture(scope="module")
def host_fleet_search():
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.search.driver import optimize_strategy
    from flexflow_tpu.search.fleet import propose_fleet

    cfg = _fleet_cfg()
    m = build_gpt_decode(cfg, **FLEET_KW)
    g, s = optimize_strategy(m.graph, cfg, return_graph=True)
    base = m.graph if g is not m.graph else None
    prop = propose_fleet(g, s, cfg, base_graph=base)
    return cfg, m.graph, g, s, prop


# ---------------------------------------------------------------------------
# the fleet search: adoption, margin gate, elastic load response
# ---------------------------------------------------------------------------
def test_fleet_search_adopts_heterogeneous_blocks(host_fleet_search):
    """THE acceptance scenario (recorded in BENCH_SEARCH "Serving
    fleet"): on the host machine model with the replica bound at 3,
    the search picks a HETEROGENEOUS replica partition whose priced
    per-class p99 beats the single-replica baseline past the margin."""
    cfg, base, g, s, prop = host_fleet_search
    assert prop is not None and prop.adopted
    widths = [r.devices for r in prop.replicas]
    assert len(widths) >= 2 and sum(widths) <= N_DEV
    assert widths == sorted(widths, reverse=True)
    assert len(set(widths)) > 1  # genuinely unequal blocks
    assert prop.fleet_cost_s < prop.single_cost_s
    # every replica carries its own searched strategy at its own width
    assert all(r.strategy for r in prop.replicas)
    # disjoint device spans inside the machine
    spans = sorted((r.start, r.start + r.devices) for r in prop.replicas)
    assert all(a1 >= b0 for (_, b0), (a1, _) in zip(spans, spans[1:]))
    # routing covers every class, each row a distribution over replicas
    names = {c["name"] for c in prop.slo_classes}
    assert set(prop.routing) == names == {"interactive", "standard",
                                          "batch"}
    for fr in prop.routing.values():
        assert len(fr) == len(widths)
        assert abs(sum(fr) - 1.0) < 1e-6
    assert set(prop.per_class_p99_s) == names


def test_fleet_margin_gate_honest_zero(host_fleet_search):
    """An extreme improvement margin keeps the single replica: the
    proposal is still returned with BOTH prices recorded — the search
    does not manufacture adoption — and the replica bound at 1 cannot
    fake a fleet at all."""
    from flexflow_tpu.search.fleet import propose_fleet

    cfg, base, g, s, _ = host_fleet_search
    hard = _fleet_cfg(serve_fleet_max_replicas=2,
                      search_improvement_margin=0.9)
    prop = propose_fleet(g, s, hard, base_graph=base)
    assert prop is not None and not prop.adopted
    assert len(prop.replicas) == 1  # the single block stands
    assert prop.fleet_cost_s < prop.single_cost_s  # honest prices

    solo = propose_fleet(g, s, _fleet_cfg(serve_fleet_max_replicas=1),
                         base_graph=base)
    assert solo is not None and not solo.adopted
    assert [r.devices for r in solo.replicas] == [N_DEV]


def test_fleet_search_resizes_with_load(host_fleet_search):
    """The elastic lever: at a light offered load the search keeps a
    small fleet; folding a drift episode into the load
    (``load_scale``, what the controller's re-search passes) shifts
    the optimum to MORE replicas — queueing dominates and narrower
    blocks buy per-class headroom."""
    from flexflow_tpu.search.fleet import propose_fleet

    cfg, base, g, s, _ = host_fleet_search
    light = _fleet_cfg(serve_fleet_offered_load=0.3)
    nominal = propose_fleet(g, s, light, base_graph=base)
    drifted = propose_fleet(g, s, light, base_graph=base,
                            load_scale=3.0)
    assert nominal is not None and nominal.adopted
    assert drifted is not None and drifted.adopted
    assert len(drifted.replicas) > len(nominal.replicas)
    assert drifted.load_scale == 3.0


# ---------------------------------------------------------------------------
# lint gates: SHD166/167 at proposal/import, STR212 on the file
# ---------------------------------------------------------------------------
def test_lint_fleet_codes(host_fleet_search):
    from flexflow_tpu.analysis import errors_only, lint_fleet

    cfg, base, g, s, prop = host_fleet_search
    meta = prop.to_meta()
    assert not errors_only(lint_fleet(base, meta, cfg))

    def corrupt(**kw):
        c = json.loads(json.dumps(meta))
        c.update(kw)
        return c

    def codes(bad):
        return [f.code for f in lint_fleet(base, bad, cfg)]

    # SHD166: frame structure
    assert "SHD166" in codes(corrupt(replicas=[]))
    bad = corrupt()
    bad["replicas"][1]["start"] = 0  # overlaps replica 0
    assert "SHD166" in codes(bad)
    bad = corrupt()
    bad["replicas"][0]["devices"] = 2 * N_DEV  # overflows the machine
    assert "SHD166" in codes(bad)
    bad = corrupt()
    bad["replicas"][0]["prefill_devices"] = \
        bad["replicas"][0]["devices"]  # split no longer fits the block
    assert "SHD166" in codes(bad)

    # SHD167: routing + pool coherence
    assert "SHD167" in codes(
        corrupt(page_size=meta["page_size"] * 2))
    bad = corrupt()
    bad["routing"]["interactive"] = \
        bad["routing"]["interactive"] + [0.0]  # row sized wrong
    assert "SHD167" in codes(bad)
    bad = corrupt()
    bad["routing"]["standard"] = \
        [f * 0.5 for f in bad["routing"]["standard"]]  # sums to 0.5
    assert "SHD167" in codes(bad)
    bad = corrupt()
    bad["routing"]["bulk"] = bad["routing"]["batch"]  # unknown class
    assert "SHD167" in codes(bad)
    bad = corrupt()
    del bad["routing"]["batch"]  # class routes nowhere
    assert "SHD167" in codes(bad)
    bad = corrupt(slo_classes=meta["slo_classes"]
                  + [meta["slo_classes"][0]])  # duplicate class
    assert "SHD167" in codes(bad)


def test_str212_fleet_meta_lint(tmp_path):
    import sys

    sys.path.insert(0, "tools")
    try:
        from fflint import lint_strategy_file
    finally:
        sys.path.pop(0)

    reps = [
        {"replica": 0, "devices": 4, "start": 0, "prefill_devices": 0,
         "decode_devices": 4, "share": 0.5, "occupancy_slots": 16,
         "step_ms": 0.4, "handoff_ms": 0.0, "spans_dcn": False,
         "strategy_ops": 12},
        {"replica": 1, "devices": 4, "start": 4, "prefill_devices": 0,
         "decode_devices": 4, "share": 0.5, "occupancy_slots": 16,
         "step_ms": 0.4, "handoff_ms": 0.0, "spans_dcn": False,
         "strategy_ops": 12},
    ]
    good = {
        "graph_digest": "d" * 32,
        "serving": {"objective": "serve", "max_seqs": 32,
                    "page_size": 16, "pages_per_seq": 32,
                    "quantile": 0.99, "p99_budget_ms": 0.0},
        "fleet": {
            "num_devices": 8, "replicas": reps,
            "routing": {"interactive": [0.5, 0.5],
                        "standard": [0.5, 0.5],
                        "batch": [1.0, 0.0]},
            "routing_policy": "uniform",
            "single_step_ms": 0.8, "fleet_step_ms": 0.4,
            "per_class_p99_ms": {"interactive": 0.5, "standard": 0.6,
                                 "batch": 0.9},
            "max_seqs": 32, "page_size": 16, "pages_per_seq": 32,
            "offered_load": 0.85, "load_scale": 1.0,
            "slo_classes": [
                {"name": "interactive", "priority": 2,
                 "deadline_frames": 64, "quantile": 0.99, "weight": 1},
                {"name": "standard", "priority": 1,
                 "deadline_frames": 0, "quantile": 0.99, "weight": 2},
                {"name": "batch", "priority": 0, "deadline_frames": 0,
                 "quantile": 0.9, "weight": 5},
            ],
        },
    }
    base = {"lm_head": {"dims": [8, 1, 1], "replica": 1, "start": 0}}

    def write(meta):
        p = tmp_path / "strategy.json"
        p.write_text(json.dumps({**base, "__meta__": meta}))
        return str(p)

    assert not [f for f in lint_strategy_file(write(good))
                if f[1] == "STR212"]

    fm = good["fleet"]

    def mut(**kw):
        return {**good, "fleet": {**json.loads(json.dumps(fm)), **kw}}

    def rep_mut(i, **kw):
        m = mut()
        m["fleet"]["replicas"][i].update(kw)
        return m

    corruptions = [
        ("not-an-object", {**good, "fleet": [1]}),
        ("zero-width replica", rep_mut(0, devices=0)),
        ("overlap", rep_mut(1, start=0)),
        ("machine overflow", rep_mut(1, devices=8)),
        ("phase split misfit", rep_mut(0, prefill_devices=2,
                                       decode_devices=4)),
        ("strategyless replica", rep_mut(0, strategy_ops=0)),
        ("share outside [0,1]", rep_mut(0, share=1.5)),
        ("nan price", mut(fleet_step_ms=float("nan"))),
        ("routing row sized wrong", mut(
            routing={**fm["routing"], "interactive": [1.0]})),
        ("routing sum != 1", mut(
            routing={**fm["routing"], "standard": [0.5, 0.2]})),
        ("unknown routed class", mut(
            routing={**fm["routing"], "bulk": [0.5, 0.5]})),
        ("uncovered class", mut(
            routing={"interactive": [0.5, 0.5],
                     "standard": [0.5, 0.5]})),
        ("geometry vs serving", mut(page_size=64)),
        ("dup slo class", mut(
            slo_classes=fm["slo_classes"] + [fm["slo_classes"][0]])),
        ("non-positive weight", mut(
            slo_classes=[{**fm["slo_classes"][0], "weight": 0}]
            + fm["slo_classes"][1:])),
    ]
    for label, meta in corruptions:
        found = [f for f in lint_strategy_file(write(meta))
                 if f[1] == "STR212" and f[0] == "error"]
        assert found, f"corruption {label!r} not caught by STR212"


# ---------------------------------------------------------------------------
# the FleetExecutor: deterministic routing, fraction tracking, roll-up
# ---------------------------------------------------------------------------
SLO_TABLE = (
    SLOClass("interactive", priority=2, deadline_frames=0),
    SLOClass("standard", priority=1, deadline_frames=0),
    SLOClass("batch", priority=0, deadline_frames=0, quantile=0.9),
)


def _synthetic_step(vocab=97, delay_s=0.0):
    import time as _time

    def step(ids, table, lens):
        if delay_s:
            _time.sleep(delay_s)
        ids = np.asarray(ids)
        lens = np.asarray(lens)
        nxt = (ids[:, 0] * 7 + lens * 13 + 5) % vocab
        logits = np.zeros((ids.shape[0], 1, vocab), np.float32)
        logits[np.arange(ids.shape[0]), 0, nxt] = 1.0
        return logits

    return step


def _mk_fleet(routing, k=2, seed=3, delay_s=0.0):
    reps = [ContinuousBatchingExecutor(
        _synthetic_step(delay_s=delay_s), max_seqs=4, page_size=4,
        pages_per_seq=4, slo_classes=SLO_TABLE)
        for _ in range(k)]
    return FleetExecutor(reps, routing, slo_classes=SLO_TABLE,
                         seed=seed)


def _trace(n=12, seed=5):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        cls = ("interactive", "standard", "batch")[
            int(rng.integers(0, 3))]
        L = int(rng.integers(1, 6))
        reqs.append(DecodeRequest(
            rid=f"r{i:02d}",
            prompt=list(map(int, rng.integers(1, 96, size=L))),
            max_new_tokens=int(rng.integers(1, 4)), slo=cls))
    return reqs


def test_fleet_router_determinism():
    """The acceptance determinism gate: equal fractions force router
    ties on every dispatch; the seeded tie-break makes a replayed
    trace map every request to the same replica, and the generated
    tokens match request-for-request."""
    routing = {"interactive": [0.5, 0.5], "standard": [0.5, 0.5],
               "batch": [0.5, 0.5]}

    def run():
        fl = _mk_fleet(routing, seed=11)
        out = fl.run(_trace(), max_frames=200)
        return dict(fl.assignments), out

    a1, o1 = run()
    a2, o2 = run()
    assert a1 == a2 and o1 == o2
    assert set(a1.values()) == {0, 1}  # both replicas genuinely used


def test_fleet_router_tracks_fractions():
    """Deficit routing is weighted round-robin, not a sampler: the
    running per-replica shares converge to the searched fractions from
    the first requests."""
    fl = _mk_fleet({"standard": [0.7, 0.3]}, seed=0)
    reqs = [DecodeRequest(rid=f"s{i}", prompt=[1 + i],
                          max_new_tokens=1, slo="standard")
            for i in range(20)]
    picks = [fl.route(r) for r in reqs]
    counts = [picks.count(0), picks.count(1)]
    assert sum(counts) == 20
    assert abs(counts[0] - 14) <= 1  # 0.7 of 20, within rounding
    # an unknown class falls back to the standard row, never crashes
    assert fl.route(DecodeRequest(rid="x", prompt=[1],
                                  max_new_tokens=1,
                                  slo="mystery")) in (0, 1)


def test_fleet_routing_validation():
    step = _synthetic_step()
    reps = [ContinuousBatchingExecutor(step, max_seqs=2, page_size=4,
                                       pages_per_seq=4)
            for _ in range(2)]
    with pytest.raises(ValueError):
        FleetExecutor([], {"standard": [1.0]})
    with pytest.raises(ValueError):
        FleetExecutor(reps, {"standard": [1.0]})  # row sized wrong
    with pytest.raises(ValueError):
        FleetExecutor(reps, {"standard": [0.0, 0.0]})  # routes nowhere


def test_fleet_rollup_per_class(tmp_path):
    """Per-replica request records merge into fleet per-class p99 (the
    measured side the controller compares), each record tagged with
    its replica, and every dispatch emits ``fleet.route``."""
    from flexflow_tpu.obs.events import BUS

    log = str(tmp_path / "obs.jsonl")
    BUS.configure(log)
    try:
        fl = _mk_fleet({"interactive": [0.5, 0.5],
                        "standard": [0.5, 0.5],
                        "batch": [0.5, 0.5]}, seed=1)
        out = fl.run(_trace(n=10), max_frames=200)
        assert len(out) == 10
        s = fl.summary()
        assert s["replicas"] == 2 and s["completed"] == 10
        assert sum(v["completed"]
                   for v in s["slo_classes"].values()) == 10
        for name, row in s["slo_classes"].items():
            assert row["ttft_p99_s"] is not None
            assert fl.measured_request_p99(
                "ttft_s", slo=name) is not None
        recs = fl.request_records
        assert {r["replica"] for r in recs} <= {0, 1}
        assert all(r["replica"] == fl.assignments[r["rid"]]
                   for r in recs)
    finally:
        BUS.close()
    kinds = [json.loads(ln) for ln in open(log)]
    routes = [e for e in kinds if e.get("kind") == "fleet.route"]
    assert len(routes) == 10
    assert all(e["replica"] == fl.assignments[e["rid"]]
               for e in routes)


# ---------------------------------------------------------------------------
# the controller: measured drift -> re-search -> hot-applied re-size
# ---------------------------------------------------------------------------
def test_controller_elastic_refleet(tmp_path):
    """THE elastic acceptance path end to end: compile under
    serve_fleet=search (the light-load fleet adopts 2 replicas),
    measure a drifted fleet (a deliberately slow step makes every
    class's p99 blow past its prediction), and the armed re-search
    RE-SIZES the fleet live — more replicas hot-applied onto
    ``model.fleet``, ``fleet.scale`` on the bus."""
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.obs.events import BUS
    from flexflow_tpu.runtime.controller import TrainingController

    cfg = _fleet_cfg(serve_fleet="search",
                     serve_fleet_offered_load=0.3)
    m = build_gpt_decode(cfg, **FLEET_KW)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              comp_mode="inference")
    old = m.fleet
    assert old is not None and old.adopted
    assert len(old.replicas) == 2  # the light-load optimum

    ctl = TrainingController(m)
    log = str(tmp_path / "obs.jsonl")
    BUS.configure(log)
    try:
        # a measured fleet shaped like the proposal, but each frame
        # far slower than the priced step: every class drifts up
        fl = _mk_fleet({c: list(fr) for c, fr in old.routing.items()},
                       k=len(old.replicas), seed=2, delay_s=0.004)
        fl.run(_trace(n=12), max_frames=300)
        ratios = ctl.observe_fleet(fl)
        assert ratios and max(ratios.values()) > 1.5

        new = ctl.maybe_refleet()
        assert new is not None and new is m.fleet and new is not old
        assert len(new.replicas) > len(old.replicas)  # re-sized live
        assert ctl.stats["fleet_scales"] == 1
        assert ctl.maybe_refleet() is None  # trigger consumed
    finally:
        BUS.close()
    events = [json.loads(ln) for ln in open(log)]
    drifts = [e for e in events
              if e.get("kind") == "controller.p99_drift"
              and e.get("slo")]
    assert {e["slo"] for e in drifts} == set(ratios)
    scales = [e for e in events if e.get("kind") == "fleet.scale"]
    assert len(scales) == 1
    assert scales[0]["from_replicas"] == len(old.replicas)
    assert scales[0]["to_replicas"] == len(new.replicas)
    assert scales[0]["resized"] is True
    assert scales[0]["load_scale"] > 1.0


# ---------------------------------------------------------------------------
# bit-identity: off means off
# ---------------------------------------------------------------------------
def test_fleet_knobs_stay_out_of_off_search_keys(host_fleet_search):
    """serve_fleet=off keys must stay byte-identical to pre-fleet
    caches no matter how the fleet knobs are set; only arming the
    search changes the key (a different search function)."""
    from flexflow_tpu.search.cost_cache import CostCache

    _, base, *_ = host_fleet_search
    off_a = _fleet_cfg(serve_fleet="off", serve_fleet_max_replicas=2)
    off_b = _fleet_cfg(serve_fleet="off", serve_fleet_max_replicas=8,
                       serve_fleet_offered_load=0.25)
    armed = _fleet_cfg(serve_fleet="search")
    assert CostCache.search_key(base, off_a) \
        == CostCache.search_key(base, off_b) \
        == CostCache.search_key(base, _fleet_cfg())
    assert CostCache.search_key(base, armed) \
        != CostCache.search_key(base, off_a)
    assert ff.FFConfig().serve_fleet == "off"
    with pytest.raises(ValueError):
        ff.FFConfig(serve_fleet="bogus")


def test_occupancy_signature_guards():
    """Partial-occupancy pricing (a replica block simulated at its
    routed share's slots) must never collide with or perturb the
    full-frame serving signature."""
    from flexflow_tpu.search.serving import ServingSpec

    spec = ServingSpec(max_seqs=16, page_size=16, pages_per_seq=16)
    part = spec.with_occupancy(4)
    assert part.occupancy_slots == 4
    assert part.signature() != spec.signature()
    # occupancy at (or past) the full frame IS the full frame
    assert spec.with_occupancy(16).occupancy_slots == 0
    assert spec.with_occupancy(99).signature() == spec.signature()
    # the floor: a tiny share still prices at least one live slot
    assert spec.with_occupancy(0).occupancy_slots == 1
