"""Joint strategy × comm-plan co-search (search/comm_plan.py, ROADMAP
item 2) — the PR 8 contracts:

* OFF-mode inertness — with ``FFConfig.co_search=False`` (the default)
  the sequential strategy→plan pipeline never touches the co-search
  machinery: a poisoned ``JointPricer`` across the 9-model zoo proves
  no code path constructs one, repeat searches stay deterministic, and
  the persisted search-result key is disjoint from joint-mode keys
  (the manual gate — zoo strategies + sim costs bit-identical to the
  pre-PR tree — was verified at PR time; these tests keep the OFF path
  structurally inert so it stays that way).
* never-worse property — on randomized machine specs the joint
  pipeline's result, scored in the joint currency (best comm plan via
  the exposed-comm simulation minus the ZeRO update credit), is never
  worse than the sequential pipeline's result scored the same way.
* comm-plan memo — repeated synced-group signatures are SERVED (memo
  then the persistent cost-cache layer), not re-searched.
* per-group optimizer sharding legality — SHD140/141 (analysis), the
  ``__meta__.zero_groups`` import gate, STR207 (fflint strategy) and
  CCH407/408 (fflint cache) seeded corruptions.
* EF residual state — ``int8_ef`` groups carry a persistent residual
  in the model-state dict: created at init, advanced by the step, and
  checkpoint round-tripped.
* match seed index — indexed ``find_matches`` is identical to the full
  scan (the FLEXFLOW_TPU_DELTA_CHECK oracle) and the skips land in
  ``search.perf``.
"""

import json
import math

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.compiler.lowering import data_parallel_strategy
from flexflow_tpu.search.cost_cache import stable_graph_digest
from flexflow_tpu.search.driver import LAST_SEARCH_STATS, optimize_strategy
from flexflow_tpu.search.simulator import Simulator


def _mlp_graph(cfg):
    m = ff.FFModel(cfg)
    x = m.create_tensor([cfg.batch_size, 128], name="cs_x")
    t = m.dense(x, 512, activation="relu", name="cs_fc1")
    t = m.dense(t, 512, activation="relu", name="cs_fc2")
    m.dense(t, 16, name="cs_head")
    return m.graph


def _bert_graph(cfg):
    from flexflow_tpu.models import build_transformer

    return build_transformer(cfg, num_layers=2, hidden=256, num_heads=4,
                             ff_dim=512, seq_len=16).graph


# ---------------------------------------------------------------------------
# OFF-mode inertness across the zoo


_ZOO = ["alexnet", "bert", "gpt", "dlrm", "candle_uno", "inception",
        "resnext50", "xdl", "mlp"]


@pytest.mark.parametrize("name", _ZOO)
def test_co_search_off_never_constructs_pricer(name, monkeypatch):
    """The bit-identical OFF gate, enforced structurally: a sequential
    (co_search=False) search across every zoo topology must never
    instantiate a JointPricer — the joint machinery is provably not on
    the path, so the pre-PR trajectory cannot be perturbed.  (The
    value-level half — zoo strategies + sim costs bit-identical to the
    pre-PR tree — was verified against the seed source at PR time.)"""
    import bench_search
    from flexflow_tpu.search import comm_plan

    def _poisoned(*a, **k):
        raise AssertionError(
            "JointPricer constructed on a co_search=False run")

    monkeypatch.setattr(comm_plan, "JointPricer", _poisoned)
    spec = bench_search._model_specs()[name]
    cfg = ff.FFConfig(batch_size=spec["batch"], num_devices=8,
                      search_budget=4, cost_cache_file="")
    assert cfg.co_search is False
    g = spec["build"](cfg)
    bg, s = optimize_strategy(g.graph if hasattr(g, "graph") else g, cfg,
                              return_graph=True)
    assert s
    assert "comm_plan_serves" not in LAST_SEARCH_STATS


def test_co_search_off_is_deterministic():
    """Two fresh OFF-mode searches agree bit-for-bit (digest, view
    sequence, exact sim cost) — the regression surface the manual
    pre-PR comparison pinned."""

    def run():
        cfg = ff.FFConfig(batch_size=64, num_devices=8, search_budget=6,
                          cost_cache_file="")
        g = _bert_graph(cfg)
        bg, s = optimize_strategy(g, cfg, return_graph=True)
        views = [repr(s[n.guid]) for n in bg.topo_order()]
        cost = Simulator(cfg.machine_spec, num_devices=8).simulate(bg, s)
        return stable_graph_digest(bg), views, cost

    assert run() == run()


def test_search_result_keys_disjoint_between_modes(tmp_path):
    """A joint-mode persisted search result must never be served to a
    sequential run (and vice versa): the result key gains an
    extension-only co_search marker."""
    from flexflow_tpu.search.cost_cache import CostCache

    cfg_off = ff.FFConfig(batch_size=8, num_devices=8, search_budget=4)
    cfg_on = ff.FFConfig(batch_size=8, num_devices=8, search_budget=4,
                         co_search=True)
    g = _mlp_graph(cfg_off)
    assert (CostCache.search_key(g, cfg_off)
            != CostCache.search_key(g, cfg_on))


# ---------------------------------------------------------------------------
# the joint currency + never-worse property


def _joint_score(spec, n, g, s, cfg):
    from flexflow_tpu.search.comm_plan import JointPricer

    sim = Simulator(spec, num_devices=n)
    sim.cost.sync_precision = getattr(cfg, "sync_precision", "fp32")
    return JointPricer(cfg).price(sim, g, s)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_joint_never_worse_than_sequential(seed):
    """Property: on a randomized machine spec, the joint pipeline's
    result — scored in the joint currency — is never worse than the
    sequential pipeline's result scored the same way.  The sequential
    result is always in the joint search space (same substitutions,
    same DP), so a worse joint pick would be a search bug, not a
    modeling disagreement."""
    import dataclasses

    rng = np.random.default_rng(seed)
    base = ff.FFConfig(batch_size=64, num_devices=8).machine_spec
    spec = dataclasses.replace(
        base,
        ici_bandwidth=base.ici_bandwidth * float(rng.uniform(0.05, 1.0)),
        hbm_bandwidth=base.hbm_bandwidth * float(rng.uniform(0.5, 1.5)),
        peak_flops=base.peak_flops * float(rng.uniform(0.5, 2.0)),
    )

    def run(co):
        cfg = ff.FFConfig(batch_size=64, num_devices=8, search_budget=8,
                          machine_spec=spec, cost_cache_file="",
                          sync_precision="search", sync_schedule="search",
                          co_search=co)
        g = _bert_graph(cfg) if seed % 2 else _mlp_graph(cfg)
        bg, s = optimize_strategy(g, cfg, return_graph=True)
        return bg, s, cfg

    g_seq, s_seq, _ = run(False)
    g_j, s_j, cfg_j = run(True)
    c_seq = _joint_score(spec, 8, g_seq, s_seq, cfg_j)
    c_j = _joint_score(spec, 8, g_j, s_j, cfg_j)
    assert math.isfinite(c_j)
    assert c_j <= c_seq * (1.0 + 1e-9)


# ---------------------------------------------------------------------------
# comm-plan memo: serve vs re-search, and the persistent layer


def test_comm_plan_memo_serves_repeated_signatures():
    from flexflow_tpu.search.comm_plan import JointPricer, synced_signature

    cfg = ff.FFConfig(batch_size=64, num_devices=8,
                      sync_precision="search", sync_schedule="search",
                      co_search=True)
    g = _mlp_graph(cfg)
    s = data_parallel_strategy(g, 8)
    sim = Simulator(cfg.machine_spec, num_devices=8)
    sim.cost.sync_precision = "search"
    jp = JointPricer(cfg)
    c1 = jp.price(sim, g, s)
    assert jp.searches == 1 and jp.serves == 0
    c2 = jp.price(sim, g, s)
    assert jp.searches == 1 and jp.serves == 1
    assert c1 == c2
    # a different strategy with the SAME synced-group signature serves
    # too — the memo key is the signature, not the strategy object
    assert synced_signature(g, s) == synced_signature(g, dict(s))
    jp.price(sim, g, dict(s))
    assert jp.searches == 1 and jp.serves == 2


def test_comm_plan_persists_across_processes_via_cost_cache(tmp_path):
    """The comm_plans cost-cache layer: a plan searched once is served
    from disk by a FRESH pricer over a FRESH cache object."""
    from flexflow_tpu.search.comm_plan import JointPricer
    from flexflow_tpu.search.cost_cache import CostCache, cost_signature

    cfg = ff.FFConfig(batch_size=64, num_devices=8,
                      sync_precision="search", sync_schedule="search",
                      co_search=True)
    g = _mlp_graph(cfg)
    s = data_parallel_strategy(g, 8)
    sim = Simulator(cfg.machine_spec, num_devices=8)
    sim.cost.sync_precision = "search"
    path = str(tmp_path / "cc.json")
    cc = CostCache(path, cost_signature(sim.cost))
    jp = JointPricer(cfg, cost_cache=cc)
    c1 = jp.price(sim, g, s)
    assert jp.searches == 1
    assert cc.comm_plans  # persisted payload staged
    cc.save()

    cc2 = CostCache(path, cost_signature(sim.cost))
    jp2 = JointPricer(cfg, cost_cache=cc2)
    sim2 = Simulator(cfg.machine_spec, num_devices=8)
    sim2.cost.sync_precision = "search"
    c2 = jp2.price(sim2, g, s)
    assert jp2.searches == 0 and jp2.serves == 1
    assert cc2.comm_plan_hits == 1
    assert c1 == c2


def test_unknown_comm_schema_drops_layer_loudly(tmp_path, capsys):
    from flexflow_tpu.search.cost_cache import CostCache, cost_signature

    cfg = ff.FFConfig(batch_size=8, num_devices=8)
    sim = Simulator(cfg.machine_spec, num_devices=8)
    path = str(tmp_path / "cc.json")
    cc = CostCache(path, cost_signature(sim.cost))
    cc.put_comm_plan("ab" * 12, {"schedule": {}, "adopted": False,
                                 "pmap": {}, "zero": [], "credit": 0.0})
    cc.save()
    with open(path) as f:
        data = json.load(f)
    data["comm_schema"] = 99
    with open(path, "w") as f:
        json.dump(data, f)
    cc2 = CostCache(path, cost_signature(sim.cost))
    assert not cc2.comm_plans
    assert "unknown comm_schema" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# per-group optimizer-state sharding: SHD140/141, import gate, STR207


def _dp_cost_model(n=8):
    from flexflow_tpu.search.machine_model import CostModel

    cfg = ff.FFConfig(batch_size=8, num_devices=n)
    return CostModel(cfg.machine_spec, num_devices=n)


def _codes(findings):
    return {f.code for f in findings}


def test_lint_zero_map_legal_and_codes():
    from flexflow_tpu.analysis import errors_only, lint_zero_map

    cfg = ff.FFConfig(batch_size=64, num_devices=8)
    g = _mlp_graph(cfg)
    s = data_parallel_strategy(g, 8)
    cm = _dp_cost_model()
    # legal: big dense layers replicate under DP and their optimizer
    # state shards evenly
    assert lint_zero_map(g, s, ["cs_fc1", "cs_fc2"], cm) == []
    # empty map is trivially legal
    assert lint_zero_map(g, s, [], cm) == []
    # SHD140: unknown op / weightless op / duplicate entry
    assert "SHD140" in _codes(lint_zero_map(g, s, ["nope"], cm))
    relu = next(n for n in g.topo_order()
                if not getattr(n.op, "_weight_specs", ()))
    assert "SHD140" in _codes(
        lint_zero_map(g, s, [relu.op.name], cm))
    assert "SHD140" in _codes(
        lint_zero_map(g, s, ["cs_fc1", "cs_fc1"], cm))
    # SHD140: an op with NO replicated weight under the strategy (full
    # tensor-parallel view) has nothing to shard optimizer state over
    from flexflow_tpu.search.views import candidate_views

    fc1 = next(n for n in g.topo_order() if n.op.name == "cs_fc1")
    tp = dict(s)
    for mv in candidate_views(fc1.op, 8):
        # feature-split: the kernel shards over the devices, nothing
        # replicates, nothing syncs
        if mv.replica_degree == 1 and mv.dim_degrees[-1] == 8:
            tp[fc1.guid] = mv
            break
    else:
        pytest.skip("no pure-TP view for cs_fc1")
    assert "SHD140" in _codes(lint_zero_map(g, tp, ["cs_fc1"], cm))


def test_lint_zero_map_shd141_unachievable_factor():
    """An op whose weight replicates but whose optimizer state cannot
    shard (no evenly-divisible factor for the free devices) is SHD141:
    the credited update win would never be realized."""
    from flexflow_tpu.analysis import lint_zero_map

    cfg = ff.FFConfig(batch_size=8, num_devices=8)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 7], name="zl_x")
    m.dense(x, 5, name="zl_odd")  # 7x5 kernel: no factor of 8 divides
    g = m.graph
    s = data_parallel_strategy(g, 8)
    cm = _dp_cost_model()
    codes = _codes(lint_zero_map(g, s, ["zl_odd"], cm))
    assert codes == {"SHD141"}


def test_zero_groups_import_gate(tmp_path):
    """__meta__.zero_groups rides the strategy file: a legal map is
    adopted at compile, an illegal one raises at import."""
    from flexflow_tpu.search.strategy_io import attach_meta, export_strategy

    cfg = ff.FFConfig(batch_size=64, num_devices=8,
                      only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([64, 128], name="zg_x")
    t = m.dense(x, 512, activation="relu", name="zg_fc1")
    m.dense(t, 16, name="zg_head")
    s = data_parallel_strategy(m.graph, 8)
    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, s)
    attach_meta(p, zero_groups=["zg_fc1"])

    def compile_with(path):
        cfg2 = ff.FFConfig(batch_size=64, num_devices=8,
                           import_strategy_file=path)
        m2 = ff.FFModel(cfg2)
        x2 = m2.create_tensor([64, 128], name="zg_x")
        t2 = m2.dense(x2, 512, activation="relu", name="zg_fc1")
        m2.dense(t2, 16, name="zg_head")
        m2.compile(optimizer=ff.SGDOptimizer(),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return m2

    m_ok = compile_with(p)
    assert m_ok.zero_groups == ("zg_fc1",)

    # illegal: a weightless op name fails SHD140 at import
    bad = str(tmp_path / "bad.json")
    export_strategy(bad, m.graph, s)
    attach_meta(bad, zero_groups=["zg_x"])
    from flexflow_tpu.analysis import AnalysisError

    with pytest.raises(AnalysisError):
        compile_with(bad)


def test_fflint_zero_groups_str207(tmp_path):
    """Stdlib corruptions of __meta__.zero_groups: each exits 1 with
    STR207; the clean file exits 0."""
    from tools.fflint import main

    from flexflow_tpu.search.strategy_io import attach_meta, export_strategy

    cfg = ff.FFConfig(batch_size=64, num_devices=8,
                      only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([64, 128], name="sl_x")
    t = m.dense(x, 256, activation="relu", name="sl_fc1")
    m.dense(t, 16, name="sl_head")
    s = data_parallel_strategy(m.graph, 8)
    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, s)
    attach_meta(p, zero_groups=["sl_fc1"])
    assert main(["strategy", p]) == 0
    with open(p) as f:
        clean = json.load(f)

    def corrupted(mutate):
        data = json.loads(json.dumps(clean))
        mutate(data["__meta__"])
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump(data, f)
        return main(["strategy", bad])

    assert corrupted(lambda meta: meta.update(zero_groups="sl_fc1")) == 1
    assert corrupted(lambda meta: meta.update(zero_groups=[])) == 1
    assert corrupted(
        lambda meta: meta.update(zero_groups=["sl_fc1", "sl_fc1"])) == 1
    assert corrupted(
        lambda meta: meta.update(zero_groups=["not_in_file"])) == 1
    assert corrupted(lambda meta: meta.update(zero_groups=[7])) == 1


def test_fflint_cache_comm_plan_layer(tmp_path, capsys):
    """CCH407 (unknown comm_schema) and CCH408 (malformed rows) seeded
    corruptions of the persisted comm-plan memo layer."""
    from tools.fflint import main

    from flexflow_tpu.search.comm_plan import JointPricer
    from flexflow_tpu.search.cost_cache import CostCache, cost_signature

    cfg = ff.FFConfig(batch_size=64, num_devices=8,
                      sync_precision="search", sync_schedule="search",
                      co_search=True)
    g = _mlp_graph(cfg)
    s = data_parallel_strategy(g, 8)
    sim = Simulator(cfg.machine_spec, num_devices=8)
    sim.cost.sync_precision = "search"
    path = str(tmp_path / "cc.json")
    cc = CostCache(path, cost_signature(sim.cost))
    JointPricer(cfg, cost_cache=cc).price(sim, g, s)
    assert cc.comm_plans
    cc.save()
    assert main(["cache", path]) == 0
    with open(path) as f:
        clean = json.load(f)

    def corrupted(mutate):
        data = json.loads(json.dumps(clean))
        mutate(data)
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump(data, f)
        return main(["cache", bad])

    # CCH407: unknown comm_schema
    assert corrupted(lambda d: d.update(comm_schema=99)) == 1
    # CCH408 family
    key = next(iter(clean["comm_plans"]))
    assert corrupted(
        lambda d: d["comm_plans"].__setitem__(key, "nope")) == 1
    assert corrupted(
        lambda d: d["comm_plans"][key].pop("schedule")) == 1
    assert corrupted(
        lambda d: d["comm_plans"][key].update(adopted="yes")) == 1
    assert corrupted(
        lambda d: d["comm_plans"][key].update(pmap={"op": "fp8"})) == 1
    assert corrupted(
        lambda d: d["comm_plans"][key].update(zero=[3])) == 1
    assert corrupted(
        lambda d: d["comm_plans"][key].update(credit=-1.0)) == 1
    assert corrupted(
        lambda d: d["comm_plans"].__setitem__("zz", d["comm_plans"][key])
    ) == 1


# ---------------------------------------------------------------------------
# EF residual: persistent training-loop state


def _train_ef(sync_ef, steps=2, seed=0):
    cfg = ff.FFConfig(batch_size=32, num_devices=8,
                      only_data_parallel=True, compute_dtype="float32",
                      sync_precision="int8", sync_ef=sync_ef, seed=seed)
    m = ff.FFModel(cfg)
    x = m.create_tensor([32, 64])
    t = m.dense(x, 2048, activation="relu", name="fc1")
    t = m.dense(t, 8, name="head")
    m.compile(optimizer=ff.AdamOptimizer(alpha=1e-3),
              loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.default_rng(0)
    y = rng.integers(0, 8, 64).astype(np.int32)
    xd = rng.normal(size=(64, 64)).astype(np.float32)
    hist = m.fit(x=xd, y=y, epochs=steps, verbose=False)
    return m, hist[-1]["loss"]


def test_ef_residual_state_round_trip(mesh8, tmp_path):
    """sync_ef='auto' upgrades the int8 group to int8_ef and threads
    the residual as model state: created at init, advanced by the
    step, checkpoint round-tripped."""
    from flexflow_tpu.runtime.checkpoint import CheckpointManager

    m, loss = _train_ef("auto")
    assert m.sync_precision_map == {"fc1": "int8_ef"}
    key = "fc1/kernel/ef_residual"
    assert key in m.state
    res = np.asarray(m.state[key])
    # after a step the residual carries the (nonzero) quantization
    # error of the last sync
    assert float(np.max(np.abs(res))) > 0.0
    assert np.isfinite(loss)

    # checkpoint round trip: the residual is ordinary model state
    mgr = CheckpointManager(str(tmp_path), use_orbax=False)
    mgr.save(1, m)
    res_before = np.asarray(m.state[key]).copy()
    m.state[key] = m.state[key] * 0.0
    mgr.restore(m)
    np.testing.assert_array_equal(np.asarray(m.state[key]), res_before)

    # off keeps the plain int8 wire — no residual state anywhere
    m_off, _ = _train_ef("off")
    assert m_off.sync_precision_map == {"fc1": "int8"}
    assert not [k for k in m_off.state if k.endswith("ef_residual")]


def test_ef_close_to_fp32(mesh8):
    m_ef, l_ef = _train_ef("auto")
    cfg = ff.FFConfig(batch_size=32, num_devices=8,
                      only_data_parallel=True, compute_dtype="float32",
                      sync_precision="fp32", seed=0)
    m = ff.FFModel(cfg)
    x = m.create_tensor([32, 64])
    t = m.dense(x, 2048, activation="relu", name="fc1")
    t = m.dense(t, 8, name="head")
    m.compile(optimizer=ff.AdamOptimizer(alpha=1e-3),
              loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.default_rng(0)
    y = rng.integers(0, 8, 64).astype(np.int32)
    xd = rng.normal(size=(64, 64)).astype(np.float32)
    l32 = m.fit(x=xd, y=y, epochs=2, verbose=False)[-1]["loss"]
    assert np.isfinite(l_ef) and np.isclose(l32, l_ef, rtol=5e-3)


# ---------------------------------------------------------------------------
# per-op-type match seed index


def test_indexed_find_matches_identical_to_full_scan(monkeypatch):
    """For every anchor-typed xfer: the indexed scan returns the SAME
    match list as the unindexed scan, and skips land in the counter.
    The in-function oracle (FLEXFLOW_TPU_DELTA_CHECK) is armed so a
    bad anchor_types declaration asserts inside find_matches."""
    from flexflow_tpu.search import substitution as subst

    monkeypatch.setattr(subst, "DELTA_MATCH_CHECK", True)
    cfg = ff.FFConfig(batch_size=64, num_devices=8)
    g = _bert_graph(cfg)
    xfers = subst.generate_all_pcg_xfers(8)
    anchored = [x for x in xfers
                if getattr(x, "anchor_types", None) is not None]
    assert anchored, "factory xfers must declare anchor types"
    before = subst._INDEX_SKIPS.value
    for x in anchored:
        if not hasattr(x, "matcher"):
            # BatchEmbeddingsXfer declares anchor_types too (for the
            # index + proofgen) but is duck-typed without a per-node
            # matcher; its indexed scan is checked against the old
            # full scan below
            continue
        got = [n.guid for n in x.find_matches(g)]
        full = [n.guid for n in g.topo_order() if x.matcher(g, n)]
        assert got == full
    from flexflow_tpu.core.optype import OperatorType

    be = subst.BatchEmbeddingsXfer()
    groups = {}
    for n in g.topo_order():
        if n.op.op_type is OperatorType.EMBEDDING:
            groups.setdefault(n.op.signature(), []).append(n.guid)
    full_be = [{i: gu for i, gu in enumerate(gs)}
               for gs in groups.values() if len(gs) >= 2]
    assert be.find_matches(g) == full_be
    assert subst._INDEX_SKIPS.value > before


def test_search_perf_reports_index_skips():
    cfg = ff.FFConfig(batch_size=64, num_devices=8, search_budget=4,
                      cost_cache_file="")
    g = _mlp_graph(cfg)
    optimize_strategy(g, cfg, return_graph=True)
    assert LAST_SEARCH_STATS.get("match_index_skips", 0) > 0


# ---------------------------------------------------------------------------
# the co-searched result executes: search → compile wiring


def test_co_search_result_wires_zero_groups_into_compile():
    """An end-to-end co-searched strategy lands its per-group
    optimizer-sharding map on the compiled model (LAST_ZERO_GROUPS →
    model.zero_groups), linted on the way."""
    from flexflow_tpu.search import driver as drv

    cfg = ff.FFConfig(batch_size=64, num_devices=8, search_budget=6,
                      cost_cache_file="", sync_precision="search",
                      sync_schedule="search", co_search=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([64, 128], name="ew_x")
    t = m.dense(x, 512, activation="relu", name="ew_fc1")
    t = m.dense(t, 512, activation="relu", name="ew_fc2")
    m.dense(t, 16, name="ew_head")
    m.compile(optimizer=ff.SGDOptimizer(),
              loss_type="sparse_categorical_crossentropy", metrics=[])
    assert m.zero_groups == tuple(drv.LAST_ZERO_GROUPS)
    if m.zero_groups:  # the search chose to shard at least one group
        assert getattr(m.compiled, "zero_groups", ()) == m.zero_groups
