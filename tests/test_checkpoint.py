"""Checkpoint/resume round-trips (capability the reference lacks —
SURVEY.md §5 'Checkpoint / resume: minimal')."""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.runtime.checkpoint import CheckpointManager


def _make_model(seed=0):
    cfg = ff.FFConfig(batch_size=8, num_devices=1, only_data_parallel=True,
                      seed=seed)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16])
    h = m.dense(x, 32, activation="relu")
    out = m.dense(h, 4)
    m.compile(optimizer=ff.AdamOptimizer(alpha=1e-2),
              loss_type="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    return m


def _train_a_bit(m, steps=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(24, 16).astype(np.float32)
    y = rng.randint(0, 4, size=(24,)).astype(np.int32)
    m.fit(x, y, batch_size=8, epochs=steps, verbose=False)
    return x, y


@pytest.mark.parametrize("use_orbax", [False, True])
def test_save_restore_roundtrip(tmp_path, use_orbax):
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError:
        if use_orbax:
            pytest.skip("orbax not installed")
    m = _make_model()
    x, y = _train_a_bit(m)
    mgr = CheckpointManager(str(tmp_path), use_orbax=use_orbax)
    mgr.save(7, m)
    assert mgr.all_steps() == [7]

    # fresh model with different init; restore must reproduce weights
    m2 = _make_model(seed=123)
    before = m2.get_weight("dense_0")
    step = mgr.restore(m2)
    assert step == 7
    after = m2.get_weight("dense_0")
    assert not np.allclose(before, after)
    np.testing.assert_allclose(after, m.get_weight("dense_0"), rtol=1e-6)
    # optimizer slots restored too (Adam m/v are arrays in the state tree)
    import jax

    leaves1 = jax.tree.leaves(m.opt_state)
    leaves2 = jax.tree.leaves(m2.opt_state)
    assert len(leaves1) == len(leaves2)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_async_save_overlaps_and_roundtrips(tmp_path):
    """async_save=True: save() returns before the snapshot is on disk
    (host copy only — donation-safe), training continues meanwhile, and
    wait()/restore() join the background write.  The restored state
    must equal the state AT SAVE TIME, not the later-trained state."""
    m = _make_model()
    _train_a_bit(m, steps=2)
    saved_params = {op: {w: np.asarray(a) for w, a in ws.items()}
                    for op, ws in m.params.items()}
    mgr = CheckpointManager(str(tmp_path), async_save=True, use_orbax=False)
    mgr.save(7, m)
    _train_a_bit(m, steps=2, seed=9)  # train OVER the in-flight save
    mgr.wait()
    assert mgr.all_steps() == [7]
    m2 = _make_model(seed=1)
    step = mgr.restore(m2)
    assert step == 7
    for op, ws in saved_params.items():
        for w, a in ws.items():
            np.testing.assert_array_equal(a, np.asarray(m2.params[op][w]))
    # a second async save joins the first and supersedes it
    mgr.save(8, m)
    mgr.wait()
    assert mgr.latest_step() == 8


def test_resume_training_continues(tmp_path):
    m = _make_model()
    x, y = _train_a_bit(m, steps=2)
    mgr = CheckpointManager(str(tmp_path), use_orbax=False)
    mgr.save(2, m)

    m2 = _make_model(seed=9)
    mgr.restore(m2)
    # training continues without error and changes weights
    w0 = m2.get_weight("dense_1")
    m2.fit(x, y, batch_size=8, epochs=1, verbose=False)
    assert not np.allclose(w0, m2.get_weight("dense_1"))


def test_restore_before_first_step_multidevice(tmp_path):
    """Restoring into a freshly-compiled multi-device model must not pin
    optimizer slots to one device (they are uncommitted until step 1)."""
    import jax

    n = len(jax.devices())
    if n < 2:
        pytest.skip("needs multi-device mesh")

    def make():
        cfg = ff.FFConfig(batch_size=8, num_devices=n, only_data_parallel=True)
        m = ff.FFModel(cfg)
        x = m.create_tensor([8, 16])
        h = m.dense(x, 32, activation="relu")
        m.dense(h, 4)
        m.compile(optimizer=ff.AdamOptimizer(alpha=1e-2),
                  loss_type="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        return m

    m = make()
    x, y = _train_a_bit(m, steps=1)
    mgr = CheckpointManager(str(tmp_path), use_orbax=False)
    mgr.save(1, m)
    m2 = make()
    mgr.restore(m2)
    m2.fit(x, y, batch_size=8, epochs=1, verbose=False)  # must not raise


def test_retention_gc(tmp_path):
    m = _make_model()
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2, use_orbax=False)
    for s in (1, 2, 3, 4):
        mgr.save(s, m)
    assert mgr.all_steps() == [3, 4]
    assert mgr.latest_step() == 4


def test_shape_mismatch_rejected(tmp_path):
    m = _make_model()
    mgr = CheckpointManager(str(tmp_path), use_orbax=False)
    mgr.save(1, m)
    cfg = ff.FFConfig(batch_size=8, num_devices=1, only_data_parallel=True)
    m2 = ff.FFModel(cfg)
    x = m2.create_tensor([8, 16])
    m2.dense(x, 8)  # different architecture
    m2.compile(loss_type="mean_squared_error", metrics=["mean_squared_error"])
    with pytest.raises(Exception):
        mgr.restore(m2)


def test_fit_checkpoint_dir_and_resume(tmp_path):
    """fit(checkpoint_dir=...) snapshots each epoch; a new fit with
    resume=True restores the latest snapshot and continues from the
    NEXT epoch — interrupted training picks up where it left off."""
    d = str(tmp_path / "ckpt")
    rng = np.random.RandomState(0)
    x = rng.randn(24, 16).astype(np.float32)
    y = rng.randint(0, 4, size=(24,)).astype(np.int32)

    m1 = _make_model()
    m1.fit(x, y, batch_size=8, epochs=3, verbose=False, checkpoint_dir=d)
    mgr = CheckpointManager(d)
    assert mgr.latest_step() == 2  # epochs 0..2 saved (every=1)

    # fresh model, same topology: resume continues at epoch 3
    m2 = _make_model()
    hist = m2.fit(x, y, batch_size=8, epochs=5, verbose=False,
                  checkpoint_dir=d, resume=True)
    assert len(hist) == 2  # epochs 3 and 4 only
    assert mgr.latest_step() == 4

    # resume with everything already trained: no epochs run
    m3 = _make_model()
    hist3 = m3.fit(x, y, batch_size=8, epochs=5, verbose=False,
                   checkpoint_dir=d, resume=True)
    assert hist3 == []

    with pytest.raises(ValueError, match="checkpoint_dir"):
        m3.fit(x, y, batch_size=8, epochs=1, verbose=False, resume=True)


def test_keras_model_checkpoint_callback(tmp_path):
    from flexflow_tpu import keras

    d = str(tmp_path / "kc")
    model = keras.Sequential([
        keras.layers.Dense(16, activation="relu", input_shape=(8,)),
        keras.layers.Dense(4),
    ])
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"],
                  config=ff.FFConfig(batch_size=8, num_devices=1,
                                     only_data_parallel=True))
    rng = np.random.RandomState(1)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(16,)).astype(np.int32)
    model.fit(x, y, epochs=2,
              callbacks=[keras.callbacks.ModelCheckpoint(d)])
    assert CheckpointManager(d).latest_step() == 1

    # every > epochs: the final epoch is still snapshotted (train-end)
    d2 = str(tmp_path / "kc2")
    model.fit(x, y, epochs=2,
              callbacks=[keras.callbacks.ModelCheckpoint(d2, every=5)])
    assert CheckpointManager(d2).latest_step() == 1

    # the keras fit path forwards checkpoint kwargs to FFModel.fit
    d3 = str(tmp_path / "kc3")
    model.fit(x, y, epochs=2, checkpoint_dir=d3)
    h = model.fit(x, y, epochs=3, checkpoint_dir=d3, resume=True)
    assert len(h) == 1  # epoch 2 only


def test_truncated_newest_step_falls_back_to_complete(tmp_path):
    """Atomic-write satellite: a torn step_N (payload truncated behind
    the manifest — the kill-mid-write case) is DETECTED by the
    completeness check and restore falls back to the newest COMPLETE
    step instead of crashing mid-device-transfer."""
    import os
    import warnings

    m = _make_model()
    _train_a_bit(m)
    mgr = CheckpointManager(str(tmp_path), max_to_keep=5, use_orbax=False)
    mgr.save(1, m)
    w_at_1 = m.get_weight("dense_0")
    _train_a_bit(m, seed=5)
    mgr.save(2, m)
    # simulate the kill: step_2's payload is half-written
    npz = os.path.join(mgr._step_dir(2), "arrays.npz")
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)
    assert mgr.snapshot_complete(1) and not mgr.snapshot_complete(2)
    assert mgr.latest_step() == 2  # raw listing still sees it
    assert mgr.latest_complete_step() == 1

    m2 = _make_model(seed=9)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        step = mgr.restore(m2)
    assert step == 1
    assert any("truncated" in str(w.message) for w in caught)
    np.testing.assert_array_equal(w_at_1, m2.get_weight("dense_0"))

    # an explicitly-requested torn step still fails loudly
    with pytest.raises(Exception):
        mgr.restore(_make_model(), step=2)


def test_manifest_key_mismatch_is_incomplete(tmp_path):
    """A snapshot whose npz payload disagrees with its manifest (torn
    differently: arrays written for another tree shape) is incomplete."""
    import json
    import os

    m = _make_model()
    mgr = CheckpointManager(str(tmp_path), use_orbax=False)
    mgr.save(3, m)
    mf = os.path.join(mgr._step_dir(3), "manifest.json")
    with open(mf) as f:
        manifest = json.load(f)
    manifest["trees"]["params"].append("ghost/kernel")
    with open(mf, "w") as f:
        json.dump(manifest, f)
    assert not mgr.snapshot_complete(3)
    assert mgr.latest_complete_step() is None


def test_interrupted_publish_leaves_no_visible_step(tmp_path):
    """A crash BEFORE the atomic publish leaves only the .tmp dir,
    which the step listing ignores and the next retention pass
    reclaims."""
    import os

    m = _make_model()
    mgr = CheckpointManager(str(tmp_path), use_orbax=False)
    mgr.save(1, m)
    # a dead writer's leftovers
    os.makedirs(os.path.join(str(tmp_path), "step_9.tmp"))
    os.makedirs(os.path.join(str(tmp_path), "step_4.old"))
    assert mgr.all_steps() == [1]
    assert mgr.restore(_make_model(seed=3)) == 1
    mgr.save(2, m)  # publish triggers gc of the stray dirs
    assert not os.path.exists(os.path.join(str(tmp_path), "step_9.tmp"))
    assert not os.path.exists(os.path.join(str(tmp_path), "step_4.old"))


def test_resave_same_step_replaces_atomically(tmp_path):
    m = _make_model()
    _train_a_bit(m)
    mgr = CheckpointManager(str(tmp_path), use_orbax=False)
    mgr.save(1, m)
    _train_a_bit(m, seed=4)
    mgr.save(1, m)  # overwrite goes through the rename-aside swap
    assert mgr.all_steps() == [1] and mgr.snapshot_complete(1)
    m2 = _make_model(seed=2)
    mgr.restore(m2)
    np.testing.assert_array_equal(m.get_weight("dense_0"),
                                  m2.get_weight("dense_0"))


def test_resume_matches_uninterrupted_run(tmp_path):
    """Interrupt+resume must be EQUIVALENT to an uninterrupted run:
    the shuffle stream is fast-forwarded (a resumed epoch N sees the
    N-th permutation, not epoch 0's) and the dropout rng counter is
    restored, so final parameters match bit-for-bit."""
    import jax

    d = str(tmp_path / "eq")
    rng = np.random.RandomState(3)
    x = rng.randn(24, 16).astype(np.float32)
    y = rng.randint(0, 4, size=(24,)).astype(np.int32)

    straight = _make_model()
    straight.fit(x, y, batch_size=8, epochs=2, verbose=False)

    part1 = _make_model()
    part1.fit(x, y, batch_size=8, epochs=1, verbose=False, checkpoint_dir=d)
    part2 = _make_model()
    part2.fit(x, y, batch_size=8, epochs=2, verbose=False,
              checkpoint_dir=d, resume=True)

    a = jax.tree_util.tree_leaves(straight.params)
    b = jax.tree_util.tree_leaves(part2.params)
    for u, v in zip(a, b):
        np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                   rtol=0, atol=0)


def test_kill_between_rename_pair_recovers_old_copy(tmp_path):
    """Review fix: a kill between the rename-aside and the publish
    leaves the ONLY complete snapshot parked at step_N.old — the next
    manager recovers it instead of deleting it."""
    import os
    import shutil

    m = _make_model()
    _train_a_bit(m)
    mgr = CheckpointManager(str(tmp_path), use_orbax=False)
    mgr.save(5, m)
    w = m.get_weight("dense_0")
    # simulate the crash window: step_5 moved aside, publish never ran
    os.rename(mgr._step_dir(5), mgr._step_dir(5) + ".old")
    assert CheckpointManager(str(tmp_path)).all_steps() == [5]  # recovered
    m2 = _make_model(seed=4)
    mgr2 = CheckpointManager(str(tmp_path), use_orbax=False)
    assert mgr2.restore(m2) == 5
    np.testing.assert_array_equal(w, m2.get_weight("dense_0"))
    # an INCOMPLETE .old (superseded or torn) is reclaimed, not revived
    os.rename(mgr2._step_dir(5), mgr2._step_dir(5) + ".old")
    shutil.rmtree(os.path.join(mgr2._step_dir(5) + ".old"),
                  ignore_errors=False)
    os.makedirs(mgr2._step_dir(5) + ".old")  # empty = incomplete
    mgr3 = CheckpointManager(str(tmp_path))
    assert mgr3.all_steps() == []
    assert not os.path.exists(mgr3._step_dir(5) + ".old")
