"""KV memory as a first-class searched resource (ISSUE 18): radix
prefix sharing (copy-on-write page refcounts + a prefix trie in the
PageAllocator) and the searched KV-cache pool precision lane
(FFConfig.kv_precision, __meta__.kv, SHD168/SHD169, STR213).

Contract highlights:

* sharing is semantically invisible: requests over a shared system
  prompt, batched through a FIXED undersized pool, produce EXACTLY the
  tokens of serving each request alone — while fitting >= 2x the
  concurrent sequences the unshared pool could hold;
* preemption and deadline expiry compose with shared pages: evicting
  one owner only drops refcounts (the sibling's cache survives), and a
  preempted sequence's continued stream is token-identical;
* the fp32 pool IS the pre-PR decode path: no attr, no extra state,
  adoption is a no-op — and the default/train-objective artifacts
  (op signature, ServingSpec signature, cost-cache search keys) stay
  byte-identical with the lane off;
* the int8 pool honors the accuracy contract (bounded drift vs fp32,
  kernel and XLA fallback agreeing), and an illegal __meta__.kv fails
  both the import gate (SHD168/169) and fflint (STR213).
"""

import json
import sys

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.machine import MachineView

N_DEV = 8


def _trivial_strategy(graph):
    return {
        n.guid: (n.op.fixed_machine_view()
                 or MachineView.trivial(n.op.output_shapes[0].ndim))
        for n in graph.topo_order()
    }


SYS_PROMPT = list(range(10, 26))  # 16 tokens = 4 full pages of 4


def _sharing_model(page_size=4, pages_per_seq=8, batch=4):
    from flexflow_tpu.models import build_gpt_decode

    kw = dict(vocab=128, num_layers=1, hidden=32, num_heads=2,
              ff_dim=32, page_size=page_size,
              pages_per_seq=pages_per_seq)
    cfg = ff.FFConfig(batch_size=batch, num_devices=1,
                      cost_cache_file="")
    m = build_gpt_decode(cfg, **kw)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              comp_mode="inference",
              strategy=_trivial_strategy(m.graph))
    return m


def _run(step, reqs, *, sharing, num_pages, max_seqs=4, page_size=4,
         pages_per_seq=8, submit_later=()):
    """Drive the executor to completion, tracking peak concurrency.
    ``submit_later`` entries are (frame, [requests]) injections."""
    from flexflow_tpu.runtime.decode import ContinuousBatchingExecutor

    # the chunked prefill lane is part of the sharing design: a
    # registrar's pages are published at admission (cached = len-1),
    # so siblings admitted in the SAME frame already claim them
    ex = ContinuousBatchingExecutor(
        step, max_seqs=max_seqs, page_size=page_size,
        pages_per_seq=pages_per_seq, num_pages=num_pages,
        prefill_fn=getattr(step, "prefill", None),
        prefill_chunk=page_size,
        prefix_sharing=sharing,
        copy_page_fn=step.copy_page if sharing else None)
    ex.submit(reqs)
    later = sorted(submit_later)
    peak = 0
    while ex.queue or any(s is not None for s in ex.slots) or later:
        assert ex.frame < 500, "kv sharing test run stuck"
        while later and later[0][0] <= ex.frame:
            ex.submit(later.pop(0)[1])
        ex.step()
        peak = max(peak, sum(s is not None for s in ex.slots))
    return ex, dict(ex.finished), peak


# ---------------------------------------------------------------------------
# PageAllocator: refcounts, the trie, reserve-on-divergence
# ---------------------------------------------------------------------------
def test_page_allocator_refcount_cow_trie():
    from flexflow_tpu.runtime.decode import PageAllocator

    pa = PageAllocator(8)
    pages = pa.alloc(3)
    tokens = list(range(100, 110))  # 2.5 pages of 4
    pa.register_prefix(tokens, 4, pages, cached=9)  # 2 full pages
    # full-page + mid-page lookup against a sibling prompt
    got, matched, partial = pa.lookup_prefix(tokens[:8], 4)
    assert got == pages[:2] and matched == 8 and partial is None
    sibling = tokens[:9] + [999, 998]
    got, matched, partial = pa.lookup_prefix(sibling, 4)
    assert got == pages[:2] and matched == 8
    assert partial is None  # page 2 (tokens 8..) was never registered
    pa.register_prefix(tokens + [55, 66], 4, pages, cached=12)
    got, matched, partial = pa.lookup_prefix(sibling, 4)
    assert partial == (pages[2], 1)  # agrees on one token mid-page
    # share raises refcounts; free only releases at zero
    pa.share(pages[:2])
    assert pa.refcount(pages[0]) == 2
    # reserve-on-divergence: a SHARED page (refcount 2) at/after the
    # write point must fail the admission assert
    with pytest.raises(AssertionError):
        pa.assert_divergence_reserved(pages[:2], 0)
    pa.assert_divergence_reserved(pages[:2], 2)
    pa.free(pages)
    assert pa.refcount(pages[0]) == 1 and pa.refcount(pages[2]) == 0
    # the freed page's trie entry is gone (its bytes will be reused)
    assert pa.lookup_prefix(sibling, 4)[2] is None
    # stale-hit guard: share() of a dead page is a loud failure
    pa.free([pages[0], pages[1]])
    with pytest.raises(AssertionError):
        pa.share([pages[0]])


# ---------------------------------------------------------------------------
# measured sharing: concurrency win + token identity (the tentpole)
# ---------------------------------------------------------------------------
def test_prefix_sharing_concurrency_and_token_identity():
    """At a FIXED 21-page pool the unshared executor fits 2 concurrent
    sequences; with radix sharing the same pool holds 4 (>= 2x), the
    mid-page divergent request exercises copy-on-write, and every
    request's tokens are EXACTLY those of serving it alone."""
    from flexflow_tpu.runtime.decode import (
        DecodeRequest,
        compiled_decode_step,
    )

    m = _sharing_model()
    step = compiled_decode_step(m, prefill_chunk=4)

    def reqs():
        return [
            # r0 registers sys + its page-4 chunk [100,101,102,103]
            DecodeRequest(rid="r0", prompt=SYS_PROMPT + [100, 101, 102,
                                                         103, 104, 105],
                          max_new_tokens=8),
            DecodeRequest(rid="r1", prompt=SYS_PROMPT + [30, 31],
                          max_new_tokens=2),
            DecodeRequest(rid="r2", prompt=SYS_PROMPT + [40, 41],
                          max_new_tokens=2),
            DecodeRequest(rid="r3", prompt=SYS_PROMPT + [50, 52],
                          max_new_tokens=2),
            # rc diverges MID-page: agrees with r0's page-4 chunk on 2
            # tokens -> claimed via copy-on-write at admission
            DecodeRequest(rid="rc", prompt=SYS_PROMPT + [100, 101, 110],
                          max_new_tokens=2),
        ]

    pool = 21  # 1 scratch + 2 full 8-page allotments + change
    _, out_off, peak_off = _run(step, reqs(), sharing=False,
                                num_pages=pool)
    ex, out_on, peak_on = _run(step, reqs(), sharing=True,
                               num_pages=pool)
    solo = {}
    for r in reqs():
        _, one, _ = _run(step, [r], sharing=False, num_pages=0)
        solo.update(one)

    assert out_off == solo and out_on == solo  # semantically invisible
    assert peak_off == 2
    assert peak_on >= 2 * peak_off  # the fixed-pool concurrency win
    s = ex.summary()
    assert s["prefix_hits"] >= 4  # r1..r3 + rc (l0 registers, no hit)
    assert s["shared_pages"] >= 12 and s["prefix_tokens"] >= 48
    assert s["cow_copies"] >= 1  # rc's mid-page divergence
    assert s["private_pages"] == (ex.total_admitted * 8
                                  - s["shared_pages"])
    # pool fully drained at the end: every refcount returned to zero
    assert ex.allocator.free_pages == pool - 1  # scratch still held
    # extension-only summary: the roll-up keys never leak when off
    ex_off, _, _ = _run(step, reqs()[:2], sharing=False, num_pages=0)
    assert "prefix_hits" not in ex_off.summary()


def test_preemption_and_expiry_with_shared_pages():
    """Preemption + deadline expiry composed with shared pages: the
    victim's eviction only drops refcounts (the registrar's cache
    survives for the high-priority claimant), the expired request
    frees nothing it never held, and the preempted stream continues
    token-identically after re-admission."""
    from flexflow_tpu.runtime.decode import (
        DecodeRequest,
        compiled_decode_step,
    )

    m = _sharing_model(pages_per_seq=6, batch=2)
    step = compiled_decode_step(m, prefill_chunk=4)
    l0 = DecodeRequest(rid="l0", prompt=SYS_PROMPT + [100, 101, 102,
                                                      103],
                       max_new_tokens=4)
    l1 = DecodeRequest(rid="l1", prompt=SYS_PROMPT + [30, 31],
                       max_new_tokens=4)
    e = DecodeRequest(rid="e", prompt=[1, 2], max_new_tokens=2,
                      deadline_frames=1)
    h = DecodeRequest(rid="h", prompt=SYS_PROMPT + [60, 61],
                      max_new_tokens=2, priority=5)

    pool = 13
    ex, out, _ = _run(step, [l0, l1, e], sharing=True, num_pages=pool,
                      max_seqs=2, pages_per_seq=6,
                      submit_later=[(1, [h])])
    assert ex.total_preempted == 1  # h evicted the shared claimant l1
    assert ex.total_expired == 1 and "e" in ex.expired
    assert set(out) == {"l0", "l1", "h"}
    solo = {}
    for r in (l0, l1, h):
        _, one, _ = _run(step, [DecodeRequest(
            rid=r.rid, prompt=list(r.prompt),
            max_new_tokens=r.max_new_tokens)],
            sharing=False, num_pages=0, max_seqs=2, pages_per_seq=6)
        solo.update(one)
    assert out == solo  # incl. l1's continued stream across preemption
    assert ex.summary()["prefix_hits"] >= 2  # l1 and h both claimed
    # every page returned: refcounts never freed a live sibling's page
    assert ex.allocator.free_pages == pool - 1


# ---------------------------------------------------------------------------
# pool precision: extension-only defaults + the accuracy contract
# ---------------------------------------------------------------------------
def test_fp32_pool_is_the_pre_pr_decode_path():
    """kv_dtype="fp32" adds NO attr, NO extra state and NO signature
    drift, and dtype adoption with fp32 is an exact no-op — the
    default pool is byte-identical to the tree before the lane."""
    from flexflow_tpu.model import _adopt_kv_dtype
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.ops.decode_attention import DecodeAttentionOp

    cfg = ff.FFConfig(batch_size=4, num_devices=1, cost_cache_file="")
    m = build_gpt_decode(cfg, vocab=64, num_layers=1, hidden=32,
                         num_heads=2, ff_dim=32, page_size=4,
                         pages_per_seq=4)
    ops = [n.op for n in m.graph.topo_order()
           if isinstance(n.op, DecodeAttentionOp)]
    assert ops and all("kv_dtype" not in op.attrs for op in ops)
    assert all(op.kv_dtype == "fp32" for op in ops)
    specs = {op.name: op.state_specs() for op in ops}
    assert all("k_scale" not in json.dumps(str(s))
               for s in specs.values())
    nodes_before = {g: n for g, n in m.graph.nodes.items()}
    _adopt_kv_dtype(m.graph, "fp32")  # no-op by contract
    _adopt_kv_dtype(m.graph, None)
    assert all(m.graph.nodes[g] is n for g, n in nodes_before.items())
    # int8 adoption DOES retype (sanity that the no-op above is real)
    _adopt_kv_dtype(m.graph, "int8")
    ops2 = [n.op for n in m.graph.topo_order()
            if isinstance(n.op, DecodeAttentionOp)]
    assert all(op.attrs.get("kv_dtype") == "int8" for op in ops2)


def test_int8_accuracy_contract_and_kernel_parity():
    """The EQuARX-style contract the searched int8 pool rides on:
    per-token symmetric quantization keeps decode attention within a
    bounded drift of the fp32 pool, and the quant Pallas kernel agrees
    with its XLA fallback to float tolerance."""
    import jax.numpy as jnp

    from flexflow_tpu.kernels.ragged_paged_attention import (
        _xla_ragged_paged_quant,
        ragged_paged_attention,
        ragged_paged_attention_quant,
    )
    from flexflow_tpu.ops.decode_attention import _quantize_kv

    rng = np.random.default_rng(11)
    P, ps, H, D, B, pps = 16, 8, 4, 16, 4, 4
    k = jnp.asarray(rng.normal(size=(P, ps, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(P, ps, H, D)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    table = jnp.asarray(
        rng.permutation(P)[:B * pps].reshape(B, pps), jnp.int32)
    lens = jnp.asarray(rng.integers(ps, ps * pps, size=B), jnp.int32)

    ref = ragged_paged_attention(q, k, v, table, lens)
    kq, ks = _quantize_kv(k)
    vq, vs = _quantize_kv(v)
    assert kq.dtype == jnp.int8 and ks.shape == (P, ps)
    got = ragged_paged_attention_quant(q, kq, vq, ks, vs, table, lens)
    assert float(jnp.max(jnp.abs(got - ref))) < 0.05  # the contract
    xla = _xla_ragged_paged_quant(q, kq, vq, ks, vs, table, lens,
                                  1.0 / np.sqrt(D))
    assert float(jnp.max(jnp.abs(got - xla))) < 1e-5
    # bf16 pool: strictly tighter than int8 on the same pages
    bf = ragged_paged_attention(
        q, k.astype(jnp.bfloat16).astype(jnp.float32),
        v.astype(jnp.bfloat16).astype(jnp.float32), table, lens)
    assert float(jnp.max(jnp.abs(bf - ref))) < 0.05


def test_kv_off_keys_and_signatures_byte_identical():
    """With the lane off, every persisted identity is byte-identical
    to the pre-lane tree: train-objective search keys ignore the kv
    knobs entirely, serve keys only extend when armed, and the
    ServingSpec signature only grows a ("shared", n) element when
    sharing is set."""
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.search.cost_cache import CostCache
    from flexflow_tpu.search.serving import ServingSpec

    kw = dict(vocab=64, num_layers=1, hidden=32, num_heads=2,
              ff_dim=32, page_size=4, pages_per_seq=4)
    base = dict(batch_size=4, num_devices=N_DEV, cost_cache_file="")
    m = build_gpt_decode(ff.FFConfig(**base), **kw)

    # train objective: the kv knobs are serve-only — keys CANNOT move
    k_train = CostCache.search_key(m.graph, ff.FFConfig(**base))
    k_train_kv = CostCache.search_key(m.graph, ff.FFConfig(
        **base, kv_precision="search", serve_shared_prefix_pages=3))
    assert k_train == k_train_kv

    # serve objective: defaults stay put, arming the lane re-keys
    k_serve = CostCache.search_key(
        m.graph, ff.FFConfig(**base, objective="serve"))
    assert k_serve == CostCache.search_key(m.graph, ff.FFConfig(
        **base, objective="serve", kv_precision="off",
        serve_shared_prefix_pages=0))
    assert k_serve != CostCache.search_key(m.graph, ff.FFConfig(
        **base, objective="serve", kv_precision="search"))
    assert k_serve != CostCache.search_key(m.graph, ff.FFConfig(
        **base, objective="serve", serve_shared_prefix_pages=2))

    spec = ServingSpec(max_seqs=8, page_size=4, pages_per_seq=4)
    shared = ServingSpec(max_seqs=8, page_size=4, pages_per_seq=4,
                         shared_prefix_pages=2)
    assert "shared" not in spec.signature()
    assert shared.signature()[-2:] == ("shared", 2)
    # the residency discount: s of pps pages held once instead of
    # max_seqs times
    assert spec.shared_residency_factor() == 1.0
    assert shared.shared_residency_factor() == (8 * 2 + 2) / (8 * 4)


# ---------------------------------------------------------------------------
# __meta__.kv: digest-gated persistence, import re-lint, STR213
# ---------------------------------------------------------------------------
def test_kv_meta_roundtrip_and_corrupt_import(tmp_path):
    """compile(objective=serve, kv_precision=search) persists
    __meta__.kv behind the digest gate; import re-lints (SHD168/169)
    BEFORE adopting the dtype onto the decode ops, so a corrupted
    artifact fails loudly and a clean one reproduces the searched
    pool."""
    from flexflow_tpu.analysis import AnalysisError
    from flexflow_tpu.models import GPT_DECODE_KW, build_gpt_decode
    from flexflow_tpu.ops.decode_attention import DecodeAttentionOp
    from flexflow_tpu.search.strategy_io import read_meta

    path = str(tmp_path / "kv_strategy.json")
    cfg = ff.FFConfig(batch_size=8, num_devices=N_DEV, search_budget=0,
                      objective="serve", cost_cache_file="",
                      kv_precision="search",
                      serve_shared_prefix_pages=2,
                      export_strategy_file=path)
    m = build_gpt_decode(cfg, **GPT_DECODE_KW)
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              comp_mode="inference")
    meta = read_meta(path)
    kv = meta.get("kv")
    assert kv and kv["searched"] and kv["shared_prefix_pages"] == 2
    assert kv["dtype"] in ("fp32", "bf16", "int8")
    assert set(kv["predicted_p99_step_ms"]) == {"fp32", "bf16", "int8"}

    # clean import: digest gate passes, the dtype is adopted
    cfg2 = ff.FFConfig(batch_size=8, num_devices=N_DEV,
                       import_strategy_file=path, cost_cache_file="")
    m2 = build_gpt_decode(cfg2, **GPT_DECODE_KW)
    m2.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
               comp_mode="inference")
    ops = [n.op for n in m2.graph.topo_order()
           if isinstance(n.op, DecodeAttentionOp)]
    want = None if kv["dtype"] == "fp32" else kv["dtype"]
    assert all(op.attrs.get("kv_dtype") == want for op in ops)

    # corrupt scale layout -> SHD169 refuses the import
    def corrupt(name, mutate):
        data = json.load(open(path))
        mutate(data["__meta__"]["kv"])
        bad = str(tmp_path / name)
        json.dump(data, open(bad, "w"))
        cfgx = ff.FFConfig(batch_size=8, num_devices=N_DEV,
                           import_strategy_file=bad,
                           cost_cache_file="")
        mx = build_gpt_decode(cfgx, **GPT_DECODE_KW)
        with pytest.raises(AnalysisError):
            mx.compile(loss_type="sparse_categorical_crossentropy",
                       metrics=[], comp_mode="inference")

    corrupt("bad_layout.json",
            lambda kv: kv.update(scale_layout="per_tensor",
                                 dtype="int8"))
    corrupt("bad_shared.json",
            lambda kv: kv.update(shared_prefix_pages=999))
    corrupt("bad_factor.json",
            lambda kv: kv.update(shared_residency_factor=0.1))


def test_lint_kv_shd168_shd169():
    from flexflow_tpu.analysis import lint_kv
    from flexflow_tpu.model import _adopt_kv_dtype
    from flexflow_tpu.models import build_gpt_decode
    from flexflow_tpu.search.serving import ServingSpec

    cfg = ff.FFConfig(batch_size=4, num_devices=1, cost_cache_file="")
    m = build_gpt_decode(cfg, vocab=64, num_layers=1, hidden=32,
                         num_heads=2, ff_dim=32, page_size=4,
                         pages_per_seq=4)
    s = _trivial_strategy(m.graph)
    spec = ServingSpec(max_seqs=4, page_size=4, pages_per_seq=4,
                       shared_prefix_pages=2)
    good = {"dtype": "int8", "searched": True,
            "scale_layout": "page_slot", "shared_prefix_pages": 2,
            "shared_residency_factor": (4 * 2 + 2) / (4 * 4)}
    assert lint_kv(m.graph, s, good, serving=spec) == []
    codes = lambda meta, **kw: {  # noqa: E731
        f.code for f in lint_kv(m.graph, s, meta, **kw)}
    assert "SHD169" in codes({**good, "dtype": "fp4"}, serving=spec)
    assert "SHD169" in codes({**good, "scale_layout": "none"},
                             serving=spec)
    assert "SHD169" in codes({**good, "dtype": "fp32"}, serving=spec)
    assert "SHD168" in codes({**good, "shared_prefix_pages": 4},
                             serving=spec)
    assert "SHD168" in codes(
        {**good, "shared_residency_factor": 0.2}, serving=spec)
    assert "SHD168" in codes({**good, "shared_prefix_pages": 1},
                             serving=spec)  # disagrees with the spec
    assert "SHD169" in codes("not-a-mapping", serving=spec)
    # post-adoption coherence: ops carrying a DIFFERENT dtype than the
    # meta is a lie about the pool
    _adopt_kv_dtype(m.graph, "bf16")
    assert "SHD169" in codes(good, serving=spec)


def test_str213_kv_meta_lint(tmp_path):
    """fflint strategy catches seeded __meta__.kv corruptions
    stdlib-only (the pre-commit gate's view of the artifact)."""
    sys.path.insert(0, "tools")
    try:
        from fflint import lint_strategy_file
    finally:
        sys.path.pop(0)

    good = {
        "graph_digest": "d" * 32,
        "serving": {"objective": "serve", "max_seqs": 8,
                    "page_size": 16, "pages_per_seq": 4,
                    "quantile": 0.99, "p99_budget_ms": 0.0,
                    "predicted_p99_step_ms": 0.05,
                    "kv_bytes_per_device": 2.1e6},
        "kv": {"dtype": "int8", "searched": True,
               "scale_layout": "page_slot", "shared_prefix_pages": 2,
               "shared_residency_factor": (8 * 2 + 2) / (8 * 4),
               "predicted_p99_step_ms": {"fp32": 0.06, "bf16": 0.055,
                                         "int8": 0.05},
               "kv_bytes_per_device": 5.25e5},
    }
    base = {"lm_head": {"dims": [8, 1, 1], "replica": 1, "start": 0}}

    def write(meta):
        p = tmp_path / "strategy.json"
        p.write_text(json.dumps({**base, "__meta__": meta}))
        return str(p)

    assert not [f for f in lint_strategy_file(write(good))
                if f[1] == "STR213"]

    def mut(**kw):
        return {**good, "kv": {**json.loads(json.dumps(good["kv"])),
                               **kw}}

    corruptions = [
        ("not-an-object", {**good, "kv": [1]}),
        ("unknown dtype", mut(dtype="fp4")),
        ("int8 without page_slot scales", mut(scale_layout="none")),
        ("fp32 with scales", mut(dtype="fp32")),
        ("non-bool searched", mut(searched="yes")),
        ("negative shared pages", mut(shared_prefix_pages=-1)),
        ("shared >= pages_per_seq", mut(shared_prefix_pages=4)),
        ("factor vs refcount arithmetic", mut(
            shared_residency_factor=0.9)),
        ("factor != 1 with sharing off", mut(
            shared_prefix_pages=0, shared_residency_factor=0.5)),
        ("nan priced p99", mut(predicted_p99_step_ms={
            "fp32": 0.06, "bf16": 0.055, "int8": float("nan")})),
        ("chosen dtype unpriced", mut(predicted_p99_step_ms={
            "fp32": 0.06})),
        ("negative pool bytes", mut(kv_bytes_per_device=-1.0)),
    ]
    for label, meta in corruptions:
        found = [f for f in lint_strategy_file(write(meta))
                 if f[1] == "STR213" and f[0] == "error"]
        assert found, f"corruption {label!r} not caught by STR213"


def test_benchdiff_learns_kv_directions():
    """The bench guard judges kv metrics in the right direction —
    notably kv_shared_bytes, whose "_s" substring the latency
    heuristic would otherwise read as lower-is-better."""
    sys.path.insert(0, "tools")
    try:
        from benchdiff import compare, direction
    finally:
        sys.path.pop(0)

    assert direction("kv_sweep.measured_sharing.kv_shared_bytes") == "up"
    assert direction("a.max_concurrent") == "up"
    assert direction("a.prefix_hits") == "up"
    assert direction("a.shared_pages") == "up"
    assert direction("kv_sweep.kv_pool_bytes") == "down"
    assert direction("a.kv_bytes_per_device") == "down"
    assert direction("a.cow_copies") == "down"
    assert direction("a.private_pages") == "down"
    # less sharing past tolerance IS a regression now
    regs, compared = compare({"x.kv_shared_bytes": 10.0},
                             {"x.kv_shared_bytes": 100.0}, 0.25)
    assert compared == 1 and regs and regs[0][4] == "lower"
