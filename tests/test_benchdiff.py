"""tools/benchdiff.py — the opt-in bench-regression gate (ISSUE 17
satellite): fresh BENCH_SEARCH.json vs the blessed BENCH_LASTGOOD.json,
non-zero exit only on a MEASURED regression past the tolerance band."""

import json
import os
import subprocess
import sys

BENCHDIFF = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools", "benchdiff.py")


def _run(*args):
    return subprocess.run([sys.executable, BENCHDIFF, *args],
                          capture_output=True, text=True)


def _write(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f)
    return str(path)


def test_direction_heuristics():
    from importlib import util

    spec = util.spec_from_file_location("benchdiff", BENCHDIFF)
    mod = util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.direction("fleet_sweep.ttft_p99_s") == "down"
    assert mod.direction("models.gpt.throughput") == "up"
    assert mod.direction("traced_serve.spans") is None  # informational
    # legacy single-headline shape maps value -> metric-named key
    flat = mod.extract({"metric": "transformer_train_throughput",
                        "value": 2961.0, "unit": "samples/s",
                        "mfu": 0.478})
    assert flat["transformer_train_throughput"] == 2961.0
    assert flat["transformer_train_throughput.mfu"] == 0.478


def test_check_regression_and_tolerance(tmp_path):
    base = _write(tmp_path / "base.json",
                  {"metrics": {"serve.ttft_p99_s": 1.0,
                               "models.x.throughput": 100.0}})
    bad = _write(tmp_path / "bad.json",
                 {"serve": {"ttft_p99_s": 1.5},
                  "models": {"x": {"throughput": 60.0}}})
    proc = _run("check", "--fresh", bad, "--lastgood", base)
    assert proc.returncode == 2
    assert "ttft_p99_s" in proc.stdout and "throughput" in proc.stdout
    ok = _write(tmp_path / "ok.json",
                {"serve": {"ttft_p99_s": 1.1},
                 "models": {"x": {"throughput": 95.0}}})
    assert _run("check", "--fresh", ok, "--lastgood", base).returncode == 0
    # a loose band blesses the same move (the up-direction band is the
    # reciprocal ratio: 0.60x clears 1/(1+0.7) ~ 0.588)
    assert _run("check", "--fresh", bad, "--lastgood", base,
                "--tolerance", "0.7").returncode == 0


def test_check_refuses_only_on_measurement(tmp_path):
    """Missing files, no metric overlap, and informational-only drift
    all exit 0 — a gate that blocks on shape drift gets disabled."""
    base = _write(tmp_path / "base.json",
                  {"metrics": {"a.spans": 5, "a.completed": 3}})
    fresh = _write(tmp_path / "fresh.json",
                   {"a": {"spans": 99, "completed": 1}})
    assert _run("check", "--fresh", fresh,
                "--lastgood", base).returncode == 0
    assert _run("check", "--fresh", str(tmp_path / "nope.json"),
                "--lastgood", base).returncode == 0


def test_snapshot_blesses_and_keeps_legacy_keys(tmp_path):
    fresh = _write(tmp_path / "fresh.json",
                   {"serve": {"ttft_p99_s": 1.5}})
    last = _write(tmp_path / "last.json",
                  {"metric": "transformer_train_throughput",
                   "value": 2961.0, "unit": "samples/s"})
    assert _run("snapshot", "--fresh", fresh,
                "--lastgood", last).returncode == 0
    doc = json.load(open(last))
    assert doc["metric"] == "transformer_train_throughput"  # legacy
    assert doc["metrics"] == {"serve.ttft_p99_s": 1.5}
    # the blessed snapshot now passes the gate
    assert _run("check", "--fresh", fresh,
                "--lastgood", last).returncode == 0
