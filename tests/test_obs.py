"""Unified telemetry (flexflow_tpu/obs): event bus, metrics registry,
Chrome-trace export, drift reporting — plus the satellites: lazy
RecursiveLogger gating, StepProfiler compile-step honesty, and
measure_operator_cost declining unmeasurable ops.

The tier-1 smoke here is the acceptance gate: a tiny search+fit with
telemetry on must emit schema-valid JSONL only, and
``tools/ffobs.py report`` must render it with exit code 0.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.obs.drift import build_drift_report
from flexflow_tpu.obs.events import BUS, EventBus, validate_event
from flexflow_tpu.obs.metrics import METRICS, MetricsRegistry
from flexflow_tpu.runtime.profiler import StepProfiler, measure_operator_cost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _bus_teardown():
    yield
    BUS.close()


def _blobs(n=64, dim=64, classes=16, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, dim)).astype(np.float32),
            rng.integers(0, classes, size=(n,)).astype(np.int32))


# ---------------------------------------------------------------------------
# event bus
def test_event_bus_off_by_default_and_cheap():
    bus = EventBus()
    assert not bus.enabled
    t0 = time.perf_counter()
    for _ in range(100_000):
        bus.emit("search.log", msg="x")
    elapsed = time.perf_counter() - t0
    # one attribute check per call: 100k disabled emits in well under a
    # second even on a loaded CI host
    assert elapsed < 1.0, f"disabled emit too slow: {elapsed:.3f}s"


def test_decode_request_spans_one_bus_check_per_frame(monkeypatch):
    """The off-by-default contract on the decode hot path: with
    FLEXFLOW_TPU_OBS unset, request-span instrumentation must cost
    exactly one ``BUS.enabled`` read per frame (plus one per submit
    batch and one at run end) — no per-slot stamps, no histogram
    traffic, no lifecycle records."""
    from flexflow_tpu.runtime import decode as decode_mod
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
    )

    class CountingBus:
        def __init__(self):
            self.reads = 0

        @property
        def enabled(self):
            self.reads += 1
            return False

        def emit(self, *a, **k):  # pragma: no cover — enabled is False
            raise AssertionError("emit while disabled")

    bus = CountingBus()
    monkeypatch.setattr(decode_mod, "BUS", bus)

    def step(ids, table, lens):
        b = np.asarray(ids).shape[0]
        logits = np.zeros((b, 1, 7), np.float32)
        logits[:, 0, 3] = 1.0
        return logits

    ex = ContinuousBatchingExecutor(step, max_seqs=2, page_size=4,
                                    pages_per_seq=2)
    ex.run([DecodeRequest(rid=f"r{i}", prompt=[1, 2], max_new_tokens=2)
            for i in range(3)], max_frames=50)
    frames = ex.frame
    # one read per frame + one per submit batch + one at run end
    assert bus.reads <= frames + 2, (bus.reads, frames)
    # and none of the span machinery ran
    assert ex.request_records == []
    assert ex.queue == []
    assert all(s is None for s in ex.slots)


def test_event_bus_jsonl_sink_and_schema(tmp_path):
    bus = EventBus()
    path = str(tmp_path / "log.jsonl")
    bus.configure(path)
    bus.emit("search.begin", nodes=3, devices=8)
    bus.emit("search.substitution", xfer="t", action="pushed", est_s=0.1)
    bus.close()
    lines = [json.loads(x) for x in open(path)]
    assert [e["kind"] for e in lines] == [
        "obs.meta", "search.begin", "search.substitution"]
    for e in lines:
        assert validate_event(e) == []


def test_validate_event_rejects_bad_events():
    assert validate_event({"kind": "search.begin"})  # no ts, no fields
    assert validate_event({"ts": 1.0, "kind": "nope.unknown"})
    assert validate_event(
        {"ts": 1.0, "kind": "search.substitution", "xfer": "t",
         "action": "exploded"})  # action outside the enum
    assert validate_event(
        {"ts": 1.0, "kind": "search.begin", "nodes": 1, "devices": 8}) == []


def test_metrics_registry_reset_keeps_objects():
    reg = MetricsRegistry()
    c = reg.counter("a")
    c.inc(3)
    h = reg.histogram("h")
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["counters"]["a"] == 3
    assert snap["histograms"]["h"]["count"] == 3
    reg.reset()
    assert reg.counter("a") is c and c.value == 0
    assert reg.histogram("h").summary() == {"count": 0}


# ---------------------------------------------------------------------------
# satellites: StepProfiler honesty + lazy RecursiveLogger
def test_step_profiler_flags_compile_only_summary():
    p = StepProfiler()
    p.start_step()
    p.end_step()
    s = p.summary(skip_first=1)
    # a single (compile) step is reported, not silently passed off as
    # steady-state
    assert s["steps"] == 1 and s["includes_compile"] is True
    for _ in range(3):
        p.start_step()
        p.end_step()
    s = p.summary(skip_first=1)
    assert s["steps"] == 3 and s["includes_compile"] is False


def test_step_profiler_phases():
    p = StepProfiler()
    for _ in range(2):
        p.start_step()
        p.start_phase("dispatch")
        p.end_phase("dispatch")
        p.start_phase("wait")
        time.sleep(0.001)
        p.end_phase("wait")
        p.end_step()
    ps = p.phase_summary()
    assert set(ps) == {"dispatch", "wait"}
    assert ps["wait"]["mean_s"] > 0 and ps["wait"]["count"] == 1


def test_recursive_logger_lazy_env_and_set_enabled(monkeypatch, tmp_path):
    import io

    from flexflow_tpu.utils.logging import RecursiveLogger

    stream = io.StringIO()
    lg = RecursiveLogger("t", stream=stream)
    monkeypatch.delenv("FLEXFLOW_TPU_SEARCH_LOG", raising=False)
    assert not lg.enabled
    # the env var is re-read lazily — the import-time snapshot this
    # replaces could never be toggled by tests
    monkeypatch.setenv("FLEXFLOW_TPU_SEARCH_LOG", "1")
    assert lg.enabled
    lg.set_enabled(False)
    assert not lg.enabled
    lg.set_enabled(None)  # re-arm the env lookup
    assert lg.enabled
    lg.set_enabled(True)
    lg.log("hello")
    assert "hello" in stream.getvalue()


def test_recursive_logger_routes_through_bus(tmp_path):
    import io

    from flexflow_tpu.utils.logging import RecursiveLogger

    path = str(tmp_path / "log.jsonl")
    BUS.configure(path)
    lg = RecursiveLogger("t", enabled=False, stream=io.StringIO())
    with lg.enter("outer"):
        lg.log("inner")
    BUS.close()
    events = [json.loads(x) for x in open(path)]
    logs = [e for e in events if e["kind"] == "search.log"]
    assert [e["msg"] for e in logs] == ["outer", "inner"]
    assert logs[1]["depth"] == 1
    for e in events:
        assert validate_event(e) == []


# ---------------------------------------------------------------------------
# satellite: measure_operator_cost declines unmeasurable ops
def test_measure_operator_cost_declines_integer_only_op():
    from flexflow_tpu.core.ptensor import ParallelTensorShape

    class IntOnlyOp:
        """No floating input or weight: the timing scan would be
        loop-invariant and XLA would hoist the op — a clamped floor
        would poison the calibration table with a free op."""

        name = "int_only"
        _weight_specs = ()
        input_shapes = [ParallelTensorShape.make((64, 32), "int32")]

        def state_specs(self):
            return ()

        def forward(self, ctx, inputs, weights):
            return [inputs[0] * 2]

    assert measure_operator_cost(IntOnlyOp(), warmup=1, repeats=1) is None


def test_declined_probe_keeps_roofline_fallback():
    from flexflow_tpu.core.machine import MachineSpec, MachineView
    from flexflow_tpu.core.ptensor import ParallelTensorShape
    from flexflow_tpu.ops.linear import LinearOp
    from flexflow_tpu.search.calibration import CalibrationTable
    from flexflow_tpu.search.machine_model import CostModel

    op = LinearOp("lin", [ParallelTensorShape.make((64, 128), "float32")],
                  out_dim=64)
    mv = MachineView.data_parallel(2, 8)
    machine = MachineSpec.tpu_v5e(8)
    empty = CalibrationTable()  # a declined probe stores nothing
    with_table = CostModel(machine, calibration=empty, num_devices=8)
    without = CostModel(machine, calibration=None, num_devices=8)
    c_t = with_table.op_cost(op, mv)
    c_r = without.op_cost(op, mv)
    assert np.isfinite(c_t) and c_t > 0
    assert c_t == c_r  # no record -> identical analytic roofline


# ---------------------------------------------------------------------------
# chrome-trace export + drift report units
def test_chrome_trace_schema(tmp_path):
    from flexflow_tpu.compiler.lowering import data_parallel_strategy
    from flexflow_tpu.search.simulator import Simulator

    cfg = ff.FFConfig(batch_size=32, num_devices=8)
    m = ff.FFModel(cfg)
    x = m.create_tensor([32, 64], name="x")
    t = m.dense(x, 64, activation="relu", name="l1")
    m.dense(t, 8, name="l2")
    g = m.graph
    sim = Simulator(cfg.machine_spec, num_devices=8)
    path = str(tmp_path / "trace.json")
    cost = sim.export_chrome_trace(g, data_parallel_strategy(g, 8), path)
    assert np.isfinite(cost) and cost > 0
    doc = json.load(open(path))
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    slices = [e for e in events if e["ph"] == "X"]
    metas = [e for e in events if e["ph"] == "M"]
    assert slices and metas
    names = {e["name"] for e in slices}
    assert {"l1", "l2"} <= names
    for e in slices:
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert isinstance(e["tid"], int) and e["pid"] == 0
    # weight-sync collectives land on the comm rows
    assert any(e["name"].endswith(":sync") for e in slices)


def test_simulate_breakdown_totals():
    from flexflow_tpu.compiler.lowering import data_parallel_strategy
    from flexflow_tpu.search.simulator import Simulator

    cfg = ff.FFConfig(batch_size=32, num_devices=8)
    m = ff.FFModel(cfg)
    x = m.create_tensor([32, 64], name="x")
    m.dense(x, 64, name="l1")
    g = m.graph
    sim = Simulator(cfg.machine_spec, num_devices=8)
    bd = {}
    cost = sim.simulate(g, data_parallel_strategy(g, 8), breakdown=bd)
    assert bd["total_s"] == cost
    assert bd["total_s"] == pytest.approx(
        max(bd["compute_end_s"], bd["comm_end_s"]))
    assert bd["sync_total_s"] > 0  # the dense weight allreduce


def test_drift_report_staleness_flags():
    pred = {"total_s": 0.010, "compute_end_s": 0.008, "comm_end_s": 0.010}
    ok = build_drift_report(pred, measured_step_s=0.011, threshold=0.5)
    assert ok is not None and not ok.stale
    assert ok.phases["step"]["ratio"] == pytest.approx(1.1)
    slow = build_drift_report(pred, measured_step_s=0.030, threshold=0.5,
                              calibrated=True)
    assert slow.stale and slow.calibration_stale
    fast = build_drift_report(pred, measured_step_s=0.005, threshold=0.5)
    assert fast.stale and not fast.calibration_stale
    assert build_drift_report({"total_s": float("inf")}, 0.01) is None


def test_strategy_io_meta_roundtrip(tmp_path):
    from flexflow_tpu.compiler.lowering import data_parallel_strategy
    from flexflow_tpu.search.strategy_io import (
        attach_meta,
        export_strategy,
        import_strategy,
        read_meta,
    )

    cfg = ff.FFConfig(batch_size=32, num_devices=8)
    m = ff.FFModel(cfg)
    x = m.create_tensor([32, 16], name="x")
    m.dense(x, 8, name="l1")
    g = m.graph
    strategy = data_parallel_strategy(g, 8)
    path = str(tmp_path / "s.json")
    export_strategy(path, g, strategy, meta={"predicted": {"total_s": 1.0}})
    # the reserved __meta__ key never leaks into the imported strategy
    imported = import_strategy(path, g)
    assert set(imported) == set(strategy)
    attach_meta(path, drift={"ratio": 1.2})
    meta = read_meta(path)
    assert meta["predicted"]["total_s"] == 1.0
    assert meta["drift"]["ratio"] == 1.2


# ---------------------------------------------------------------------------
# tier-1 smoke: search + fit with telemetry on, schema-valid log,
# ffobs report exits 0
def test_search_fit_telemetry_smoke(tmp_path):
    log = str(tmp_path / "obs.jsonl")
    strat = str(tmp_path / "strategy.json")
    trace = str(tmp_path / "pred_timeline.json")
    cfg = ff.FFConfig(batch_size=32, epochs=2, num_devices=8,
                      compute_dtype="float32", profiling=True,
                      search_budget=4, search_timeout_s=30.0,
                      obs_log_file=log, obs_trace_file=trace,
                      export_strategy_file=strat)
    model = ff.FFModel(cfg)
    x = model.create_tensor([32, 64], name="in")
    t = model.dense(x, 256, activation="relu", name="d1")
    model.dense(t, 16, name="d2")
    model.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    dx, dy = _blobs()
    model.fit(x=dx, y=dy, verbose=False)
    BUS.close()

    kinds = set()
    with open(log) as f:
        for line in f:
            obj = json.loads(line)
            assert validate_event(obj) == [], (validate_event(obj), line)
            kinds.add(obj["kind"])
    # the three layers all reported: search decisions, compile-time
    # strategy table, runtime profile + drift
    assert {"search.begin", "search.baseline", "search.floor",
            "search.result", "dp.summary", "strategy.table",
            "profile.summary", "drift.report"} <= kinds

    assert model.drift_report is not None
    assert model.drift_report.phases["step"]["ratio"] is not None
    # drift persisted alongside the exported strategy
    meta = json.load(open(strat))["__meta__"]
    assert "predicted" in meta and "drift" in meta
    # predicted timeline is Perfetto-loadable chrome-trace JSON
    doc = json.load(open(trace))
    assert doc["traceEvents"]

    # metrics registry saw the fit steps (the PROFILE-print replacement)
    assert METRICS.counter("fit.steps").value > 0

    # the CLI renders the log and exits 0
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ffobs.py"),
         "report", log],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "Chosen strategy" in proc.stdout
    assert "Drift" in proc.stdout
    val = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ffobs.py"),
         "validate", log],
        capture_output=True, text=True)
    assert val.returncode == 0, val.stdout + val.stderr


# ---------------------------------------------------------------------------
# event-kind completeness guard (ISSUE 9 satellite): every emit site in
# the tree must name a registered kind, so the PR-8 class of
# "pre-existing ffobs validate gap" (search.chain emitted but never
# registered) cannot recur


def test_every_emit_site_names_a_registered_kind():
    """AST sweep over flexflow_tpu/ + tools/ + the bench drivers: every
    event-bus ``emit("<kind>", ...)`` call with a literal kind must
    name a key of ``EVENT_KINDS`` — an unregistered kind would make
    every log containing it fail ``ffobs validate``.  Bus receivers
    are identified by name (``BUS`` / ``_obs_bus`` bindings, plus the
    bus's own ``self.emit`` inside obs/events.py) so the frontends'
    unrelated ``emit(op_kind, ...)`` builders do not false-positive."""
    import ast

    from flexflow_tpu.obs.events import EVENT_KINDS

    def _receiver_is_bus(func: ast.Attribute, path: str) -> bool:
        base = func.value
        if isinstance(base, ast.Name):
            if base.id in ("BUS", "_obs_bus", "bus"):
                return True
            return base.id == "self" and path.endswith(
                os.path.join("obs", "events.py"))
        # dotted spellings like events.BUS.emit / obs.events.BUS.emit
        return isinstance(base, ast.Attribute) and base.attr == "BUS"

    roots = [os.path.join(REPO, "flexflow_tpu"),
             os.path.join(REPO, "tools")]
    files = [os.path.join(REPO, f) for f in os.listdir(REPO)
             if f.startswith("bench") and f.endswith(".py")]
    for root in roots:
        for dirpath, _dirs, names in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            files += [os.path.join(dirpath, n) for n in names
                      if n.endswith(".py")]
    assert files
    unregistered = []
    emit_sites = 0
    for path in sorted(files):
        tree = ast.parse(open(path).read(), filename=path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"
                    and _receiver_is_bus(node.func, path)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            emit_sites += 1
            kind = node.args[0].value
            if kind not in EVENT_KINDS:
                unregistered.append(
                    f"{path}:{node.lineno}: emit({kind!r})")
    assert emit_sites > 20, "the sweep found implausibly few emit sites"
    assert not unregistered, (
        "emit sites with unregistered kinds (add them to "
        "obs.events.EVENT_KINDS so ffobs validate accepts the logs):\n"
        + "\n".join(unregistered))


# ---------------------------------------------------------------------------
# event-volume sampling (ISSUE 17 satellite): per-kind caps/rates for
# the serving hot-path kinds, deterministic under a seed, with exact
# suppressed counts so totals stay recoverable from the log


def test_sampling_deterministic_and_interleave_independent(tmp_path):
    """A fractional rate keeps a seeded, per-ordinal subset: the same
    (kind, seed) keeps the same ordinals regardless of how OTHER kinds
    interleave, so two runs of the same workload sample identically."""

    def kept_ordinals(interleave):
        bus = EventBus()
        path = str(tmp_path / f"s{interleave}.jsonl")
        bus.configure(path)
        bus.configure_sampling("decode.request=0.25", seed=3)
        for i in range(200):
            bus.emit("decode.request", rid=f"r{i}", phase="finish")
            if interleave:
                bus.emit("search.log", msg="noise")
        bus.close()
        evs = [json.loads(ln) for ln in open(path)]
        return [e["rid"] for e in evs if e["kind"] == "decode.request"]

    plain = kept_ordinals(0)
    noisy = kept_ordinals(1)
    assert plain == noisy
    assert 20 < len(plain) < 80  # ~25% of 200, seeded not exact


def test_sampling_cap_and_exact_suppressed_counts(tmp_path):
    """An integer spec caps a kind at its first N events; everything
    suppressed is counted exactly and rolled up as one ``obs.sampled``
    event at close — the log's totals stay reconstructible."""
    bus = EventBus()
    path = str(tmp_path / "cap.jsonl")
    bus.configure(path)
    bus.configure_sampling({"fleet.route": 10})
    for i in range(90):
        bus.emit("fleet.route", rid=f"r{i}", replica=0, slo="standard")
    bus.emit("search.log", msg="unlisted kinds are never sampled")
    assert bus.sampled_out == {"fleet.route": 80}
    bus.close()
    evs = [json.loads(ln) for ln in open(path)]
    routed = [e for e in evs if e["kind"] == "fleet.route"]
    assert len(routed) == 10
    assert [e["rid"] for e in routed] == [f"r{i}" for i in range(10)]
    assert any(e["kind"] == "search.log" for e in evs)
    rollup = [e for e in evs if e["kind"] == "obs.sampled"]
    assert len(rollup) == 1
    assert rollup[0]["counts"] == {"fleet.route": 80}


def test_sampling_keeps_summary_counts_exact(tmp_path):
    """Sampling thins the LOG, never the measurement: with
    ``decode.request`` capped at 1, the executor's request_records and
    summary still see every completion."""
    from flexflow_tpu.runtime.decode import (
        ContinuousBatchingExecutor,
        DecodeRequest,
    )

    path = str(tmp_path / "obs.jsonl")
    BUS.configure(path)
    BUS.configure_sampling("decode.request=1")
    try:

        def step(ids, table, lens):
            b = np.asarray(ids).shape[0]
            logits = np.zeros((b, 1, 7), np.float32)
            logits[:, 0, 3] = 1.0
            return logits

        ex = ContinuousBatchingExecutor(step, max_seqs=2, page_size=4,
                                        pages_per_seq=2)
        ex.run([DecodeRequest(rid=f"r{i}", prompt=[1, 2],
                              max_new_tokens=2) for i in range(4)])
        assert len(ex.request_records) == 4  # the measurement is whole
        assert ex.summary()["completed"] == 4
        BUS.close()
        evs = [json.loads(ln) for ln in open(path)]
        assert sum(e["kind"] == "decode.request" for e in evs) == 1
        rollup = [e for e in evs if e["kind"] == "obs.sampled"]
        assert rollup and rollup[0]["counts"] == {"decode.request": 3}
    finally:
        BUS.configure_sampling(None)


def test_sampling_off_keeps_disabled_emit_cheap():
    """The one-boolean contract survives the sampling knob: with no
    spec armed (the default), a disabled bus still costs one attribute
    read per emit — 100k emits well under a second."""
    bus = EventBus()
    assert bus._sample is None
    t0 = time.perf_counter()
    for _ in range(100_000):
        bus.emit("search.log", msg="x")
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.0, f"disabled emit too slow: {elapsed:.3f}s"


# ---------------------------------------------------------------------------
# exposition label edge cases (ISSUE 17 satellite)


def test_exposition_labeled_histogram_renders_label_blocks():
    from flexflow_tpu.obs.exposition import render_prometheus

    reg = MetricsRegistry()
    hist = reg.histogram("decode.ttft_s|replica=0,slo=interactive")
    for v in (0.01, 0.02, 0.03):
        hist.observe(v)
    reg.counter("fleet.route.total|slo=interactive").inc()
    text = render_prometheus(reg.snapshot())
    assert ('flexflow_tpu_decode_ttft_s_count'
            '{replica="0",slo="interactive"} 3') in text
    # labeled quantile lines merge the series labels with the quantile
    assert ('flexflow_tpu_decode_ttft_s'
            '{replica="0",slo="interactive",quantile="0.50"}') in text
    assert ('flexflow_tpu_fleet_route_total'
            '{slo="interactive"} 1') in text


def test_exposition_empty_registry_renders_empty():
    from flexflow_tpu.obs.exposition import render_prometheus

    assert render_prometheus(MetricsRegistry().snapshot()) == ""
    assert render_prometheus({}) == ""


def test_exposition_zero_observation_histogram():
    """A histogram that exists but never observed renders only its
    ``_count 0`` line — no NaN quantiles, no sum."""
    from flexflow_tpu.obs.exposition import render_prometheus

    text = render_prometheus(
        {"histograms": {"trace.span_s|span=queue": {"count": 0}}})
    assert text == ("# TYPE flexflow_tpu_trace_span_s summary\n"
                    'flexflow_tpu_trace_span_s_count{span="queue"} 0\n')


def test_exposition_malformed_label_suffix_keeps_series():
    from flexflow_tpu.obs.exposition import render_prometheus

    text = render_prometheus(
        {"gauges": {"slo.burn_rate|slo=": 2.5, "ok|a=b": 1.0}})
    # the malformed suffix stays part of the name; the series survives
    assert "2.5" in text and 'ok{a="b"} 1.0' in text
