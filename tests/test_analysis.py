"""Static-analysis subsystem tests (flexflow_tpu/analysis + tools/fflint).

Contract under test (ISSUE 4):
* seeded corruptions are each caught by the RIGHT pass with a distinct
  finding code (mutation-style tests);
* every registered GraphXfer carries a passing executable equivalence
  proof (the substitution test suite runs the invariant checker
  unconditionally through it);
* FLEXFLOW_TPU_VERIFY=1 searches choose strategies bit-identical to
  unverified runs;
* strategy import refuses digest/coverage mismatches;
* cost-cache-served search results are gated (bad entries evicted);
* tools/fflint.py is tier-1-fast and exits 0 on the committed
  artifacts and the full registry.
"""

import json
import math
import os

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.analysis import (
    AnalysisError,
    GraphInvariantError,
    check_graph,
    lint_strategy,
    set_verify,
    verification_enabled,
)
from flexflow_tpu.compiler.lowering import data_parallel_strategy
from flexflow_tpu.core.graph import Edge, Graph, Node
from flexflow_tpu.core.machine import MachineView

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_model(batch=8, in_dim=16):
    cfg = ff.FFConfig(batch_size=batch, num_devices=8,
                      only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([batch, in_dim], name="ta_x")
    a = m.dense(x, 16, name="ta_fc1")
    b = m.dense(x, 16, name="ta_fc2")
    t = m.add(a, b, name="ta_add")
    m.dense(t, 4, name="ta_head")
    return m


def codes(findings):
    return {f.code for f in findings}


# ---------------------------------------------------------------------------
# mutation tests: seeded corruptions, each caught with its code


def test_clean_graph_has_no_findings():
    m = small_model()
    assert check_graph(m.graph) == []


def test_mutation_cycle_pcg001():
    m = small_model()
    g = m.graph.copy()
    head = m.node_by_name("ta_head")
    fc1 = m.node_by_name("ta_fc1")
    e = Edge(head.guid, fc1.guid, 0, 0)
    g.out_edges[head.guid] = g.out_edges[head.guid] + [e]
    g.in_edges[fc1.guid] = g.in_edges[fc1.guid] + [e]
    assert "PCG001" in codes(check_graph(g))


def test_mutation_guid_mismatch_pcg002():
    m = small_model()
    g = m.graph.copy()
    fc1 = m.node_by_name("ta_fc1")
    g.nodes[fc1.guid] = Node(fc1.guid + 100, fc1.op)
    assert "PCG002" in codes(check_graph(g))


def test_mutation_guid_above_next_guid_pcg002():
    m = small_model()
    g = m.graph.copy()
    g._next_guid = min(g.nodes)  # later splices would re-allocate guids
    assert "PCG002" in codes(check_graph(g))


def test_mutation_dangling_edge_pcg003():
    m = small_model()
    g = m.graph.copy()
    fc1 = m.node_by_name("ta_fc1")
    ghost = 9999
    e = Edge(ghost, fc1.guid, 0, 0)
    g.in_edges[fc1.guid] = g.in_edges[fc1.guid] + [e]
    assert "PCG003" in codes(check_graph(g))


def test_mutation_mirror_asymmetry_pcg004():
    m = small_model()
    g = m.graph.copy()
    fc1 = m.node_by_name("ta_fc1")
    head = m.node_by_name("ta_head")
    e = Edge(fc1.guid, head.guid, 0, 0)
    g.out_edges[fc1.guid] = g.out_edges[fc1.guid] + [e]  # out only
    assert "PCG004" in codes(check_graph(g))


def test_mutation_duplicate_edge_pcg005():
    m = small_model()
    g = m.graph.copy()
    fc1 = m.node_by_name("ta_fc1")
    e = g.in_edges[fc1.guid][0]
    g.in_edges[fc1.guid] = g.in_edges[fc1.guid] + [e]
    g.out_edges[e.src] = g.out_edges[e.src] + [e]
    assert "PCG005" in codes(check_graph(g))


def test_mutation_missing_input_slot_pcg006():
    m = small_model()
    g = m.graph.copy()
    add = m.node_by_name("ta_add")
    e = next(x for x in g.in_edges[add.guid] if x.dst_idx == 1)
    g.in_edges[add.guid] = [x for x in g.in_edges[add.guid] if x is not e]
    g.out_edges[e.src] = [x for x in g.out_edges[e.src] if x is not e]
    assert "PCG006" in codes(check_graph(g))


def test_mutation_src_idx_out_of_range_pcg007():
    m = small_model()
    g = m.graph.copy()
    fc1 = m.node_by_name("ta_fc1")
    e = g.in_edges[fc1.guid][0]
    bad = Edge(e.src, e.dst, 5, e.dst_idx)  # InputOp has 1 output
    g.in_edges[fc1.guid] = [bad]
    g.out_edges[e.src] = [bad if x is e else x for x in g.out_edges[e.src]]
    assert "PCG007" in codes(check_graph(g))


def test_mutation_shape_disagreement_pcg008():
    cfg = ff.FFConfig(batch_size=8, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16], name="sh_x")
    a = m.dense(x, 16, name="sh_wide")
    m.dense(x, 8, name="sh_narrow")
    m.dense(a, 4, name="sh_head")  # expects the [8, 16] producer
    g = m.graph.copy()
    head = m.node_by_name("sh_head")
    narrow = m.node_by_name("sh_narrow")
    e = g.in_edges[head.guid][0]
    bad = Edge(narrow.guid, head.guid, 0, e.dst_idx)  # [8, 8] != [8, 16]
    g.in_edges[head.guid] = [bad]
    g.out_edges[e.src] = [x for x in g.out_edges[e.src] if x is not e]
    g.out_edges[narrow.guid] = g.out_edges[narrow.guid] + [bad]
    assert "PCG008" in codes(check_graph(g))


def test_mutation_view_rank_shd101():
    m = small_model()
    s = data_parallel_strategy(m.graph, 8)
    fc1 = m.node_by_name("ta_fc1")
    s[fc1.guid] = MachineView.trivial(3)  # rank-2 output
    assert "SHD101" in codes(lint_strategy(m.graph, s, 8))


def test_mutation_indivisible_dim_shd102():
    m = small_model(batch=6)  # 6 % 4 != 0, 4 divides 8
    s = data_parallel_strategy(m.graph, 8)
    fc1 = m.node_by_name("ta_fc1")
    s[fc1.guid] = MachineView(dim_degrees=(4, 1))
    found = codes(lint_strategy(m.graph, s, 8))
    assert "SHD102" in found and "SHD103" not in found


def test_mutation_capacity_overflow_shd103():
    m = small_model(batch=24)  # 24 % 3 == 0, 3 does not divide 8
    s = data_parallel_strategy(m.graph, 8)
    fc1 = m.node_by_name("ta_fc1")
    s[fc1.guid] = MachineView(dim_degrees=(3, 1))
    found = codes(lint_strategy(m.graph, s, 8))
    assert "SHD103" in found and "SHD102" not in found


def test_mutation_fixed_view_violation_shd104():
    cfg = ff.FFConfig(batch_size=16, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([16, 8], name="sv_x")
    t = m.repartition(x, dim=0, degree=4, name="sv_rep")
    m.dense(t, 8, name="sv_fc")
    s = data_parallel_strategy(m.graph, 8)
    rep = m.node_by_name("sv_rep")
    s[rep.guid] = MachineView.trivial(2)  # pin says dim0 degree 4
    assert "SHD104" in codes(lint_strategy(m.graph, s, 8))


def test_mutation_unsplittable_dim_shd106():
    cfg = ff.FFConfig(batch_size=8, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16], name="sv_x")
    m.softmax(x, name="sv_sm")
    s = data_parallel_strategy(m.graph, 8)
    sm = m.node_by_name("sv_sm")
    # the softmax axis needs the full row — splitting it is illegal
    # (propagate would silently drop the split: exactly the
    # search/lowering drift the linter pins down)
    s[sm.guid] = MachineView(dim_degrees=(1, 2))
    assert "SHD106" in codes(lint_strategy(m.graph, s, 8))


def test_mutation_missing_view_shd109():
    m = small_model()
    s = data_parallel_strategy(m.graph, 8)
    del s[m.node_by_name("ta_fc1").guid]
    assert "SHD109" in codes(lint_strategy(m.graph, s, 8))


def test_clean_strategy_has_no_findings():
    m = small_model()
    assert lint_strategy(m.graph, data_parallel_strategy(m.graph, 8), 8) == []


# ---------------------------------------------------------------------------
# reduction-plan mutations (SHD13x + STR206): seeded corruptions of the
# staged hierarchical plans, each caught with its code


def _two_slice_cm(n=8, gap=10.0):
    import dataclasses

    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.machine_model import CostModel

    base = MachineSpec.tpu_v5e(n)
    spec = dataclasses.replace(
        base, devices_per_host=n // 2,
        dcn_bandwidth=base.ici_bandwidth / gap)
    return CostModel(spec, num_devices=n)


def _planned_schedule(m, s, cm, precision="fp32", cross_precision=None):
    import math

    from flexflow_tpu.search.reduction_plan import (
        ReductionPlan,
        canonical_stages,
    )
    from flexflow_tpu.search.sync_schedule import (
        build_bucketed_schedule,
        synced_weight_groups,
    )

    synced = synced_weight_groups(m.graph, s, cm)
    pmap = {node.op.name: precision for node, _mv, _parts in synced}
    sched = build_bucketed_schedule(synced, pmap, math.inf)
    plan = ReductionPlan(
        "staged_l1", canonical_stages(1, cross_precision or precision))
    import dataclasses

    buckets = [dataclasses.replace(b, plan=plan) for b in sched.buckets]
    from flexflow_tpu.search.sync_schedule import SyncSchedule

    return SyncSchedule(buckets, dict(sched.meta))


def test_clean_reduction_plan_has_no_findings():
    from flexflow_tpu.analysis import lint_reduction_plan

    m = small_model()
    s = data_parallel_strategy(m.graph, 8)
    cm = _two_slice_cm()
    sched = _planned_schedule(m, s, cm)
    assert lint_reduction_plan(m.graph, s, sched, cm) == []


def test_mutation_noncanonical_stages_shd130():
    import dataclasses

    from flexflow_tpu.analysis import lint_reduction_plan
    from flexflow_tpu.search.reduction_plan import ReductionPlan
    from flexflow_tpu.search.sync_schedule import SyncSchedule

    m = small_model()
    s = data_parallel_strategy(m.graph, 8)
    cm = _two_slice_cm()
    sched = _planned_schedule(m, s, cm)
    # drop the trailing all_gather: the bracketing is broken
    b = sched.buckets[0]
    broken = ReductionPlan("x", b.plan.stages[:-1])
    mut = SyncSchedule([dataclasses.replace(b, plan=broken)])
    assert "SHD130" in codes(lint_reduction_plan(m.graph, s, mut, cm))


def test_mutation_level_coverage_shd131():
    import dataclasses

    from flexflow_tpu.analysis import lint_reduction_plan
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.machine_model import CostModel

    m = small_model()
    s = data_parallel_strategy(m.graph, 8)
    # 3-level machine: DP-8 groups span level 2, but the plan stops at 1
    spec3 = dataclasses.replace(
        MachineSpec.tpu_v5e(8), devices_per_host=2,
        slice_levels=((4, 5e9, 5e-6), (8, 1e9, 2e-5)))
    cm3 = CostModel(spec3, num_devices=8)
    sched = _planned_schedule(m, s, cm3)
    assert "SHD131" in codes(lint_reduction_plan(m.graph, s, sched, cm3))


def test_mutation_no_spanning_group_shd132():
    import dataclasses

    from flexflow_tpu.analysis import lint_reduction_plan
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.machine_model import CostModel

    m = small_model()
    s = data_parallel_strategy(m.graph, 8)
    cm = _two_slice_cm()
    sched = _planned_schedule(m, s, cm)
    # 12-device 2-slice machine: the strategy's power-of-two replica
    # degrees do not factor into the (2, 2, 3) axis pool, so no group
    # provably crosses the slice boundary — the plan has no wire to ride
    spec12 = dataclasses.replace(
        MachineSpec.tpu_v5e(12), devices_per_host=4)
    cm12 = CostModel(spec12, num_devices=12)
    assert "SHD132" in codes(lint_reduction_plan(m.graph, s, sched, cm12))


def test_mutation_precision_contradiction_shd133():
    from flexflow_tpu.analysis import lint_reduction_plan

    m = small_model()
    s = data_parallel_strategy(m.graph, 8)
    cm = _two_slice_cm()
    # int8 cross stage on an fp32 bucket contradicts the precision map
    sched = _planned_schedule(m, s, cm, precision="fp32",
                              cross_precision="int8")
    assert "SHD133" in codes(lint_reduction_plan(m.graph, s, sched, cm))


def test_fflint_persisted_plan_str206(tmp_path):
    """Stdlib-only seeded corruptions of a persisted reduction plan:
    each malformation exits 1 with STR206."""
    from tools.fflint import main

    from flexflow_tpu.search.strategy_io import attach_meta, export_strategy

    m = small_model()
    s = data_parallel_strategy(m.graph, 8)
    cm = _two_slice_cm()
    sched = _planned_schedule(m, s, cm)
    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, s)
    attach_meta(p, sync_schedule=sched.to_jsonable())
    assert main(["strategy", p]) == 0
    with open(p) as f:
        clean = json.load(f)

    def corrupted(mutate):
        data = json.loads(json.dumps(clean))
        plan = data["__meta__"]["sync_schedule"]["buckets"][0]["plan"]
        mutate(plan)
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump(data, f)
        return main(["strategy", bad])

    # unknown stage kind / negative level / unknown precision /
    # compressed RS stage / two cross allreduces: all STR206
    assert corrupted(
        lambda pl: pl["stages"][0].update(kind="teleport")) == 1
    assert corrupted(
        lambda pl: pl["stages"][0].update(level=-1)) == 1
    assert corrupted(
        lambda pl: pl["stages"][1].update(precision="fp8")) == 1
    assert corrupted(
        lambda pl: pl["stages"][0].update(precision="int8")) == 1
    assert corrupted(
        lambda pl: pl["stages"].append(
            dict(kind="allreduce", level=1, precision="fp32"))) == 1
    assert corrupted(lambda pl: pl.pop("stages")) == 1


# ---------------------------------------------------------------------------
# substitution soundness: the registry's executable proof + the
# unconditional invariant run over every rewrite


def test_registry_equivalence_proof():
    """Every registered GraphXfer (all partition/replicate degrees,
    fusions, chain simplifications, BatchEmbeddingsXfer) matches a
    proof graph, rewrites it into a well-formed PCG, and preserves the
    value of every surviving node."""
    from flexflow_tpu.analysis.equivalence import verify_registry

    findings = verify_registry(num_devices=8)
    assert findings == [], [str(f) for f in findings]


def test_equivalence_catches_semantics_change():
    """A rewrite that splices out a relu (changing the function) must
    fail the numeric proof with EQV301."""
    from flexflow_tpu.analysis.equivalence import verify_rewrite
    from flexflow_tpu.core.optype import OperatorType
    from flexflow_tpu.search.substitution import GraphXfer, _bypass_node

    def matcher(graph, node):
        return (node.op.op_type is OperatorType.RELU
                and graph.in_edges[node.guid]
                and graph.out_edges[node.guid])

    def apply_fn(graph, node):
        g = graph.copy()
        if _bypass_node(g, node.guid) is None:
            return None
        return g

    bad = GraphXfer(name="drop_relu_unsound", matcher=matcher,
                    apply_fn=apply_fn)
    cfg = ff.FFConfig(batch_size=8, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16], name="eq_x")
    t = m.dense(x, 16, name="eq_fc")
    t = m.relu(t, name="eq_act")
    m.dense(t, 4, name="eq_head")
    matches = bad.find_matches(m.graph)
    assert matches
    findings = verify_rewrite(m.graph, bad, matches[0])
    assert "EQV301" in codes(findings), [str(f) for f in findings]


def test_verify_hook_catches_corrupting_rewrite():
    """Under FLEXFLOW_TPU_VERIFY semantics, GraphXfer.apply runs the
    invariant checker and a splice that leaves a consumer reading a
    deleted guid raises at the rewrite."""
    from flexflow_tpu.core.optype import OperatorType
    from flexflow_tpu.search.substitution import GraphXfer

    def matcher(graph, node):
        return node.op.op_type is OperatorType.RELU

    def apply_fn(graph, node):
        g = graph.copy()
        # raw (un-audited) surgery: drop the node but leave its out
        # edges dangling in the consumers' in-lists
        for e in list(g.in_edges[node.guid]):
            g.out_edges[e.src] = [x for x in g.out_edges[e.src]
                                  if x is not e]
        g.in_edges.pop(node.guid)
        g.out_edges.pop(node.guid)
        g.nodes.pop(node.guid)
        g._invalidate()
        return g

    corrupt = GraphXfer(name="corrupting_rewrite", matcher=matcher,
                        apply_fn=apply_fn)
    cfg = ff.FFConfig(batch_size=8, num_devices=8, only_data_parallel=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16], name="vh_x")
    t = m.relu(x, name="vh_act")
    m.dense(t, 4, name="vh_head")
    match = corrupt.find_matches(m.graph)[0]
    was = verification_enabled()
    set_verify(True)
    try:
        with pytest.raises(GraphInvariantError) as ei:
            corrupt.apply(m.graph, match)
        assert "PCG003" in {f.code for f in ei.value.findings}
    finally:
        set_verify(was)
    # with verification off the same apply silently returns the corrupt
    # graph — exactly what the checker exists to catch
    g_bad = corrupt.apply(m.graph, match)
    assert g_bad is not None and "PCG003" in codes(check_graph(g_bad))


# ---------------------------------------------------------------------------
# FLEXFLOW_TPU_VERIFY end-to-end: verified searches are bit-identical


@pytest.mark.parametrize("model_name", ["mlp", "bert"])
def test_verified_search_bit_identical(model_name):
    from flexflow_tpu.models import build_transformer
    from flexflow_tpu.search.driver import optimize_strategy

    def build():
        cfg = ff.FFConfig(batch_size=8, num_devices=8, search_budget=4,
                          cost_cache_file="")
        if model_name == "bert":
            m = build_transformer(cfg, num_layers=1, hidden=64, num_heads=4,
                                  ff_dim=128, seq_len=16)
        else:
            m = ff.FFModel(cfg)
            x = m.create_tensor([8, 256], name="vs_x")
            t = m.dense(x, 256, activation="relu", name="vs_fc1")
            m.dense(t, 16, name="vs_head")
        return m.graph, cfg

    g1, cfg1 = build()
    was = verification_enabled()
    set_verify(False)
    try:
        bg1, s1 = optimize_strategy(g1, cfg1, return_graph=True)
        g2, cfg2 = build()
        set_verify(True)
        bg2, s2 = optimize_strategy(g2, cfg2, return_graph=True)
    finally:
        set_verify(was)
    # the process-stable digest (graph.hash() keys InputOp signatures by
    # the frontend's global tensor-guid counter, which moves between
    # builds) and the topo-ordered view sequence must be bit-identical
    from flexflow_tpu.search.cost_cache import stable_graph_digest

    assert stable_graph_digest(bg1) == stable_graph_digest(bg2)
    v1 = [s1[n.guid] for n in bg1.topo_order()]
    v2 = [s2[n.guid] for n in bg2.topo_order()]
    assert v1 == v2


# ---------------------------------------------------------------------------
# strategy_io provenance


def test_export_embeds_digest_and_roundtrips(tmp_path):
    from flexflow_tpu.search.cost_cache import stable_graph_digest
    from flexflow_tpu.search.strategy_io import (
        export_strategy,
        import_strategy,
        read_meta,
    )

    m = small_model()
    dp = data_parallel_strategy(m.graph, 8)
    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, dp)
    assert read_meta(p)["graph_digest"] == stable_graph_digest(m.graph)
    assert import_strategy(p, m.graph) == dp


def test_import_rejects_wrong_graph_digest(tmp_path):
    from flexflow_tpu.search.strategy_io import export_strategy, import_strategy

    m = small_model()
    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, data_parallel_strategy(m.graph, 8))
    other = small_model(in_dim=32)  # same op names, different graph
    with pytest.raises(AnalysisError) as ei:
        import_strategy(p, other.graph)
    assert "digest" in str(ei.value)
    assert "STR201" in {f.code for f in ei.value.findings}


def test_import_rejects_partial_and_unknown(tmp_path):
    from flexflow_tpu.search.strategy_io import export_strategy, import_strategy

    m = small_model()
    dp = data_parallel_strategy(m.graph, 8)
    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, dp)
    with open(p) as f:
        data = json.load(f)
    # drop one op (partial map) and add an alien one — without touching
    # the digest, so coverage is the failing check
    data.pop("ta_fc1")
    data["not_in_graph"] = {"dims": [1, 1], "replica": 1, "start": 0}
    with open(p, "w") as f:
        json.dump(data, f)
    with pytest.raises(AnalysisError) as ei:
        import_strategy(p, m.graph)
    assert "STR202" in {f.code for f in ei.value.findings}
    # allow_partial is the DELIBERATE escape hatch (the historical
    # best-effort behavior, opt-in instead of silent): every check
    # downgrades to a warning and matching names are applied
    got = import_strategy(p, m.graph, allow_partial=True)
    assert m.node_by_name("ta_fc1").guid not in got and got


def test_import_allow_partial_spans_graphs(tmp_path):
    """The rewritten-search export scenario: a file keyed to a
    different graph digest imports best-effort under allow_partial
    (strict mode refuses with STR201 — cross-process reuse of rewritten
    searches is the cost cache's job)."""
    from flexflow_tpu.search.strategy_io import export_strategy, import_strategy

    m = small_model()
    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, data_parallel_strategy(m.graph, 8))
    other = small_model(in_dim=32)
    got = import_strategy(p, other.graph, allow_partial=True)
    assert set(got) == {n.guid for n in other.graph.topo_order()}


# ---------------------------------------------------------------------------
# cost-cache gate: a poisoned served result is refused and evicted


def test_cache_served_result_is_gated(tmp_path):
    import pickle

    from flexflow_tpu.search.cost_cache import CostCache, cost_signature
    from flexflow_tpu.search.driver import optimize_strategy
    from flexflow_tpu.search.simulator import Simulator

    path = str(tmp_path / "cache.json")
    cfg = ff.FFConfig(batch_size=8, num_devices=8, search_budget=4,
                      cost_cache_file=path)
    m = small_model()
    g = m.graph
    sim = Simulator.for_config(cfg)
    cache = sim.cost_cache
    assert cache is not None
    # poison: an illegal strategy (rank-mismatched trivial views) for
    # this exact (graph digest, knobs) key
    topo = [n.guid for n in g.topo_order()]
    bad_strategy = {guid: MachineView.trivial(7) for guid in topo}
    cache.put_search_result(g, cfg, (topo, None, bad_strategy, 0.001), 0.001)
    cache.save()
    del cache, sim

    bg, strategy = optimize_strategy(g, cfg, return_graph=True)
    assert lint_strategy(bg, strategy, 8) == []  # gate forced a re-search
    # and the poisoned entry was evicted from the persisted cache
    cache2 = CostCache(path, cost_signature(
        Simulator.for_config(
            ff.FFConfig(batch_size=8, num_devices=8, search_budget=4,
                        cost_cache_file="")).cost))
    got = cache2.get_search_result(g, cfg)
    if got is not None:  # the re-search stored its own (legal) result
        _topo, _bg, served_strategy, _cost = got
        assert all(len(v.dim_degrees) != 7 for v in served_strategy.values())


# ---------------------------------------------------------------------------
# ffobs schema + fflint CLI (tier-1, fast)


def test_obs_schema_knows_analysis_finding():
    from flexflow_tpu.obs.events import validate_event

    ok = {"ts": 1.0, "kind": "analysis.finding", "pass": "invariants",
          "code": "PCG001", "msg": "x", "op": None, "severity": "error"}
    assert validate_event(ok) == []
    assert validate_event({"ts": 1.0, "kind": "analysis.finding"}) != []


def test_findings_flow_through_bus(tmp_path):
    from flexflow_tpu.obs.events import BUS, validate_event

    log = str(tmp_path / "obs.jsonl")
    BUS.configure(log)
    try:
        m = small_model()
        s = data_parallel_strategy(m.graph, 8)
        s[m.node_by_name("ta_fc1").guid] = MachineView.trivial(3)
        from flexflow_tpu.analysis import emit_findings

        emit_findings(lint_strategy(m.graph, s, 8))
        BUS.flush()
    finally:
        BUS.close()
    events = [json.loads(line) for line in open(log)]
    af = [e for e in events if e["kind"] == "analysis.finding"]
    assert af and af[0]["code"] == "SHD101"
    assert all(validate_event(e) == [] for e in events)


def test_fflint_strategy_and_cache(tmp_path):
    from tools.fflint import main

    m = small_model()
    from flexflow_tpu.search.strategy_io import export_strategy

    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, data_parallel_strategy(m.graph, 8))
    assert main(["strategy", p]) == 0
    with open(p) as f:
        data = json.load(f)
    # a digest-less legacy file is a WARNING (imports with a warning
    # too — one severity per finding code, CLI and runtime agreeing)
    legacy = dict(data)
    legacy.pop("__meta__")
    lp = str(tmp_path / "legacy.json")
    with open(lp, "w") as f:
        json.dump(legacy, f)
    assert main(["strategy", lp]) == 0
    # malformed views are errors
    data["ta_fc1"] = {"dims": [0, "x"], "replica": 1}
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump(data, f)
    assert main(["strategy", bad]) == 1
    committed = os.path.join(REPO, "COST_CACHE.json")
    if os.path.exists(committed):
        assert main(["cache", committed]) == 0
    corrupt = str(tmp_path / "cc.json")
    with open(corrupt, "w") as f:
        json.dump({"schema": 99, "signature": "zz", "rows": [{"bad": 1}]}, f)
    assert main(["cache", corrupt]) == 1


def test_fflint_cache_dp_row_layer(tmp_path, capsys):
    """CCH405/406: the persisted DP-memo-row layer must lint — a
    well-formed layer passes, an unknown dp_schema is the DISTINCT
    loud-refusal code (CCH405), malformed rows are CCH406."""
    from flexflow_tpu.search.cost_cache import DP_SCHEMA
    from tools.fflint import main

    good = {"schema": 1, "signature": "0123456789abcdef", "calibration_stale": False,
            "rows": [],
            "dp_schema": DP_SCHEMA,
            "dp_rows": {"aabb:ccdd": {
                "cost": 1.5e-3,
                "strategy": [["0123abcd", [1, 8], 1, 0]]}}}
    p = str(tmp_path / "cc.json")
    with open(p, "w") as f:
        json.dump(good, f)
    assert main(["cache", p]) == 0

    for mutate, code in (
        (lambda d: d.update(dp_schema=99), "CCH405"),
        (lambda d: d.update(dp_rows={"nocolon": good["dp_rows"][
            "aabb:ccdd"]}), "CCH406"),
        (lambda d: d.update(dp_rows={"aa:bb": {"cost": -1.0,
                                               "strategy": [
            ["0123abcd", [1, 8], 1, 0]]}}), "CCH406"),
        (lambda d: d.update(dp_rows={"aa:bb": {"cost": 1.0,
                                               "strategy": []}}),
         "CCH406"),
        (lambda d: d.update(dp_rows={"aa:bb": {"cost": 1.0, "strategy": [
            ["XYZ", [0], 1, -1]]}}), "CCH406"),
    ):
        bad = {k: (dict(v) if isinstance(v, dict) else v)
               for k, v in good.items()}
        mutate(bad)
        with open(p, "w") as f:
            json.dump(bad, f)
        capsys.readouterr()
        assert main(["cache", p]) == 1
        out = capsys.readouterr().out
        assert code in out, (code, out)


def test_fflint_registry_exits_zero():
    """The CI contract: the full rewrite registry carries passing
    proofs through the CLI entry point."""
    from tools.fflint import main

    assert main(["registry", "--devices", "8"]) == 0


# ---------------------------------------------------------------------------
# driver gate: optimize_strategy output always passes the lint


def test_optimize_strategy_output_passes_lint():
    from flexflow_tpu.search.driver import optimize_strategy

    cfg = ff.FFConfig(batch_size=8, num_devices=8, search_budget=4,
                      cost_cache_file="")
    m = small_model()
    bg, s = optimize_strategy(m.graph, cfg, return_graph=True)
    assert check_graph(bg) == []
    assert lint_strategy(bg, s, 8) == []


def test_config_verify_is_scoped_not_sticky():
    """FFConfig.verify arms the checker for ITS search only — a later
    verify=False search in the same process must not keep paying (or
    raising) for verification it did not ask for."""
    from flexflow_tpu.analysis import CHECK_STATS
    from flexflow_tpu.search.driver import optimize_strategy

    was = verification_enabled()
    set_verify(False)
    try:
        cfg_v = ff.FFConfig(batch_size=8, num_devices=8, search_budget=2,
                            cost_cache_file="", verify=True)
        m = small_model()
        optimize_strategy(m.graph, cfg_v, return_graph=True)
        assert not verification_enabled()  # restored after the call
        before = CHECK_STATS["checks"]
        cfg_p = ff.FFConfig(batch_size=8, num_devices=8, search_budget=2,
                            cost_cache_file="", verify=False)
        optimize_strategy(small_model().graph, cfg_p, return_graph=True)
        assert CHECK_STATS["checks"] == before  # unverified run: no checks
    finally:
        set_verify(was)


def test_compile_verify_knob_runs_checker():
    from flexflow_tpu.analysis import CHECK_STATS

    cfg = ff.FFConfig(batch_size=8, num_devices=8, search_budget=2,
                      compute_dtype="float32", cost_cache_file="",
                      verify=True)
    m = ff.FFModel(cfg)
    x = m.create_tensor([8, 16], name="cv_x")
    t = m.dense(x, 16, activation="relu", name="cv_fc")
    m.dense(t, 4, name="cv_head")
    was = verification_enabled()
    before = CHECK_STATS["checks"]
    try:
        m.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    finally:
        set_verify(was)
    assert CHECK_STATS["checks"] > before
    xd = np.random.default_rng(0).normal(size=(16, 16)).astype(np.float32)
    y = np.zeros(16, dtype=np.int32)
    m.fit(x=xd, y=y, verbose=False)


# ---------------------------------------------------------------------------
# generative equivalence proofs (ISSUE 9 tentpole): proof graphs derived
# from the rewrite matchers themselves (analysis/proofgen.py)


def test_registry_generated_proof_zero_eqv305():
    """Every factory xfer anchors on GENERATED graphs and passes the
    numeric proof there — the EQV305 coverage-hole class is closed by
    construction, per dtype lane."""
    from flexflow_tpu.analysis.proofgen import verify_registry_generated

    findings, stats = verify_registry_generated(num_devices=8, seed=0)
    assert findings == [], [str(f) for f in findings]
    assert stats["unproven"] == 0
    # every float-family xfer proven on BOTH dtype lanes, embeddings
    # on the int32 lane (ids are integer by construction)
    assert stats["lanes"]["float32"] == stats["lanes"]["bfloat16"] > 0
    assert stats["lanes"]["int32"] > 0
    assert stats["graphs_generated"] > 0


def test_proofgen_generation_is_deterministic():
    from flexflow_tpu.analysis.proofgen import synthesize_anchor_graphs
    from flexflow_tpu.core.optype import OperatorType

    def sig(graphs):
        return [
            (lane, mult, pv, tuple(
                (n.op.op_type.value, tuple(n.op.output_shapes[0].sizes))
                for n in g.topo_order()))
            for lane, mult, pv, g in graphs
        ]

    for t in (OperatorType.LINEAR, OperatorType.EMBEDDING,
              OperatorType.REPARTITION):
        a = synthesize_anchor_graphs(t, 8, seed=3)
        b = synthesize_anchor_graphs(t, 8, seed=3)
        assert a and sig(a) == sig(b)


def test_proofgen_factory_hole_is_eqv305():
    """A factory GraphXfer whose anchor type has no motif family (or
    whose matcher anchors nowhere) is a LOUD coverage hole."""
    from flexflow_tpu.analysis.proofgen import verify_registry_generated
    from flexflow_tpu.core.optype import OperatorType
    from flexflow_tpu.search.substitution import GraphXfer

    bogus = GraphXfer(
        name="bogus_bmm_xfer",
        matcher=lambda g, n: False,
        apply_fn=lambda g, n: None,
        anchor_types=frozenset({OperatorType.BATCH_MATMUL}),
    )
    findings, stats = verify_registry_generated(num_devices=8, xfers=[bogus])
    assert codes(findings) == {"EQV305"}
    assert all(f.severity == "error" for f in findings)


def test_proofgen_unproven_json_rule_is_eqv306():
    """A multi-node JSON pattern outside the synthesizer's motif
    families is explicitly reported (EQV306, warn) instead of silently
    un-proven."""
    from flexflow_tpu.analysis.proofgen import verify_registry_generated
    from flexflow_tpu.core.optype import OperatorType
    from flexflow_tpu.search.substitution_loader import (
        PatternOp,
        PatternRule,
    )

    rule = PatternRule(
        name="taso_like_double_conv",
        src_ops=[
            PatternOp(type=OperatorType.CONV2D, inputs=[(-1, 0), (-2, 0)]),
            PatternOp(type=OperatorType.CONV2D, inputs=[(0, 0), (-3, 0)]),
        ],
        dst_ops=[
            PatternOp(type=OperatorType.CONV2D, inputs=[(-1, 0), (-2, 0)]),
        ],
        mapped_outputs=[(1, 0, 0, 0)],
        anchor_types=frozenset({OperatorType.CONV2D}),
    )
    findings, stats = verify_registry_generated(num_devices=8, xfers=[rule])
    assert codes(findings) == {"EQV306"}
    assert all(f.severity == "warn" for f in findings)
    assert stats["unproven"] == 1


def test_pattern_rule_indexed_scan_matches_full_scan():
    """The loader's per-op-type seed index (anchor_types derived from
    the pattern's ROOT op) finds exactly the full scan's binding set —
    asserted inline here and by the FLEXFLOW_TPU_DELTA_CHECK oracle."""
    from flexflow_tpu.core.optype import OperatorType
    from flexflow_tpu.search import substitution as subst
    from flexflow_tpu.search.substitution_loader import (
        PatternOp,
        PatternRule,
    )

    rule = PatternRule(
        name="rep_rep_fuse",
        src_ops=[
            # PM dims are Legion-ordered (innermost first): on a
            # rank-3 tensor PM dim 1 = logical dim 1, PM dim 2 =
            # logical dim 0 (_logical_dim mirrors the index)
            PatternOp(type=OperatorType.REPARTITION, inputs=[(-1, 0)],
                      params={"PM_REPARTITION_DIM": 1,
                              "PM_REPARTITION_DEGREE": 2}),
            PatternOp(type=OperatorType.REPARTITION, inputs=[(0, 0)],
                      params={"PM_REPARTITION_DIM": 2,
                              "PM_REPARTITION_DEGREE": 2}),
        ],
        dst_ops=[
            PatternOp(type=OperatorType.REPARTITION, inputs=[(-1, 0)],
                      params={"PM_PARALLEL_DIM": 0,
                              "PM_PARALLEL_DEGREE": 4}),
        ],
        mapped_outputs=[(1, 0, 0, 0)],
    )
    m = ff.FFModel(ff.FFConfig(num_devices=8))
    x = m.create_tensor([16, 8, 4])
    t = m.repartition(x, dim=1, degree=2)   # logical dim 1 = PM dim 1
    t = m.repartition(t, dim=0, degree=2)
    m.dense(t, 8)
    full = rule.find_matches(m.graph)
    assert full, "fixture pattern must match"
    # arm the index via the derived anchor (what _parse_rule sets)
    rule.anchor_types = frozenset({rule.src_ops[0].type})
    was = subst.DELTA_MATCH_CHECK
    subst.DELTA_MATCH_CHECK = True  # oracle: indexed == full, inline
    try:
        indexed = rule.find_matches(m.graph)
    finally:
        subst.DELTA_MATCH_CHECK = was
    as_set = lambda ms: sorted(tuple(sorted(mm.items())) for mm in ms)  # noqa: E731
    assert as_set(indexed) == as_set(full)


# ---------------------------------------------------------------------------
# pipeline/placement proposal legality (ISSUE 9 tentpole): SHD150-155
# seeded corruptions, each caught with its code


def _chain_model(layers=6):
    cfg = ff.FFConfig(batch_size=16, num_devices=8,
                      only_data_parallel=True)
    m = ff.FFModel(cfg)
    t = m.create_tensor([16, 32], name="pl_x")
    for i in range(layers):
        t = m.dense(t, 32, activation="relu", name=f"pl_fc{i}")
    m.dense(t, 4, name="pl_head")
    return m, cfg


def _stages_of(graph, num_stages):
    topo = [n.guid for n in graph.topo_order()]
    per = (len(topo) + num_stages - 1) // num_stages
    return [topo[i * per:(i + 1) * per] for i in range(num_stages)]


def test_clean_pipeline_stages_have_no_findings():
    from flexflow_tpu.analysis import lint_pipeline_stages

    m, cfg = _chain_model()
    stages = _stages_of(m.graph, 2)
    assert lint_pipeline_stages(m.graph, stages, 2, 4, cfg) == []


def test_mutation_pipeline_structure_shd150():
    from flexflow_tpu.analysis import lint_pipeline_stages

    m, cfg = _chain_model()
    stages = _stages_of(m.graph, 2)
    # microbatches below the stage count: the bubble eats the win
    found = codes(lint_pipeline_stages(m.graph, stages, 2, 1, cfg))
    assert "SHD150" in found
    # stage count that does not divide the machine
    found = codes(lint_pipeline_stages(
        m.graph, _stages_of(m.graph, 3), 3, 6, cfg))
    assert "SHD150" in found
    # unknown guid
    bad = [list(s) for s in stages]
    bad[0][0] = 99_999
    assert "SHD150" in codes(
        lint_pipeline_stages(m.graph, bad, 2, 4, cfg))


def test_mutation_pipeline_coverage_shd151():
    from flexflow_tpu.analysis import lint_pipeline_stages

    m, cfg = _chain_model()
    stages = [list(s) for s in _stages_of(m.graph, 2)]
    dup = stages[0][0]
    stages[1].append(dup)  # node in two stages
    found = codes(lint_pipeline_stages(m.graph, stages, 2, 4, cfg))
    assert "SHD151" in found
    stages = [list(s) for s in _stages_of(m.graph, 2)]
    stages[1] = stages[1][:-1]  # node in no stage
    found = codes(lint_pipeline_stages(m.graph, stages, 2, 4, cfg))
    assert "SHD151" in found


def test_mutation_pipeline_back_edge_shd152():
    from flexflow_tpu.analysis import lint_pipeline_stages

    m, cfg = _chain_model()
    stages = _stages_of(m.graph, 2)
    swapped = [stages[1], stages[0]]  # every chain edge now crosses back
    found = codes(lint_pipeline_stages(m.graph, swapped, 2, 4, cfg))
    assert "SHD152" in found and "SHD151" not in found


def _placed_model():
    cfg = ff.FFConfig(batch_size=16, num_devices=8,
                      compute_dtype="float32")
    m = ff.FFModel(cfg)
    ids = m.create_tensor([16, 4], dtype="int32", name="pm_ids")
    e = m.embedding(ids, 64, 8, name="pm_emb")
    h = m.flat(e, name="pm_flat")
    h = m.dense(h, 32, activation="relu", name="pm_mlp")
    m.dense(h, 4, name="pm_head")
    strat = {}
    for node in m.graph.topo_order():
        nd = node.op.output_shapes[0].ndim
        if node.op.name in ("pm_mlp", "pm_head"):
            strat[node.guid] = MachineView(
                dim_degrees=(4,) + (1,) * (nd - 1), start_part=4)
        else:
            strat[node.guid] = (
                node.op.fixed_machine_view()
                or MachineView(dim_degrees=(4,) + (1,) * (nd - 1)))
    return m, cfg, strat


def test_clean_placement_has_no_findings():
    from flexflow_tpu.analysis import lint_placement

    m, cfg, strat = _placed_model()
    assert lint_placement(m.graph, strat, cfg) == []


def test_mutation_placement_three_blocks_shd153():
    from flexflow_tpu.analysis import lint_placement

    m, cfg, strat = _placed_model()
    g = m.node_by_name("pm_head").guid
    strat[g] = MachineView(dim_degrees=(2, 1), start_part=6)
    found = codes(lint_placement(m.graph, strat, cfg))
    assert "SHD153" in found


def test_mutation_placement_overlap_shd154():
    from flexflow_tpu.analysis import lint_placement

    m, cfg, strat = _placed_model()
    # block B slid onto block A's devices: A needs 4 from 0, B starts at 2
    for name in ("pm_mlp", "pm_head"):
        g = m.node_by_name(name).guid
        strat[g] = MachineView(dim_degrees=(4, 1), start_part=2)
    found = codes(lint_placement(m.graph, strat, cfg))
    assert "SHD154" in found


def test_mutation_placement_overflow_shd154():
    from flexflow_tpu.analysis import lint_placement

    m, cfg, strat = _placed_model()
    for name in ("pm_mlp", "pm_head"):
        g = m.node_by_name(name).guid
        strat[g] = MachineView(dim_degrees=(4, 1), start_part=6)
    found = codes(lint_placement(m.graph, strat, cfg))  # 6 + 4 > 8
    assert "SHD154" in found


def test_mutation_placement_cut_shape_shd155():
    from flexflow_tpu.analysis import lint_placement

    m, cfg, strat = _placed_model()
    # sink pulled back into block A: B no longer owns the loss program
    # AND the head's input edge now flows B -> A
    g = m.node_by_name("pm_head").guid
    strat[g] = MachineView(dim_degrees=(4, 1), start_part=0)
    found = codes(lint_placement(m.graph, strat, cfg))
    assert "SHD155" in found


def test_mutation_placement_segment_views_shd1xx():
    """The per-segment flat lint runs in each block's OWN submesh
    geometry: a view legal on the 8-device machine but not on its
    4-device block is caught (SHD103 against the block size)."""
    from flexflow_tpu.analysis import lint_placement

    m, cfg, strat = _placed_model()
    g = m.node_by_name("pm_ids").guid
    # 8 parts on a 4-device block: fits the machine, not the block
    strat[g] = MachineView(dim_degrees=(8, 1))
    found = codes(lint_placement(m.graph, strat, cfg))
    assert found & {"SHD103", "SHD154"}


def test_compile_gates_placed_strategy_with_findings():
    """The compile-time placed-lowering gate: a 2-block strategy that
    passes ``placeable()``'s structural checks but whose views are
    illegal in their block's submesh geometry fails with an
    AnalysisError carrying findings — not an opaque lowering error."""
    m, cfg, strat = _placed_model()
    # 8-part input view in block A: placeable() (cut shape only) still
    # holds, but block A's width now collides with block B's start —
    # the constructor would raise a bare ValueError; the gate reports
    # SHD154 first
    g = m.node_by_name("pm_ids").guid
    strat[g] = MachineView(dim_degrees=(8, 1))
    from flexflow_tpu.compiler.placement_lowering import placeable

    assert placeable(m.graph, strat, cfg)
    with pytest.raises(AnalysisError) as ei:
        m.compile(loss_type="sparse_categorical_crossentropy",
                  metrics=[], strategy=strat)
    assert {f.code for f in ei.value.findings} & {"SHD154", "SHD103"}


def test_pipeline_proposal_is_gated_and_general_proposal_lints():
    """propose_pipeline_general's returned partition passes SHD150-152
    (the always-on gate ran inside the proposal path)."""
    import dataclasses

    from flexflow_tpu.analysis import lint_pipeline_stages
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.pipeline_search import propose_pipeline_general
    from flexflow_tpu.search.simulator import Simulator

    spec = MachineSpec(num_devices=8, devices_per_host=4, platform="cpu",
                       hbm_capacity=40e6)
    cfg = ff.FFConfig(batch_size=16, num_devices=8,
                      compute_dtype="float32", machine_spec=spec)
    m = ff.FFModel(cfg)
    t = m.create_tensor([16, 1021])
    for i, w in enumerate((1019, 1013, 1009, 1021)):
        t = m.dense(t, w, activation="relu", name=f"gl{i}_fc")
    m.dense(t, 1021, name="gl_head")
    sim = Simulator.for_config(cfg)
    prop = propose_pipeline_general(m.graph, cfg, sim, math.inf)
    assert prop is not None
    assert lint_pipeline_stages(
        m.graph, prop.stage_guids, prop.num_stages,
        prop.num_microbatches, cfg) == []


# ---------------------------------------------------------------------------
# STR208: stdlib lint of persisted placement/pipeline proposal meta +
# the fflint --json machine-readable contract


def _export_placed(tmp_path):
    from flexflow_tpu.analysis import placement_meta
    from flexflow_tpu.search.strategy_io import attach_meta, export_strategy

    m, cfg, strat = _placed_model()
    p = str(tmp_path / "placed.json")
    export_strategy(p, m.graph, strat)
    attach_meta(p, placement=placement_meta(m.graph, strat, cfg),
                pipeline={"num_stages": 2, "num_microbatches": 4,
                          "stages": [["pm_ids", "pm_emb", "pm_flat"],
                                     ["pm_mlp", "pm_head"]]})
    return p


def test_fflint_persisted_placement_meta_str208(tmp_path):
    from tools.fflint import main

    p = _export_placed(tmp_path)
    assert main(["strategy", p]) == 0
    with open(p) as f:
        clean = json.load(f)

    def corrupted(mutate):
        data = json.loads(json.dumps(clean))
        mutate(data)
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump(data, f)
        return main(["strategy", bad])

    meta = "__meta__"
    # overlapping blocks / block A off device 0 / overflow / op outside
    # the declared blocks / op wider than its block: all STR208
    assert corrupted(
        lambda d: d[meta]["placement"]["blocks"].__setitem__(
            1, [2, 4])) == 1
    assert corrupted(
        lambda d: d[meta]["placement"]["blocks"].__setitem__(
            0, [1, 4])) == 1
    assert corrupted(
        lambda d: d[meta]["placement"].update(num_devices=6)) == 1
    assert corrupted(
        lambda d: d["pm_head"].update(start=3)) == 1
    assert corrupted(
        lambda d: d["pm_head"].update(dims=[8, 1])) == 1
    # pipeline meta corruptions: M < S / duplicated op / unknown op
    assert corrupted(
        lambda d: d[meta]["pipeline"].update(num_microbatches=1)) == 1
    assert corrupted(
        lambda d: d[meta]["pipeline"]["stages"][1].append("pm_ids")) == 1
    assert corrupted(
        lambda d: d[meta]["pipeline"]["stages"][1].append("ghost")) == 1


def test_fflint_json_output_and_exit_contract(tmp_path, capsys):
    """--json: one JSON object per line (findings first, summary last);
    exit codes keep the 0/1/2 contract."""
    from tools.fflint import main

    m = small_model()
    from flexflow_tpu.search.strategy_io import export_strategy

    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, data_parallel_strategy(m.graph, 8))
    capsys.readouterr()
    assert main(["strategy", "--json", p]) == 0  # clean -> 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    assert lines[-1]["summary"] is True and lines[-1]["errors"] == 0

    with open(p) as f:
        data = json.load(f)
    data["ta_fc1"] = {"dims": [0, "x"], "replica": 1}
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump(data, f)
    capsys.readouterr()
    assert main(["strategy", "--json", bad]) == 1  # findings -> 1
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    finding = next(ln for ln in lines if not ln.get("summary"))
    assert finding["code"] == "STR204" and finding["severity"] == "error"
    assert lines[-1]["errors"] >= 1

    assert main(["strategy"]) == 2  # usage error -> 2
    assert main(["no-such-subcommand"]) == 2


def test_placed_compile_persists_and_reimports_placement_meta(tmp_path):
    """persist/import legs of the proposal gate: a placed compile
    exports ``__meta__.placement`` behind the digest gate, fflint
    checks it stdlib-only (STR208), and re-importing the file re-lints
    the cut against the fresh graph before the placed lowering runs."""
    from tools.fflint import main

    from flexflow_tpu.compiler.placement_lowering import PlacedCompiledModel
    from flexflow_tpu.search.strategy_io import read_meta

    p = str(tmp_path / "placed_export.json")
    m, _cfg, strat = _placed_model()
    m.config.export_strategy_file = p
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              strategy=strat)
    assert isinstance(m.compiled, PlacedCompiledModel)
    meta = read_meta(p)
    assert meta["placement"]["blocks"] == [[0, 4], [4, 4]]
    assert main(["strategy", p]) == 0

    # re-import onto a fresh build of the same model: the placement
    # meta is re-linted against THIS graph and the placed lowering runs
    m2, _cfg2, _ = _placed_model()
    m2.config.import_strategy_file = p
    m2.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    assert isinstance(m2.compiled, PlacedCompiledModel)

    # a corrupted placement frame fails the import with a finding
    data = json.load(open(p))
    data["__meta__"]["placement"]["blocks"] = [[0, 4], [2, 4]]
    bad = str(tmp_path / "bad_placed.json")
    with open(bad, "w") as f:
        json.dump(data, f)
    m3, _cfg3, _ = _placed_model()
    m3.config.import_strategy_file = bad
    with pytest.raises(AnalysisError):
        m3.compile(loss_type="sparse_categorical_crossentropy",
                   metrics=[])


def test_failed_placed_compile_leaves_no_placement_artifact(tmp_path):
    """Review fix: a compile that fails the placed-lowering gate must
    not first persist a __meta__.placement frame claiming the cut
    executes."""
    from flexflow_tpu.search.strategy_io import read_meta

    p = str(tmp_path / "failed_placed.json")
    m, _cfg, strat = _placed_model()
    g = m.node_by_name("pm_ids").guid
    strat[g] = MachineView(dim_degrees=(8, 1))  # SHD154 at the gate
    m.config.export_strategy_file = p
    with pytest.raises(AnalysisError):
        m.compile(loss_type="sparse_categorical_crossentropy",
                  metrics=[], strategy=strat)
    assert not os.path.exists(p) or "placement" not in read_meta(p)


def test_import_malformed_pipeline_meta_is_a_finding(tmp_path):
    """Review fix: non-int num_stages / non-list stages in a
    hand-edited __meta__.pipeline fail the import gate with an
    AnalysisError finding, never a bare TypeError."""
    from flexflow_tpu.search.strategy_io import attach_meta, export_strategy

    for corrupt in ({"num_stages": None, "num_microbatches": 4},
                    {"num_stages": 2, "num_microbatches": 4,
                     "stages": 5},
                    "not-an-object"):
        m = small_model()
        p = str(tmp_path / "pm.json")
        export_strategy(p, m.graph, data_parallel_strategy(m.graph, 8))
        attach_meta(p, pipeline=corrupt)
        m2 = small_model()
        m2.config.import_strategy_file = p
        with pytest.raises(AnalysisError) as ei:
            m2.compile(loss_type="sparse_categorical_crossentropy",
                       metrics=[])
        assert "SHD150" in {f.code for f in ei.value.findings}


def test_imported_pipeline_meta_with_stages_adopts_staged_lowering(tmp_path):
    """Review fix: an imported __meta__.pipeline with explicit stages
    is ADOPTED (staged wavefront executor), not merely validated — an
    import that re-lints but silently lowers flat would defeat the
    proposal it just checked."""
    from flexflow_tpu.compiler.staged_pipeline_lowering import (
        StagedPipelinedModel,
    )
    from flexflow_tpu.search.strategy_io import attach_meta, export_strategy

    m, cfg = _chain_model()
    p = str(tmp_path / "pp.json")
    s = data_parallel_strategy(m.graph, 8)
    export_strategy(p, m.graph, s)
    names = {n.guid: n.op.name for n in m.graph.topo_order()}
    stage_guids = _stages_of(m.graph, 2)
    attach_meta(p, pipeline={
        "num_stages": 2, "num_microbatches": 4,
        "stages": [[names[g] for g in st] for st in stage_guids]})

    m2, _cfg2 = _chain_model()
    m2.config.import_strategy_file = p
    m2.compile(loss_type="mean_squared_error", metrics=[])
    assert m2.pipeline_proposal is not None
    assert m2.pipeline_proposal.num_stages == 2
    assert isinstance(m2.compiled, StagedPipelinedModel)


def test_imported_stacked_pipeline_meta_adopts_pipeline_config(tmp_path):
    """S x M meta without explicit stages (the stacked-block shape)
    round-trips to the scan-based pipelined lowering, exactly as if
    the user had passed compile(pipeline=...)."""
    from flexflow_tpu.compiler.pipeline_lowering import (
        PipelinedCompiledModel,
    )
    from flexflow_tpu.parallel.pipeline import PipelineConfig

    def build():
        cfg = ff.FFConfig(batch_size=16, num_devices=8,
                          compute_dtype="float32")
        mm = ff.FFModel(cfg)
        t = mm.create_tensor([16, 32], name="st_x")
        for i in range(4):
            t = mm.dense(t, 32, activation="relu", name=f"layer{i}_fc")
        mm.dense(t, 4, name="st_head")
        return mm

    p = str(tmp_path / "stacked.json")
    m = build()
    m.config.export_strategy_file = p
    m.compile(loss_type="sparse_categorical_crossentropy", metrics=[],
              pipeline=PipelineConfig(num_stages=2, num_microbatches=4))
    assert isinstance(m.compiled, PipelinedCompiledModel)
    meta = json.load(open(p))["__meta__"]
    assert meta["pipeline"] == {"num_stages": 2, "num_microbatches": 4}

    m2 = build()
    m2.config.import_strategy_file = p
    m2.compile(loss_type="sparse_categorical_crossentropy", metrics=[])
    assert isinstance(m2.compiled, PipelinedCompiledModel)


def test_fflint_calibration_signature_str210(tmp_path, capsys):
    """STR210 (always-on loop satellite): a strategy file whose
    persisted __meta__.calibration_signature no longer matches the live
    CALIBRATION.json is flagged STALE (warn — exit stays 0), matching
    exactly; seeded corruption of any record flips it."""
    from tools.fflint import _calibration_digest, lint_strategy_file, main

    from flexflow_tpu.search.calibration import CalibrationTable
    from flexflow_tpu.search.cost_cache import calibration_digest
    from flexflow_tpu.search.strategy_io import export_strategy

    cal = str(tmp_path / "CALIBRATION.json")
    table = CalibrationTable()
    table.put(small_model().graph.topo_order()[1].op,
              MachineView.trivial(2), 1.5e-4)
    table._clusters[(("a", "b"), (2, 1), 1)] = 3e-4
    table.backend = "cpu"
    table.save(cal)
    # the stdlib mirror digests the JSON identically to the package
    with open(cal) as f:
        assert _calibration_digest(json.load(f)) == calibration_digest(
            CalibrationTable.load(cal))

    m = small_model()
    p = str(tmp_path / "s.json")
    export_strategy(p, m.graph, data_parallel_strategy(m.graph, 8),
                    meta={"calibration_signature": calibration_digest(
                        CalibrationTable.load(cal))})
    # matching live table: clean (sibling default resolution)
    assert lint_strategy_file(p) == []
    assert main(["strategy", p]) == 0

    # seeded corruption: each mutation rotates the live digest -> STR210
    for mutate in (
        lambda d: d["records"][0].__setitem__("seconds", 9e9),
        lambda d: d["records"].pop(),
        lambda d: d.__setitem__("backend", "tpu"),
        lambda d: d["clusters"][0].__setitem__("replica", 4),
    ):
        table.save(cal)  # restore the healthy table
        with open(cal) as f:
            data = json.load(f)
        mutate(data)
        with open(cal, "w") as f:
            json.dump(data, f)
        findings = lint_strategy_file(p)
        assert [(s, c) for s, c, _ in findings] == [("warn", "STR210")], \
            findings
        assert main(["strategy", p]) == 0  # warn does not gate
        capsys.readouterr()

    # explicit --calibration beats the sibling default
    other = str(tmp_path / "other_cal.json")
    CalibrationTable().save(other)
    assert any(c == "STR210" for _, c, _ in lint_strategy_file(
        p, calibration_path=other))
    # no live table at all: nothing to compare, nothing to say
    assert lint_strategy_file(
        p, calibration_path=str(tmp_path / "missing.json")) == []
    # valid JSON with malformed rows: a warn finding, never a traceback
    # (the pre-commit hook runs this path)
    broken = str(tmp_path / "broken_cal.json")
    with open(broken, "w") as f:
        json.dump({"records": [{"sig": "x"}]}, f)
    findings = lint_strategy_file(p, calibration_path=broken)
    assert [(s, c) for s, c, _ in findings] == [("warn", "STR210")]
    assert main(["strategy", p, "--calibration", broken]) == 0


def test_lint_swap_codes_and_clean_pass():
    """SHD170-172 (hot-swap gate): clean swaps have no findings; each
    corruption class reports its own code."""
    from flexflow_tpu.analysis import lint_swap

    m = small_model()
    strat = data_parallel_strategy(m.graph, 8)
    assert lint_swap(m.graph, m.graph, strat, 8) == []
    # composes the flat SHD1xx lint on the target pair
    bad_views = dict(strat)
    guid = m.graph.topo_order()[1].guid
    bad_views[guid] = MachineView(dim_degrees=(3, 3), replica_degree=1)
    assert any(f.code.startswith("SHD1")
               for f in lint_swap(m.graph, m.graph, bad_views, 8))
