"""Simulator-to-execution coherence for the searched-vs-DP contract.

Round-3 verdict: a 4.15x simulated BERT win coexisted with a 0.88x
measured one — a ~5x unbounded modeling error.  These tests bound the
seam from both sides on the 8-virtual-device CPU mesh, where the
machine model's constants are measured from this very host
(core/machine.py host_cpu):

1. NEVER-LOSE: whatever the search returns must not execute slower
   than plain data parallelism beyond timing noise.  DP is always in
   the search space, and the champion-vs-DP floor (search/driver.py)
   discards sub-margin "wins", so a real loss means the cost model is
   misranking — the round-3 failure mode.
2. DIRECTION: when the simulator predicts a LARGE win (>= 1.5x), the
   executed ratio must actually exceed 1.0.

Documented bound: executed_ratio >= NOISE_FLOOR (0.85) for every
model; single-core hosts jitter 8-18% between timing blocks, the
median-of-blocks measurement keeps residual noise within ~10%.
The magnitude of big wins is NOT asserted (a host-bound CPU mesh
cannot reproduce a 74x simulated ratio — see BENCH_SEARCH.md honesty
notes); the sign is what the search's decisions ride on.

Reference: scripts/osdi22ae/*.sh runs the same two-program comparison
on real hardware.
"""

import time

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.compiler.lowering import data_parallel_strategy
from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.search.simulator import Simulator

N_DEV = 8
# round-4 verdict weak #5: 0.85 tolerated a 15% executed loss.  Every
# genuinely-different program pair currently wins >=1.8x executed
# (BENCH_SEARCH.md), so the floor now only absorbs single-core timing
# jitter, not modeling error.
NOISE_FLOOR = 0.92
BIG_WIN = 1.5


def _tiny_bert(cfg):
    from flexflow_tpu.models import build_transformer

    return build_transformer(
        cfg, num_layers=2, hidden=128, num_heads=4, ff_dim=256, seq_len=32
    )


def _tiny_gpt(cfg):
    from flexflow_tpu.models import build_gpt

    return build_gpt(
        cfg, vocab=2048, num_layers=2, hidden=128, num_heads=4, ff_dim=256,
        seq_len=32,
    )


def _sync_bound_bert(cfg):
    """The osdi22ae/bert.sh regime, scaled to the CPU mesh: full
    hidden/ff widths at short seq so the per-device batch is 1 and
    DP's weight-gradient allreduce dominates — the search's
    compute-parallel (TP) strategy must win at EXECUTION, not just in
    the simulator (round-4 verdict: no configuration had shown a
    compute-parallel searched strategy beating DP when executed).
    The spec is SHARED with bench_search.py's bert exec tier — the CI
    gate and the benchmark must measure the same program pair."""
    from bench_search import SYNC_BOUND_BERT_KW

    from flexflow_tpu.models import build_transformer

    return build_transformer(cfg, **SYNC_BOUND_BERT_KW)


def _tiny_mlp(cfg):
    from flexflow_tpu.models import build_mlp_unify

    return build_mlp_unify(cfg, in_dim=512, hidden=(512, 512))


def _tiny_dlrm(cfg):
    """The flagship table-sharding phenomenon (dlrm.cc +
    osdi22ae/dlrm.sh): DP pays the full-table gradient allreduce the
    search avoids by sharding whole tables."""
    from flexflow_tpu.models import build_dlrm

    return build_dlrm(cfg, embedding_sizes=(50000,) * 4, embedding_dim=32,
                      bot_mlp=(64, 32), top_mlp=(64, 1))


CASES = {
    "bert": (_tiny_bert, "mean_squared_error"),
    "bert_tp": (_sync_bound_bert, "mean_squared_error"),
    "gpt": (_tiny_gpt, "sparse_categorical_crossentropy"),
    "mlp": (_tiny_mlp, "sparse_categorical_crossentropy"),
    "dlrm": (_tiny_dlrm, "mean_squared_error"),
}


def _block_timer(model, loss, steps=4):
    """Warm up the compiled step and return a callable running ONE
    timed block (mean seconds/step over ``steps``).  Factored out so
    the re-measure pass can INTERLEAVE blocks of the two programs —
    one-sided host drift (the machine slowing down over the suite)
    then biases both medians equally instead of penalizing whichever
    program is measured second."""
    import jax
    import jax.random as jrandom

    from examples.common import synthetic_inputs, synthetic_labels

    xs = synthetic_inputs(model, model.config.batch_size)
    y = synthetic_labels(model, model.config.batch_size, loss)
    compiled = model.compiled
    li = [jax.device_put(x, compiled.input_sharding(i)) for i, x in enumerate(xs)]
    lab = jax.device_put(y, compiled.batch_sharding())
    state = {"pos": [model.params, model.opt_state, model.state],
             "i": 0}
    for i in range(3):
        p, o, s = state["pos"]
        p, o, s, lval, _ = compiled.train_step(p, o, s, jrandom.key(i), li, lab)
        state["pos"] = [p, o, s]
    float(lval)

    def block():
        p, o, s = state["pos"]
        t0 = time.perf_counter()
        for _ in range(steps):
            state["i"] += 1
            p, o, s, lval, _ = compiled.train_step(
                p, o, s, jrandom.key(100 + state["i"]), li, lab)
        float(lval)
        state["pos"] = [p, o, s]
        return (time.perf_counter() - t0) / steps

    return block


def _step_seconds(model, loss, steps=4, blocks=3):
    import statistics

    b = _block_timer(model, loss, steps)
    return statistics.median([b() for _ in range(blocks)])


_PAIR_CACHE: dict = {}


def _run_pair(name):
    # memoized: bert_tp is asserted by two tests; re-searching and
    # re-timing the identical program pair would double its CI cost
    if name in _PAIR_CACHE:
        return _PAIR_CACHE[name]
    build, loss = CASES[name]
    out = {"_models": {}}
    for mode in ("dp", "searched"):
        cfg = ff.FFConfig(
            batch_size=8, num_devices=N_DEV, search_budget=20,
            search_timeout_s=30.0, compute_dtype="float32",
            machine_spec=MachineSpec.host_cpu(N_DEV),
            only_data_parallel=(mode == "dp"),
        )
        model = build(cfg)
        if mode == "dp":
            strategy = data_parallel_strategy(model.graph, N_DEV)
            model.compile(loss_type=loss, metrics=[], strategy=strategy)
            sim = Simulator(cfg.machine_spec, num_devices=N_DEV)
            out["sim_dp"] = sim.simulate(model.graph, strategy)
        else:
            model.compile(loss_type=loss, metrics=[])
            sim = Simulator(cfg.machine_spec, num_devices=N_DEV)
            out["sim_searched"] = sim.simulate(model.graph, model.strategy)
            out["searched_is_dp"] = (
                model.strategy == data_parallel_strategy(model.graph, N_DEV)
            )
        out["_models"][mode] = model
        out[mode] = _step_seconds(model, loss)
    out["sim_ratio"] = out["sim_dp"] / max(out["sim_searched"], 1e-12)
    out["exec_ratio"] = out["dp"] / max(out["searched"], 1e-12)
    _PAIR_CACHE[name] = out
    return out


def _remeasure(name, blocks=4):
    """One fresh timing pass over the SAME two compiled programs (no
    re-search, no re-compile), with the two programs' timing blocks
    INTERLEAVED.

    NOTE (flake stabilization, oscillating on both trees since PR 4):
    identical compiled programs have measured up to 1.7x apart on this
    single-core-contended host — and the bias is one-sided (the host
    slows across the suite, so the program measured SECOND loses both
    back-to-back passes), which median-of-blocks per program cannot
    cancel.  The retry alternates single blocks between the two
    programs (dp, searched, dp, searched, …) so any drift taxes both
    medians equally; a genuinely misranked strategy still fails — it
    is slower in the interleaved blocks too."""
    r = _PAIR_CACHE[name]
    _build, loss = CASES[name]
    for m in r["_models"].values():
        # the first pass DONATED params/opt_state/state into the jitted
        # step; re-initialize before re-timing the same compiled program
        m.params, m.state = m.compiled.init_params(m.config.seed)
        m.opt_state = m.compiled.shard_opt_state(
            m.optimizer.init_state(m.params))
    import statistics

    bdp = _block_timer(r["_models"]["dp"], loss)
    bse = _block_timer(r["_models"]["searched"], loss)
    t_dp, t_se = [], []
    for _ in range(blocks):
        t_dp.append(bdp())
        t_se.append(bse())
    r["dp"] = statistics.median(t_dp)
    r["searched"] = statistics.median(t_se)
    r["exec_ratio"] = r["dp"] / max(r["searched"], 1e-12)
    return r


@pytest.mark.parametrize("name", sorted(CASES))
def test_searched_never_loses_to_dp(name):
    r = _run_pair(name)
    if r["searched_is_dp"]:
        # the champion-vs-DP floor kept plain DP: both compiled
        # programs are IDENTICAL, so the never-lose guarantee holds by
        # construction and the ratio check is purely a timing-harness
        # sanity band.  NOTE (flake, oscillating since PR 4): two
        # independently-jitted copies of the same program have measured
        # up to ~1.7x apart under full-suite load on this host (heap
        # layout + one-sided drift), so a first out-of-band median gets
        # one interleaved re-timing and only a >2x post-retry gap —
        # a genuinely broken harness, not noise — fails.
        if not 0.7 <= r["exec_ratio"] <= 1.4:
            r = _remeasure(name)
        assert 0.5 <= r["exec_ratio"] <= 2.0, (
            f"{name}: identical programs measured exec_ratio "
            f"{r['exec_ratio']:.3f} even after the interleaved "
            f"re-timing pass — timing harness is broken; {r}"
        )
        return
    # 1. the never-lose bound for genuinely different programs — a
    # sub-floor first pass gets ONE independent re-timing (see
    # _remeasure NOTE) so a single jittered block cannot fail CI
    if r["exec_ratio"] < NOISE_FLOOR:
        r = _remeasure(name)
    assert r["exec_ratio"] >= NOISE_FLOOR, (
        f"{name}: searched strategy executed {1 / r['exec_ratio']:.2f}x "
        f"SLOWER than plain DP on two independent timing passes (sim "
        f"predicted {r['sim_ratio']:.2f}x win) — "
        f"the cost model is misranking; details: {r}"
    )
    # 2. sub-margin predictions must collapse to DP itself (identical
    # programs — the champion-vs-DP floor's whole point)
    assert r["sim_ratio"] >= 1.03, (
        f"{name}: predicted win {r['sim_ratio']:.3f} is inside the "
        f"uncertainty margin yet the search returned a non-DP strategy"
    )
    # 3. direction: a big predicted win must be a real win — with the
    # same one-shot interleaved re-timing as the never-lose bound
    # (_remeasure NOTE): a first pass measured on the contended host
    # can report the searched program a few % slow even when the win is
    # real, and this was the only timing assert without the retry
    if r["sim_ratio"] >= BIG_WIN:
        if r["exec_ratio"] <= 1.0:
            r = _remeasure(name)
        assert r["exec_ratio"] > 1.0, (
            f"{name}: sim predicted {r['sim_ratio']:.2f}x but execution "
            f"measured {r['exec_ratio']:.3f} — direction violated; {r}"
        )


def test_compute_parallel_search_win_executes_for_bert():
    """The round-4 gap, closed: a COMPUTE-PARALLEL (TP) searched
    strategy for a transformer must beat plain DP by >=1.1x when both
    programs actually run — not merely in the simulator (reference
    contract: scripts/osdi22ae/bert.sh runs the same two-program
    comparison; measured here: ~3.7x on the 8-device CPU mesh)."""
    r = _run_pair("bert_tp")
    assert not r["searched_is_dp"], (
        "search returned plain DP for the sync-bound regime — the "
        "two-program comparison degenerated"
    )
    assert r["sim_ratio"] >= 1.5, r
    if r["exec_ratio"] < 1.1:  # same one-shot re-timing as the
        r = _remeasure("bert_tp")  # never-lose bound (_remeasure NOTE)
    assert r["exec_ratio"] >= 1.1, (
        f"compute-parallel searched strategy won only "
        f"{r['exec_ratio']:.3f}x executed on two independent timing "
        f"passes (sim {r['sim_ratio']:.3f}x); {r}"
    )
