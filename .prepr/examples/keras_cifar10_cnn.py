#!/usr/bin/env python
"""Keras CIFAR-10 CNN example (reference:
examples/python/keras/ — the cifar10_cnn family of scripts, plus the
accuracy-callback discipline of accuracy.py).

Loads CIFAR-10 through the dataset loader — REAL data when the archive
is cached locally, a loudly-warned deterministic synthetic fallback
otherwise (zero-egress environments) — and trains a small conv net
with checkpointing and early stopping.

Usage: python examples/keras_cifar10_cnn.py -b 32 -e 2
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

from flexflow_tpu import keras
from flexflow_tpu.config import FFConfig


def main():
    config = FFConfig.parse_args()
    (x_train, y_train), _ = keras.datasets.cifar10.load_data()
    # loader is NCHW like the reference's; the model is NHWC-native
    n = min(len(x_train), config.batch_size * 16)
    x = (x_train[:n].transpose(0, 2, 3, 1) / 255.0).astype(np.float32)
    y = y_train[:n].astype(np.int32)

    model = keras.Sequential([
        keras.layers.Conv2D(32, (3, 3), activation="relu", padding="same",
                            input_shape=(32, 32, 3)),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Conv2D(64, (3, 3), activation="relu", padding="same"),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dropout(0.25),
        keras.layers.Dense(10),
    ])
    ckpt_dir = os.path.join(tempfile.gettempdir(), "ff_keras_cifar_ckpt")
    model.compile(optimizer=keras.optimizers.SGD(0.02),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], config=config)
    model.fit(x, y, epochs=config.epochs, callbacks=[
        keras.callbacks.ModelCheckpoint(ckpt_dir),
        keras.callbacks.EarlyStopping(monitor="loss", patience=3),
    ])
    print(model.summary())


if __name__ == "__main__":
    main()
