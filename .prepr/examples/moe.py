#!/usr/bin/env python
"""Mixture-of-Experts example with dynamic recompilation
(reference: examples/cpp/mixture_of_experts/moe.cc:46-92 — the cache
score drives a RecompileState trigger; alter() flips the gate to the
cached expert assignments mid-training)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_moe
from flexflow_tpu.runtime.recompile import RecompileState, cache_score


def main():
    config = ff.FFConfig.parse_args()
    model = build_moe(config, use_cache=True)

    # reference moe.cc:73-84: trigger when the gate assignments have
    # stabilized — cache score (mean |live - cached|) dropped below the
    # initial churn — then switch to the cached assignments
    cache_node = model.node_by_name("gate_cache")
    scores = []

    def trigger(m):
        try:
            s = cache_score(m, "gate_cache")
        except KeyError:
            return False
        scores.append(s)
        return len(scores) >= 3 and s < 0.92 * max(scores[:3])

    def alter(m):
        print(f"[moe] recompiling with cached assignments (score={scores[-1]:.4f})")
        cache_node.op.attrs["use_cached"] = True

    run_example(model, "moe", recompile_state=RecompileState(trigger, alter))


if __name__ == "__main__":
    main()
