#!/usr/bin/env python
"""AlexNet example (reference: examples/cpp/AlexNet/alexnet.cc).

Usage: python examples/alexnet.py -b 64 -e 1 [--only-data-parallel]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_alexnet_cifar10


def main():
    config = ff.FFConfig.parse_args()
    model = build_alexnet_cifar10(config)
    run_example(model, "alexnet", optimizer=ff.SGDOptimizer(lr=0.01, momentum=0.9))


if __name__ == "__main__":
    main()
