#!/usr/bin/env python
"""PyTorch import example (reference: examples/python/pytorch/*):
trace a torch module, import via torch.fx, train with the framework.

Usage: python examples/pytorch_import.py -b 32 -e 2
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.frontends import PyTorchModel, transfer_torch_weights


def main():
    import torch.nn as nn

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(64, 256)
            self.act = nn.ReLU()
            self.fc2 = nn.Linear(256, 10)

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    config = ff.FFConfig.parse_args()
    torch_net = Net()
    model = ff.FFModel(config)
    x = model.create_tensor([config.batch_size, 64])
    PyTorchModel(torch_net).torch_to_ff(model, [x])
    model.compile(loss_type="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    transfer_torch_weights(torch_net, model)

    rng = np.random.default_rng(0)
    n = config.batch_size * 8
    centers = rng.normal(size=(10, 64)) * 2
    y = rng.integers(0, 10, n)
    xs = (centers[y] + rng.normal(size=(n, 64))).astype(np.float32)
    model.fit(x=xs, y=y.astype(np.int32))


if __name__ == "__main__":
    main()
