"""Shared runner for example scripts: synthetic data generation, train
loop, throughput report — the role of each reference example's
top_level_task + DataLoader (e.g. transformer.cc:112-211)."""

from __future__ import annotations

import time
from typing import List, Sequence

import numpy as np

import flexflow_tpu as ff


def synthetic_inputs(model: ff.FFModel, num_samples: int, seed: int = 0) -> List[np.ndarray]:
    """Generate arrays matching the model's input tensors (batch dim
    replaced by num_samples)."""
    rng = np.random.default_rng(seed)
    out = []
    for t in model._input_tensors:
        shape = (num_samples,) + tuple(t.sizes[1:])
        if t.dtype.value.startswith("int"):
            # embedding ids: stay in-range; find the consumer's vocab if any
            vocab = 1000
            node, _ = model._producer[t.guid]
            for e in model.graph.out_edges[node.guid]:
                consumer = model.graph.nodes[e.dst].op
                if "num_entries" in consumer.attrs:
                    vocab = consumer.attrs["num_entries"]
            out.append(rng.integers(0, vocab, size=shape).astype(np.int32))
        else:
            out.append(rng.normal(size=shape).astype(np.float32))
    return out


def lm_sequence_data(num_samples: int, seq_len: int, vocab: int, seed: int = 0):
    """(x, y) for next-token training on the deterministic rule
    token[j] = (token[j-1] * 3 + 1) mod vocab — learnable by a causal
    model; shared by examples/gpt.py and the zoo test so the asserted
    rule and the demonstrated rule cannot drift apart."""
    rng = np.random.default_rng(seed)
    x = np.empty((num_samples, seq_len), np.int32)
    x[:, 0] = rng.integers(0, vocab, num_samples)
    for j in range(1, seq_len):
        x[:, j] = (x[:, j - 1] * 3 + 1) % vocab
    return x, np.roll(x, -1, axis=1)


def synthetic_labels(model: ff.FFModel, num_samples: int, loss: str, seed: int = 1):
    rng = np.random.default_rng(seed)
    sink = model.graph.sinks()[-1]
    out_shape = sink.op.output_shapes[0].sizes
    if loss == "sparse_categorical_crossentropy":
        if len(out_shape) > 2:  # per-position logits (causal LM)
            return rng.integers(
                0, out_shape[-1], (num_samples,) + tuple(out_shape[1:-1])
            ).astype(np.int32)
        return rng.integers(0, out_shape[-1], num_samples).astype(np.int32)
    return rng.normal(size=(num_samples,) + tuple(out_shape[1:])).astype(np.float32)


def run_example(model: ff.FFModel, name: str, loss: str = "sparse_categorical_crossentropy",
                metrics: Sequence[str] = ("accuracy",), num_samples: int = 0,
                optimizer=None, recompile_state=None, skip_compile=False):
    cfg = model.config
    num_samples = num_samples or cfg.batch_size * 8
    if not skip_compile:
        t0 = time.perf_counter()
        model.compile(optimizer=optimizer, loss_type=loss, metrics=list(metrics))
        print(f"[{name}] compile (incl. strategy search): {time.perf_counter()-t0:.2f}s")
    xs = synthetic_inputs(model, num_samples)
    y = synthetic_labels(model, num_samples, loss)
    model.fit(x=xs if len(xs) > 1 else xs[0], y=y, recompile_state=recompile_state)
    thr = getattr(model, "last_throughput", None)
    if thr:
        print(f"[{name}] THROUGHPUT = {thr:.2f} samples/s")
    return model
