#!/usr/bin/env python
"""Real-data accuracy regression example (reference:
examples/python/keras/accuracy.py + tests/accuracy_tests.sh — train a
model on real data to a checked accuracy).  Uses the UCI digits
bundled with scikit-learn: genuine handwritten scans available with
zero egress.  The mnist/cifar10 loaders use the true datasets when
their archives are cached locally and WARN when falling back.

Usage: python examples/digits_accuracy.py -b 32 -e 20
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.keras import datasets


def main():
    config = ff.FFConfig.parse_args()
    (xtr, ytr), (xte, yte) = datasets.digits.load_data()
    xtr = (xtr / 16.0).reshape(len(xtr), 64).astype(np.float32)
    xte = (xte / 16.0).reshape(len(xte), 64).astype(np.float32)

    m = ff.FFModel(config)
    x = m.create_tensor([config.batch_size, 64], name="pix")
    t = m.dense(x, 64, activation="relu", name="fc1")
    t = m.dense(t, 10, name="fc2")
    m.compile(optimizer=ff.SGDOptimizer(lr=0.1),
              loss_type="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x=xtr, y=ytr.astype(np.int32), epochs=config.epochs)
    logs = m.evaluate(x=xte, y=yte.astype(np.int32))
    print(f"TEST accuracy on real digits: {logs['accuracy']:.4f}")
    target = 0.90
    if logs["accuracy"] < target:
        raise SystemExit(f"accuracy {logs['accuracy']:.4f} below {target}")
    print("PASS")


if __name__ == "__main__":
    main()
