#!/usr/bin/env python
"""Search-discovered staged pipeline over a HETEROGENEOUS stack.

Eight dense layers with pairwise-different PRIME widths: no
tensor-parallel divisor exists and no two layers are isomorphic, so
neither TP nor the stacked-block pipeline applies — and the full
weight+optimizer footprint exceeds the modeled per-device HBM, so
every flat strategy is memory-infeasible.  compile() finds the
balanced S-stage partition itself (search/pipeline_search.py
propose_pipeline_general) and executes it with the general staged
executor: per-stage submesh programs driven as a microbatch wavefront
(compiler/staged_pipeline_lowering.py).

The reference stubs this capability entirely (OP_PIPELINE,
ffconst.h:148; inter-op splits graph.cc:161-295 are search-only).

Usage: python examples/staged_pipeline.py -b 16 -e 2
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import flexflow_tpu as ff


def main():
    import dataclasses

    import jax

    from flexflow_tpu.core.machine import MachineSpec

    config = ff.FFConfig.parse_args()
    n = config.num_devices or len(jax.devices())
    if n < 4:
        raise SystemExit(f"need >= 4 devices, have {n}")
    config.num_devices = n
    # model the memory-bound 2-host machine the regime needs
    config.machine_spec = dataclasses.replace(
        MachineSpec.tpu_v5e(n) if jax.devices()[0].platform == "tpu"
        else MachineSpec(num_devices=n, platform="cpu"),
        devices_per_host=max(2, n // 2), hbm_capacity=40e6, ici_torus=())

    m = ff.FFModel(config)
    t = m.create_tensor([config.batch_size, 1021], name="x")
    for i, w in enumerate((1019, 1013, 1009, 997, 991, 983, 977, 1021)):
        t = m.dense(t, w, activation="relu", name=f"layer{i}_fc")
    t = m.dense(t, 1021, name="head")
    m.compile(optimizer=ff.SGDOptimizer(lr=0.05),
              loss_type="mean_squared_error",
              metrics=["mean_squared_error"])

    from flexflow_tpu.compiler.staged_pipeline_lowering import (
        StagedPipelinedModel,
    )

    if config.only_data_parallel:
        # smoke tier runs every example with --only-data-parallel: the
        # search is bypassed, so the flat lowering is expected here
        print("only-data-parallel: staged pipelining bypassed")
    else:
        assert isinstance(m.compiled, StagedPipelinedModel), type(m.compiled)
        print(f"search staged the stack: S={m.compiled.num_stages} stages"
              f" x {config.num_devices // m.compiled.num_stages} devices, "
              f"M={m.compiled.num_microbatches} microbatches — executed, "
              f"not simulated")

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(64, 1021)).astype(np.float32)
    ys = np.zeros((64, 1021), np.float32)
    m.fit(x=xs, y=ys, epochs=config.epochs)


if __name__ == "__main__":
    main()
