#!/usr/bin/env python
"""tf.keras import example (reference: python/flexflow/keras_exp/ —
traverse a built tf.keras model's layer graph, emit the matching
FFModel, transfer weights, train).  Imports a small transformer
encoder block — MultiHeadAttention included (round-4 addition).

Usage: python examples/tf_keras_import.py -b 8 -e 2
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import flexflow_tpu as ff


def main():
    config = ff.FFConfig.parse_args()
    try:
        import tensorflow as tf
        from tensorflow.keras import layers as L
    except ImportError:
        raise SystemExit("tensorflow is not installed; this example "
                         "needs the tf.keras frontend's source library")

    from flexflow_tpu.frontends import TFKerasModel, transfer_tf_weights

    D, H, S = 32, 4, 10
    inp = tf.keras.Input((S, D))
    att = L.MultiHeadAttention(num_heads=H, key_dim=D // H, name="mha")(
        inp, inp)
    h = L.LayerNormalization(name="ln1")(L.Add(name="res1")([inp, att]))
    f = L.Dense(64, activation="gelu", name="ff1")(h)
    f = L.Dense(D, name="ff2")(f)
    h = L.LayerNormalization(name="ln2")(L.Add(name="res2")([h, f]))
    out = L.Dense(4, name="cls")(L.Flatten(name="fl")(h))
    tfm = tf.keras.Model(inp, out)

    model = ff.FFModel(config)
    x = model.create_tensor([config.batch_size, S, D])
    TFKerasModel(tfm).to_ff(model, [x])
    model.compile(loss_type="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    n = transfer_tf_weights(tfm, model)
    print(f"imported tf.keras transformer block: {model.graph.num_nodes} "
          f"ops, {n} weights transferred")

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(64, S, D)).astype(np.float32)
    ys = rng.integers(0, 4, 64).astype(np.int32)
    model.fit(x=xs, y=ys, epochs=config.epochs)


if __name__ == "__main__":
    main()
