#!/usr/bin/env python
"""DLRM example (reference: examples/cpp/DLRM/dlrm.cc; osdi22ae/dlrm.sh)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_dlrm


def main():
    config = ff.FFConfig.parse_args()
    import jax

    if jax.devices()[0].platform == "tpu":
        model = build_dlrm(config)  # full reference size (dlrm.cc:27-44)
    else:
        # CPU/virtual-mesh smoke size: full-size tables (8 x 1M x 64
        # + optimizer state, replicated per virtual device) exceed host
        # RAM; the reference sizes its examples per-hardware via flags
        # the same way
        model = build_dlrm(config, embedding_sizes=(100000,) * 8,
                           embedding_dim=32)
    run_example(model, "dlrm", loss="mean_squared_error",
                metrics=["mean_squared_error"])


if __name__ == "__main__":
    main()
