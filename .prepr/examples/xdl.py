#!/usr/bin/env python
"""XDL example — embedding-heavy ads/recommendation model
(reference: examples/cpp/XDL/xdl.cc).

Usage: python examples/xdl.py -b 256 -e 1
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_xdl


def main():
    config = ff.FFConfig.parse_args()
    model = build_xdl(config)
    run_example(model, "xdl")


if __name__ == "__main__":
    main()
