#!/usr/bin/env python
"""MLP_Unify example — the minimal two-branch MLP whose best strategy
mixes data and model parallelism (reference: examples/cpp/MLP_Unify/
mlp.cc; an osdi22ae workload).

Usage: python examples/mlp_unify.py -b 64 -e 1
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_mlp_unify


def main():
    config = ff.FFConfig.parse_args()
    import jax

    if jax.devices()[0].platform == "tpu":
        model = build_mlp_unify(config)  # full 8192^3 (mlp.cc)
    else:
        # CPU/virtual-mesh smoke size: three 8192^2 dense layers take
        # minutes per epoch on a 1-core host; the reference sizes its
        # examples per-hardware via flags the same way
        model = build_mlp_unify(config, in_dim=1024,
                                hidden=(1024, 1024, 1024))
    run_example(model, "mlp_unify")


if __name__ == "__main__":
    main()
