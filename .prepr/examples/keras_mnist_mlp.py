#!/usr/bin/env python
"""Keras-frontend example (reference: examples/python/keras/ scripts —
Sequential MNIST-style MLP with callbacks).

Usage: python examples/keras_mnist_mlp.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import flexflow_tpu as ffpkg  # noqa: F401 (package path setup)
from flexflow_tpu import keras
from flexflow_tpu.config import FFConfig


def main():
    config = FFConfig.parse_args()
    model = keras.Sequential([
        keras.layers.Dense(256, activation="relu", input_shape=(784,)),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dropout(0.1),
        keras.layers.Dense(10),
    ])
    model.compile(optimizer=keras.optimizers.SGD(0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], config=config)
    # synthetic MNIST-shaped data (the reference ships dataset loaders;
    # zero-egress environments use synthetic samples)
    rng = np.random.default_rng(0)
    n = config.batch_size * 16
    digits = rng.integers(0, 10, n)
    x = (rng.normal(size=(n, 784)) * 0.1 + digits[:, None] / 10.0).astype(np.float32)
    model.fit(x, digits.astype(np.int32), epochs=config.epochs,
              callbacks=[keras.callbacks.EarlyStopping(monitor="loss", patience=2)])
    print(model.summary())


if __name__ == "__main__":
    main()
