#!/usr/bin/env python
"""CANDLE-Uno example (reference: examples/cpp/candle_uno/candle_uno.cc)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_candle_uno


def main():
    config = ff.FFConfig.parse_args()
    model = build_candle_uno(config)
    run_example(model, "candle_uno", loss="mean_squared_error",
                metrics=["mean_squared_error"])


if __name__ == "__main__":
    main()
