#!/usr/bin/env python
"""Transformer example (reference: examples/cpp/Transformer/transformer.cc;
osdi22ae/bert.sh runs this with -b 8 --budget 30).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_transformer


def main():
    config = ff.FFConfig.parse_args()
    import jax

    if jax.devices()[0].platform == "tpu":
        # full reference size (transformer.cc:112-211: 12-layer encoder)
        model = build_transformer(config, num_layers=12, hidden=512,
                                  num_heads=8, ff_dim=2048, seq_len=512)
    else:
        # CPU smoke size: XLA CPU compiles the full-size 8-way-sharded
        # program impractically slowly (SPMD rematerialization); the
        # reference sizes examples per-hardware via flags the same way
        model = build_transformer(config, num_layers=4, hidden=256,
                                  num_heads=4, ff_dim=512, seq_len=128)
    run_example(model, "transformer", loss="mean_squared_error",
                metrics=["mean_squared_error"])


if __name__ == "__main__":
    main()
