#!/usr/bin/env python
"""ONNX import example (reference: examples/python/onnx/ — load an
.onnx model, apply it onto an FFModel, train).

With ``--model file.onnx`` any ONNX file is imported (the vendored
wire-format reader parses it even without the onnx package); without
one, a small CNN is built and serialized first so the example is
self-contained in a zero-egress environment.

Usage: python examples/onnx_import.py -b 16 -e 2 [--model net.onnx]
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.frontends import ONNXModel


def _make_demo_onnx(path: str) -> None:
    from flexflow_tpu.frontends.onnx_minimal import (
        TensorProto,
        helper,
        numpy_helper,
        save,
    )

    rng = np.random.default_rng(0)
    wc = rng.normal(size=(8, 3, 3, 3)).astype(np.float32) * 0.2
    bc = np.zeros(8, np.float32)
    wl = rng.normal(size=(10, 8 * 8 * 8)).astype(np.float32) * 0.1
    bl = np.zeros(10, np.float32)
    nodes = [
        helper.make_node("Conv", ["x", "wc", "bc"], ["h1"], name="conv1",
                         kernel_shape=[3, 3], strides=[1, 1],
                         pads=[1, 1, 1, 1]),
        helper.make_node("Relu", ["h1"], ["h2"], name="relu1"),
        helper.make_node("MaxPool", ["h2"], ["h3"], name="pool1",
                         kernel_shape=[2, 2], strides=[2, 2]),
        helper.make_node("Flatten", ["h3"], ["h4"], name="flat"),
        helper.make_node("Gemm", ["h4", "wl", "bl"], ["y"], name="fc",
                         transB=1),
    ]
    g = helper.make_graph(
        nodes, "demo_cnn",
        [helper.make_tensor_value_info("x", TensorProto.FLOAT,
                                       (0, 3, 16, 16))],
        [helper.make_tensor_value_info("y", TensorProto.FLOAT, (0, 10))],
        [numpy_helper.from_array(a, n) for a, n in
         ((wc, "wc"), (bc, "bc"), (wl, "wl"), (bl, "bl"))],
    )
    save(helper.make_model(g), path)


def main():
    path = None
    argv = sys.argv[1:]
    if "--model" in argv:
        i = argv.index("--model")
        path = argv[i + 1]
        del argv[i:i + 2]
        sys.argv = [sys.argv[0]] + argv
    config = ff.FFConfig.parse_args()
    if path is None:
        path = os.path.join(tempfile.gettempdir(), "ff_demo_cnn.onnx")
        _make_demo_onnx(path)
        print(f"serialized demo CNN to {path}")

    model = ff.FFModel(config)
    om = ONNXModel(path)
    x = model.create_tensor([config.batch_size, 3, 16, 16], name="x")
    om.apply(model, {om.model.graph.input[0].name: x})
    model.compile(optimizer=ff.SGDOptimizer(lr=0.05),
                  loss_type="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    n = om.transfer_onnx_weights(model)
    print(f"imported {path}: {model.graph.num_nodes} ops, "
          f"{n} weights transferred")

    rng = np.random.default_rng(1)
    xs = rng.normal(size=(128, 3, 16, 16)).astype(np.float32)
    ys = rng.integers(0, 10, 128).astype(np.int32)
    model.fit(x=xs, y=ys, epochs=config.epochs)


if __name__ == "__main__":
    main()
