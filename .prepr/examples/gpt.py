#!/usr/bin/env python
"""GPT-style causal language model (beyond the reference zoo — its
Transformer example is a non-causal MSE proxy, transformer.cc:112-211).

Trains next-token prediction with per-token sparse CCE; the causal MHA
rides the Pallas flash kernel, and sharding the seq dim takes the
zigzag ring-attention path for long contexts.

Usage: python examples/gpt.py -b 8 -e 1
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import lm_sequence_data
from flexflow_tpu.models import build_gpt


def main():
    config = ff.FFConfig.parse_args()
    on_tpu = False
    try:
        import jax

        on_tpu = jax.devices()[0].platform != "cpu"
    except Exception:
        pass
    if on_tpu:
        vocab, layers, hidden, heads, ff_dim, seq = 32000, 12, 768, 12, 3072, 512
    else:  # CI-sized
        vocab, layers, hidden, heads, ff_dim, seq = 512, 2, 64, 4, 128, 32

    model = build_gpt(config, vocab=vocab, num_layers=layers, hidden=hidden,
                      num_heads=heads, ff_dim=ff_dim, seq_len=seq)
    model.compile(
        optimizer=ff.AdamOptimizer(alpha=3e-4),
        loss_type="sparse_categorical_crossentropy",
        metrics=["accuracy", "sparse_categorical_crossentropy"],
    )

    n = config.batch_size * 8
    x, y = lm_sequence_data(n, seq, vocab, seed=config.seed)
    model.fit(x=x, y=y, epochs=config.epochs)


if __name__ == "__main__":
    main()
