#!/usr/bin/env python
"""InceptionV3 example (reference: examples/cpp/InceptionV3/inception.cc;
osdi22ae/inception.sh runs -b 64 --budget 10)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_inception_v3


def main():
    config = ff.FFConfig.parse_args()
    model = build_inception_v3(config)
    run_example(model, "inception_v3", optimizer=ff.SGDOptimizer(lr=0.01, momentum=0.9))


if __name__ == "__main__":
    main()
