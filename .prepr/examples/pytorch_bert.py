#!/usr/bin/env python
"""HuggingFace BERT import example (reference: examples/python/pytorch
bert_proxy / mt5 — those trace hand-built proxies; this imports the
real `transformers.BertModel` through torch.fx and trains it).

The importer constant-folds the HF mask-construction chain, decomposes
scaled_dot_product_attention into PCG ops, and carries module buffers
(position ids) as compile-time constants — see
flexflow_tpu/frontends/torch_fx.py.

Usage: python examples/pytorch_bert.py -b 8 -e 1
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.frontends import PyTorchModel, transfer_torch_weights


def main():
    import torch
    import transformers
    from transformers.utils import fx as hf_fx

    config = ff.FFConfig.parse_args()
    B, S, H = config.batch_size, 32, 128

    bcfg = transformers.BertConfig(
        hidden_size=H, num_hidden_layers=4, num_attention_heads=4,
        intermediate_size=4 * H, vocab_size=2048, max_position_embeddings=S,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    tmodel = transformers.BertModel(bcfg)
    tmodel.eval()
    gm = hf_fx.symbolic_trace(tmodel, input_names=["input_ids"])

    model = ff.FFModel(config)
    ids = model.create_tensor([B, S], dtype="int32")
    example = torch.randint(0, bcfg.vocab_size, (B, S))
    outs = PyTorchModel(gm, example_inputs=[example]).torch_to_ff(model, [ids])
    print("imported BERT outputs:", [tuple(o.sizes) for o in outs])

    model.compile(
        optimizer=ff.AdamOptimizer(alpha=1e-4),
        loss_type="mean_squared_error",
        metrics=["mean_squared_error"],
    )
    transfer_torch_weights(tmodel, model)

    rng = np.random.default_rng(config.seed)
    n = B * 8
    x = rng.integers(0, bcfg.vocab_size, (n, S)).astype(np.int32)
    y = rng.normal(size=(n, outs[-1].sizes[-1])).astype(np.float32)
    model.fit(x=x, y=y, epochs=config.epochs)


if __name__ == "__main__":
    main()
