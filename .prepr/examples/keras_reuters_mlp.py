#!/usr/bin/env python
"""Keras Reuters topic-classification MLP (reference:
examples/python/keras/reuters_mlp.py — tokenized newswire sequences,
multi-hot encoding, dense classifier).

Usage: python examples/keras_reuters_mlp.py -b 32 -e 2
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

from flexflow_tpu import keras
from flexflow_tpu.config import FFConfig

NUM_WORDS = 2000
CLASSES = 46


def _multi_hot(seqs: np.ndarray) -> np.ndarray:
    """keras reuters semantics: ids >= num_words are out-of-vocabulary
    and simply absent from the multi-hot encoding (folding them with a
    modulo would alias unrelated words onto real features)."""
    out = np.zeros((len(seqs), NUM_WORDS), np.float32)
    for i, row in enumerate(seqs):
        ids = np.asarray(row)
        out[i, ids[ids < NUM_WORDS]] = 1.0
    return out


def main():
    config = FFConfig.parse_args()
    (x_train, y_train), _ = keras.datasets.reuters.load_data(
        num_words=NUM_WORDS, maxlen=100)
    n = min(len(x_train), config.batch_size * 16)
    x = _multi_hot(x_train[:n])
    y = y_train[:n].astype(np.int32)

    model = keras.Sequential([
        keras.layers.Dense(256, activation="relu",
                           input_shape=(NUM_WORDS,)),
        keras.layers.Dropout(0.2),
        keras.layers.Dense(CLASSES),
    ])
    model.compile(optimizer=keras.optimizers.Adam(1e-3),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], config=config)
    model.fit(x, y, epochs=config.epochs)
    print(model.summary())


if __name__ == "__main__":
    main()
