#!/usr/bin/env python
"""Ulysses all-to-all sequence parallelism example.

A small encoder whose attention runs under an explicit seq-sharded
strategy with ``sp_mode="ulysses"``: the head-exchange all-to-all pair
(parallel/ulysses.py) serves the sharded sequence dim instead of the
K/V ring, moving 2/n of the ring's wire bytes.  The reference cannot
split MHA's sequence dim at all (substitution.cc:2599-2654 — sample-dim
repartition and head split only; SURVEY.md §5 gap), so both SP schemes
are beyond-reference capabilities.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.core.machine import MachineView


def main():
    config = ff.FFConfig.parse_args()
    b, s, e, heads = config.batch_size, 64, 64, 8
    m = ff.FFModel(config)
    x = m.create_tensor([b, s, e], name="tokens")
    t = m.multihead_attention(x, x, x, embed_dim=e, num_heads=heads,
                              causal=True, sp_mode="ulysses", name="mha")
    t = m.dense(t, e, activation="relu", name="ff1")
    t = m.mean(t, dims=[1], name="pool")
    t = m.dense(t, 8, name="head")

    # dp x sp hybrid: batch degree 2 everywhere (the stock DP helper
    # handles divisibility/fixed-view edge cases), the attention also
    # shards its sequence dim sp-ways — served by the ulysses exchange
    from flexflow_tpu.compiler.lowering import data_parallel_strategy

    n = config.num_devices
    sp = max(1, min(4, n // 2, heads))
    strategy = dict(data_parallel_strategy(m.graph, min(2, n)))
    mha = m.node_by_name("mha")
    dp_deg = strategy[mha.guid].dim_degrees[0]
    if n >= dp_deg * sp and s % sp == 0 and b % max(dp_deg, 1) == 0:
        strategy[mha.guid] = MachineView(dim_degrees=(dp_deg, sp, 1))
    m.compile(loss_type="sparse_categorical_crossentropy",
              metrics=["accuracy"], strategy=strategy)
    run_example(m, "ulysses_sp", loss="sparse_categorical_crossentropy",
                skip_compile=True)


if __name__ == "__main__":
    main()
