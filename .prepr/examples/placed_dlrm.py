#!/usr/bin/env python
"""Executed inter-op placement example: DLRM-style embeddings on the
FIRST half of the devices while the MLP runs on the SECOND half — the
reference mapper's VERTICAL placement (mapper.cc:371-475), executed as
two submesh programs whose async dispatch overlaps consecutive steps
(compiler/placement_lowering.py).

Usage: python examples/placed_dlrm.py -b 32 -e 2
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import flexflow_tpu as ff


def main():
    config = ff.FFConfig.parse_args()
    import jax

    n = config.num_devices or len(jax.devices())
    if n < 2 or n % 2:
        raise SystemExit(f"need an even device count >= 2, have {n}")
    half = n // 2
    config.num_devices = n

    V, D, S = 1000, 16, 4
    m = ff.FFModel(config)
    ids = m.create_tensor([config.batch_size, S], dtype="int32", name="ids")
    e = m.embedding(ids, V, D, name="emb")
    h = m.flat(e, name="flatten")
    h = m.dense(h, 64, activation="relu", name="mlp1")
    h = m.dense(h, 1, name="head")

    strat = {}
    for node in m.graph.topo_order():
        nd = node.op.output_shapes[0].ndim
        start = half if node.op.name in ("mlp1", "head") else 0
        strat[node.guid] = (
            node.op.fixed_machine_view()
            or ff.MachineView(dim_degrees=(half,) + (1,) * (nd - 1),
                              start_part=start)
        )
    m.compile(loss_type="mean_squared_error", metrics=["mean_squared_error"],
              strategy=strat)

    from flexflow_tpu.compiler.placement_lowering import PlacedCompiledModel

    assert isinstance(m.compiled, PlacedCompiledModel)
    print(f"embeddings on devices [0,{half}), MLP on [{half},{n}) — "
          f"executed, not simulated")

    rng = np.random.default_rng(0)
    xs = rng.integers(0, V, (256, S)).astype(np.int32)
    ys = (xs.sum(axis=1, keepdims=True) / (S * V)).astype(np.float32)
    m.fit(x=xs, y=ys, epochs=config.epochs)


if __name__ == "__main__":
    main()
