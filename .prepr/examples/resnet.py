#!/usr/bin/env python
"""ResNet / ResNeXt-50 example (reference: examples/cpp/ResNet/resnet.cc,
examples/cpp/resnext50/resnext.cc).

Usage: python examples/resnet.py -b 64 -e 1 [--resnext] [--only-data-parallel]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import flexflow_tpu as ff
from examples.common import run_example
from flexflow_tpu.models import build_resnet, build_resnext50


def main():
    config = ff.FFConfig.parse_args()
    if "--resnext" in sys.argv:
        model = build_resnext50(config, num_classes=1000, image=64)
        name = "resnext50"
    else:
        model = build_resnet(config, num_classes=1000, image=64)
        name = "resnet"
    run_example(model, name, optimizer=ff.SGDOptimizer(lr=0.01, momentum=0.9))


if __name__ == "__main__":
    main()
