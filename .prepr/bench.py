#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line.

Measures training throughput (samples/s) and MFU of the flagship model
(Transformer encoder, the reference's examples/cpp/Transformer workload:
transformer.cc:112-211 self-reports THROUGHPUT the same way) on the
available accelerator.  The reference repo publishes no absolute
numbers (BASELINE.md), so vs_baseline reports delivered MFU against a
0.40 good-utilization bar for this workload — exceeding 1.0 means the
chip is running at better than 40% of bf16 MXU peak.
"""

import json
import os
import sys
import time

import numpy as np


LAST_GOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_LASTGOOD.json")


def _subprocess_probe(timeout_s: float):
    """Probe the accelerator in a FRESH subprocess: a wedged device
    tunnel hangs backend init forever IN-PROCESS (observed: a
    remote-compile failure left the relay claiming for hours), and a
    hung plugin cannot be re-initialized from the same interpreter —
    only a new process gets a clean attempt.  Returns
    ("ok" | "error" | "hung", stderr_text) — a fast nonzero exit is a
    deterministic environment breakage whose cause must be SURFACED,
    not papered over with a stale fallback."""
    import subprocess

    # JAX_PLATFORMS=cpu alone is NOT honored under the axon TPU plugin
    # (its sitecustomize re-selects the platform at import); a CPU-
    # forced bench must force it via jax.config before backend init
    code = (
        "import os, jax; "
        "os.environ.get('JAX_PLATFORMS') == 'cpu' and "
        "jax.config.update('jax_platforms', 'cpu'); "
        "import jax.numpy as jnp; "
        "d = jax.devices(); x = jnp.ones((64, 64)); "
        "(x @ x).block_until_ready(); print(d[0].platform)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            timeout=timeout_s, text=True,
        )
        return ("ok" if r.returncode == 0 else "error"), r.stderr
    except subprocess.TimeoutExpired as e:
        err = getattr(e, "stderr", None) or b""
        if isinstance(err, bytes):
            err = err.decode("utf-8", "replace")
        return "hung", err


def _probe_backend(timeout_s: float = 120.0, attempts: int = 3,
                   retry_wait_s: float = 20.0):
    """Fail-SOFT accelerator probe with bounded recovery.  Each attempt
    runs in a fresh subprocess (see _subprocess_probe); only after the
    subprocess confirms a live backend does THIS process touch jax.
    Returns jax.devices() on success, None when the backend stays
    unresponsive — the caller then falls back to the last good
    measurement window instead of recording nothing (round-3 failure:
    BENCH_r03.json was an rc=3 tombstone)."""
    for attempt in range(1, attempts + 1):
        status, stderr = _subprocess_probe(timeout_s)
        if status == "error":
            # deterministic breakage (bad plugin/env), not a wedge:
            # surface the actual cause and fail hard — a stale fallback
            # here would report an old number forever
            print("# bench: backend probe ERRORED (not hung); stderr:",
                  file=sys.stderr)
            print(stderr[-2000:], file=sys.stderr)
            os._exit(2)
        if status == "ok":
            import threading

            done = threading.Event()
            out = []

            def _try():
                try:
                    import jax

                    if os.environ.get("JAX_PLATFORMS") == "cpu":
                        jax.config.update("jax_platforms", "cpu")
                    import jax.numpy as jnp

                    devs = jax.devices()
                    x = jnp.ones((64, 64))
                    (x @ x).block_until_ready()
                    out.append(devs)
                except Exception as e:  # pragma: no cover
                    out.append(e)
                finally:
                    done.set()

            t = threading.Thread(target=_try, daemon=True)
            t.start()
            # subprocess said alive; in-process init can still wedge
            if done.wait(timeout_s):
                if isinstance(out[0], Exception):
                    # the tunnel's documented failure mode is transient
                    # RPC errors FOLLOWED by wedges — surface the error
                    # and spend the remaining attempts before falling
                    # back (the subprocess 'error' path above handles
                    # deterministic env breakage with a hard exit)
                    print(
                        f"# bench: in-process backend init raised "
                        f"(attempt {attempt}/{attempts}): "
                        f"{type(out[0]).__name__}: {out[0]}",
                        file=sys.stderr,
                    )
                    if attempt < attempts:
                        time.sleep(retry_wait_s)
                    continue
                return out[0]
            print(
                f"# bench: in-process backend init hung after a "
                f"successful subprocess probe (attempt {attempt})",
                file=sys.stderr,
            )
            return None  # this interpreter is wedged; don't retry here
        print(
            f"# bench: accelerator unresponsive after {timeout_s:.0f}s "
            f"(attempt {attempt}/{attempts})"
            + (f"; retrying in {retry_wait_s:.0f}s" if attempt < attempts
               else ""),
            file=sys.stderr,
        )
        if attempt < attempts:
            time.sleep(retry_wait_s)
    return None


def _emit_last_good_or_die():
    """The tunnel stayed wedged: re-emit the most recent good
    measurement window, clearly marked stale, so the round still
    records a parsed number with provenance instead of a tombstone."""
    if os.path.exists(LAST_GOOD_PATH):
        with open(LAST_GOOD_PATH) as f:
            rec = json.load(f)
        rec["stale"] = True
        rec["stale_reason"] = (
            "accelerator tunnel unresponsive; value is the last good "
            f"measurement window from {rec.get('measured_at', 'unknown')}"
        )
        print(json.dumps(rec))
        sys.stdout.flush()  # os._exit skips stdio flush — a piped stdout
        # would otherwise drop the record and exit 0 with empty output
        os._exit(0)
    print(
        "# bench: accelerator unreachable and no last-good window "
        "recorded",
        file=sys.stderr,
    )
    os._exit(3)  # hung init threads cannot be joined


def main():
    """Orchestrator: probe, then run the ENTIRE measurement in a fresh
    subprocess with a hard deadline — the tunnel's documented failure
    mode can wedge MID-measurement, and a wedged interpreter can only
    be abandoned, not recovered (round-4: two rc=3 tombstones).  The
    subprocess prints the JSON record; on timeout/failure the parent
    falls back to the last good window."""
    devices = _probe_backend()
    if devices is None:
        _emit_last_good_or_die()
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure"],
            timeout=1500.0, text=True, capture_output=True,
        )
    except subprocess.TimeoutExpired:
        print("# bench: measurement subprocess exceeded its deadline "
              "(tunnel wedged mid-run); falling back", file=sys.stderr)
        _emit_last_good_or_die()
    if r.returncode == 0 and r.stdout.strip():
        sys.stderr.write(r.stderr)
        print(r.stdout.strip().splitlines()[-1])
        return
    print(f"# bench: measurement subprocess failed rc={r.returncode}; "
          f"stderr tail:", file=sys.stderr)
    print(r.stderr[-2000:], file=sys.stderr)
    _emit_last_good_or_die()


def measure():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon plugin's sitecustomize overrides the env var; only a
        # pre-init jax.config update reliably forces CPU
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()

    on_tpu = devices[0].platform == "tpu" or "TPU" in str(devices[0])
    # sized for a single v5e chip; shrink on CPU so CI-style runs finish
    if on_tpu:
        batch, seq, hidden, layers, heads, ff_dim = 64, 256, 512, 6, 8, 2048
        steps = 30
        dtype = "bfloat16"
    else:
        batch, seq, hidden, layers, heads, ff_dim = 8, 32, 64, 2, 4, 128
        steps = 5
        dtype = "float32"

    import flexflow_tpu as ff
    from flexflow_tpu.models import build_transformer

    cfg = ff.FFConfig(
        batch_size=batch,
        epochs=1,
        num_devices=len(devices),
        only_data_parallel=len(devices) == 1,
        compute_dtype=dtype,
    )
    # bf16 activation stream on TPU: ops cast outputs back to the input
    # tensor's dtype, so a bf16 input keeps every inter-op activation at
    # 2 bytes (half the HBM traffic); matmuls still accumulate f32 and
    # loss/metrics upcast internally
    model = build_transformer(
        cfg, num_layers=layers, hidden=hidden, num_heads=heads,
        ff_dim=ff_dim, seq_len=seq, dtype=dtype,
    )
    model.compile(
        optimizer=ff.AdamOptimizer(alpha=1e-4),
        loss_type="mean_squared_error",
        metrics=["mean_squared_error"],
    )

    rng = np.random.default_rng(0)
    # N distinct batches stacked on a leading step axis: one
    # train_steps() call scans all N inside a single compiled program —
    # the XLA analogue of the reference's Legion iteration tracing
    # (flexflow_cffi.py:1867-1874), amortizing per-call dispatch (which
    # dominates through a remote-device tunnel)
    trace_n = 10 if on_tpu else steps
    import ml_dtypes

    in_np = np.float32 if dtype == "float32" else np.dtype(
        getattr(ml_dtypes, dtype))
    xs = rng.normal(size=(trace_n, batch, seq, hidden)).astype(in_np)
    ys = rng.normal(size=(trace_n, batch, seq, hidden)).astype(np.float32)
    xs_d = jax.device_put(xs, model.compiled.stacked_input_sharding(0))
    ys_d = jax.device_put(ys, model.compiled.stacked_batch_sharding())

    import jax.random as jrandom

    # warmup: first call compiles; later calls through the device tunnel
    # still need a few rounds to reach steady state
    params, opt_state, state = model.params, model.opt_state, model.state
    for i in range(3 if on_tpu else 1):
        params, opt_state, state, losses, m = model.compiled.train_steps(
            params, opt_state, state, jrandom.key(1000 + i), [xs_d], ys_d
        )
    float(losses[-1])  # host readback — block_until_ready may not fence
    # through remote-device tunnels, a readback always does

    # Timed block: reps calls dispatched back-to-back (async dispatch
    # keeps the device pipelined, as a real training loop would), one
    # readback fence at the end.  The block repeats and the MEDIAN block
    # time is reported — robust to tunnel-latency outliers that made
    # single-block runs swing by ~8%.  Per-call fencing would serialize
    # the pipeline and measure round-trips, not training.
    reps = max(1, steps // trace_n)
    block_times = []
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        for i in range(reps):
            params, opt_state, state, losses, m = model.compiled.train_steps(
                params, opt_state, state, jrandom.key(i + 1), [xs_d], ys_d
            )
        float(losses[-1])
        block_times.append(time.perf_counter() - t0)
    elapsed = float(np.median(block_times))
    steps = reps * trace_n
    throughput = steps * batch / elapsed

    # MFU = model FLOPs actually trained / elapsed / chip peak.  Forward
    # FLOPs come from the PCG's own per-op estimates (the same numbers the
    # cost model ranks strategies with); training ≈ 3x forward (bwd does
    # the two grad matmuls per fwd matmul).
    fwd_flops = sum(
        n.op.flops() for n in model.graph.nodes.values()
    )
    train_flops_per_step = 3.0 * fwd_flops
    from flexflow_tpu.core.machine import MachineSpec

    if on_tpu:
        kind = getattr(devices[0], "device_kind", "").lower().replace(" ", "")
        # bf16 MXU peaks per chip by generation; v5 "lite" spellings all
        # mean v5e silicon (the tunnel reports "tpuv5lite")
        known_peaks = {
            "v5p": 4.59e14,
            "v5e": 1.97e14,
            "v5litepod": 1.97e14,
            "v5lite": 1.97e14,
            "v6e": 9.2e14,
            "v6": 9.2e14,
            "v4": 2.75e14,
            "v3": 1.23e14,
        }
        peak = next(
            (p for k, p in known_peaks.items() if k in kind),
            MachineSpec.tpu_v5e(1).peak_flops,
        )
        if not any(k in kind for k in known_peaks):
            print(f"# warning: unknown TPU kind {kind!r}, assuming v5e peak",
                  file=sys.stderr)
    else:
        peak = MachineSpec.host_cpu(1).peak_flops
    mfu = train_flops_per_step * steps / elapsed / (peak * len(devices))
    # vs_baseline: the reference publishes no absolute numbers
    # (BASELINE.md); its per-chip contract is utilization, so report the
    # ratio of delivered MFU to a 40% good-MFU bar for this workload.
    record = {
        "metric": "transformer_train_throughput",
        "value": round(throughput, 2),
        "unit": "samples/s",
        "mfu": round(mfu, 4),
        "vs_baseline": round(mfu / 0.40, 3),
    }
    print(json.dumps(record))
    if on_tpu:
        # persist the window so a later wedged-tunnel run can re-emit a
        # real (stale-marked) number instead of a tombstone
        with open(LAST_GOOD_PATH, "w") as f:
            json.dump(
                {**record,
                 "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())},
                f, indent=1,
            )


if __name__ == "__main__":
    if "--measure" in sys.argv:
        measure()
    else:
        main()
