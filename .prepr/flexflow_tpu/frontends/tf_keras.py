"""tf.keras graph-traversal frontend.

Parity with the reference's experimental keras_exp frontend
(reference: python/flexflow/keras_exp/models/model.py — traverses a
real tf.keras Model's layer graph and emits the matching FFModel
calls).  TensorFlow weight layouts already match this framework
(Dense kernels are (in, out); convs are HWIO NHWC), so
``transfer_tf_weights`` is a straight copy.

TensorFlow is an optional dependency: constructing TFKerasModel
without it raises ImportError; nothing else imports tf.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

__all__ = ["TFKerasModel", "transfer_tf_weights"]


def _pads(padding: str, kernel, strides, in_hw) -> tuple:
    """Symmetric padding reproducing TF 'same' exactly, or raise.

    TF SAME pads total = max((ceil(in/s)-1)*s + k - in, 0) per dim,
    putting the extra pixel on the bottom/right when odd.  Our conv2d
    only supports symmetric padding, so an odd total (strided/even-
    kernel cases) cannot be reproduced — fail loudly instead of
    silently shifting the feature map."""
    if padding != "same":
        return (0, 0)
    out = []
    for i in range(2):
        s, k, n = strides[i], kernel[i], in_hw[i]
        total = max((-(-n // s) - 1) * s + k - n, 0)
        if total % 2:
            raise NotImplementedError(
                f"TF 'same' padding is asymmetric here (kernel={k}, "
                f"stride={s}, size={n}); pad explicitly in the source model")
        out.append(total // 2)
    return tuple(out)


def _act_name(layer):
    """tf layer activation -> framework activation name (None when
    linear) — one place for the idiom the Dense/Conv branches share."""
    act = (layer.activation.__name__
           if layer.activation is not None else None)
    return None if act == "linear" else act


def _conv_act(ff, layer, emit_conv, name):
    """Emit a conv-family layer honoring tf activation semantics: a
    separate EXACT-erf gelu (tf's default form; the fused one is the
    tanh approximation), fused otherwise — ConvOp itself asserts the
    fused activation is supported at BUILD time, so unsupported ones
    fail loudly at import for every caller."""
    act = _act_name(layer)
    if act == "gelu":
        y = emit_conv(None)
        return ff.gelu(y, name=f"{name}.gelu", approximate=False)
    return emit_conv(act)


class TFKerasModel:
    """Importer for a built tf.keras functional/Sequential model."""

    def __init__(self, tf_model):
        try:
            import tensorflow  # noqa: F401
        except ImportError as e:  # pragma: no cover
            raise ImportError("tensorflow is required for TFKerasModel") from e
        self.tf_model = tf_model

    # ------------------------------------------------------------------
    def to_ff(self, ffmodel, input_tensors: Sequence) -> List:
        """Emit the traversed layer graph onto ``ffmodel``; returns the
        output Tensors. ``input_tensors`` bind to tf_model.inputs in
        order."""
        import tensorflow as tf
        from tensorflow.keras import layers as L

        tfm = self.tf_model
        env: Dict[int, object] = {}
        for kt, t in zip(tfm.inputs, input_tensors):
            env[id(kt)] = t

        for layer in tfm.layers:
            if isinstance(layer, L.InputLayer):
                continue
            for node in layer._inbound_nodes:
                ins = []
                kept = node.keras_inputs if hasattr(node, "keras_inputs") else (
                    node.input_tensors)
                for kt in kept:
                    if id(kt) not in env:
                        break
                    ins.append(env[id(kt)])
                else:
                    outs = node.output_tensors if hasattr(node, "output_tensors") \
                        else [node.outputs]
                    if not isinstance(outs, (list, tuple)):
                        outs = [outs]
                    y = self._emit(ffmodel, layer, ins)
                    for kt, t in zip(outs, y if isinstance(y, list) else [y]):
                        env[id(kt)] = t
        missing = [kt for kt in tfm.outputs if id(kt) not in env]
        if missing:
            raise NotImplementedError(
                "could not resolve graph outputs "
                f"{[getattr(kt, 'name', '?') for kt in missing]}: some "
                "layer's inputs were never produced (unsupported layer "
                "ordering or layers shared with another model)"
            )
        return [env[id(kt)] for kt in tfm.outputs]

    # ------------------------------------------------------------------
    def _emit(self, ff, layer, ins):
        from tensorflow.keras import layers as L

        name = layer.name
        if isinstance(layer, L.Dense):
            act = _act_name(layer)
            if act == "gelu":
                # tf.keras gelu defaults to the EXACT erf form; the
                # framework's fused dense-gelu is the tanh approximation
                # — emit a separate exact gelu for bit-parity
                y = ff.dense(ins[0], layer.units, use_bias=layer.use_bias,
                             name=name)
                return ff.gelu(y, name=f"{name}.gelu", approximate=False)
            return ff.dense(ins[0], layer.units, activation=act,
                            use_bias=layer.use_bias, name=name)
        if isinstance(layer, L.DepthwiseConv2D):
            # depthwise = grouped conv with groups == in_channels and
            # out = in * depth_multiplier (MobileNet-family blocks)
            if layer.data_format == "channels_first":
                raise NotImplementedError("channels_first DepthwiseConv2D")
            if tuple(layer.dilation_rate) != (1, 1):
                raise NotImplementedError("dilated DepthwiseConv2D")
            c_in = ins[0].sizes[-1]
            mult = layer.depth_multiplier
            k = layer.kernel_size
            s = layer.strides
            ph, pw = _pads(layer.padding, k, s, ins[0].sizes[1:3])
            return _conv_act(
                ff, layer,
                lambda act: ff.conv2d(
                    ins[0], c_in * mult, k[0], k[1], s[0], s[1], ph, pw,
                    activation=act, groups=c_in,
                    use_bias=layer.use_bias, name=name),
                name)
        if isinstance(layer, L.Conv2D):
            if layer.data_format == "channels_first":
                raise NotImplementedError("channels_first Conv2D")
            if tuple(layer.dilation_rate) != (1, 1):
                raise NotImplementedError("dilated Conv2D")
            k = layer.kernel_size
            s = layer.strides
            ph, pw = _pads(layer.padding, k, s, ins[0].sizes[1:3])
            return _conv_act(
                ff, layer,
                lambda act: ff.conv2d(
                    ins[0], layer.filters, k[0], k[1], s[0], s[1], ph, pw,
                    activation=act, groups=layer.groups,
                    use_bias=layer.use_bias, name=name),
                name)
        if isinstance(layer, (L.MaxPooling2D, L.AveragePooling2D)):
            k = layer.pool_size
            s = layer.strides or k
            ph, pw = _pads(layer.padding, k, s, ins[0].sizes[1:3])
            pt = "max" if isinstance(layer, L.MaxPooling2D) else "avg"
            return ff.pool2d(ins[0], k[0], k[1], s[0], s[1], ph, pw,
                             pool_type=pt, name=name)
        if isinstance(layer, L.GlobalAveragePooling2D):
            if getattr(layer, "data_format",
                       "channels_last") == "channels_first":
                raise NotImplementedError(
                    "channels_first GlobalAveragePooling2D")
            return ff.mean(ins[0], dims=(1, 2),
                           keepdims=getattr(layer, "keepdims", False),
                           name=name)
        if isinstance(layer, L.GlobalMaxPooling2D):
            if getattr(layer, "data_format", "channels_last") == "channels_first":
                raise NotImplementedError("channels_first GlobalMaxPooling2D")
            h, w = ins[0].sizes[1:3]
            t = ff.pool2d(ins[0], h, w, 1, 1, 0, 0, pool_type="max",
                          name=name)
            if getattr(layer, "keepdims", False):
                return t  # already (N, 1, 1, C)
            return ff.flat(t, name=f"{name}.squeeze")
        if isinstance(layer, L.Flatten):
            return ff.flat(ins[0], name=name)
        if isinstance(layer, L.Reshape):
            b = ins[0].sizes[0]
            return ff.reshape(ins[0], (b,) + tuple(layer.target_shape), name=name)
        if isinstance(layer, L.Dropout):
            return ff.dropout(ins[0], rate=layer.rate, name=name)
        if isinstance(layer, L.BatchNormalization):
            return ff.batch_norm(ins[0], relu=False,
                                 momentum=layer.momentum, name=name)
        if isinstance(layer, L.LayerNormalization):
            axes = layer.axis if isinstance(layer.axis, (list, tuple)) else [layer.axis]
            return ff.layer_norm(ins[0], axes=tuple(axes),
                                 eps=layer.epsilon, name=name)
        if isinstance(layer, L.Embedding):
            return ff.embedding(ins[0], layer.input_dim, layer.output_dim,
                                name=name)
        if isinstance(layer, L.Activation):
            act_name = layer.activation.__name__
            if act_name == "gelu":
                return ff.gelu(ins[0], name=name, approximate=False)
            fn = getattr(ff, act_name, None)
            if fn is None:
                raise NotImplementedError(f"activation {act_name!r}")
            return fn(ins[0], name=name)
        if isinstance(layer, L.ReLU):
            return ff.relu(ins[0], name=name)
        if isinstance(layer, L.Softmax):
            axis = layer.axis if isinstance(layer.axis, int) else -1
            return ff.softmax(ins[0], axis=axis, name=name)
        if isinstance(layer, L.MultiHeadAttention):
            # tf call order is (query, VALUE, key); key defaults to value
            q = ins[0]
            v = ins[1] if len(ins) > 1 else ins[0]
            k = ins[2] if len(ins) > 2 else v
            heads = getattr(layer, "num_heads", None) or layer._num_heads
            key_dim = getattr(layer, "key_dim", None) or layer._key_dim
            value_dim = getattr(layer, "value_dim", None) or getattr(
                layer, "_value_dim", None)
            out_shape = getattr(layer, "_output_shape", None)
            e_out = q.sizes[-1]
            if out_shape is not None:
                raise NotImplementedError(
                    "MultiHeadAttention with output_shape= is not supported")
            if value_dim not in (None, key_dim):
                raise NotImplementedError(
                    f"MultiHeadAttention with value_dim={value_dim} != "
                    f"key_dim={key_dim}")
            if heads * key_dim != e_out:
                raise NotImplementedError(
                    f"MultiHeadAttention needs num_heads*key_dim == "
                    f"query dim ({heads}*{key_dim} != {e_out})")
            return ff.multihead_attention(
                q, k, v, embed_dim=e_out, num_heads=heads,
                dropout=float(getattr(layer, "dropout", 0.0) or 0.0),
                bias=getattr(layer, "_use_bias", True), name=name)
        if isinstance(layer, L.Concatenate):
            return ff.concat(list(ins), axis=layer.axis, name=name)
        if isinstance(layer, L.Add):
            out = ins[0]
            for t in ins[1:]:
                out = ff.add(out, t, name=name if len(ins) == 2 else None)
            return out
        if isinstance(layer, L.Subtract):
            return ff.subtract(ins[0], ins[1], name=name)
        if isinstance(layer, L.Multiply):
            out = ins[0]
            for t in ins[1:]:
                out = ff.multiply(out, t, name=name if len(ins) == 2 else None)
            return out
        raise NotImplementedError(f"tf.keras layer {type(layer).__name__}")


def transfer_tf_weights(tf_model, ffmodel) -> int:
    """Copy trained tf.keras weights into a compiled FFModel (layouts
    already match: Dense (in,out), Conv HWIO)."""
    from tensorflow.keras import layers as L

    copied = 0
    for layer in tf_model.layers:
        name = layer.name
        if name not in ffmodel.params:
            continue
        w = layer.get_weights()
        if isinstance(layer, L.DepthwiseConv2D) and w:
            # tf depthwise kernel (kh, kw, C, mult) -> grouped HWIO
            # (kh, kw, 1, C*mult); C-major reshape matches the
            # feature_group_count output-channel ordering
            kh, kw, c, mult = w[0].shape
            ffmodel.set_weight(name, "kernel", w[0].reshape(kh, kw, 1,
                                                            c * mult))
            copied += 1
            if layer.use_bias and len(w) > 1:
                ffmodel.set_weight(name, "bias", w[1])
                copied += 1
        elif isinstance(layer, (L.Dense, L.Conv2D)) and w:
            ffmodel.set_weight(name, "kernel", w[0])
            copied += 1
            if layer.use_bias and len(w) > 1:
                ffmodel.set_weight(name, "bias", w[1])
                copied += 1
        elif isinstance(layer, L.Embedding) and w:
            ffmodel.set_weight(name, "table", w[0])
            copied += 1
        elif isinstance(layer, L.MultiHeadAttention) and w:
            # tf builds query/key/value/output EinsumDense sublayers in
            # that order; kernels are (in, H, dk) / (H, dk, out) —
            # byte-identical to this framework's wq/wk/wv/wo layout
            use_bias = getattr(layer, "_use_bias", True)
            names = (["wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo"]
                     if use_bias else ["wq", "wk", "wv", "wo"])
            for nm, arr in zip(names, w):
                ffmodel.set_weight(name, nm, arr)
                copied += 1
        elif isinstance(layer, L.LayerNormalization) and len(w) == 2:
            ffmodel.set_weight(name, "gamma", w[0])
            ffmodel.set_weight(name, "beta", w[1])
            copied += 2
        elif isinstance(layer, L.BatchNormalization) and len(w) == 4:
            ffmodel.set_weight(name, "scale", w[0])
            ffmodel.set_weight(name, "bias", w[1])
            ffmodel.set_state_var(f"{name}/running_mean", w[2])
            ffmodel.set_state_var(f"{name}/running_var", w[3])
            copied += 4
    return copied
