"""Frontends — import models from other ecosystems onto FFModel.

Parity targets (reference, structure only — no code shared):
* python/flexflow/torch/model.py  — torch.fx symbolic-trace importer
  (~60 Node subclasses with parse/to_ff) + ``torch_to_flexflow`` file
  format round-trip.
* python/flexflow/onnx/model.py   — ONNX graph importer (handle_* per
  ONNX op type).
* python/flexflow/keras/          — drop-in Sequential / functional
  Model frontend with callbacks.
"""

from flexflow_tpu.frontends.torch_fx import (  # noqa: F401
    PyTorchModel,
    torch_to_flexflow,
    transfer_torch_weights,
)
from flexflow_tpu.frontends.onnx_frontend import ONNXModel  # noqa: F401
from flexflow_tpu.frontends.tf_keras import (  # noqa: F401
    TFKerasModel,
    transfer_tf_weights,
)

__all__ = [
    "PyTorchModel",
    "torch_to_flexflow",
    "transfer_torch_weights",
    "ONNXModel",
    "TFKerasModel",
    "transfer_tf_weights",
]
