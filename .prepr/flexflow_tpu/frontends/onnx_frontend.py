"""ONNX frontend: ONNX graph → FFModel graph.

Parity with the reference's ONNX importer
(reference: python/flexflow/onnx/model.py — ``ONNXModel(file)`` +
``apply(ffmodel, input_dict)`` with one ``handle_<OpType>`` per ONNX op,
model.py:74-287), re-designed for this framework:

* handlers emit onto the NHWC-native FFModel with the same NCHW↔NHWC
  transpose bracketing the torch importer uses (XLA cancels the pairs);
* graph initializers (weights baked into the ONNX file) are captured and
  can be copied into a compiled model with ``transfer_onnx_weights``.

The ``onnx`` package is optional: when absent, the vendored minimal
protobuf reader (onnx_minimal.py) parses the file instead, so real
.onnx models import in any environment.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

__all__ = ["ONNXModel"]

_NCHW_TO_NHWC = (0, 2, 3, 1)
_NHWC_TO_NCHW = (0, 3, 1, 2)


def _onnx_modules():
    """(onnx-like module, numpy_helper) — the real package when
    installed, the vendored wire-format reader otherwise."""
    try:
        import onnx
        from onnx import numpy_helper

        return onnx, numpy_helper
    except ImportError:
        from flexflow_tpu.frontends import onnx_minimal

        return onnx_minimal, onnx_minimal.numpy_helper


def _attrs(node) -> Dict[str, Any]:
    out = {}
    for a in node.attribute:
        if a.type == a.INT:
            out[a.name] = a.i
        elif a.type == a.FLOAT:
            out[a.name] = a.f
        elif a.type == a.INTS:
            out[a.name] = list(a.ints)
        elif a.type == a.FLOATS:
            out[a.name] = list(a.floats)
        elif a.type == a.STRING:
            out[a.name] = a.s.decode()
        elif a.type == a.TENSOR:
            _, numpy_helper = _onnx_modules()
            out[a.name] = numpy_helper.to_array(a.t)
    return out


class ONNXModel:
    """reference: python/flexflow/onnx/model.py ONNXModel."""

    def __init__(self, source):
        onnx, numpy_helper = _onnx_modules()
        if isinstance(source, str):
            self.model = onnx.load(source)
        elif isinstance(source, bytes):
            self.model = onnx.load_model_from_string(source)
        else:
            self.model = source
        self.weights = {
            init.name: numpy_helper.to_array(init)
            for init in self.model.graph.initializer
        }
        self._ff_weight_map: Dict[str, tuple] = {}
        self._state_map: Dict[str, Any] = {}

    # ------------------------------------------------------------------
    def apply(self, ffmodel, input_dict: Dict[str, Any]) -> List:
        """Emit the graph onto ``ffmodel``; ``input_dict`` maps ONNX graph
        input names to FFModel Tensors. Returns output tensors."""
        env: Dict[str, Any] = dict(input_dict)
        g = self.model.graph
        # consumers map for MatMul+Add(bias) fusion (the decomposition
        # exporters emit instead of Gemm)
        self._consumers: Dict[str, List] = {}
        for node in g.node:
            for i in node.input:
                self._consumers.setdefault(i, []).append(node)
        self._fused_adds: Dict[int, str] = {}  # id(add_node) -> alias source
        for node in g.node:
            if id(node) in self._fused_adds:
                env[node.output[0]] = env[self._fused_adds[id(node)]]
                continue
            handler = getattr(self, f"handle_{node.op_type}", None)
            if handler is None:
                raise NotImplementedError(f"unsupported ONNX op {node.op_type}")
            outs = handler(ffmodel, node, env, _attrs(node))
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            for name, t in zip(node.output, outs):
                env[name] = t
        return [env[o.name] for o in g.output]

    # -- helpers ----------------------------------------------------------
    def _w(self, name: str):
        return self.weights.get(name)

    def _record(self, op_name: str, weight_name: str, array) -> None:
        self._ff_weight_map[f"{op_name}/{weight_name}"] = (op_name, weight_name, array)

    # -- handlers (reference: onnx/model.py handle_* table) ----------------
    def handle_Conv(self, ff, node, env, a):
        x = env[node.input[0]]
        w = self._w(node.input[1])  # OIHW
        bias = self._w(node.input[2]) if len(node.input) > 2 else None
        kh, kw = a.get("kernel_shape", list(w.shape[2:]))
        strides = a.get("strides", [1, 1])
        pads = a.get("pads", [0, 0, 0, 0])
        assert pads[0] == pads[2] and pads[1] == pads[3], "asymmetric padding"
        groups = a.get("group", 1)
        name = node.name or node.output[0]
        t = ff.transpose(x, _NCHW_TO_NHWC, name=f"{name}.nhwc")
        y = ff.conv2d(t, w.shape[0], kh, kw, strides[0], strides[1], pads[0],
                      pads[1], groups=groups, use_bias=bias is not None, name=name)
        if w is not None:
            self._record(name, "kernel", w.transpose(2, 3, 1, 0))
        if bias is not None:
            self._record(name, "bias", bias)
        return ff.transpose(y, _NHWC_TO_NCHW, name=f"{name}.nchw")

    def handle_Gemm(self, ff, node, env, a):
        x = env[node.input[0]]
        w = self._w(node.input[1])
        if w is None:
            raise NotImplementedError(
                f"Gemm with non-initializer B operand {node.input[1]!r}"
            )
        bias = self._w(node.input[2]) if len(node.input) > 2 else None
        if a.get("transA", 0):
            raise NotImplementedError("Gemm with transA=1")
        alpha, beta = a.get("alpha", 1.0), a.get("beta", 1.0)
        trans_b = a.get("transB", 0)
        out_dim = w.shape[0] if trans_b else w.shape[1]
        name = node.name or node.output[0]
        y = ff.dense(x, out_dim, use_bias=bias is not None, name=name)
        kernel = (w.T if trans_b else w) * alpha  # fold alpha/beta into weights
        self._record(name, "kernel", kernel)
        if bias is not None:
            self._record(name, "bias", bias * beta)
        return y

    def handle_MatMul(self, ff, node, env, a):
        name = node.name or node.output[0]
        w = self._w(node.input[1])
        if w is not None and w.ndim == 2:
            bias, add_node = self._find_bias_add(node, w.shape[1])
            y = ff.dense(env[node.input[0]], w.shape[1], use_bias=bias is not None,
                         name=name)
            self._record(name, "kernel", w)
            if bias is not None:
                self._record(name, "bias", bias)
                self._fused_adds[id(add_node)] = node.output[0]
            return y
        if w is not None:  # batched (>2-D) initializer — not importable
            raise NotImplementedError(
                f"MatMul with {w.ndim}-D initializer operand {node.input[1]!r}"
            )
        return ff.batch_matmul(env[node.input[0]], env[node.input[1]], name=name)

    def _find_bias_add(self, node, out_dim):
        """MatMul whose sole consumer is Add(out, 1-D initializer) — the
        exporter decomposition of a biased dense; fuse it."""
        users = self._consumers.get(node.output[0], [])
        if len(users) == 1 and users[0].op_type == "Add":
            add = users[0]
            other = add.input[1] if add.input[0] == node.output[0] else add.input[0]
            b = self._w(other)
            if b is not None and b.ndim == 1 and b.shape[0] == out_dim:
                return b, add
        return None, None

    def _pool(self, ff, node, env, a, pool_type):
        x = env[node.input[0]]
        k = a["kernel_shape"]
        s = a.get("strides", [1, 1])
        p = a.get("pads", [0, 0, 0, 0])
        name = node.name or node.output[0]
        t = ff.transpose(x, _NCHW_TO_NHWC, name=f"{name}.nhwc")
        y = ff.pool2d(t, k[0], k[1], s[0], s[1], p[0], p[1],
                      pool_type=pool_type, name=name)
        return ff.transpose(y, _NHWC_TO_NCHW, name=f"{name}.nchw")

    def handle_MaxPool(self, ff, node, env, a):
        return self._pool(ff, node, env, a, "max")

    def handle_AveragePool(self, ff, node, env, a):
        return self._pool(ff, node, env, a, "avg")

    def handle_GlobalAveragePool(self, ff, node, env, a):
        x = env[node.input[0]]
        name = node.name or node.output[0]
        return ff.mean(x, dims=(2, 3), keepdims=True, name=name)

    def handle_BatchNormalization(self, ff, node, env, a):
        x = env[node.input[0]]
        name = node.name or node.output[0]
        t = ff.transpose(x, _NCHW_TO_NHWC, name=f"{name}.nhwc")
        y = ff.batch_norm(t, relu=False, momentum=a.get("momentum", 0.9), name=name)
        scale, bias = self._w(node.input[1]), self._w(node.input[2])
        if scale is not None:
            self._record(name, "scale", scale)
        if bias is not None:
            self._record(name, "bias", bias)
        if len(node.input) > 4:  # trained running statistics
            mean, var = self._w(node.input[3]), self._w(node.input[4])
            if mean is not None:
                self._state_map[f"{name}/running_mean"] = mean
            if var is not None:
                self._state_map[f"{name}/running_var"] = var
        return ff.transpose(y, _NHWC_TO_NCHW, name=f"{name}.nchw")

    def handle_Flatten(self, ff, node, env, a):
        x = env[node.input[0]]
        axis = a.get("axis", 1)
        shp = list(x.sizes)
        lead = 1
        for s in shp[:axis]:
            lead *= s
        tail = 1
        for s in shp[axis:]:
            tail *= s
        return ff.reshape(x, (lead, tail), name=node.name or node.output[0])

    def handle_Reshape(self, ff, node, env, a):
        x = env[node.input[0]]
        shape = [int(s) for s in self._w(node.input[1])]
        # ONNX conventions: 0 copies the input dim, -1 infers from the rest
        shape = [x.sizes[i] if s == 0 else s for i, s in enumerate(shape)]
        total = 1
        for s in x.sizes:
            total *= s
        if -1 in shape:
            known = 1
            for s in shape:
                if s != -1:
                    known *= s
            shape = [total // known if s == -1 else s for s in shape]
        return ff.reshape(x, shape, name=node.name or node.output[0])

    def handle_Transpose(self, ff, node, env, a):
        return ff.transpose(env[node.input[0]], a["perm"],
                            name=node.name or node.output[0])

    def handle_Concat(self, ff, node, env, a):
        return ff.concat([env[i] for i in node.input], axis=a["axis"],
                         name=node.name or node.output[0])

    def handle_Split(self, ff, node, env, a):
        x = env[node.input[0]]
        axis = a.get("axis", 0)
        sizes = a.get("split")
        if sizes is None and len(node.input) > 1:
            sizes = [int(s) for s in self._w(node.input[1])]
        if sizes is None:
            n = len(node.output)
            sizes = [x.sizes[axis] // n] * n
        return ff.split(x, list(sizes), axis=axis, name=node.name or node.output[0])

    def handle_Softmax(self, ff, node, env, a):
        return ff.softmax(env[node.input[0]], axis=a.get("axis", -1),
                          name=node.name or node.output[0])

    def handle_Dropout(self, ff, node, env, a):
        rate = a.get("ratio")
        if rate is None and len(node.input) > 1:  # opset >= 12: ratio input
            r = self._w(node.input[1])
            rate = float(r) if r is not None else None
        return ff.dropout(env[node.input[0]], rate=0.5 if rate is None else rate,
                          name=node.name or node.output[0])

    # ONNX TensorProto dtype enum -> our DataType strings
    _ONNX_DTYPE = {1: "float32", 6: "int32", 7: "int64", 9: "bool",
                   10: "float16", 11: "float64", 16: "bfloat16"}

    def handle_Cast(self, ff, node, env, a):
        to = self._ONNX_DTYPE.get(a.get("to"))
        if to is None:
            raise NotImplementedError(f"Cast to ONNX dtype enum {a.get('to')}")
        return ff.cast(env[node.input[0]], to, name=node.name or node.output[0])

    def handle_ReduceMean(self, ff, node, env, a):
        x = env[node.input[0]]
        axes = a.get("axes")
        if axes is None and len(node.input) > 1:  # opset >= 18: axes input
            w = self._w(node.input[1])
            axes = [int(s) for s in w] if w is not None else None
        if axes is None:  # ONNX default: reduce over ALL dims
            axes = list(range(len(x.sizes)))
        return ff.mean(x, dims=axes, keepdims=bool(a.get("keepdims", 1)),
                       name=node.name or node.output[0])

    def handle_Gather(self, ff, node, env, a):
        # embedding lookup: data is an initializer table
        table = self._w(node.input[0])
        name = node.name or node.output[0]
        if table is not None and table.ndim == 2 and a.get("axis", 0) == 0:
            y = ff.embedding(env[node.input[1]], table.shape[0], table.shape[1],
                             name=name)
            self._record(name, "table", table)
            return y
        return ff.gather(env[node.input[0]], env[node.input[1]],
                         axis=a.get("axis", 0), name=name)

    def _binary(self, ff, node, env, op, scalar_op):
        name = node.name or node.output[0]
        a_in, b_in = node.input[0], node.input[1]
        wa, wb = self._w(a_in), self._w(b_in)
        if wb is not None and wb.size == 1:
            return getattr(ff, scalar_op)(env[a_in], float(wb), name=name)
        if wa is not None and wa.size == 1:
            return getattr(ff, scalar_op)(env[b_in], float(wa), name=name)
        for side, w in ((a_in, wa), (b_in, wb)):
            if w is not None and side not in env:
                raise NotImplementedError(
                    f"{node.op_type} with non-scalar initializer operand "
                    f"{side!r} (shape {w.shape}) — only MatMul+Add bias "
                    "fusion is supported for tensor constants"
                )
        return getattr(ff, op)(env[a_in], env[b_in], name=name)

    def handle_Add(self, ff, node, env, a):
        return self._binary(ff, node, env, "add", "scalar_add")

    def handle_Sub(self, ff, node, env, a):
        return self._binary(ff, node, env, "subtract", "scalar_sub")

    def handle_Mul(self, ff, node, env, a):
        return self._binary(ff, node, env, "multiply", "scalar_multiply")

    def handle_Div(self, ff, node, env, a):
        return self._binary(ff, node, env, "divide", "scalar_true_divide")

    def handle_Relu(self, ff, node, env, a):
        return ff.relu(env[node.input[0]], name=node.name or node.output[0])

    def handle_Sigmoid(self, ff, node, env, a):
        return ff.sigmoid(env[node.input[0]], name=node.name or node.output[0])

    def handle_Tanh(self, ff, node, env, a):
        return ff.tanh(env[node.input[0]], name=node.name or node.output[0])

    def handle_Elu(self, ff, node, env, a):
        return ff.elu(env[node.input[0]], name=node.name or node.output[0])

    def handle_Gelu(self, ff, node, env, a):
        # ONNX Gelu's spec default is approximate='none' (exact erf)
        return ff.gelu(env[node.input[0]], name=node.name or node.output[0],
                       approximate=a.get("approximate", "none") == "tanh")

    def handle_Exp(self, ff, node, env, a):
        return ff.exp(env[node.input[0]], name=node.name or node.output[0])

    def handle_Log(self, ff, node, env, a):
        return ff.log(env[node.input[0]], name=node.name or node.output[0])

    def handle_Identity(self, ff, node, env, a):
        return ff.identity(env[node.input[0]], name=node.name or node.output[0])

    def handle_Pow(self, ff, node, env, a):
        exp = self._w(node.input[1])
        return ff.pow(env[node.input[0]], float(exp),
                      name=node.name or node.output[0])

    def handle_LayerNormalization(self, ff, node, env, a):
        x = env[node.input[0]]
        name = node.name or node.output[0]
        axis = a.get("axis", -1)
        rank = len(x.sizes)
        axes = list(range(axis + rank if axis < 0 else axis, rank))
        y = ff.layer_norm(x, axes=axes, eps=a.get("epsilon", 1e-5), name=name)
        gamma = self._w(node.input[1]) if len(node.input) > 1 else None
        beta = self._w(node.input[2]) if len(node.input) > 2 else None
        if gamma is not None:
            self._record(name, "gamma", gamma)
        if beta is not None:
            self._record(name, "beta", beta)
        return y

    # ------------------------------------------------------------------
    def transfer_onnx_weights(self, ffmodel) -> int:
        """Copy ONNX initializer weights (and BN running statistics)
        into a compiled FFModel."""
        copied = 0
        for op_name, weight_name, array in self._ff_weight_map.values():
            try:
                ffmodel.set_weight(op_name, weight_name, array)
                copied += 1
            except KeyError:
                pass
        for key, array in self._state_map.items():
            try:
                ffmodel.set_state_var(key, array)
                copied += 1
            except KeyError:
                pass
        return copied
