"""Minimal self-contained ONNX reader/writer (no ``onnx`` dependency).

The environment has no ``onnx`` package, so the frontend
(onnx_frontend.py) vendors the tiny slice of it that importing a model
actually needs: the protobuf *wire format* (public spec) and the ONNX
message subset {Model, Graph, Node, Attribute, Tensor, ValueInfo}
with field numbers from the public onnx.proto
(github.com/onnx/onnx/blob/main/onnx/onnx.proto — data layout only;
this is an original implementation, not a port).

Provides the exact API surface onnx_frontend.py consumes —
``load``/``save``, ``numpy_helper.to_array``/``from_array``, and a
``helper`` with ``make_node``/``make_graph``/``make_model``/
``make_tensor_value_info`` — so tests can build real .onnx files and
the importer can read files produced by any exporter.  When the real
``onnx`` package is installed it is preferred (onnx_frontend.py falls
back here only on ImportError).

Reference parity: python/flexflow/onnx/model.py:74-287 assumes the
``onnx`` package; this shim removes that assumption.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# protobuf wire format
# ---------------------------------------------------------------------------

_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value += 1 << 64  # two's-complement 10-byte encoding
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _signed64(value: int) -> int:
    return value - (1 << 64) if value >= 1 << 63 else value


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) — value is int for
    varint/fixed, bytes for length-delimited."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == _VARINT:
            v, pos = _read_varint(buf, pos)
        elif wt == _I64:
            v = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wt == _LEN:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == _I32:
            v = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _emit(out: bytearray, field: int, wt: int, payload) -> None:
    _write_varint(out, (field << 3) | wt)
    if wt == _VARINT:
        _write_varint(out, payload)
    elif wt == _LEN:
        _write_varint(out, len(payload))
        out += payload
    elif wt == _I32:
        out += struct.pack("<I", payload)
    else:
        out += struct.pack("<Q", payload)


# field kinds: how to decode/encode one ONNX message field
# int64 — signed varint; string/bytes — length-delimited; float — fixed32;
# msg — nested message; packed variants accept both packed and unpacked.
class _Field:
    def __init__(self, name: str, kind: str, repeated: bool = False,
                 msg: Optional[type] = None):
        self.name, self.kind, self.repeated, self.msg = name, kind, repeated, msg


class Message:
    """Declarative protobuf message: subclasses define FIELDS."""

    FIELDS: Dict[int, _Field] = {}

    def __init__(self, **kw):
        for f in self.FIELDS.values():
            setattr(self, f.name, [] if f.repeated else None)
        for k, v in kw.items():
            setattr(self, k, v)

    # -- decode --
    @classmethod
    def parse(cls, buf: bytes):
        self = cls()
        for field, wt, raw in _iter_fields(buf):
            f = cls.FIELDS.get(field)
            if f is None:
                continue  # unknown field: skip (forward compat)
            vals = self._decode(f, wt, raw)
            if f.repeated:
                getattr(self, f.name).extend(vals)
            elif vals:
                setattr(self, f.name, vals[-1])
        return self

    @staticmethod
    def _decode(f: _Field, wt: int, raw) -> List[Any]:
        k = f.kind
        if k == "int64":
            if wt == _LEN:  # packed repeated
                out, pos = [], 0
                while pos < len(raw):
                    v, pos = _read_varint(raw, pos)
                    out.append(_signed64(v))
                return out
            return [_signed64(raw)]
        if k == "float":
            if wt == _LEN:
                return list(struct.unpack(f"<{len(raw) // 4}f", raw))
            return [struct.unpack("<f", struct.pack("<I", raw))[0]]
        if k == "double":
            if wt == _LEN:
                return list(struct.unpack(f"<{len(raw) // 8}d", raw))
            return [struct.unpack("<d", struct.pack("<Q", raw))[0]]
        if k == "string":
            return [raw.decode("utf-8", "replace")]
        if k == "bytes":
            return [bytes(raw)]
        if k == "msg":
            return [f.msg.parse(raw)]
        raise ValueError(f"unknown kind {k}")

    # -- encode --
    def serialize(self) -> bytes:
        out = bytearray()
        for field, f in sorted(self.FIELDS.items()):
            v = getattr(self, f.name)
            if v is None or (f.repeated and not v):
                continue
            vals = v if f.repeated else [v]
            for x in vals:
                if f.kind == "int64":
                    _emit(out, field, _VARINT, x)
                elif f.kind == "float":
                    _emit(out, field, _I32, struct.unpack(
                        "<I", struct.pack("<f", x))[0])
                elif f.kind == "double":
                    _emit(out, field, _I64, struct.unpack(
                        "<Q", struct.pack("<d", x))[0])
                elif f.kind == "string":
                    _emit(out, field, _LEN, x.encode("utf-8"))
                elif f.kind == "bytes":
                    _emit(out, field, _LEN, x)
                elif f.kind == "msg":
                    _emit(out, field, _LEN, x.serialize())
        return bytes(out)

    def __repr__(self):
        parts = []
        for f in self.FIELDS.values():
            v = getattr(self, f.name)
            if v not in (None, []):
                parts.append(f"{f.name}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"


# ---------------------------------------------------------------------------
# ONNX messages (field numbers: public onnx.proto)
# ---------------------------------------------------------------------------


class TensorProto(Message):
    # elem type enum (public onnx.proto TensorProto.DataType)
    FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
    STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
    BFLOAT16 = 16
    FIELDS = {
        1: _Field("dims", "int64", True),
        2: _Field("data_type", "int64"),
        4: _Field("float_data", "float", True),
        5: _Field("int32_data", "int64", True),
        6: _Field("string_data", "bytes", True),
        7: _Field("int64_data", "int64", True),
        8: _Field("name", "string"),
        9: _Field("raw_data", "bytes"),
        10: _Field("double_data", "double", True),
        11: _Field("uint64_data", "int64", True),
    }


class AttributeProto(Message):
    UNDEFINED, FLOAT, INT, STRING, TENSOR, GRAPH = 0, 1, 2, 3, 4, 5
    FLOATS, INTS, STRINGS, TENSORS, GRAPHS = 6, 7, 8, 9, 10
    FIELDS = {
        1: _Field("name", "string"),
        2: _Field("f", "float"),
        3: _Field("i", "int64"),
        4: _Field("s", "bytes"),
        5: _Field("t", "msg", msg=TensorProto),
        7: _Field("floats", "float", True),
        8: _Field("ints", "int64", True),
        9: _Field("strings", "bytes", True),
        10: _Field("tensors", "msg", True, msg=TensorProto),
        20: _Field("type", "int64"),
    }


class NodeProto(Message):
    FIELDS = {
        1: _Field("input", "string", True),
        2: _Field("output", "string", True),
        3: _Field("name", "string"),
        4: _Field("op_type", "string"),
        5: _Field("attribute", "msg", True, msg=AttributeProto),
        6: _Field("doc_string", "string"),
        7: _Field("domain", "string"),
    }


class _Dimension(Message):
    FIELDS = {
        1: _Field("dim_value", "int64"),
        2: _Field("dim_param", "string"),
    }


class _TensorShapeProto(Message):
    FIELDS = {1: _Field("dim", "msg", True, msg=_Dimension)}


class _TensorTypeProto(Message):
    FIELDS = {
        1: _Field("elem_type", "int64"),
        2: _Field("shape", "msg", msg=_TensorShapeProto),
    }


class TypeProto(Message):
    FIELDS = {1: _Field("tensor_type", "msg", msg=_TensorTypeProto)}


class ValueInfoProto(Message):
    FIELDS = {
        1: _Field("name", "string"),
        2: _Field("type", "msg", msg=TypeProto),
        3: _Field("doc_string", "string"),
    }


class GraphProto(Message):
    FIELDS = {
        1: _Field("node", "msg", True, msg=NodeProto),
        2: _Field("name", "string"),
        5: _Field("initializer", "msg", True, msg=TensorProto),
        10: _Field("doc_string", "string"),
        11: _Field("input", "msg", True, msg=ValueInfoProto),
        12: _Field("output", "msg", True, msg=ValueInfoProto),
        13: _Field("value_info", "msg", True, msg=ValueInfoProto),
    }


class OperatorSetIdProto(Message):
    FIELDS = {
        1: _Field("domain", "string"),
        2: _Field("version", "int64"),
    }


class ModelProto(Message):
    FIELDS = {
        1: _Field("ir_version", "int64"),
        2: _Field("producer_name", "string"),
        3: _Field("producer_version", "string"),
        4: _Field("domain", "string"),
        5: _Field("model_version", "int64"),
        6: _Field("doc_string", "string"),
        7: _Field("graph", "msg", msg=GraphProto),
        8: _Field("opset_import", "msg", True, msg=OperatorSetIdProto),
    }


# ---------------------------------------------------------------------------
# numpy_helper / helper / load / save — the API slice the frontend uses
# ---------------------------------------------------------------------------

_DTYPES = {
    TensorProto.FLOAT: np.float32,
    TensorProto.UINT8: np.uint8,
    TensorProto.INT8: np.int8,
    TensorProto.UINT16: np.uint16,
    TensorProto.INT16: np.int16,
    TensorProto.INT32: np.int32,
    TensorProto.INT64: np.int64,
    TensorProto.BOOL: np.bool_,
    TensorProto.FLOAT16: np.float16,
    TensorProto.DOUBLE: np.float64,
    TensorProto.UINT32: np.uint32,
    TensorProto.UINT64: np.uint64,
}
_NP_TO_ONNX = {np.dtype(v): k for k, v in _DTYPES.items()}


class numpy_helper:
    @staticmethod
    def to_array(t: TensorProto) -> np.ndarray:
        dtype = _DTYPES.get(t.data_type)
        if dtype is None:
            raise ValueError(f"unsupported TensorProto data_type {t.data_type}")
        dims = tuple(t.dims)
        if t.raw_data:
            return np.frombuffer(t.raw_data, dtype=dtype).reshape(dims).copy()
        if t.data_type == TensorProto.FLOAT and t.float_data:
            return np.asarray(t.float_data, np.float32).reshape(dims)
        if t.data_type == TensorProto.DOUBLE and t.double_data:
            return np.asarray(t.double_data, np.float64).reshape(dims)
        if t.data_type == TensorProto.INT64 and t.int64_data:
            return np.asarray(t.int64_data, np.int64).reshape(dims)
        if t.int32_data:
            if t.data_type == TensorProto.FLOAT16:
                # onnx.proto stores float16 in int32_data as raw bit
                # patterns, not values: bits 15360 decode as 1.0
                return (
                    np.asarray(t.int32_data, np.uint16)
                    .view(np.float16)
                    .reshape(dims)
                )
            return np.asarray(t.int32_data, np.int64).astype(dtype).reshape(dims)
        return np.zeros(dims, dtype)

    @staticmethod
    def from_array(arr: np.ndarray, name: str = "") -> TensorProto:
        arr = np.asarray(arr)
        if arr.dtype not in _NP_TO_ONNX:
            raise ValueError(f"unsupported numpy dtype {arr.dtype}")
        return TensorProto(
            dims=list(arr.shape),
            data_type=_NP_TO_ONNX[arr.dtype],
            raw_data=np.ascontiguousarray(arr).tobytes(),
            name=name,
        )


class helper:
    @staticmethod
    def make_attribute(name: str, value) -> AttributeProto:
        a = AttributeProto(name=name)
        if isinstance(value, bool):
            a.i, a.type = int(value), AttributeProto.INT
        elif isinstance(value, int):
            a.i, a.type = value, AttributeProto.INT
        elif isinstance(value, float):
            a.f, a.type = value, AttributeProto.FLOAT
        elif isinstance(value, str):
            a.s, a.type = value.encode(), AttributeProto.STRING
        elif isinstance(value, bytes):
            a.s, a.type = value, AttributeProto.STRING
        elif isinstance(value, TensorProto):
            a.t, a.type = value, AttributeProto.TENSOR
        elif isinstance(value, (list, tuple)):
            if all(isinstance(x, (int, np.integer)) for x in value):
                a.ints, a.type = [int(x) for x in value], AttributeProto.INTS
            elif all(isinstance(x, (float, int, np.floating)) for x in value):
                a.floats = [float(x) for x in value]
                a.type = AttributeProto.FLOATS
            else:
                raise ValueError(f"unsupported attribute list {value!r}")
        else:
            raise ValueError(f"unsupported attribute {value!r}")
        return a

    @staticmethod
    def make_node(op_type: str, inputs, outputs, name: str = "", **attrs):
        return NodeProto(
            op_type=op_type, input=list(inputs), output=list(outputs),
            name=name or f"{op_type}_{id(inputs) & 0xFFFF}",
            attribute=[helper.make_attribute(k, v) for k, v in attrs.items()],
        )

    @staticmethod
    def make_tensor_value_info(name: str, elem_type: int, shape) -> ValueInfoProto:
        dims = [
            _Dimension(dim_param=d) if isinstance(d, str)
            else _Dimension(dim_value=int(d))
            for d in shape
        ]
        return ValueInfoProto(
            name=name,
            type=TypeProto(tensor_type=_TensorTypeProto(
                elem_type=elem_type, shape=_TensorShapeProto(dim=dims))),
        )

    @staticmethod
    def make_graph(nodes, name, inputs, outputs, initializer=()):
        return GraphProto(
            node=list(nodes), name=name, input=list(inputs),
            output=list(outputs), initializer=list(initializer),
        )

    @staticmethod
    def make_model(graph: GraphProto, opset_version: int = 17) -> ModelProto:
        return ModelProto(
            ir_version=8, producer_name="flexflow_tpu.onnx_minimal",
            graph=graph,
            opset_import=[OperatorSetIdProto(domain="", version=opset_version)],
        )


def load(source) -> ModelProto:
    if isinstance(source, (str, bytes)) and not isinstance(source, bytes):
        with open(source, "rb") as f:
            data = f.read()
    elif isinstance(source, bytes):
        data = source
    else:  # file-like
        data = source.read()
    return ModelProto.parse(data)


def load_model_from_string(data: bytes) -> ModelProto:
    return ModelProto.parse(data)


def save(model: ModelProto, path: str) -> None:
    with open(path, "wb") as f:
        f.write(model.serialize())
