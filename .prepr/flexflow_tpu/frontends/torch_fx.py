"""PyTorch frontend: torch.fx symbolic trace → FFModel graph.

Parity with the reference's torch frontend
(reference: python/flexflow/torch/model.py — symbolic_trace to a Node
list, per-node ``to_ff`` emission, and a serialized op-list file format
via ``torch_to_flexflow``), re-designed for this framework:

* the traced graph is normalized into neutral, JSON-serializable
  ``OpRecord``s first; both the file writer and the FFModel applier
  consume records, so the in-memory and on-disk paths are one code path;
* torch models are NCHW; this framework is NHWC (TPU-native).  Conv /
  pool / batch-norm records are lowered with NCHW↔NHWC transposes on
  each side, preserving torch semantics exactly.  XLA cancels the
  adjacent transpose pairs between consecutive spatial ops at compile
  time, so the imported program carries no runtime layout cost;
* ``transfer_torch_weights`` copies trained torch parameters into a
  compiled FFModel (transposing Linear (out,in)→(in,out) and Conv
  OIHW→HWIO), which is what the reference's align/ harness does with
  set_tensor.

Usage::

    model = ff.FFModel(cfg)
    x = model.create_tensor((batch, 3, 32, 32))
    outs = PyTorchModel(torch_module).torch_to_ff(model, [x])
    # or round-trip through a file:
    torch_to_flexflow(torch_module, "model.ffir", example_inputs)
    outs = PyTorchModel("model.ffir").torch_to_ff(model, [x])
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["OpRecord", "PyTorchModel", "torch_to_flexflow", "transfer_torch_weights"]

FILE_MAGIC = "flexflow_tpu.torch_fx.v1"


@dataclass
class OpRecord:
    """One neutral imported operator (serializable)."""

    name: str
    kind: str
    inputs: List[str] = field(default_factory=list)
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        def _default(o):
            if hasattr(o, "tolist"):  # ndarray constants stay unboxed
                return o.tolist()     # in memory; lists only on disk
            raise TypeError(f"unserializable attr {type(o).__name__}")

        return json.dumps(
            {"name": self.name, "kind": self.kind, "inputs": self.inputs,
             "attrs": self.attrs},
            default=_default,
        )

    @staticmethod
    def from_json(line: str) -> "OpRecord":
        d = json.loads(line)
        return OpRecord(d["name"], d["kind"], d["inputs"], d["attrs"])


# ---------------------------------------------------------------------------
# Tracing: torch.fx graph -> OpRecord list
# ---------------------------------------------------------------------------


def _tensor_shape(node) -> Optional[List[int]]:
    tm = node.meta.get("tensor_meta")
    if tm is None:
        return None
    try:
        return list(tm.shape)
    except AttributeError:  # tuple of TensorMetadata (multi-output)
        return None


def _norm_dim(dim: int, rank: int) -> int:
    return dim + rank if dim < 0 else dim


def _torch_dtype_str(arg) -> Optional[str]:
    """torch.dtype -> our DataType string (None if arg isn't a dtype)."""
    import torch

    table = {
        torch.float32: "float32", torch.float16: "float16",
        torch.bfloat16: "bfloat16", torch.float64: "float64",
        torch.int32: "int32", torch.int64: "int64", torch.bool: "bool",
    }
    return table.get(arg)


class _Tracer:
    """Walk an fx.GraphModule and emit OpRecords."""

    def __init__(self, module, example_inputs: Sequence):
        import torch
        from torch import fx
        from torch.fx.passes.shape_prop import ShapeProp

        self.torch = torch
        if isinstance(module, fx.GraphModule):
            gm = module
        else:
            gm = fx.symbolic_trace(module)
        self.gm = gm
        ShapeProp(gm).propagate(*example_inputs)
        self.records: List[OpRecord] = []
        self.env: Dict[str, str] = {}  # fx node name -> record output name
        self.literals: Dict[str, Any] = {}  # shape/int values traced as nodes
        self.constants: Dict[str, Any] = {}  # node name -> folded torch.Tensor
        self.kinds: Dict[str, str] = {}  # record name -> record kind
        self.input_names: List[str] = []
        self.output_names: List[str] = []

    # -- helpers ----------------------------------------------------------
    def emit(self, kind: str, name: str, inputs: List[str], **attrs) -> str:
        self.records.append(OpRecord(name, kind, inputs, attrs))
        self.kinds[name] = kind
        return name

    def ref(self, arg) -> str:
        if arg.name not in self.env and arg.name in self.constants:
            # a folded constant flowing into a real graph op: materialize
            # it as a ConstantOp record on first use
            val = self.constants[arg.name]
            import numpy as np

            arr = val.detach().cpu().numpy() if hasattr(val, "detach") else np.asarray(val)
            self.env[arg.name] = self.emit(
                "constant", arg.name, [],
                value=arr, dtype=str(arr.dtype),
            )
        return self.env[arg.name]

    # -- constant folding -------------------------------------------------
    def _resolve_const(self, a):
        """(value, ok): resolve an fx arg to a concrete python/torch
        value if it is a folded constant, a traced literal, or a plain
        literal (recursing into tuples/lists/slices).  ok=False means
        the arg depends on real graph tensors."""
        fx = self.torch.fx
        if isinstance(a, fx.Node):
            if a.name in self.constants:
                return self.constants[a.name], True
            if a.name in self.literals:
                return self.literals[a.name], True
            return None, False
        if isinstance(a, (tuple, list)):
            vals = []
            for x in a:
                v, ok = self._resolve_const(x)
                if not ok:
                    return None, False
                vals.append(v)
            return type(a)(vals), True
        if isinstance(a, slice):
            parts = []
            for x in (a.start, a.stop, a.step):
                v, ok = self._resolve_const(x)
                if not ok:
                    return None, False
                parts.append(v)
            return slice(*parts), True
        return a, True

    # Targets that must never constant-fold: executing them bakes ONE
    # RNG draw (or an uninitialized buffer) into the imported program as
    # a frozen constant.  Matched by name so tensor methods (normal_,
    # uniform_, ...) are caught too.
    _NONDETERMINISTIC = frozenset({
        "rand", "randn", "randint", "randperm", "rand_like", "randn_like",
        "randint_like", "normal", "bernoulli", "poisson", "multinomial",
        "empty", "empty_like", "empty_strided", "new_empty",
        "normal_", "uniform_", "random_", "bernoulli_", "exponential_",
        "cauchy_", "log_normal_", "geometric_",
        "dropout", "dropout_", "rrelu", "rrelu_",
    })

    def _try_fold(self, node) -> bool:
        """Execute a node whose inputs are all constants/literals (the
        imported model's mask-construction and position-id chains —
        transformers BERT builds its extended attention mask from
        ones/eq/sub/finfo/masked_fill on traced shapes).  Stores a
        tensor result in ``constants``, anything else in ``literals``.
        Non-deterministic targets are refused — folding them would
        freeze a single RNG draw into the program."""
        torch = self.torch
        tname = (node.target if isinstance(node.target, str)
                 else getattr(node.target, "__name__", str(node.target)))
        if tname in self._NONDETERMINISTIC:
            return False
        for a in list(node.args) + list(node.kwargs.values()):
            _, ok = self._resolve_const(a)
            if not ok:
                return False
        args = []
        for a in node.args:
            v, _ = self._resolve_const(a)
            args.append(v)
        kwargs = {}
        for k, a in node.kwargs.items():
            v, _ = self._resolve_const(a)
            kwargs[k] = v
        try:
            if node.op == "call_method":
                out = getattr(args[0], node.target)(*args[1:], **kwargs)
            else:
                out = node.target(*args, **kwargs)
        except Exception:
            return False
        if isinstance(out, torch.Tensor):
            self.constants[node.name] = out
        else:
            self.literals[node.name] = out
        logging.getLogger(__name__).debug(
            "folded %s (%s) -> %s", node.name, tname, type(out).__name__
        )
        return True

    def run(self) -> List[OpRecord]:
        for node in self.gm.graph.nodes:
            out = self.visit(node)
            if out is not None:
                self.env[node.name] = out
        return self.records

    # -- node dispatch ----------------------------------------------------
    def visit(self, node) -> Optional[str]:
        if node.op == "placeholder":
            self.input_names.append(node.name)
            self.emit("input", node.name, [], shape=_tensor_shape(node))
            return node.name
        if node.op == "output":
            args = node.args[0]
            if isinstance(args, dict):  # HF ModelOutput-style dict
                outs = tuple(args.values())
            else:
                outs = args if isinstance(args, (tuple, list)) else (args,)
            self.output_names = [self.ref(a) for a in outs]
            return None
        if node.op == "call_module":
            mod = self.gm.get_submodule(node.target)
            return self.visit_module(node, mod)
        if node.op in ("call_function", "call_method"):
            return self.visit_function(node)
        if node.op == "get_attr":
            # module buffers (position_ids, token_type_ids, ...) are
            # compile-time constants of the imported graph
            import operator as _op

            try:
                val = _op.attrgetter(node.target)(self.gm)
            except AttributeError:
                val = None
            if isinstance(val, self.torch.nn.Parameter):
                # a TRAINABLE tensor used functionally (F.linear(x,
                # self.weight), custom scales): baking it in as a frozen
                # constant would silently stop it training
                raise NotImplementedError(
                    f"get_attr parameter {node.target!r}: functionally-used "
                    "nn.Parameters are not importable; wrap them in a "
                    "supported layer module"
                )
            if isinstance(val, self.torch.Tensor):
                self.constants[node.name] = val  # non-trainable buffer
                return None
            raise NotImplementedError(
                f"get_attr node {node.target!r}: free non-tensor attributes "
                "are not importable; register them as module buffers/"
                "parameters of a supported layer"
            )
        raise NotImplementedError(f"fx node op {node.op!r}")

    def visit_module(self, node, mod) -> str:
        nn = self.torch.nn
        name = node.name
        x = [self.ref(a) for a in node.args if hasattr(a, "name")]
        if isinstance(mod, nn.Linear):
            return self.emit("linear", name, x, out_dim=mod.out_features,
                             use_bias=mod.bias is not None)
        if isinstance(mod, nn.Conv2d):
            assert mod.padding_mode == "zeros", "only zero padding supported"
            pad = mod.padding if isinstance(mod.padding, tuple) else (mod.padding,) * 2
            return self.emit(
                "conv2d", name, x, out_channels=mod.out_channels,
                kernel=list(mod.kernel_size), stride=list(mod.stride),
                padding=[int(pad[0]), int(pad[1])], groups=mod.groups,
                use_bias=mod.bias is not None)
        if isinstance(mod, (nn.MaxPool2d, nn.AvgPool2d)):
            k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else (mod.kernel_size,) * 2
            s = mod.stride if isinstance(mod.stride, tuple) else (mod.stride or k[0],) * 2
            p = mod.padding if isinstance(mod.padding, tuple) else (mod.padding,) * 2
            return self.emit(
                "pool2d", name, x, kernel=[k[0], k[1]], stride=[s[0], s[1]],
                padding=[p[0], p[1]],
                pool_type="max" if isinstance(mod, nn.MaxPool2d) else "avg")
        if isinstance(mod, (nn.AdaptiveAvgPool2d, nn.AdaptiveMaxPool2d)):
            in_shape = _tensor_shape(node.args[0])
            out = mod.output_size if isinstance(mod.output_size, tuple) else (mod.output_size,) * 2
            h, w = in_shape[2], in_shape[3]
            assert h % out[0] == 0 and w % out[1] == 0, (
                f"adaptive pool {in_shape} -> {out} is not an integer stride")
            kh, kw = h // out[0], w // out[1]
            return self.emit(
                "pool2d", name, x, kernel=[kh, kw], stride=[kh, kw],
                padding=[0, 0],
                pool_type="avg" if isinstance(mod, nn.AdaptiveAvgPool2d) else "max")
        if isinstance(mod, nn.BatchNorm2d):
            # torch momentum=None means cumulative averaging, which a
            # static graph can't express — fall back to torch's default 0.1
            tm = 0.1 if mod.momentum is None else mod.momentum
            return self.emit("batchnorm2d", name, x, momentum=1.0 - tm,
                             relu=False)
        if isinstance(mod, nn.LayerNorm):
            rank = len(_tensor_shape(node.args[0]))
            axes = list(range(rank - len(mod.normalized_shape), rank))
            return self.emit("layernorm", name, x, axes=axes,
                             elementwise_affine=mod.elementwise_affine,
                             eps=mod.eps)
        if isinstance(mod, nn.Embedding):
            return self.emit("embedding", name, x, num_entries=mod.num_embeddings,
                             out_dim=mod.embedding_dim)
        if isinstance(mod, nn.Softmax):
            return self.emit("softmax", name, x, axis=mod.dim if mod.dim is not None else -1)
        if isinstance(mod, nn.Dropout):
            return self.emit("dropout", name, x, rate=mod.p)
        if isinstance(mod, nn.Flatten):
            return self.emit("flatten", name, x, start_dim=mod.start_dim,
                             end_dim=mod.end_dim,
                             in_shape=_tensor_shape(node.args[0]))
        if isinstance(mod, nn.MultiheadAttention):
            raise NotImplementedError(
                "nn.MultiheadAttention cannot be fx-traced generically; build "
                "it with FFModel.multihead_attention")
        if isinstance(mod, nn.GELU):
            # nn.GELU(approximate='none') is torch's default: exact erf
            return self.emit(
                "gelu", name, x,
                approximate=getattr(mod, "approximate", "none") == "tanh")
        for cls, kind in ((nn.ReLU, "relu"), (nn.Sigmoid, "sigmoid"),
                          (nn.Tanh, "tanh"),
                          (nn.ELU, "elu"), (nn.Identity, "identity")):
            if isinstance(mod, cls):
                return self.emit(kind, name, x)
        raise NotImplementedError(f"unsupported torch module {type(mod).__name__}")

    def _sdpa(self, node) -> str:
        """torch.nn.functional.scaled_dot_product_attention, decomposed
        into the PCG's own vocabulary (transpose / batch_matmul /
        scalar_multiply / softmax / dropout) — the reference's frontend
        has no sdpa path at all (its MHA is the fused cuDNN op only);
        on TPU the decomposition is exactly what XLA fuses well."""
        import math

        name = node.name
        q, k, v = node.args[:3]
        # positional tail follows torch's signature
        # (q, k, v, attn_mask, dropout_p, is_causal, *, scale)
        pos = {i + 3: a for i, a in enumerate(node.args[3:])}
        kwargs = dict(node.kwargs)

        def arg(key, pos_idx, default):
            raw = kwargs.get(key, pos.get(pos_idx, default))
            val, ok = self._resolve_const(raw)
            if not ok:
                raise NotImplementedError(
                    f"sdpa with tensor-dependent {key} is not importable"
                )
            return val

        mask = arg("attn_mask", 3, None)
        dropout_p = float(arg("dropout_p", 4, 0.0) or 0.0)
        is_causal = bool(arg("is_causal", 5, False))
        scale = arg("scale", 6, None)
        if is_causal:
            raise NotImplementedError(
                "sdpa(is_causal=True) import is not supported; build causal "
                "attention with FFModel.multihead_attention(causal=True)"
            )
        if mask is not None:
            if mask.dtype == self.torch.bool:
                trivial = bool(mask.all())  # all-True = keep everything
            else:
                trivial = float(mask.abs().max()) == 0.0  # additive zeros
            if not trivial:
                raise NotImplementedError(
                    "sdpa with a non-trivial attn_mask is not supported "
                    "(trace with input_names=['input_ids'] so the all-ones "
                    "mask constant-folds to a no-op)"
                )
        q_shape = _tensor_shape(q)
        rank = len(q_shape)
        dh = q_shape[-1]
        if scale is None:
            scale = 1.0 / math.sqrt(dh)
        perm = list(range(rank))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        kt = self.emit("transpose", f"{name}_kt", [self.ref(k)], perm=perm)
        scores = self.emit("batch_matmul", f"{name}_scores",
                           [self.ref(q), kt])
        scaled = self.emit("scalar_multiply", f"{name}_scaled", [scores],
                           scalar=float(scale))
        probs = self.emit("softmax", f"{name}_probs", [scaled], axis=-1)
        if dropout_p > 0.0:
            probs = self.emit("dropout", f"{name}_dropout", [probs],
                              rate=dropout_p)
        return self.emit("batch_matmul", name, [probs, self.ref(v)])

    def _tensor_getitem(self, node, src, idx) -> str:
        """Graph-tensor subscripts: integer indexing realized as
        split + select (+ final reshape to drop the indexed dims and
        insert None dims); full slices pass through."""
        in_shape = _tensor_shape(src)
        idx_t = idx if isinstance(idx, tuple) else (idx,)
        cur = self.ref(src)
        out_shape: List[int] = []
        d = 0  # current dim in the (possibly split) source tensor
        squeeze = False
        for it in idx_t:
            it_v, ok = self._resolve_const(it)
            if not ok:
                raise NotImplementedError("tensor-dependent subscript index")
            if it_v is None:
                out_shape.append(1)
                squeeze = True
                continue
            if isinstance(it_v, slice):
                dim = in_shape[d]
                s0 = 0 if it_v.start is None else int(it_v.start)
                s1 = dim if it_v.stop is None else int(it_v.stop)
                if s0 < 0:
                    s0 += dim
                if s1 < 0:
                    s1 += dim
                s0, s1 = max(0, min(s0, dim)), max(0, min(s1, dim))
                if s1 <= s0:
                    raise NotImplementedError(f"empty tensor slice [{s0}:{s1}]")
                if it_v.step not in (None, 1):
                    raise NotImplementedError("strided tensor slicing")
                if s0 == 0 and s1 == in_shape[d]:
                    out_shape.append(in_shape[d])
                    d += 1
                    continue
                sizes = [s for s in (s0, s1 - s0, in_shape[d] - s1) if s > 0]
                part_idx = 1 if s0 > 0 else 0
                sp = self.emit("split", f"{node.name}_split{d}", [cur],
                               sizes=sizes, axis=d)
                cur = self.emit("getitem", f"{node.name}_part{d}", [sp],
                                index=part_idx)
                out_shape.append(s1 - s0)
                d += 1
                continue
            if isinstance(it_v, int):
                i = it_v % in_shape[d]
                if in_shape[d] > 1:
                    sizes = [s for s in (i, 1, in_shape[d] - i - 1) if s > 0]
                    part_idx = 1 if i > 0 else 0
                    sp = self.emit("split", f"{node.name}_split{d}", [cur],
                                   sizes=sizes, axis=d)
                    cur = self.emit("getitem", f"{node.name}_part{d}", [sp],
                                    index=part_idx)
                squeeze = True
                d += 1
                continue
            raise NotImplementedError(f"unsupported subscript element {it_v!r}")
        out_shape.extend(in_shape[d:])
        target = _tensor_shape(node)
        if squeeze or (target is not None and list(target) != out_shape):
            cur = self.emit("reshape", node.name + "_sq", [cur],
                            shape=[int(s) for s in (target or out_shape)])
        self.env[node.name] = cur
        return cur

    # mapping of simple unary call_function/method targets
    _UNARY = {
        "relu": "relu", "sigmoid": "sigmoid", "tanh": "tanh", "gelu": "gelu",
        "elu": "elu", "exp": "exp", "log": "log", "rsqrt": "rsqrt",
        "contiguous": "identity", "clone": "identity", "detach": "identity",
    }
    _BINARY = {"add": "add", "sub": "subtract", "mul": "multiply",
               "truediv": "divide", "div": "divide", "matmul": "batch_matmul",
               "bmm": "batch_matmul", "maximum": "max", "minimum": "min"}
    _SCALAR = {"add": "scalar_add", "sub": "scalar_sub", "mul": "scalar_multiply",
               "truediv": "scalar_true_divide", "div": "scalar_true_divide",
               "pow": "pow"}

    def visit_function(self, node) -> str:
        import operator

        name = node.name
        target = node.target
        fname = target if isinstance(target, str) else getattr(target, "__name__", str(target))
        fname = fname.rstrip("_")  # in-place variants (relu_, add_) fold to pure

        if fname == "getattr" and len(node.args) == 2:
            attr = node.args[1]
            if attr == "shape":
                self.literals[node.name] = _tensor_shape(node.args[0])
                return None
            # dtype/device queries on real graph tensors fold to the
            # traced metadata (constants are handled by _try_fold below)
            src = node.args[0]
            if (
                attr in ("dtype", "device")
                and hasattr(src, "meta")
                and src.name not in self.constants
            ):
                tm = src.meta.get("tensor_meta")
                if attr == "dtype" and tm is not None:
                    self.literals[node.name] = tm.dtype
                    return None
                if attr == "device":
                    self.literals[node.name] = self.torch.device("cpu")
                    return None
        if fname in ("size", "dim") and node.args and hasattr(node.args[0], "meta") \
                and node.args[0].name not in self.constants \
                and node.args[0].name not in self.literals:
            shape = _tensor_shape(node.args[0])
            if shape is not None:
                if fname == "dim":
                    self.literals[node.name] = len(shape)
                elif len(node.args) > 1:
                    self.literals[node.name] = shape[_norm_dim(node.args[1], len(shape))]
                else:
                    self.literals[node.name] = self.torch.Size(shape)
                return None
        if fname in ("_assert", "_assert_async"):
            cond, ok = self._resolve_const(node.args[0])
            if ok and bool(cond):
                return None
            raise NotImplementedError("data-dependent torch._assert")
        # whole-node constant folding: the imported model's mask and
        # position-id chains (ones/eq/sub/finfo/masked_fill/expand/to on
        # traced shapes and buffers) execute at import time
        if self._try_fold(node):
            return None
        if target is operator.getitem or fname == "getitem":
            src, idx = node.args
            if hasattr(src, "name") and src.name in self.literals:
                idx_v, ok = self._resolve_const(idx)
                assert ok, "literal getitem with graph-tensor index"
                self.literals[node.name] = self.literals[src.name][idx_v]
                return None
            if isinstance(idx, int) and self.kinds.get(
                self.env.get(getattr(src, "name", ""), "")
            ) == "split":
                # select one output of the only multi-output op (split/
                # chunk); x[0] on a PLAIN tensor is real dim-0 indexing
                return self.emit("getitem", name, [self.ref(src)], index=idx)
            return self._tensor_getitem(node, src, idx)
        if fname == "scaled_dot_product_attention":
            return self._sdpa(node)

        def _lit(a):  # resolve traced ints (e.g. x.shape[0]) to values
            if hasattr(a, "name") and a.name in self.literals:
                return self.literals[a.name]
            return a

        is_tensor = lambda a: hasattr(a, "name") and a.name not in self.literals
        node_args = [_lit(a) for a in node.args]
        if fname in self._UNARY and len(node.args) >= 1:
            if fname == "gelu":
                # torch F.gelu defaults to the EXACT erf form
                # (approximate='none'); only an explicit
                # approximate='tanh' selects the tanh approximation
                approx = node.kwargs.get("approximate", "none") == "tanh"
                return self.emit("gelu", name, [self.ref(node.args[0])],
                                 approximate=approx)
            return self.emit(self._UNARY[fname], name, [self.ref(node.args[0])])
        if fname in ("float", "to", "type_as", "type"):
            dtype = None
            if fname == "float":
                dtype = "float32"
            elif fname == "type_as":
                tm = node.args[1].meta.get("tensor_meta")
                dtype = _torch_dtype_str(tm.dtype) if tm is not None else None
            else:
                for arg in list(node.args[1:]) + list(node.kwargs.values()):
                    s = _torch_dtype_str(arg)
                    if s is not None:
                        dtype = s
                        break
            if dtype is None:  # .to(device) etc. — dtype unchanged
                return self.emit("identity", name, [self.ref(node.args[0])])
            return self.emit("cast", name, [self.ref(node.args[0])], dtype=dtype)
        if fname in self._BINARY or fname in self._SCALAR:
            a, b = node_args[0], node_args[1]
            if is_tensor(a) and is_tensor(b):
                if fname not in self._BINARY:
                    raise NotImplementedError(f"tensor-tensor {fname}")
                return self.emit(self._BINARY[fname], name, [self.ref(a), self.ref(b)])
            if is_tensor(a):
                return self.emit(self._SCALAR[fname], name, [self.ref(a)],
                                 scalar=float(b))
            # scalar - tensor / scalar / tensor: normalize
            if fname == "add":
                return self.emit("scalar_add", name, [self.ref(b)], scalar=float(a))
            if fname == "mul":
                return self.emit("scalar_multiply", name, [self.ref(b)], scalar=float(a))
            raise NotImplementedError(f"scalar-first {fname}")
        if fname == "cat":
            tensors = node.args[0]
            axis = node.args[1] if len(node.args) > 1 else node.kwargs.get("dim", 0)
            return self.emit("concat", name, [self.ref(t) for t in tensors], axis=axis)
        if fname in ("split", "chunk"):
            src = node.args[0]
            sizes = node.args[1]
            axis = node.args[2] if len(node.args) > 2 else node.kwargs.get("dim", 0)
            in_shape = _tensor_shape(src)
            axis = _norm_dim(axis, len(in_shape))
            if fname == "chunk":
                n = int(sizes)
                assert in_shape[axis] % n == 0
                sizes = [in_shape[axis] // n] * n
            elif isinstance(sizes, int):
                total = in_shape[axis]
                sizes = [sizes] * (total // sizes) + ([total % sizes] if total % sizes else [])
            return self.emit("split", name, [self.ref(src)], sizes=list(sizes), axis=axis)
        if fname == "flatten":
            start = node.args[1] if len(node.args) > 1 else node.kwargs.get("start_dim", 0)
            end = node.args[2] if len(node.args) > 2 else node.kwargs.get("end_dim", -1)
            return self.emit("flatten", name, [self.ref(node.args[0])],
                             start_dim=start, end_dim=end,
                             in_shape=_tensor_shape(node.args[0]))
        if fname in ("reshape", "view"):
            shape = node.args[1] if isinstance(node.args[1], (tuple, list)) else list(node.args[1:])
            out_shape = _tensor_shape(node)
            return self.emit("reshape", name, [self.ref(node.args[0])],
                             shape=[int(s) for s in out_shape] if out_shape else list(shape))
        if fname == "permute":
            perm = node.args[1] if isinstance(node.args[1], (tuple, list)) else list(node.args[1:])
            return self.emit("transpose", name, [self.ref(node.args[0])], perm=list(perm))
        if fname == "transpose":
            d0, d1 = node.args[1], node.args[2]
            rank = len(_tensor_shape(node.args[0]))
            perm = list(range(rank))
            d0, d1 = _norm_dim(d0, rank), _norm_dim(d1, rank)
            perm[d0], perm[d1] = perm[d1], perm[d0]
            return self.emit("transpose", name, [self.ref(node.args[0])], perm=perm)
        if fname in ("unsqueeze", "squeeze"):
            out_shape = _tensor_shape(node)
            return self.emit("reshape", name, [self.ref(node.args[0])],
                             shape=[int(s) for s in out_shape])
        if fname == "mean":
            dims = node.args[1] if len(node.args) > 1 else node.kwargs.get("dim")
            keep = node.kwargs.get("keepdim", node.args[2] if len(node.args) > 2 else False)
            rank = len(_tensor_shape(node.args[0]))
            if dims is None:
                dims = list(range(rank))
            if isinstance(dims, int):
                dims = [dims]
            dims = [_norm_dim(d, rank) for d in dims]
            return self.emit("mean", name, [self.ref(node.args[0])],
                             dims=dims, keepdims=bool(keep))
        if fname == "softmax":
            axis = node.args[1] if len(node.args) > 1 else node.kwargs.get("dim", -1)
            return self.emit("softmax", name, [self.ref(node.args[0])], axis=axis)
        if fname == "dropout":
            rate = node.kwargs.get("p", node.args[1] if len(node.args) > 1 else 0.5)
            return self.emit("dropout", name, [self.ref(node.args[0])], rate=float(rate))
        if fname in ("expand", "expand_as", "broadcast_to"):
            # broadcast is implicit in elementwise consumers; anything
            # shape-sensitive (cat/reshape/matmul/...) would silently see
            # the un-expanded shape, so reject those explicitly
            _ELEMENTWISE_OK = {"add", "sub", "mul", "truediv", "div",
                               "maximum", "minimum", "relu", "sigmoid",
                               "tanh", "gelu", "exp", "log", "pow"}
            nn = self.torch.nn
            _ELEMENTWISE_MODULES = (nn.ReLU, nn.Sigmoid, nn.Tanh, nn.GELU,
                                    nn.ELU, nn.Identity, nn.Dropout)
            for user in node.users:
                if user.op == "call_module":
                    mod = self.gm.get_submodule(user.target)
                    if isinstance(mod, _ELEMENTWISE_MODULES):
                        continue
                    uname = type(mod).__name__
                else:
                    uname = (user.target if isinstance(user.target, str)
                             else getattr(user.target, "__name__", "?")).rstrip("_")
                    if user.op != "output" and uname in _ELEMENTWISE_OK:
                        continue
                raise NotImplementedError(
                    f"expand() feeding non-elementwise consumer {uname!r} "
                    "is not supported (the broadcast would be dropped)"
                )
            return self.emit("identity", name, [self.ref(node.args[0])])
        raise NotImplementedError(f"unsupported torch function/method {fname!r}")


# ---------------------------------------------------------------------------
# Applying records onto an FFModel
# ---------------------------------------------------------------------------

_NCHW_TO_NHWC = (0, 2, 3, 1)
_NHWC_TO_NCHW = (0, 3, 1, 2)


class PyTorchModel:
    """Importer: a traced torch module or a serialized record file.

    Reference surface: python/flexflow/torch/model.py PyTorchModel
    (file or module ctor; ``torch_to_ff(ffmodel, input_tensors)``).
    """

    def __init__(self, source, example_inputs: Optional[Sequence] = None):
        self._module = None
        if isinstance(source, str):
            with open(source) as f:
                lines = f.read().splitlines()
            assert lines and lines[0] == FILE_MAGIC, f"bad file magic in {source}"
            meta = json.loads(lines[1])
            self.records = [OpRecord.from_json(l) for l in lines[2:] if l.strip()]
            self.input_names = meta["inputs"]
            self.output_names = meta["outputs"]
        else:
            self._module = source
            if example_inputs is None:
                self.records = None  # trace lazily in torch_to_ff from ff shapes
                self.input_names = self.output_names = None
            else:
                self._trace(example_inputs)

    def _trace(self, example_inputs: Sequence) -> None:
        tr = _Tracer(self._module, example_inputs)
        tr.run()
        self.records = tr.records
        self.input_names = tr.input_names
        self.output_names = tr.output_names

    # -- emission ---------------------------------------------------------
    def torch_to_ff(self, ffmodel, input_tensors: Sequence) -> List:
        """Build the imported graph on ``ffmodel``; returns output Tensors."""
        if self.records is None:
            import torch

            to_torch = {"float32": torch.float32, "float16": torch.float16,
                        "bfloat16": torch.bfloat16, "float64": torch.float64,
                        "int32": torch.int32, "int64": torch.int64,
                        "bool": torch.bool}
            zeros = [
                torch.zeros(*t.sizes,
                            dtype=to_torch.get(str(getattr(t.dtype, "value", t.dtype)),
                                               torch.float32))
                for t in input_tensors
            ]
            self._trace(zeros)
        env: Dict[str, Any] = {}
        it = iter(input_tensors)
        for rec in self.records:
            env[rec.name] = self._apply(ffmodel, rec, env, it)
        return [env[n] for n in self.output_names]

    def _apply(self, ff, rec: OpRecord, env, input_iter):
        a = rec.attrs
        x = [env[i] for i in rec.inputs]
        k = rec.kind
        if k == "input":
            return next(input_iter)
        if k == "linear":
            return ff.dense(x[0], a["out_dim"], use_bias=a["use_bias"], name=rec.name)
        if k == "conv2d":
            t = ff.transpose(x[0], _NCHW_TO_NHWC, name=f"{rec.name}.nhwc")
            y = ff.conv2d(t, a["out_channels"], a["kernel"][0], a["kernel"][1],
                          a["stride"][0], a["stride"][1], a["padding"][0],
                          a["padding"][1], groups=a["groups"],
                          use_bias=a["use_bias"], name=rec.name)
            return ff.transpose(y, _NHWC_TO_NCHW, name=f"{rec.name}.nchw")
        if k == "pool2d":
            t = ff.transpose(x[0], _NCHW_TO_NHWC, name=f"{rec.name}.nhwc")
            y = ff.pool2d(t, a["kernel"][0], a["kernel"][1], a["stride"][0],
                          a["stride"][1], a["padding"][0], a["padding"][1],
                          pool_type=a["pool_type"], name=rec.name)
            return ff.transpose(y, _NHWC_TO_NCHW, name=f"{rec.name}.nchw")
        if k == "batchnorm2d":
            t = ff.transpose(x[0], _NCHW_TO_NHWC, name=f"{rec.name}.nhwc")
            y = ff.batch_norm(t, relu=a["relu"], momentum=a["momentum"], name=rec.name)
            return ff.transpose(y, _NHWC_TO_NCHW, name=f"{rec.name}.nchw")
        if k == "layernorm":
            return ff.layer_norm(x[0], axes=a["axes"],
                                 elementwise_affine=a["elementwise_affine"],
                                 eps=a["eps"], name=rec.name)
        if k == "embedding":
            return ff.embedding(x[0], a["num_entries"], a["out_dim"], name=rec.name)
        if k == "softmax":
            return ff.softmax(x[0], axis=a["axis"], name=rec.name)
        if k == "dropout":
            return ff.dropout(x[0], rate=a["rate"], name=rec.name)
        if k == "flatten":
            shp = list(x[0].sizes)
            start = _norm_dim(a["start_dim"], len(shp))
            end = _norm_dim(a["end_dim"], len(shp))
            merged = 1
            for s in shp[start:end + 1]:
                merged *= s
            out = shp[:start] + [merged] + shp[end + 1:]
            return ff.reshape(x[0], out, name=rec.name)
        if k == "concat":
            return ff.concat(x, axis=a["axis"], name=rec.name)
        if k == "split":
            return ff.split(x[0], a["sizes"], axis=a["axis"], name=rec.name)
        if k == "getitem":
            return x[0][a["index"]]
        if k == "constant":
            import numpy as np

            return ff.create_constant(
                np.asarray(a["value"], dtype=a["dtype"]), name=rec.name
            )
        if k == "reshape":
            shape = [s if s != -1 else -1 for s in a["shape"]]
            return ff.reshape(x[0], shape, name=rec.name)
        if k == "transpose":
            return ff.transpose(x[0], a["perm"], name=rec.name)
        if k == "mean":
            return ff.mean(x[0], dims=a["dims"], keepdims=a["keepdims"], name=rec.name)
        if k == "cast":
            return ff.cast(x[0], a["dtype"], name=rec.name)
        if k == "batch_matmul":
            return ff.batch_matmul(x[0], x[1], name=rec.name)
        if k == "pow":
            return ff.pow(x[0], a["scalar"], name=rec.name)
        if k in ("scalar_add", "scalar_sub", "scalar_multiply", "scalar_true_divide"):
            return getattr(ff, k)(x[0], a["scalar"], name=rec.name)
        if k == "gelu":
            # exact erf unless the trace explicitly chose tanh
            return ff.gelu(x[0], name=rec.name,
                           approximate=bool(a.get("approximate", False)))
        if k in ("add", "subtract", "multiply", "divide", "max", "min",
                 "relu", "sigmoid", "tanh", "elu", "exp", "log",
                 "rsqrt", "identity"):
            return getattr(ff, k)(*x, name=rec.name)
        raise NotImplementedError(f"record kind {k!r}")


def torch_to_flexflow(module, filename: str, example_inputs: Sequence) -> None:
    """Serialize a torch module's traced graph to ``filename``
    (reference: torch/model.py torch_to_flexflow — two-env workflow:
    trace in a torch env, apply in a TPU env with no torch)."""
    tr = _Tracer(module, example_inputs)
    tr.run()
    with open(filename, "w") as f:
        f.write(FILE_MAGIC + "\n")
        f.write(json.dumps({"inputs": tr.input_names, "outputs": tr.output_names}) + "\n")
        for rec in tr.records:
            f.write(rec.to_json() + "\n")


# ---------------------------------------------------------------------------
# Weight transfer (align/-style parity: reference align/align_utils.py)
# ---------------------------------------------------------------------------


def transfer_torch_weights(torch_module, ffmodel) -> int:
    """Copy trained torch parameters into a compiled FFModel.

    Op names produced by the importer equal fx node names, which equal
    sanitized module paths — so ``layers.0.fc`` ↔ ``layers_0_fc``.
    Returns the number of arrays copied.
    """
    import numpy as np

    copied = 0
    params = ffmodel.params
    by_name = {n.replace(".", "_"): m for n, m in torch_module.named_modules()}
    for op_name in list(params.keys()):
        mod = by_name.get(op_name) or by_name.get(op_name.replace(".", "_"))
        if mod is None:
            continue
        import torch.nn as nn

        w = {k: v.detach().cpu().numpy() for k, v in mod.state_dict().items()}
        if isinstance(mod, nn.Linear):
            ffmodel.set_weight(op_name, "kernel", np.ascontiguousarray(w["weight"].T))
            copied += 1
            if "bias" in w:
                ffmodel.set_weight(op_name, "bias", w["bias"]); copied += 1
        elif isinstance(mod, nn.Conv2d):
            ffmodel.set_weight(op_name, "kernel",
                               np.ascontiguousarray(w["weight"].transpose(2, 3, 1, 0)))
            copied += 1
            if "bias" in w:
                ffmodel.set_weight(op_name, "bias", w["bias"]); copied += 1
        elif isinstance(mod, nn.Embedding):
            ffmodel.set_weight(op_name, "table", w["weight"]); copied += 1
        elif isinstance(mod, nn.LayerNorm):
            if "weight" in w:
                ffmodel.set_weight(op_name, "gamma", w["weight"])
                ffmodel.set_weight(op_name, "beta", w["bias"])
                copied += 2
        elif isinstance(mod, nn.BatchNorm2d):
            if "weight" in w:  # affine=False has no scale/bias
                ffmodel.set_weight(op_name, "scale", w["weight"])
                ffmodel.set_weight(op_name, "bias", w["bias"])
                copied += 2
            # eval-mode parity needs the trained running statistics too
            if "running_mean" in w:  # track_running_stats=False has none
                ffmodel.set_state_var(f"{op_name}/running_mean", w["running_mean"])
                ffmodel.set_state_var(f"{op_name}/running_var", w["running_var"])
                copied += 2
    return copied
