"""CANDLE-Uno drug-response model (reference:
examples/cpp/candle_uno/candle_uno.cc:1-453): several input feature
towers, each its own MLP, concatenated into a deep head — the OSDI'22
hybrid-parallel showcase (independent towers place on disjoint
devices)."""

from __future__ import annotations

from typing import Dict, Sequence

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def build_candle_uno(
    config: FFConfig,
    feature_shapes: Dict[str, int] = None,
    input_features: Sequence[str] = None,
    dense_layers: Sequence[int] = (1000,) * 3,
    dense_feature_layers: Sequence[int] = (1000,) * 3,
):
    """reference: candle_uno.cc:27-60 default config — towers for
    dose/cell/drug features feeding a 3x1000 head."""
    feature_shapes = feature_shapes or {
        "dose": 1, "cell.rnaseq": 942, "drug.descriptors": 5270,
        "drug.fingerprints": 2048,
    }
    input_features = input_features or [
        "dose1", "dose2", "cell.rnaseq", "drug1.descriptors",
        "drug1.fingerprints", "drug2.descriptors", "drug2.fingerprints",
    ]
    model = FFModel(config)
    b = config.batch_size
    towers = []
    for feat in input_features:
        # map e.g. "drug1.descriptors" -> "drug.descriptors", "dose1" ->
        # "dose" (reference: candle_uno.cc:38-39 feature-name mapping)
        if "." in feat:
            base = feat.split(".")[-1]
            key = next((k for k in feature_shapes if k.endswith(base)), None)
        else:
            stripped = feat.rstrip("0123456789")
            key = stripped if stripped in feature_shapes else None
        assert key is not None, f"no feature shape for input {feat!r}"
        dim = feature_shapes[key]
        x = model.create_tensor([b, dim], name=f"in_{feat.replace('.', '_')}")
        t = x
        if dim > 1:  # feature towers get their own MLP (candle_uno.cc build_feature_model)
            for i, h in enumerate(dense_feature_layers):
                t = model.dense(t, h, activation="relu",
                                name=f"tower_{feat.replace('.', '_')}_{i}")
        towers.append(t)
    t = model.concat(towers, axis=1, name="concat")
    for i, h in enumerate(dense_layers):
        t = model.dense(t, h, activation="relu", name=f"head_{i}")
    t = model.dense(t, 1, name="out")
    return model
