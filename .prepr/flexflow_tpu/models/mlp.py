"""MLP_Unify (reference: examples/cpp/MLP_Unify/mlp.cc:1-93): the
minimal two-tower MLP used by the Unity artifact's mlp.sh benchmark."""

from __future__ import annotations

from typing import Sequence

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def build_mlp_unify(
    config: FFConfig,
    in_dim: int = 8192,
    hidden: Sequence[int] = (8192, 8192, 8192),
    num_classes: int = 10,
):
    model = FFModel(config)
    b = config.batch_size
    x = model.create_tensor([b, in_dim], name="features")
    t = x
    for i, h in enumerate(hidden):
        t = model.dense(t, h, activation="relu", name=f"fc{i}")
    t = model.dense(t, num_classes, name="head")
    return model
