"""ResNet / ResNeXt-50 (reference: examples/cpp/ResNet/resnet.cc:1-417,
examples/cpp/resnext50/resnext.cc:1-140).  NHWC, batch-norm blocks."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def _bottleneck(model, t, out_ch, stride, name, groups=1, width=None):
    """1x1 -> 3x3(groups) -> 1x1 with projection shortcut
    (reference: resnet.cc BottleneckBlock; resnext.cc groups=32)."""
    width = width or out_ch // 4
    shortcut = t
    in_ch = t.sizes[-1]
    u = model.conv2d(t, width, 1, 1, 1, 1, 0, 0, name=f"{name}_c1", use_bias=False)
    u = model.batch_norm(u, relu=True, name=f"{name}_bn1")
    u = model.conv2d(u, width, 3, 3, stride, stride, 1, 1, groups=groups,
                     name=f"{name}_c2", use_bias=False)
    u = model.batch_norm(u, relu=True, name=f"{name}_bn2")
    u = model.conv2d(u, out_ch, 1, 1, 1, 1, 0, 0, name=f"{name}_c3", use_bias=False)
    u = model.batch_norm(u, relu=False, name=f"{name}_bn3")
    if stride != 1 or in_ch != out_ch:
        shortcut = model.conv2d(shortcut, out_ch, 1, 1, stride, stride, 0, 0,
                                name=f"{name}_proj", use_bias=False)
        shortcut = model.batch_norm(shortcut, relu=False, name=f"{name}_bnp")
    u = model.add(u, shortcut, name=f"{name}_add")
    return model.relu(u, name=f"{name}_relu")


def build_resnet(config: FFConfig, num_classes: int = 1000, image: int = 224,
                 layers=(3, 4, 6, 3), groups: int = 1, base_width: int = 64):
    model = FFModel(config)
    b = config.batch_size
    x = model.create_tensor([b, image, image, 3], name="image")
    t = model.conv2d(x, 64, 7, 7, 2, 2, 3, 3, use_bias=False, name="conv1")
    t = model.batch_norm(t, relu=True, name="bn1")
    t = model.pool2d(t, 3, 3, 2, 2, 1, 1, name="pool1")
    channels = [256, 512, 1024, 2048]
    for stage, (n_blocks, out_ch) in enumerate(zip(layers, channels)):
        for i in range(n_blocks):
            stride = 2 if (i == 0 and stage > 0) else 1
            # ResNeXt widths: base_width=4 per group x 32 groups doubles
            # the 3x3 width vs ResNet (resnext.cc)
            if groups == 1:
                width = (out_ch // 4) * base_width // 64
            else:
                width = out_ch // 2
            t = _bottleneck(model, t, out_ch, stride,
                            f"s{stage}b{i}", groups=groups, width=width)
    t = model.pool2d(t, t.sizes[1], t.sizes[2], 1, 1, pool_type="avg", name="avgpool")
    t = model.flat(t, name="flat")
    t = model.dense(t, num_classes, name="fc")
    return model


def build_resnext50(config: FFConfig, num_classes: int = 1000, image: int = 224):
    """ResNeXt-50 32x4d (reference: resnext.cc — groups=32)."""
    return build_resnet(config, num_classes, image, layers=(3, 4, 6, 3), groups=32)
