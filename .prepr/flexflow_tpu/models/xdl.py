"""XDL ads-ranking model (reference: examples/cpp/XDL/xdl.cc:1-438):
many small embedding tables + deep MLP over concatenated features."""

from __future__ import annotations

from typing import Sequence

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def build_xdl(
    config: FFConfig,
    num_tables: int = 16,
    vocab: int = 100000,
    embedding_dim: int = 16,
    mlp: Sequence[int] = (256, 128, 1),
):
    model = FFModel(config)
    b = config.batch_size
    embeds = []
    for i in range(num_tables):
        ids = model.create_tensor([b, 1], dtype="int32", name=f"sparse_{i}")
        embeds.append(
            model.embedding(ids, vocab, embedding_dim, aggr="sum", name=f"embed_{i}")
        )
    t = model.concat(embeds, axis=1, name="concat")
    for i, h in enumerate(mlp[:-1]):
        t = model.dense(t, h, activation="relu", name=f"mlp_{i}")
    t = model.dense(t, mlp[-1], activation="sigmoid", name="out")
    return model
