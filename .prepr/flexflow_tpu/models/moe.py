"""Mixture-of-Experts classifier (reference:
examples/cpp/mixture_of_experts/moe.cc:1-501): top-k gating -> group_by
dispatch -> per-expert MLPs -> weighted aggregate, with assignment
caching feeding dynamic recompilation (moe.cc:46-92).

TPU-native: experts are a batched [E, cap, D] computation (one Linear
over the expert dim is expert-parallel when dim 0 is sharded)."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def build_moe(
    config: FFConfig,
    in_dim: int = 784,
    num_classes: int = 10,
    num_exp: int = 4,
    num_select: int = 2,
    hidden: int = 64,
    alpha: float = 2.0,
    lambda_bal: float = 0.04,
    use_cache: bool = False,
):
    """reference: moe.cc:94-148 (num_exp=4 k=2 alpha=2 on MNIST-784)."""
    model = FFModel(config)
    b = config.batch_size
    x = model.create_tensor([b, in_dim], name="features")
    # gating network (moe.cc: dense -> softmax -> topk)
    gate = model.dense(x, num_exp, name="gate_dense")
    gate = model.softmax(gate, name="gate_softmax")
    if use_cache:
        gate = model.cache(gate, name="gate_cache")
    topk_vals, topk_idx = model.top_k(gate, k=num_select, name="gate_topk")
    grouped, eidx, pos, valid = model.group_by(x, topk_idx, n_experts=num_exp,
                                               alpha=alpha, name="dispatch")
    # experts: batched MLP over [E, cap, D] — dim 0 sharding = EP
    h = model.dense(grouped, hidden, activation="relu", name="expert_fc1")
    h = model.dense(h, num_classes, name="expert_fc2")
    out = model.aggregate(topk_vals, eidx, pos, valid, h,
                          lambda_bal=lambda_bal, name="combine")
    return model
