"""Transformer/BERT-style encoder (reference:
examples/cpp/Transformer/transformer.cc:112-211 — 12 layers of
MultiHeadAttention + 2-layer FFN with residuals; the OSDI'22 BERT
benchmark config is batch 8, seq 512, hidden 768, 12 heads).

TPU-native extras over the reference: optional causal masking, flash
attention (Pallas), and the sequence dim is partitionable (ring/context
parallelism — the reference cannot split MHA's seq dim, SURVEY.md §5)."""

from __future__ import annotations

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def encoder_layer(model, t, hidden, num_heads, ff_dim, name, dropout=0.1,
                  layer_norm=True, causal=False, sp_mode="ring"):
    """reference: transformer.cc create_attention_encoder.
    ``sp_mode`` picks the sequence-parallel scheme serving seq-sharded
    strategies (ops/attention.py: ring | ulysses | auto)."""
    a = model.multihead_attention(
        t, t, t, embed_dim=hidden, num_heads=num_heads, dropout=dropout,
        causal=causal, sp_mode=sp_mode, name=f"{name}_mha",
    )
    t = model.add(a, t, name=f"{name}_res1")
    if layer_norm:
        t = model.layer_norm(t, name=f"{name}_ln1")
    f = model.dense(t, ff_dim, activation="relu", name=f"{name}_ff1")
    f = model.dense(f, hidden, name=f"{name}_ff2")
    t = model.add(f, t, name=f"{name}_res2")
    if layer_norm:
        t = model.layer_norm(t, name=f"{name}_ln2")
    return t


def build_transformer(config: FFConfig, num_layers: int = 12, hidden: int = 512,
                      num_heads: int = 8, ff_dim: int = 2048, seq_len: int = 512,
                      dropout: float = 0.0, layer_norm: bool = False,
                      causal: bool = False, dtype: str = "float32",
                      sp_mode: str = "ring"):
    """The reference Transformer example: raw float inputs [B, S, H],
    per-position dense head back to hidden (transformer.cc:112-211 uses
    no embedding/LN — dense proxies).

    ``dtype`` sets the activation-stream dtype: ops cast their outputs
    back to their input dtype, so a "bfloat16" input tensor keeps every
    inter-op activation at 2 bytes (half the HBM traffic of the default
    float32 stream) while matmuls still accumulate in f32."""
    model = FFModel(config)
    b = config.batch_size
    x = model.create_tensor([b, seq_len, hidden], dtype=dtype, name="tokens")
    t = x
    for i in range(num_layers):
        t = encoder_layer(model, t, hidden, num_heads, ff_dim, f"layer{i}",
                          dropout=dropout, layer_norm=layer_norm,
                          causal=causal, sp_mode=sp_mode)
    t = model.dense(t, hidden, name="head")
    return model


def build_bert(config: FFConfig, vocab: int = 30522, num_layers: int = 12,
               hidden: int = 768, num_heads: int = 12, ff_dim: int = 3072,
               seq_len: int = 512, num_classes: int = 2, dropout: float = 0.1):
    """BERT-base-style classifier: token embedding + encoder stack +
    pooled classification head (the osdi22ae bert.sh scenario)."""
    model = FFModel(config)
    b = config.batch_size
    ids = model.create_tensor([b, seq_len], dtype="int32", name="input_ids")
    t = model.embedding(ids, vocab, hidden, aggr="none", name="tok_embed")
    t = model.layer_norm(t, name="embed_ln")
    for i in range(num_layers):
        t = encoder_layer(model, t, hidden, num_heads, ff_dim, f"layer{i}",
                          dropout=dropout, layer_norm=True)
    t = model.mean(t, dims=[1], name="pool")  # mean-pool over seq
    t = model.dense(t, hidden, activation="tanh", name="pooler")
    t = model.dense(t, num_classes, name="classifier")
    return model


def build_gpt(config: FFConfig, vocab: int = 32000, num_layers: int = 12,
              hidden: int = 768, num_heads: int = 12, ff_dim: int = 3072,
              seq_len: int = 1024, dropout: float = 0.0):
    """GPT-style causal language model: token + learned positional
    embeddings, post-LN causal encoder stack (the zoo's shared
    encoder_layer), untied vocab head;
    trains with per-token sparse CCE on shifted targets.  Beyond the
    reference zoo (its Transformer example is a non-causal MSE proxy,
    transformer.cc:112-211); the causal MHA takes the flash/ring
    attention paths, so the seq dim is partitionable for long-context
    training (zigzag ring — parallel/ring_attention.py)."""
    model = FFModel(config)
    b = config.batch_size
    ids = model.create_tensor([b, seq_len], dtype="int32", name="input_ids")
    t = model.embedding(ids, vocab, hidden, aggr="none", name="tok_embed")
    pos = model.create_constant(
        np.arange(seq_len, dtype=np.int32)[None, :].repeat(b, axis=0),
        name="positions",
    )
    p = model.embedding(pos, seq_len, hidden, aggr="none", name="pos_embed")
    t = model.add(t, p, name="embed_sum")
    for i in range(num_layers):
        t = encoder_layer(model, t, hidden, num_heads, ff_dim, f"layer{i}",
                          dropout=dropout, layer_norm=True, causal=True)
    t = model.layer_norm(t, name="final_ln")
    t = model.dense(t, vocab, use_bias=False, name="lm_head")
    return model
