"""DLRM (reference: examples/cpp/DLRM/dlrm.cc:27-736): sparse embedding
tables + bottom/top MLPs + pairwise feature interaction.  The embedding
tables are the parameter-parallel workhorse — the search shards them
over vocab (partial-sum gather) or channel (reference:
embedding.cc:123-190)."""

from __future__ import annotations

from typing import List, Sequence

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def build_dlrm(
    config: FFConfig,
    embedding_sizes: Sequence[int] = (1000000,) * 8,
    embedding_dim: int = 64,
    indices_per_table: int = 1,
    dense_dim: int = 13,
    bot_mlp: Sequence[int] = (512, 256, 64),
    top_mlp: Sequence[int] = (512, 256, 1),
):
    """reference: dlrm.cc:27-44 (default sparse-feature config)."""
    model = FFModel(config)
    b = config.batch_size

    dense_in = model.create_tensor([b, dense_dim], name="dense_features")
    t = dense_in
    for i, h in enumerate(bot_mlp):
        t = model.dense(t, h, activation="relu", name=f"bot_mlp_{i}")
    bottom = t  # [B, embedding_dim]

    sparse_outs: List = []
    for i, vocab in enumerate(embedding_sizes):
        ids = model.create_tensor([b, indices_per_table], dtype="int32",
                                  name=f"sparse_{i}")
        e = model.embedding(ids, vocab, embedding_dim, aggr="sum",
                            name=f"embed_{i}")
        sparse_outs.append(e)

    # feature interaction: concat (reference dlrm.cc interact_features
    # "cat" mode)
    t = model.concat([bottom] + sparse_outs, axis=1, name="interact")
    for i, h in enumerate(top_mlp[:-1]):
        t = model.dense(t, h, activation="relu", name=f"top_mlp_{i}")
    t = model.dense(t, top_mlp[-1], activation="sigmoid", name="top_out")
    return model
