"""AlexNet (reference: examples/cpp/AlexNet/alexnet.cc:1-428 and
bootcamp_demo/ff_alexnet_cifar10.py).  NHWC layout."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def build_alexnet(config: FFConfig, num_classes: int = 1000, image: int = 224):
    """Classic AlexNet over [B, image, image, 3]."""
    model = FFModel(config)
    b = config.batch_size
    x = model.create_tensor([b, image, image, 3], name="image")
    t = model.conv2d(x, 64, 11, 11, 4, 4, 2, 2, activation="relu", name="conv1")
    t = model.pool2d(t, 3, 3, 2, 2, name="pool1")
    t = model.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation="relu", name="conv2")
    t = model.pool2d(t, 3, 3, 2, 2, name="pool2")
    t = model.conv2d(t, 384, 3, 3, 1, 1, 1, 1, activation="relu", name="conv3")
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu", name="conv4")
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu", name="conv5")
    t = model.pool2d(t, 3, 3, 2, 2, name="pool5")
    t = model.flat(t, name="flat")
    t = model.dense(t, 4096, activation="relu", name="fc6")
    t = model.dropout(t, 0.5, name="drop6")
    t = model.dense(t, 4096, activation="relu", name="fc7")
    t = model.dropout(t, 0.5, name="drop7")
    t = model.dense(t, num_classes, name="fc8")
    return model


def build_alexnet_cifar10(config: FFConfig, num_classes: int = 10):
    """CIFAR-sized variant (reference: bootcamp_demo/ff_alexnet_cifar10.py):
    32x32 input, shrunk convs."""
    model = FFModel(config)
    b = config.batch_size
    x = model.create_tensor([b, 32, 32, 3], name="image")
    t = model.conv2d(x, 64, 5, 5, 1, 1, 2, 2, activation="relu", name="conv1")
    t = model.pool2d(t, 2, 2, 2, 2, name="pool1")
    t = model.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation="relu", name="conv2")
    t = model.pool2d(t, 2, 2, 2, 2, name="pool2")
    t = model.conv2d(t, 384, 3, 3, 1, 1, 1, 1, activation="relu", name="conv3")
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu", name="conv4")
    t = model.pool2d(t, 2, 2, 2, 2, name="pool4")
    t = model.flat(t, name="flat")
    t = model.dense(t, 2048, activation="relu", name="fc1")
    t = model.dropout(t, 0.5, name="drop1")
    t = model.dense(t, num_classes, name="fc2")
    return model
