"""TPU-idiomatic MoE token dispatch (sort-based).

The reference's Group_by scatters tokens into per-expert buffers with a
CUDA kernel (reference: src/ops/group_by.cu).  A row-wise scatter is
exactly what TPUs are bad at (dynamic HBM writes defeat XLA's tiling),
so the TPU-native formulation inverts it:

1. stable-sort token→expert assignments (XLA sorts are fast on TPU),
2. compute each token's rank within its expert (its capacity slot),
3. scatter only the *token indices* into the [E*cap] slot table — a
   narrow int32 scatter,
4. gather the wide [T, D] rows through the slot table — one big gather,
   which XLA lowers to efficient DMA.

Everything is jnp, so autodiff gives the combine (gather-backward)
for free; the one-hot cumsum alternative is O(T·E) memory, this is
O(T log T).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def dispatch_indices(flat_e: jax.Array, n_experts: int, capacity: int
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-token capacity positions + validity + slot->token table.

    flat_e: [T] int32 expert ids in token order.
    Returns (pos [T] int32, valid [T] bool, token_for_slot [E*cap] int32
    where T marks an empty slot).  Position semantics match the
    arrival-order cumsum definition (reference group_by.cc): the i-th
    token routed to expert e gets slot i.
    """
    t = flat_e.shape[0]
    in_range = (flat_e >= 0) & (flat_e < n_experts)  # reference semantics:
    # out-of-range expert ids drop the token (one_hot gave pos=-1 there)
    order = jnp.argsort(flat_e, stable=True)  # token ids grouped by expert
    sorted_e = flat_e[order]
    starts = jnp.searchsorted(sorted_e, jnp.arange(n_experts, dtype=flat_e.dtype))
    safe_e = jnp.clip(sorted_e, 0, n_experts - 1)
    ranks = jnp.arange(t, dtype=jnp.int32) - starts[safe_e].astype(jnp.int32)
    pos = jnp.zeros(t, jnp.int32).at[order].set(ranks)  # narrow scatter
    valid = (pos < capacity) & (pos >= 0) & in_range
    slot = (jnp.clip(flat_e, 0, n_experts - 1).astype(jnp.int32) * capacity
            + jnp.clip(pos, 0, capacity - 1))
    # invalid tokens write to a trash slot beyond the table
    slot = jnp.where(valid, slot, n_experts * capacity)
    token_for_slot = jnp.full((n_experts * capacity + 1,), t, jnp.int32)
    token_for_slot = token_for_slot.at[slot].set(
        jnp.arange(t, dtype=jnp.int32), mode="drop"
    )[: n_experts * capacity]
    return pos, valid, token_for_slot


def moe_dispatch(src: jax.Array, flat_e: jax.Array, n_experts: int,
                 capacity: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(src [T, D], expert ids [T]) -> (grouped [E, cap, D], pos [T],
    valid [T]).  Empty slots are zero rows; differentiable."""
    t, d = src.shape
    pos, valid, token_for_slot = dispatch_indices(flat_e, n_experts, capacity)
    padded = jnp.concatenate([src, jnp.zeros((1, d), src.dtype)], axis=0)
    grouped = padded[token_for_slot].reshape(n_experts, capacity, d)
    return grouped, pos, valid
