"""Pallas TPU kernels for the ops where XLA's default lowering is weak
(SURVEY.md §7: flash attention, MoE dispatch) — the counterpart of the
reference's hand-written CUDA kernels, written against the MXU/VMEM
model instead."""

from flexflow_tpu.kernels.flash_attention import flash_attention

__all__ = ["flash_attention"]
